"""Determinism + structural contract lints, now a thin wrapper over the
nf-lint engine (ISSUE 12; originally a test-embedded AST walker from
ISSUEs 4/6/7/10/11).

The checks themselves live in ``noahgameframe_tpu/lint/`` as named
rules — ``wall-clock``, ``unseeded-rng``, ``pump-surface``,
``fsync-barrier``, ``drill-clockless``, ``journal-tap-guard`` — and the
scan is WIDER than the old five-directory allowlist: the whole package,
with intentional reads carrying inline ``# nf-lint: disable=... -- ...``
waivers.  This file keeps two guarantees alive across that migration:

- every offense class the legacy linter caught is still caught (the
  meta-test snippets below are the original corpus, verbatim), and
- the real tree stays clean under the migrated rules.

Per-rule fixtures and engine-protocol tests live in tests/test_lint.py.
"""

from pathlib import Path

import pytest

from noahgameframe_tpu.lint import run_lint
from noahgameframe_tpu.lint.rules_contracts import (
    DrillClocklessRule,
    FsyncBarrierRule,
    JournalTapGuardRule,
    PumpSurfaceRule,
)
from noahgameframe_tpu.lint.rules_determinism import (
    UnseededRngRule,
    WallClockRule,
)

PKG = Path(__file__).resolve().parent.parent / "noahgameframe_tpu"

DETERMINISM_RULES = (WallClockRule, UnseededRngRule)
CONTRACT_RULES = (PumpSurfaceRule, FsyncBarrierRule, DrillClocklessRule,
                  JournalTapGuardRule)


def _snippet(src: str, rules, tmp_path, rel="game/_lint_probe.py"):
    """Open findings for a synthetic module injected at ``rel``."""
    report = run_lint(tmp_path, rules=list(rules), overrides={rel: src})
    return [f for f in report.open_findings if f.path == rel]


# --- the real tree stays clean under the migrated (and widened) rules ----

def test_no_nondeterminism_in_package():
    report = run_lint(PKG, rules=list(DETERMINISM_RULES))
    offenses = [f"{f.path}:{f.line}: {f.message}"
                for f in report.open_findings]
    assert not offenses, "\n".join(offenses)


@pytest.mark.parametrize("rule_cls", CONTRACT_RULES,
                         ids=lambda c: c.name)
def test_structural_contracts_hold(rule_cls):
    report = run_lint(PKG, rules=[rule_cls])
    offenses = [f"{f.path}:{f.line}: {f.message}"
                for f in report.open_findings]
    assert not offenses, "\n".join(offenses)


# --- the linter itself must catch what it claims to (meta-tests on
# synthetic sources, so a refactor can't silently blunt the lint).
# This corpus is the original test-embedded linter's, verbatim.

@pytest.mark.parametrize("src", [
    "import time\ntime.time()",
    "import time as _time\n_time.time()",
    "from time import time\ntime()",
    "from time import time as now\nnow()",
    "import random\nrandom.random()",
    "import random as _r\n_r.randint(0, 9)",
    "import random\nrandom.Random()",  # unseeded instance = global-ish
    "import numpy as np\nnp.random.rand(3)",
    "import numpy as np\nnp.random.default_rng()",  # seedless
    "import numpy\nnumpy.random.normal()",
])
def test_linter_catches(src, tmp_path):
    assert _snippet(src, DETERMINISM_RULES, tmp_path), src


@pytest.mark.parametrize("src", [
    "import time\ntime.monotonic()",  # injectable-now pattern, not wall time
    "import random\nr = random.Random(7)\nr.random()",
    "import numpy as np\nrng = np.random.default_rng(5)\nrng.normal()",
    "import numpy as np\ndef f(rng: np.random.Generator): ...",
    "import numpy as np\nnp.arange(4)",
])
def test_linter_allows(src, tmp_path):
    assert not _snippet(src, DETERMINISM_RULES, tmp_path), src


# --- contract meta-tests: a mutated module at the scoped path must flag

_WB_BAD_PUMP = """\
class WriteBehindPipeline:
    def enqueue(self, batch):
        self.backend.put_many(batch)
    def enqueue_one(self, rec): pass
    def note_tick(self, tick): pass
    def barrier(self): pass
    def pump(self): pass
    def pending(self): pass
    def discard(self): pass
    def lag_ticks(self): pass
    def queue_depth(self): pass
    def degraded(self): pass
    def _flush_batch(self, batch):
        self.backend.put_many(batch)
"""

_WB_BAD_FSYNC = _WB_BAD_PUMP.replace(
    "    def note_tick(self, tick): pass",
    "    def note_tick(self, tick):\n        self.wal.sync()")


def test_pump_surface_rule_catches_store_on_pump(tmp_path):
    found = _snippet(_WB_BAD_PUMP, [PumpSurfaceRule], tmp_path,
                     rel="persist/writebehind.py")
    assert any("store/sleep" in f.message for f in found)


def test_pump_surface_rule_catches_vanished_class(tmp_path):
    found = _snippet("x = 1\n", [PumpSurfaceRule], tmp_path,
                     rel="persist/writebehind.py")
    assert any("vanished" in f.message for f in found)


def test_fsync_rule_catches_per_tick_sync(tmp_path):
    found = _snippet(_WB_BAD_FSYNC, [FsyncBarrierRule], tmp_path,
                     rel="persist/writebehind.py")
    assert any("fsync" in f.message for f in found)


def test_drill_rule_catches_clocked_schedule(tmp_path):
    found = _snippet("import time\nT = time.monotonic()\n",
                     [DrillClocklessRule], tmp_path,
                     rel="drill/schedule.py")
    assert found


def test_drill_rule_allows_runner_pacing(tmp_path):
    found = _snippet("import time\ntime.sleep(time.monotonic() % 1)\n",
                     [DrillClocklessRule], tmp_path,
                     rel="drill/runner.py")
    assert not found


def test_journal_tap_rule_catches_unguarded_write(tmp_path):
    src = (
        "class GameRole:\n"
        "    def _journal_tap(self):\n"
        "        def tap(conn_id, msg_id, payload):\n"
        "            self.journal.event(conn_id, msg_id, payload)\n"
        "        return tap\n"
    )
    found = _snippet(src, [JournalTapGuardRule], tmp_path,
                     rel="net/roles/game.py")
    assert any("TRACE_MSG_IDS" in f.message for f in found)
