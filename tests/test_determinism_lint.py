"""Determinism lint (ISSUE 4 satellite): the simulation layers must not
read wall clocks or unseeded RNGs.

Record/replay's whole contract is that device state is a pure function
of (checkpoint, journaled inputs).  One stray ``time.time()`` or global
``random.random()`` in a tick-path module silently breaks every replay,
so this test walks the AST of ``kernel/``, ``ops/`` and ``game/`` and
fails on:

- ``time.time()`` calls, under any import alias (``import time as _t``,
  ``from time import time``),
- module-level ``random.*`` calls (the process-global RNG) — seeded
  instance construction ``random.Random(seed)`` is fine,
- ``np.random.*`` calls except ``np.random.default_rng(seed...)`` with
  an explicit seed argument; references to ``np.random.Generator`` in
  annotations are attribute loads, not calls, and pass.

Methods on a seeded generator object (``rng.normal()``) are untouched:
only *module*-rooted calls are nondeterministic by construction.
"""

import ast
from pathlib import Path

import pytest

PKG = Path(__file__).resolve().parent.parent / "noahgameframe_tpu"
# persist/ rides along (ISSUE 6): write-behind batch identity (seq, tick)
# must never include a wall clock — recovery flushes have to be
# byte-identical to the flushes a crash interrupted
# drill/ rides along (ISSUE 11): campaign scheduling is tick-indexed by
# contract — a wall clock in a schedule or invariant would turn a
# repeatable game-day drill back into an anecdote
SCANNED_DIRS = ("kernel", "ops", "game", "persist", "drill")
# frame observatory (ISSUE 7): the stage clock and the trace wire path
# (game emit/ack, proxy stamp, client echo) stamp with perf_counter_ns —
# fine — but a time.time() anywhere on these paths could leak wall clock
# into journaled inputs or compiled functions, so they join the scan
EXTRA_FILES = (
    "telemetry/pipeline.py",
    "net/roles/base.py",
    "net/roles/game.py",
    "net/roles/proxy.py",
    "client/sdk.py",
    # session failover (ISSUE 10): park/replay decisions are journaled
    # inputs downstream (the frames they order feed game handlers), and
    # the driver's retry/deadline arithmetic runs on injected `now` —
    # a wall clock here would make re-homes non-reproducible
    "net/failover.py",
)


def _files():
    for d in SCANNED_DIRS:
        yield from sorted((PKG / d).rglob("*.py"))
    for f in EXTRA_FILES:
        yield PKG / f


def _dotted(node):
    """Attribute/Name chain as a dotted string ('np.random.normal'),
    or None for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, path: Path):
        self.path = path
        self.offenses = []
        # alias maps rebuilt per file from its own imports
        self.time_aliases = set()  # modules: import time [as _t]
        self.time_fn_aliases = set()  # names: from time import time [as t]
        self.random_aliases = set()  # modules: import random [as _r]
        self.numpy_aliases = set()  # modules: import numpy [as np]

    def _flag(self, node, what):
        self.offenses.append(
            f"{self.path.relative_to(PKG.parent)}:{node.lineno}: {what}"
        )

    def visit_Import(self, node):
        for a in node.names:
            name = a.asname or a.name
            if a.name == "time":
                self.time_aliases.add(name)
            elif a.name == "random":
                self.random_aliases.add(name)
            elif a.name == "numpy":
                self.numpy_aliases.add(name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == "time":
            for a in node.names:
                if a.name == "time":
                    self.time_fn_aliases.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node):
        dotted = _dotted(node.func)
        if dotted is not None:
            self._check_call(node, dotted)
        self.generic_visit(node)

    def _check_call(self, node, dotted):
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        if dotted in self.time_fn_aliases:
            self._flag(node, f"wall clock read: {dotted}()")
        elif head in self.time_aliases and rest == ["time"]:
            self._flag(node, f"wall clock read: {dotted}()")
        elif head in self.random_aliases and len(rest) == 1:
            if rest[0] == "Random" and node.args:
                return  # seeded instance
            self._flag(node, f"process-global RNG: {dotted}()")
        elif (head in self.numpy_aliases and len(rest) == 2
              and rest[0] == "random"):
            if rest[1] == "default_rng" and node.args:
                return  # explicitly seeded generator
            self._flag(node, f"unseeded numpy RNG: {dotted}()")


def _lint(path: Path):
    linter = _Linter(path)
    linter.visit(ast.parse(path.read_text(), filename=str(path)))
    return linter.offenses


@pytest.mark.parametrize(
    "path", list(_files()),
    ids=lambda p: str(p.relative_to(PKG)),
)
def test_no_nondeterminism_in_tick_layers(path):
    offenses = _lint(path)
    assert not offenses, "\n".join(offenses)


# --- the linter itself must catch what it claims to (meta-tests on
# synthetic sources, so a refactor can't silently blunt the lint)
def _lint_source(src: str, tmp_path) -> list:
    f = PKG / "game" / "_lint_probe.py"  # relative_to(PKG.parent) must work
    linter = _Linter(f)
    linter.visit(ast.parse(src))
    return linter.offenses


@pytest.mark.parametrize("src", [
    "import time\ntime.time()",
    "import time as _time\n_time.time()",
    "from time import time\ntime()",
    "from time import time as now\nnow()",
    "import random\nrandom.random()",
    "import random as _r\n_r.randint(0, 9)",
    "import random\nrandom.Random()",  # unseeded instance = global-ish
    "import numpy as np\nnp.random.rand(3)",
    "import numpy as np\nnp.random.default_rng()",  # seedless
    "import numpy\nnumpy.random.normal()",
])
def test_linter_catches(src, tmp_path):
    assert _lint_source(src, tmp_path), src


@pytest.mark.parametrize("src", [
    "import time\ntime.monotonic()",  # injectable-now pattern, not wall time
    "import random\nr = random.Random(7)\nr.random()",
    "import numpy as np\nrng = np.random.default_rng(5)\nrng.normal()",
    "import numpy as np\ndef f(rng: np.random.Generator): ...",
    "import numpy as np\nnp.arange(4)",
])
def test_linter_allows(src, tmp_path):
    assert not _lint_source(src, tmp_path), src


# --- write-behind thread contract (ISSUE 6): the pump-thread surface of
# WriteBehindPipeline must never touch the store or sleep — the compiled
# tick cannot be allowed to block on a socket — and only barrier/drain/
# close may fsync the WAL (enqueue/pump run every tick; an fsync there
# would put disk latency on the tick path).
WB_PATH = PKG / "persist" / "writebehind.py"
PUMP_METHODS = {"enqueue", "enqueue_one", "note_tick", "barrier", "pump",
                "pending", "discard", "lag_ticks", "queue_depth",
                "degraded"}
SYNC_ALLOWED = {"barrier", "drain", "close", "kill"}


def _pipeline_methods():
    tree = ast.parse(WB_PATH.read_text(), filename=str(WB_PATH))
    cls = next(
        n for n in tree.body
        if isinstance(n, ast.ClassDef) and n.name == "WriteBehindPipeline"
    )
    return {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}


def _calls(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted is not None:
                yield node.lineno, dotted


def test_pipeline_declares_expected_pump_surface():
    missing = PUMP_METHODS - set(_pipeline_methods())
    assert not missing, f"pump-thread methods vanished: {sorted(missing)}"


@pytest.mark.parametrize("method", sorted(PUMP_METHODS))
def test_pump_surface_never_touches_store_or_sleeps(method):
    fn = _pipeline_methods()[method]
    offenses = [
        f"{method}:{line}: {dotted}"
        for line, dotted in _calls(fn)
        if dotted.startswith("self.backend.")
        or dotted == "self._flush_batch"
        or dotted.endswith(".sleep") or dotted == "sleep"
    ]
    assert not offenses, (
        "store/sleep call on the pump-thread surface:\n" + "\n".join(offenses)
    )


def test_wal_fsync_only_at_barriers():
    for name, fn in _pipeline_methods().items():
        if name in SYNC_ALLOWED:
            continue
        offenses = [
            f"{name}:{line}" for line, dotted in _calls(fn)
            if dotted in ("self.wal.sync", "os.fsync")
        ]
        assert not offenses, (
            "per-tick WAL fsync (disk latency on the tick path):\n"
            + "\n".join(offenses)
        )


def test_flusher_owns_every_store_call():
    methods = _pipeline_methods()
    callers = {
        name for name, fn in methods.items()
        if any(dotted.startswith("self.backend.")
               for _, dotted in _calls(fn))
    }
    # _flush_batch (called only from _run, the flusher thread) is the
    # single place store I/O happens
    assert callers == {"_flush_batch"}, callers


# --- trace journal-exclusion contract (ISSUE 7): replay bit-identity
# with tracing on vs off requires that FRAME_TRACE / FRAME_TRACE_ACK
# events never enter the journal — the recorded input stream must not
# depend on whether a session was sampled.  Enforced structurally: the
# journal tap's write is guarded by a TRACE_MSG_IDS membership test.
GAME_PATH = PKG / "net" / "roles" / "game.py"


def _journal_tap_fn():
    tree = ast.parse(GAME_PATH.read_text(), filename=str(GAME_PATH))
    cls = next(n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == "GameRole")
    outer = next(n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "_journal_tap")
    return next(n for n in ast.walk(outer)
                if isinstance(n, ast.FunctionDef) and n.name == "tap")


def _class_methods(path: Path, class_name: str):
    tree = ast.parse(path.read_text(), filename=str(path))
    cls = next(n for n in tree.body
               if isinstance(n, ast.ClassDef) and n.name == class_name)
    return {n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)}


# --- parking-path thread contract (ISSUE 10): the proxy parks, replays
# and expires client frames on its dispatch/pump thread — while every
# OTHER client's traffic waits behind it.  A sleep, a blocking file or
# store call, or an unbounded busy loop there turns one session's
# failover stall into a whole-proxy stall.  Enforced structurally, like
# the write-behind pump surface above.
FAILOVER_PATH = PKG / "net" / "failover.py"
PROXY_PATH = PKG / "net" / "roles" / "proxy.py"
PARKING_METHODS = {"park", "expire", "replay", "discard", "depth", "keys"}
PROXY_PARKING_SURFACE = {"_parking_pump", "_on_client_message",
                         "_on_switch_route", "_notify_switch"}
_BLOCKING = ("sleep", "fsync", "open", "connect", "recv", "accept")


def _blocking_calls(fn):
    for line, dotted in _calls(fn):
        leaf = dotted.rsplit(".", 1)[-1]
        if leaf in _BLOCKING:
            yield f"{fn.name}:{line}: {dotted}"


def test_parking_buffer_declares_expected_surface():
    missing = PARKING_METHODS - set(_class_methods(FAILOVER_PATH,
                                                   "ParkingBuffer"))
    assert not missing, f"parking methods vanished: {sorted(missing)}"


@pytest.mark.parametrize("method", sorted(PARKING_METHODS))
def test_parking_buffer_never_blocks(method):
    fn = _class_methods(FAILOVER_PATH, "ParkingBuffer")[method]
    offenses = list(_blocking_calls(fn))
    assert not offenses, (
        "blocking call inside ParkingBuffer:\n" + "\n".join(offenses)
    )


@pytest.mark.parametrize("method", sorted(PROXY_PARKING_SURFACE))
def test_proxy_parking_pump_never_blocks(method):
    methods = _class_methods(PROXY_PATH, "ProxyRole")
    assert method in methods, f"proxy parking surface lost {method}"
    offenses = list(_blocking_calls(methods[method]))
    assert not offenses, (
        "blocking call on the proxy parking path:\n" + "\n".join(offenses)
    )


# --- drill clock contract (ISSUE 11): campaigns and invariants are
# tick-indexed, never wall-timed.  Stronger than the RNG/wall-clock lint
# above: schedule.py and invariants.py must not reference the `time`
# module AT ALL (even monotonic would smuggle a runtime clock into what
# is declaratively a tick schedule); runner.py is the single component
# allowed to touch the clock, and only as pump pacing — monotonic()
# and sleep(), nothing else.
DRILL = PKG / "drill"
DRILL_CLOCKLESS = ("schedule.py", "invariants.py")
RUNNER_CLOCK_ALLOWED = {"monotonic", "sleep"}


def _time_refs(path: Path):
    """Every dotted use rooted in a `time` import, plus the imports
    themselves (`import time [as x]` / `from time import ...`)."""
    tree = ast.parse(path.read_text(), filename=str(path))
    aliases = set()
    refs = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    aliases.add(a.asname or a.name)
                    refs.append((node.lineno, "import time"))
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                refs.append((node.lineno, f"from time import {a.name}"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            dotted = _dotted(node)
            if dotted is not None and dotted.split(".")[0] in aliases:
                refs.append((node.lineno, dotted))
    return refs


@pytest.mark.parametrize("fname", DRILL_CLOCKLESS)
def test_drill_schedule_and_invariants_are_clockless(fname):
    refs = _time_refs(DRILL / fname)
    assert not refs, (
        f"drill/{fname} references the time module — campaign "
        "schedules/invariants are tick-indexed by contract:\n"
        + "\n".join(f"  line {ln}: {what}" for ln, what in refs)
    )


def test_drill_runner_clock_is_pacing_only():
    offenses = [
        (ln, what) for ln, what in _time_refs(DRILL / "runner.py")
        if "." in what  # attribute uses; the import line itself is fine
        and what.split(".")[-1] not in RUNNER_CLOCK_ALLOWED
    ]
    assert not offenses, (
        "drill/runner.py touches the clock beyond monotonic/sleep "
        "pacing:\n"
        + "\n".join(f"  line {ln}: {what}" for ln, what in offenses)
    )


def test_journal_tap_excludes_trace_sidecars():
    tap = _journal_tap_fn()
    writes = [n for n in ast.walk(tap)
              if isinstance(n, ast.Call)
              and _dotted(n.func) is not None
              and _dotted(n.func).endswith(".event")]
    assert writes, "journal tap no longer writes events?"
    guarded = [
        n for n in ast.walk(tap)
        if isinstance(n, ast.If)
        and any(isinstance(x, ast.Name) and x.id == "TRACE_MSG_IDS"
                for x in ast.walk(n.test))
        and any(w in ast.walk(n) for w in writes)
    ]
    assert guarded, (
        "journal writes are not guarded by a TRACE_MSG_IDS test — "
        "trace sidecars would enter the journal and break replay "
        "identity between traced and untraced runs"
    )
