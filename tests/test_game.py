"""Game-layer tests: stat groups, level-ups, movement, combat, regen.

Mirrors the reference's gameplay semantics (NFCPropertyModule /
NFCLevelModule / NFCSkillModule / NFCNPCRefreshModule) as pytest units —
the test suite the reference never had (SURVEY §4).
"""

import numpy as np
import pytest

from noahgameframe_tpu.game import (
    GameEvent,
    GameWorld,
    PropertyGroup,
    WorldConfig,
    build_benchmark_world,
)


@pytest.fixture(scope="module")
def small_world():
    w = GameWorld(WorldConfig(npc_capacity=64, player_capacity=8, extent=64.0))
    w.property_config.fill_linear(
        0,
        base={"MAXHP": 100, "MAXMP": 50, "ATK_VALUE": 10},
        per_level={"MAXHP": 10, "MAXMP": 5, "ATK_VALUE": 2},
        max_exp_base=100,
        max_exp_per_level=0,
    )
    w.start()
    w.scene.create_scene(1, width=64.0)
    return w


def test_stat_group_sum_becomes_property(small_world):
    w = small_world
    g = w.kernel.create_object("Player", {"Job": 0, "Level": 1}, scene=1)
    w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.JOBLEVEL, 12)
    w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.EQUIP, 5)
    # RUNTIME_BUFF is device-owned by BuffModule (recomputed every tick);
    # manual contributions belong in the other groups
    w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.STATIC_BUFF, 3)
    w.tick()
    assert w.kernel.get_property(g, "ATK_VALUE") == 20
    # removing the buff contribution drops the final stat
    w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.STATIC_BUFF, 0)
    w.tick()
    assert w.kernel.get_property(g, "ATK_VALUE") == 17


def test_host_add_exp_levels_up_and_refills(small_world):
    w = small_world
    g = w.kernel.create_object("Player", {"Job": 0, "Level": 0}, scene=1)
    w.properties.refresh_base_property(g, w.property_config)
    w.properties.recompute_now(g)
    assert w.kernel.get_property(g, "MAXHP") == 100
    lvl = w.level.add_exp(g, 250)  # 100-per-level thresholds -> level 2
    assert lvl == 2
    assert w.kernel.get_property(g, "EXP") == 50
    assert w.kernel.get_property(g, "MAXHP") == 120
    assert w.kernel.get_property(g, "HP") == 120  # FullHPMP on level-up


def test_device_level_phase_matches_host(small_world):
    w = small_world
    g = w.kernel.create_object("Player", {"Job": 0, "Level": 0, "EXP": 330}, scene=1)
    events = []
    w.kernel.events.subscribe_batch(
        int(GameEvent.ON_LEVEL_UP), lambda c, m, p: events.append((c, m.copy(), p))
    )
    w.tick()
    assert w.kernel.get_property(g, "Level") == 3
    assert w.kernel.get_property(g, "EXP") == 30
    assert w.kernel.get_property(g, "MAXHP") == 130
    assert w.kernel.get_property(g, "HP") == 130
    cname, mask, params = events[-1]
    assert cname == "Player"
    _, row = w.kernel.store.row_of(g)
    assert mask[row]
    assert params["new_level"][row] == 3


def test_movement_seeks_target():
    w = GameWorld(WorldConfig(npc_capacity=16, extent=100.0, combat=False, regen=False))
    w.start()
    w.scene.create_scene(1, width=100.0)
    g = w.kernel.create_object(
        "NPC", {"Position": (0.0, 0.0, 0.0), "TargetPos": (30.0, 40.0), "HP": 10}, scene=1
    )
    w.properties.set_group_value(g, "MOVE_SPEED", PropertyGroup.EFFECTVALUE, 50000)
    w.tick()  # recompute publishes MOVE_SPEED=5.0
    for _ in range(30):  # 1 s at 30 Hz, speed 5 -> distance 5 of 50
        w.tick()
    pos = w.kernel.get_property(g, "Position")
    d = np.hypot(pos[0], pos[1])
    assert 3.5 <= d <= 6.5  # moved ~5 units along the 3-4-5 diagonal
    assert abs(pos[0] / max(pos[1], 1e-9) - 0.75) < 0.05  # on the bearing


def test_combat_kill_event_respawn():
    w = GameWorld(
        WorldConfig(
            npc_capacity=16,
            extent=32.0,
            aoe_radius=5.0,
            respawn_s=0.5,
            attack_period_s=1.0 / 30.0,  # attack every tick
            movement=False,
            regen=False,
        )
    )
    w.start()
    w.scene.create_scene(1, width=32.0)
    k = w.kernel
    a = k.create_object("NPC", {"Position": (10.0, 10.0, 0.0), "Camp": 0, "HP": 50}, scene=1)
    b = k.create_object("NPC", {"Position": (12.0, 10.0, 0.0), "Camp": 1, "HP": 50}, scene=1)
    for g, atk in ((a, 40), (b, 8)):
        w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.EFFECTVALUE, atk)
        w.properties.set_group_value(g, "MAXHP", PropertyGroup.EFFECTVALUE, 50)
        w.combat.arm_all()
    killed = []
    k.events.subscribe_batch(
        int(GameEvent.ON_OBJECT_BE_KILLED), lambda c, m, p: killed.append((m.copy(), dict(p)))
    )
    respawned = []
    k.events.subscribe_batch(
        int(GameEvent.ON_NPC_RESPAWN), lambda c, m, p: respawned.append(m.copy())
    )
    w.tick()  # recompute stats
    w.tick()  # first exchange: b takes 40 dmg -> 10 HP, a takes 8
    hp_b = k.get_property(b, "HP")
    assert hp_b == 10
    w.tick()  # b dies
    assert k.get_property(b, "HP") == 0
    assert killed, "BE_KILLED event expected"
    mask, params = killed[-1]
    _, row_b = k.store.row_of(b)
    assert mask[row_b]
    # killer is a's packed handle
    killer = k.store.guid_of_handle(int(params["killer"][row_b]))
    assert killer == a
    assert k.get_property(b, "LastAttacker") == a
    # dead don't fight back: a stops taking damage once b is at 0
    hp_a_dead = k.get_property(a, "HP")
    w.tick()
    assert k.get_property(a, "HP") == hp_a_dead
    # disarm a so the respawned b isn't instantly re-killed
    from noahgameframe_tpu.game import ATTACK_TIMER

    k.state = k.schedule.cancel_timer(k.state, k.store, a, ATTACK_TIMER)
    # respawn after 0.5 s (15 ticks) with full HP
    for _ in range(17):
        w.tick()
    assert k.get_property(b, "HP") == 50
    assert respawned and any(m.any() for m in respawned)


def test_combat_overflow_event_fires():
    """Bucket overflow must be observable at runtime, not only via
    bench.py's offline replay: piling entities past the cell bucket
    fires ON_COMBAT_TABLE_OVERFLOW with the drop counts."""
    w = GameWorld(
        WorldConfig(
            npc_capacity=32, extent=32.0, aoe_radius=5.0,
            attack_period_s=1.0 / 30.0, movement=False, regen=False,
            middleware=False,
        )
    )
    w.combat.bucket = 4  # force tiny cells: 12 stacked entities overflow
    w.start()
    w.scene.create_scene(1, width=32.0)
    k = w.kernel
    for i in range(12):
        k.create_object(
            "NPC", {"Position": (5.0, 5.0, 0.0), "Camp": i % 2, "HP": 100},
            scene=1,
        )
    w.combat.arm_all()
    seen = []
    k.events.subscribe_batch(
        int(GameEvent.ON_COMBAT_TABLE_OVERFLOW),
        lambda c, m, p: seen.append((m.copy(), {k2: v.copy() for k2, v in p.items()})),
    )
    w.tick()
    w.tick()
    assert seen, "overflow event expected"
    _, params = seen[0]
    assert int(params["dropped_victims"][0]) == 8  # 12 - bucket 4
    # the runtime monitor auto-resized after the breach (bucket x2), so
    # a later tick drops strictly less
    _, last = seen[-1]
    assert int(last["dropped_victims"][0]) <= 4
    assert w.combat.overflow_alerts >= 1


def test_regen_heals_to_cap(small_world):
    w = small_world
    g = w.kernel.create_object("NPC", {"HP": 10}, scene=1)
    w.properties.set_group_value(g, "MAXHP", PropertyGroup.EFFECTVALUE, 40)
    w.properties.set_group_value(g, "HPREGEN", PropertyGroup.EFFECTVALUE, 10)
    w.regen.arm(g)
    for _ in range(31 * 5):
        w.tick()
    assert w.kernel.get_property(g, "HP") == 40  # capped at MAXHP


def test_skill_module_parity(small_world):
    w = small_world
    w.kernel.elements.add_element("NPC", "FireBall", {})
    att = w.kernel.create_object("Player", {}, scene=1)
    tgt = w.kernel.create_object("NPC", {"HP": 25}, scene=1)
    assert w.skills.use_skill(att, "FireBall", tgt)
    assert w.kernel.get_property(tgt, "HP") == 15  # HP-10 resolution
    assert w.kernel.get_property(tgt, "LastAttacker") == att
    assert not w.skills.use_skill(att, "NoSuchSkill", tgt)
    w.kernel.set_property(tgt, "HP", 0)
    assert not w.skills.use_skill(att, "FireBall", tgt)  # dead target


def test_wallet_and_vitals_api(small_world):
    w = small_world
    g = w.kernel.create_object("Player", {"Gold": 100, "HP": 30}, scene=1)
    w.properties.set_group_value(g, "MAXHP", PropertyGroup.JOBLEVEL, 50)
    w.properties.recompute_now(g)
    assert w.properties.add_hp(g, 100)
    assert w.kernel.get_property(g, "HP") == 50  # clamped
    assert w.properties.consume_hp(g, 20)
    assert not w.properties.consume_hp(g, 999)
    assert w.properties.enough_money(g, 100)
    assert w.properties.consume_money(g, 40)
    assert w.kernel.get_property(g, "Gold") == 60
    assert not w.properties.consume_money(g, 61)


def test_unconfigured_job_never_levels():
    """All-zero MAXEXP table (job not configured) must not promote anyone
    (regression: searchsorted over zero thresholds jumped to max_level)."""
    w = GameWorld(WorldConfig(npc_capacity=16, combat=False, movement=False, regen=False))
    w.start()
    w.scene.create_scene(1)
    g = w.kernel.create_object("Player", {"Job": 1, "Level": 0, "EXP": 500}, scene=1)
    w.tick()
    w.tick()
    assert w.kernel.get_property(g, "Level") == 0
    assert w.kernel.get_property(g, "EXP") == 500


def test_combat_is_scene_scoped():
    """Entities at overlapping coordinates in different scenes/groups never
    damage each other (reference broadcast is (scene, group)-scoped)."""
    w = GameWorld(
        WorldConfig(
            npc_capacity=16, extent=32.0, aoe_radius=5.0,
            attack_period_s=1.0 / 30.0, movement=False, regen=False,
        )
    )
    w.start()
    w.scene.create_scene(1, width=32.0)
    w.scene.create_scene(2, width=32.0)
    k = w.kernel
    a = k.create_object("NPC", {"Position": (10.0, 10.0, 0.0), "Camp": 0, "HP": 50}, scene=1)
    b = k.create_object("NPC", {"Position": (11.0, 10.0, 0.0), "Camp": 1, "HP": 50}, scene=2)
    for g in (a, b):
        w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.EFFECTVALUE, 40)
        w.properties.set_group_value(g, "MAXHP", PropertyGroup.EFFECTVALUE, 50)
    w.combat.arm_all()
    for _ in range(5):
        w.tick()
    assert k.get_property(a, "HP") == 50
    assert k.get_property(b, "HP") == 50


def test_no_maxhp_stays_dead():
    """A killed entity with no MAXHP contribution must stay dead instead of
    re-firing BE_KILLED every respawn interval."""
    w = GameWorld(
        WorldConfig(
            npc_capacity=16, extent=32.0, respawn_s=0.1,
            attack_period_s=1.0 / 30.0, movement=False, regen=False,
        )
    )
    w.start()
    w.scene.create_scene(1, width=32.0)
    k = w.kernel
    tgt = k.create_object("NPC", {"HP": 5, "Position": (5.0, 5.0, 0.0)}, scene=1)
    killed = []
    k.events.subscribe_batch(
        int(GameEvent.ON_OBJECT_BE_KILLED), lambda c, m, p: killed.append(int(m.sum()))
    )
    k.set_property(tgt, "HP", 0)
    for _ in range(30):  # 10x the respawn interval
        w.tick()
    assert sum(killed) <= 1
    assert k.get_property(tgt, "HP") == 0


def test_seed_waves_differ():
    w = GameWorld(WorldConfig(npc_capacity=64, combat=False, regen=False))
    w.start()
    w.scene.create_scene(1)
    w.seed_npcs(10)
    w.seed_npcs(10)
    pos = np.asarray(
        w.kernel.state.classes["NPC"].vec[
            :, w.kernel.store.spec("NPC").slot("Position").col, :2
        ]
    )
    alive = np.asarray(w.kernel.state.classes["NPC"].alive)
    live_pos = pos[alive]
    assert not np.allclose(live_pos[:10], live_pos[10:20])


def test_benchmark_world_progresses():
    w = build_benchmark_world(500, seed=3)
    k = w.kernel
    w.run(60)
    alive = np.asarray(k.state.classes["NPC"].alive)
    assert alive.sum() == 500
    maxhp = np.asarray(k.store.column(k.state, "NPC", "MAXHP"))
    assert (maxhp[alive] == 100).all()


def test_nine_group_recompute_parity(small_world):
    """The reference folds NINE NPG_* contribution groups
    (NFCPropertyModule.cpp:193-240); the record bank is sized from
    PropertyGroup.ALL so FIGHTING_HERO and TALENT rows must (a) exist,
    (b) be summed by the DEVICE phase, and (c) agree with the host-side
    recompute_now fold — one fixture pins all three."""
    w = small_world
    g = w.kernel.create_object("Player", {"Job": 0, "Level": 1}, scene=1)
    contributions = {
        PropertyGroup.JOBLEVEL: 12,
        PropertyGroup.EFFECTVALUE: 1,
        PropertyGroup.REBIRTH_ADD: 2,
        PropertyGroup.EQUIP: 5,
        PropertyGroup.EQUIP_AWARD: 4,
        PropertyGroup.STATIC_BUFF: 3,
        # RUNTIME_BUFF stays 0: device-owned by BuffModule
        PropertyGroup.FIGHTING_HERO: 7,
        PropertyGroup.TALENT: 6,
    }
    assert len(contributions) + 1 == int(PropertyGroup.ALL)
    for grp, val in contributions.items():
        w.properties.set_group_value(g, "ATK_VALUE", grp, val)
        assert w.properties.get_group_value(g, "ATK_VALUE", grp) == val
    expect = sum(contributions.values())
    # host fold first (read-after-write path)...
    w.properties.recompute_now(g)
    assert w.kernel.get_property(g, "ATK_VALUE") == expect
    # ...then the device phase must land on the same sum
    w.tick()
    assert w.kernel.get_property(g, "ATK_VALUE") == expect
    # dropping the two NEW groups subtracts exactly their contribution —
    # proves they are real rows, not aliases of the original seven
    w.properties.set_group_value(
        g, "ATK_VALUE", PropertyGroup.FIGHTING_HERO, 0)
    w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.TALENT, 0)
    w.tick()
    assert w.kernel.get_property(g, "ATK_VALUE") == expect - 13
