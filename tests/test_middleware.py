"""Gameplay middleware: pack/item/equip, hero, task, device-expired buffs
(SURVEY §2.8 NFGameLogicPlugin, §2.9)."""

from __future__ import annotations

import numpy as np
import pytest

from noahgameframe_tpu.game import (
    GameWorld,
    ItemSubType,
    ItemType,
    PropertyGroup,
    TaskDef,
    TaskState,
    WorldConfig,
)


@pytest.fixture()
def world():
    w = GameWorld(WorldConfig(combat=True, movement=False, regen=False,
                              npc_capacity=64, player_capacity=8,
                              attack_period_s=1 / 30, aoe_radius=1e6,
                              respawn_s=1e6)).start()
    w.scene.create_scene(1)
    return w


@pytest.fixture()
def player(world):
    g = world.kernel.create_object("Player", {"Name": "P", "Account": "p"},
                                   scene=1, group=0)
    world.kernel.set_property(g, "Level", 3)
    return g


def define_potion(world, item_id="potion_hp", sub=ItemSubType.HP, value=30):
    world.kernel.elements.add_element("Item", item_id, {
        "ItemType": int(ItemType.ITEM), "ItemSubType": int(sub),
        "AwardValue": value})
    return item_id


# ---------------------------------------------------------------- pack/item


def test_pack_stack_and_consume(world, player):
    p = world.pack
    assert p.create_item(player, "potion_hp", 3)
    assert p.create_item(player, "potion_hp", 2)  # stacks
    assert p.item_count(player, "potion_hp") == 5
    assert p.enough_item(player, "potion_hp", 5)
    assert p.delete_item(player, "potion_hp", 4)
    assert p.item_count(player, "potion_hp") == 1
    assert p.delete_item(player, "potion_hp", 1)
    assert p.item_count(player, "potion_hp") == 0
    assert not p.delete_item(player, "potion_hp", 1)


def test_use_potion_restores_hp(world, player):
    k = world.kernel
    define_potion(world)
    world.properties.set_group_value(player, "MAXHP", PropertyGroup.JOBLEVEL, 100)
    world.properties.recompute_now(player)
    k.set_property(player, "HP", 50)
    world.pack.create_item(player, "potion_hp", 2)
    assert world.items.use_item(player, "potion_hp")
    assert int(k.get_property(player, "HP")) == 80
    assert world.items.use_item(player, "potion_hp")
    assert int(k.get_property(player, "HP")) == 100  # capped at MAXHP
    assert not world.items.use_item(player, "potion_hp")  # bag empty


def test_token_grants_gold(world, player):
    world.kernel.elements.add_element("Item", "gold_pouch", {
        "ItemType": int(ItemType.TOKEN),
        "ItemSubType": int(ItemSubType.CURRENCY), "AwardValue": 250})
    world.pack.create_item(player, "gold_pouch", 1)
    g0 = int(world.kernel.get_property(player, "Gold"))
    assert world.items.use_item(player, "gold_pouch")
    assert int(world.kernel.get_property(player, "Gold")) == g0 + 250


def test_equip_wear_feeds_stat_group(world, player):
    world.kernel.elements.add_element("Item", "sword_1", {
        "ItemType": int(ItemType.EQUIP), "ATK_VALUE": 15, "MAXHP": 40})
    eq = world.pack.create_equip(player, "sword_1")
    assert eq is not None
    assert world.equip.wear(player, eq)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 15
    world.properties.recompute_now(player)
    assert int(world.kernel.get_property(player, "ATK_VALUE")) == 15
    assert world.equip.take_off(player, eq)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 0


# ---------------------------------------------------------------- hero


def test_hero_collect_level_fight_stats(world, player):
    world.kernel.elements.add_element("Item", "hero_knight", {
        "ATK_VALUE": 5, "MAXHP": 20})
    h = world.heroes
    row = h.add_hero(player, "hero_knight")
    assert row is not None
    assert h.add_hero(player, "hero_knight") == row  # dedupe
    assert h.set_fight_hero(player, row)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.FIGHTING_HERO) == 5  # level 1
    # progressive curve (NFIHeroModule.h): level N->N+1 costs (N+1)*100,
    # so 1000 exp from level 1 = 200+300+400 spent -> level 4, 100 left
    lvl = h.add_hero_exp(player, row, 1000)
    assert lvl == 4
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.FIGHTING_HERO) == 20


# ---------------------------------------------------------------- task


def test_task_accept_progress_award(world, player):
    t = world.tasks
    t.define_task(TaskDef("t_kill3", target_config="", count=3,
                          award_gold=100, award_exp=0))
    assert t.accept(player, "t_kill3")
    assert not t.accept(player, "t_kill3")  # no duplicates
    assert t.status(player, "t_kill3") == TaskState.IN_PROCESS
    t.add_process(player, "t_kill3", 2)
    assert t.process(player, "t_kill3") == 2
    assert not t.draw_award(player, "t_kill3")  # not done yet
    t.add_process(player, "t_kill3", 5)  # clamped at count
    assert t.status(player, "t_kill3") == TaskState.DONE
    g0 = int(world.kernel.get_property(player, "Gold"))
    assert t.draw_award(player, "t_kill3")
    assert int(world.kernel.get_property(player, "Gold")) == g0 + 100
    assert t.status(player, "t_kill3") == TaskState.FINISH
    assert not t.draw_award(player, "t_kill3")  # no double draw


def test_task_counts_device_kills(world, player):
    """Kill events from the jitted combat phase advance tasks batched."""
    k = world.kernel
    t = world.tasks
    t.define_task(TaskDef("t_hunt", count=2, award_gold=10))
    t.accept(player, "t_hunt")
    # plant two NPCs about to die, attacker = the player
    world.seed_npcs(2, scene=1, group=0, hp=1)
    handle = k.store.handle_of(player)
    npcs = world.scene.objects_in_group(1, 0, "NPC")
    for npc in npcs:
        k.state = k.store.set_property(k.state, npc, "HP", 0)
        k.state = k.store.set_property(k.state, npc, "LastAttacker", handle)
    k.tick()  # death phase emits ON_OBJECT_BE_KILLED with killer column
    assert t.process(player, "t_hunt") == 2
    assert t.status(player, "t_hunt") == TaskState.DONE


# ---------------------------------------------------------------- buffs


def test_buff_applies_and_expires_on_device(world, player):
    b = world.buffs
    b.define_buff("haste", duration_s=3 / 30, stats={"ATK_VALUE": 7,
                                                     "MOVE_SPEED": 100})
    assert b.apply_buff(player, "haste")
    world.tick()
    assert b.active_buffs(player) == ["haste"]
    assert int(world.kernel.get_property(player, "ATK_VALUE")) == 7
    # re-apply refreshes rather than stacking a second row
    assert b.apply_buff(player, "haste")
    world.run(2)
    assert int(world.kernel.get_property(player, "ATK_VALUE")) == 7
    world.run(4)  # past expiry
    assert b.active_buffs(player) == []
    assert int(world.kernel.get_property(player, "ATK_VALUE")) == 0


def test_buffs_stack_distinct_kinds(world, player):
    b = world.buffs
    b.define_buff("b1", duration_s=10.0, stats={"DEF_VALUE": 3})
    b.define_buff("b2", duration_s=10.0, stats={"DEF_VALUE": 4})
    b.apply_buff(player, "b1")
    b.apply_buff(player, "b2")
    world.tick()
    assert sorted(b.active_buffs(player)) == ["b1", "b2"]
    assert int(world.kernel.get_property(player, "DEF_VALUE")) == 7
