"""Driver-contract smoke tests: bench.py and __graft_entry__ must always
produce their artifacts (round-1 failure: both died/hung at TPU backend
init, leaving the driver with nothing to parse)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout):
    return subprocess.run(
        [sys.executable, *args],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


def test_bench_smoke_emits_parseable_json():
    r = _run(
        ["bench.py", "--platform", "cpu", "--entities", "2000", "--ticks", "5"],
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    d = json.loads(line)
    assert d["metric"] == "entities_ticked_per_sec_per_chip"
    assert d["value"] > 0
    assert d["detail"]["platform"] == "cpu"
    assert "tick_ms_p99" in d["detail"]


def test_bench_served_smoke():
    r = _run(
        ["bench.py", "--served", "--platform", "cpu",
         "--entities", "2000", "--ticks", "4", "--sessions", "5"],
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    d = json.loads(r.stdout.strip().splitlines()[-1])
    assert d["metric"] == "served_entity_ticks_per_sec_per_chip"
    assert d["value"] > 0
    assert d["detail"]["sync_msgs"] > 0  # fan-out actually happened


def test_bench_mesh_migrate_smoke():
    """The r09 unified-engine ladder at toy scale: full-row migration
    actually moves rows, drops nothing, and the post-warmup sweep loop
    compiles nothing new (the zero-unexplained-recompiles gate)."""
    r = _run(
        ["bench.py", "--mesh-migrate", "4", "--mig-entities", "4096",
         "--mig-widths", "2,4", "--mig-budgets", "64", "--mig-ticks", "3"],
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    d = json.loads(r.stdout.strip().splitlines()[-1])
    assert d["metric"] == "mesh_migrate_entity_ticks_per_sec"
    assert "error" not in d, d.get("error")
    assert d["value"] > 0
    assert d["detail"]["unexplained_recompiles"] == 0
    pts = d["detail"]["points"]
    assert len(pts) == 2  # 1 entity count x 2 widths x 1 budget
    for p in pts:
        assert p["migrated_total"] > 0, "ladder exercised no migration"
        assert p["mig_dropped_total"] == 0
        assert p["row_bytes"] > 0
        assert p["costbook"]["compiles"] >= 1


def test_dryrun_multichip_forces_cpu_and_finishes():
    r = _run(["__graft_entry__.py", "multichip", "4"], timeout=180)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip OK" in r.stdout


def test_entry_compiles_and_steps():
    """The driver compile-checks entry() single-chip; keep it compiling
    (conftest has already forced the CPU platform in-process)."""
    sys.path.insert(0, REPO)
    try:
        import jax

        import __graft_entry__ as ge

        fn, args = ge.entry()
        st, summary = jax.jit(fn)(*args)
        jax.block_until_ready(summary)
        assert summary.ndim == 1
    finally:
        sys.path.remove(REPO)
