"""Schema layer: definitions, inheritance flattening, bank compilation,
flag masks, reference-format XML loading."""

import textwrap

import numpy as np
import pytest

from noahgameframe_tpu.core import Bank, DataType, load_logic_class_xml
from noahgameframe_tpu.core.schema import load_class_xml

from fixtures import base_registry


def test_inheritance_flattens_parent_first():
    reg = base_registry()
    spec = reg.spec("Player")
    # parent (IObject) properties come first, in declaration order
    assert spec.prop_order[:6] == (
        "ID",
        "ClassName",
        "SceneID",
        "GroupID",
        "ConfigID",
        "Position",
    )
    assert "HP" in spec.prop_order
    assert spec.slots["SceneID"].prop.type == DataType.INT


def test_bank_compilation_partitions_by_dtype():
    reg = base_registry()
    spec = reg.spec("Player")
    # every property landed in exactly one bank with a unique column
    for bank in Bank:
        cols = [s.col for s in spec.bank_props(bank)]
        assert cols == list(range(len(cols)))
    assert spec.n_i32 + spec.n_f32 + spec.n_vec == len(spec.prop_order)
    # strings and objects are i32 columns
    assert spec.slots["Name"].bank == Bank.I32
    assert spec.slots["FirstTarget"].bank == Bank.I32
    assert spec.slots["MoveSpeed"].bank == Bank.F32
    assert spec.slots["Position"].bank == Bank.VEC


def test_flag_masks():
    reg = base_registry()
    spec = reg.spec("Player")
    pub = spec.mask(Bank.I32, "public")
    sav = spec.mask(Bank.I32, "save")
    assert pub[spec.slots["HP"].col]
    assert not pub[spec.slots["Gold"].col]
    assert sav[spec.slots["Gold"].col]
    up = spec.mask(Bank.I32, "upload")
    assert up[spec.slots["Gold"].col] and up.sum() == 1
    assert not spec.mask(Bank.VEC, "upload")[spec.slots["Position"].col]
    assert spec.mask(Bank.VEC, "public")[spec.slots["Position"].col]


def test_record_spec():
    reg = base_registry()
    spec = reg.spec("Player")
    rs = spec.records["PlayerHero"]
    assert rs.max_rows == 8
    assert rs.col_order == ("GUID", "ConfigID", "Level", "Exp")
    assert rs.n_i32 == 4 and rs.n_f32 == 0
    assert rs.cols["Level"].bank == Bank.I32


def test_duplicate_class_rejected():
    reg = base_registry()
    from noahgameframe_tpu.core import ClassDef

    with pytest.raises(ValueError):
        reg.define(ClassDef(name="Player"))


def test_load_reference_format_xml(tmp_path):
    """Loader accepts the reference's on-disk format (LogicClass tree +
    per-class Propertys/Records XML), verified against a synthetic config
    written in that format."""
    (tmp_path / "Struct" / "Class").mkdir(parents=True)
    (tmp_path / "Struct" / "LogicClass.xml").write_text(
        textwrap.dedent(
            """\
            <XML>
              <Class Id="IObject" Path="Struct/Class/IObject.xml" InstancePath="">
                <Class Id="Mob" Path="Struct/Class/Mob.xml" InstancePath="Ini/Mob.xml"/>
              </Class>
            </XML>
            """
        )
    )
    (tmp_path / "Struct" / "Class" / "IObject.xml").write_text(
        textwrap.dedent(
            """\
            <XML>
              <Propertys>
                <Property Id="ID" Type="string" Public="0" Private="1"/>
                <Property Id="SceneID" Type="int" Public="0" Private="1"/>
                <Property Id="X" Type="float" Public="1" Private="1" Save="1" Cache="1"/>
              </Propertys>
            </XML>
            """
        )
    )
    (tmp_path / "Struct" / "Class" / "Mob.xml").write_text(
        textwrap.dedent(
            """\
            <XML>
              <Propertys>
                <Property Id="HP" Type="int" Public="1" Private="1" Save="1"/>
                <Property Id="Master" Type="object" Public="0"/>
              </Propertys>
              <Records>
                <Record Id="Drops" Row="4" Col="2" Public="0" Private="1" Save="1">
                  <Col Type="string" Tag="ItemID"/>
                  <Col Type="int" Tag="Count"/>
                </Record>
              </Records>
              <Components>
                <Component Name="AI" Language="python" Enable="1"/>
              </Components>
            </XML>
            """
        )
    )
    reg = load_logic_class_xml(tmp_path / "Struct" / "LogicClass.xml", data_root=tmp_path)
    assert "Mob" in reg and "IObject" in reg
    spec = reg.spec("Mob")
    assert spec.prop_order == ("ID", "SceneID", "X", "HP", "Master")
    assert spec.slots["X"].prop.save and spec.slots["X"].prop.cache
    assert spec.records["Drops"].max_rows == 4
    flat = reg._flatten("Mob")
    assert flat.components[0].name == "AI"
    assert reg.get_def("Mob").instance_path == "Ini/Mob.xml"
