"""nf-lint engine tests (ISSUE 12).

Three layers:

- per-rule fixture pairs: every rule in the catalog must fire on its
  ``tests/lint_fixtures/bad/`` counterpart and stay quiet on
  ``tests/lint_fixtures/good/`` — a rule change that flags the good
  fixture is a false-positive regression, one that misses the bad
  fixture is a blunted check;
- engine protocol: suppression parsing (same-line + wrapped standalone),
  unused/malformed suppressions as findings, JSON report shape,
  baseline matching and staleness, rule filtering;
- the package gate: the real ``noahgameframe_tpu/`` tree has zero open
  findings against the committed baseline, the CLI exit codes encode
  that, and an injected ``block_until_ready`` in a jit-reachable tick
  helper is demonstrably caught (the call-graph stays alive).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from noahgameframe_tpu.lint import ALL_RULES, RULES_BY_NAME, run_lint
from noahgameframe_tpu.lint.engine import (
    BAD_SUPPRESSION,
    UNUSED_SUPPRESSION,
    write_baseline,
)

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "noahgameframe_tpu"
FIX = Path(__file__).resolve().parent / "lint_fixtures"
BASELINE = REPO / "nf_lint_baseline.json"

RULE_NAMES = [cls.name for cls in ALL_RULES]


def _open(report, rule=None):
    return [f for f in report.open_findings
            if rule is None or f.rule == rule]


# --- per-rule fixture pairs ----------------------------------------------

@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_fires_on_bad_fixture(rule):
    report = run_lint(FIX / "bad", rule_filter=[rule])
    assert _open(report, rule), (
        f"rule {rule} found nothing in lint_fixtures/bad — the check "
        "has been blunted")


@pytest.mark.parametrize("rule", RULE_NAMES)
def test_rule_quiet_on_good_fixture(rule):
    report = run_lint(FIX / "good", rule_filter=[rule])
    assert not _open(report, rule), (
        f"rule {rule} flagged the clean fixture: "
        + "; ".join(f"{f.path}:{f.line} {f.message}"
                    for f in _open(report, rule)))


def test_good_fixture_is_fully_clean():
    report = run_lint(FIX / "good")
    assert not report.open_findings, [
        f"{f.rule} {f.path}:{f.line}" for f in report.open_findings]


def test_trace_safety_catches_every_escape_class():
    report = run_lint(FIX / "bad", rule_filter=["trace-safety"])
    msgs = " | ".join(f.message for f in _open(report, "trace-safety"))
    for marker in ("block_until_ready", "print", "os.environ",
                   "`float()`", ".item()", "np.asarray"):
        assert marker in msgs, f"trace-safety no longer catches {marker}"


def test_recompile_hazard_catches_every_trap_class():
    report = run_lint(FIX / "bad", rule_filter=["recompile-hazard"])
    msgs = " | ".join(f.message for f in _open(report, "recompile-hazard"))
    for marker in ("not declared static", "arange(len(...))", ".tolist()"):
        assert marker in msgs, f"recompile-hazard no longer catches {marker}"


def test_struct_codec_catches_every_mismatch_class():
    report = run_lint(FIX / "bad", rule_filter=["struct-codec"])
    msgs = " | ".join(f.message for f in _open(report, "struct-codec"))
    for marker in ("paired constant", "comment claims", "invalid struct",
                   "values, 3 supplied", "values, 3 targets"):
        assert marker in msgs, f"struct-codec no longer catches: {marker}"


# --- suppression protocol -------------------------------------------------

def test_same_line_and_wrapped_suppressions_apply():
    report = run_lint(FIX / "suppress")
    ok = [f for f in report.findings if f.path == "ok.py"]
    assert len(ok) == 2
    assert all(f.status == "suppressed" for f in ok)
    reasons = {f.reason for f in ok}
    assert "reviewed boot stamp" in reasons
    # the wrapped form records the tag line's reason text; continuation
    # comment lines only extend the anchor, not the recorded reason
    assert any("wrapped reason" in r for r in reasons)


def test_unused_suppression_is_a_finding():
    report = run_lint(FIX / "suppress")
    unused = [f for f in report.open_findings
              if f.rule == UNUSED_SUPPRESSION]
    assert [f.path for f in unused] == ["unused.py"]


def test_malformed_suppression_is_a_finding_and_does_not_suppress():
    report = run_lint(FIX / "suppress")
    mal = [f for f in report.findings if f.path == "malformed.py"]
    assert {f.rule for f in mal} == {BAD_SUPPRESSION, "wall-clock"}
    assert all(f.status == "open" for f in mal)


def test_rule_filter_does_not_misreport_other_waivers_as_unused():
    # wall-clock never ran, so its suppressions cannot be judged stale
    report = run_lint(FIX / "suppress", rule_filter=["struct-codec"])
    assert not [f for f in report.findings
                if f.rule == UNUSED_SUPPRESSION]


def test_unknown_rule_filter_raises():
    with pytest.raises(ValueError, match="no-such-rule"):
        run_lint(FIX / "good", rule_filter=["no-such-rule"])


# --- report + baseline ----------------------------------------------------

def test_json_report_shape():
    report = run_lint(FIX / "suppress")
    data = report.to_json()
    assert data["version"] == 1
    assert set(data) == {"version", "root", "rules", "counts", "findings",
                         "stale_baseline"}
    assert data["rules"] == RULE_NAMES
    c = data["counts"]
    assert c["total"] == len(data["findings"])
    assert c["open"] + c["suppressed"] + c["baselined"] == c["total"]
    assert c["open"] == 3 and c["suppressed"] == 2
    for entry in data["findings"]:
        assert {"rule", "path", "line", "message", "status"} <= set(entry)
    suppressed = [e for e in data["findings"]
                  if e["status"] == "suppressed"]
    assert all("reason" in e for e in suppressed)


def test_baseline_marks_known_findings_and_reports_stale(tmp_path):
    first = run_lint(FIX / "bad")
    base = tmp_path / "base.json"
    write_baseline(base, first.open_findings)

    again = run_lint(FIX / "bad", baseline_path=base)
    assert not again.open_findings
    assert all(f.status == "baselined" for f in again.findings)
    assert not again.stale_baseline

    # against the clean tree every entry is stale (debt paid down)
    clean = run_lint(FIX / "good", baseline_path=base)
    assert clean.stale_baseline
    assert not clean.open_findings


# --- the package gate -----------------------------------------------------

def test_package_has_zero_unsuppressed_findings():
    report = run_lint(PKG, baseline_path=BASELINE
                      if BASELINE.exists() else None)
    assert not report.open_findings, "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}"
        for f in report.open_findings)
    assert not report.stale_baseline, report.stale_baseline


def test_package_suppressions_all_carry_reasons():
    report = run_lint(PKG)
    suppressed = [f for f in report.findings if f.status == "suppressed"]
    assert suppressed, "expected the repo's reviewed waivers to be visible"
    assert all(f.reason for f in suppressed)


def test_injected_block_until_ready_is_caught():
    """The acceptance probe: seed a host sync into a jit-reachable tick
    helper (ops/verlet.need_rebuild, reached from the spatial jit root)
    and the call-graph walk must flag it."""
    src = (PKG / "ops" / "verlet.py").read_text(encoding="utf-8")
    anchor = "    d = pos[:, :2] - cache.anchor_pos"
    assert anchor in src, "need_rebuild anchor moved — update this probe"
    injected = src.replace(
        anchor, "    pos.block_until_ready()\n" + anchor, 1)
    report = run_lint(PKG, rule_filter=["trace-safety"],
                      overrides={"ops/verlet.py": injected})
    hits = [f for f in _open(report, "trace-safety")
            if f.path == "ops/verlet.py"
            and "block_until_ready" in f.message]
    assert hits, "injected host sync was NOT caught — the trace-safety "\
                 "call graph lost the spatial root"


def test_injected_sync_in_phase_root_is_caught():
    """Same probe through the add_phase root family (combat)."""
    src = (PKG / "game" / "combat.py").read_text(encoding="utf-8")
    anchor = "def combat_fold_closure(v, radius: float):"
    assert anchor in src, "combat_fold_closure anchor moved"
    injected = src.replace(
        anchor, anchor + "\n    v.block_until_ready()", 1)
    report = run_lint(PKG, rule_filter=["trace-safety"],
                      overrides={"game/combat.py": injected})
    hits = [f for f in _open(report, "trace-safety")
            if f.path == "game/combat.py"
            and "block_until_ready" in f.message]
    assert hits


# --- CLI ------------------------------------------------------------------

def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "nf_lint.py"), *args],
        capture_output=True, text=True, cwd=cwd, timeout=300)


def test_cli_clean_package_exits_zero_with_json():
    res = _cli("--json")
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["counts"]["open"] == 0


def test_cli_violations_exit_nonzero():
    res = _cli("--root", str(FIX / "bad"), "--json")
    assert res.returncode == 1
    data = json.loads(res.stdout)
    assert data["counts"]["open"] > 0


def test_cli_rule_filter_and_unknown_rule():
    res = _cli("--root", str(FIX / "bad"), "--rule", "struct-codec",
               "--json")
    assert res.returncode == 1
    data = json.loads(res.stdout)
    assert data["rules"] == ["struct-codec"]
    assert {e["rule"] for e in data["findings"]} == {"struct-codec"}

    bad = _cli("--rule", "no-such-rule")
    assert bad.returncode == 2


def test_cli_update_baseline_roundtrip(tmp_path):
    base = tmp_path / "base.json"
    res = _cli("--root", str(FIX / "bad"), "--baseline", str(base),
               "--update-baseline")
    assert res.returncode == 0, res.stdout + res.stderr
    assert base.exists()

    res = _cli("--root", str(FIX / "bad"), "--baseline", str(base),
               "--json")
    assert res.returncode == 0
    data = json.loads(res.stdout)
    assert data["counts"]["open"] == 0
    assert data["counts"]["baselined"] > 0


def test_cli_list_rules_matches_catalog():
    res = _cli("--list-rules")
    assert res.returncode == 0
    listed = [line.split()[0] for line in res.stdout.splitlines() if line]
    assert listed == RULE_NAMES
    assert set(listed) == set(RULES_BY_NAME)
