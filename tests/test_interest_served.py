"""Interest-filtered served path: per-session Position streams replace
group-wide broadcast — each client sees only nearby entities, quantized,
with a >=10x byte cut at density (round-3 verdict item 3)."""

from __future__ import annotations

import numpy as np
import pytest

from noahgameframe_tpu.core.datatypes import next_pow2
from noahgameframe_tpu.game import build_benchmark_world
from noahgameframe_tpu.net.defines import MsgID
from noahgameframe_tpu.net.roles.base import RoleConfig
from noahgameframe_tpu.net.roles.game import GameRole, Session
from noahgameframe_tpu.net.wire import (
    Ident,
    InterestPosSync,
    MsgBase,
    ident_key,
)
from noahgameframe_tpu.ops.interest import QMAX

N, SESSIONS, RADIUS = 4000, 16, 8.0


def make_role(interest_radius):
    world = build_benchmark_world(
        N, combat=False, seed=7,
        player_capacity=next_pow2(SESSIONS + 8, lo=64),
    )
    role = GameRole(
        RoleConfig(6, 0, "IntGame", "127.0.0.1", 0),
        backend="py",
        world=world,
        cross_server_sync=False,
        interest_radius=interest_radius,
    )
    sent = []

    def fake_send(conn_id, msg_id, body):
        sent.append((conn_id, msg_id, body))
        return True

    role.server.send_raw = fake_send
    rng = np.random.default_rng(3)
    ext = world.config.extent
    for i in range(SESSIONS):
        ident = Ident(svrid=99, index=i + 1)
        sess = Session(ident=ident, conn_id=2000 + i, account=f"bot{i}")
        g = role.kernel.create_object(
            "Player", {"Name": f"Bot{i}"}, scene=1, group=0
        )
        role.kernel.set_property(
            g, "Position",
            (float(rng.uniform(0, ext)), float(rng.uniform(0, ext)), 0.0),
        )
        sess.guid = g
        role.sessions[ident_key(ident)] = sess
        role._guid_session[g] = ident_key(ident)
    return role, world, sent


def run_frames(role, world, n_frames=3):
    dt = world.config.dt * 1.0001
    now = 1000.0
    for _ in range(n_frames):
        now += dt
        role.execute(now)
    return now


def test_interest_stream_bytes_vs_broadcast():
    """>=10x fewer sync bytes than the group-broadcast lane on the same
    world/session geometry."""
    role_b, world_b, sent_b = make_role(interest_radius=None)
    run_frames(role_b, world_b)
    bytes_b = sum(len(b) for c, m, b in sent_b
                  if m == int(MsgID.ACK_BATCH_PROPERTY))

    role_i, world_i, sent_i = make_role(interest_radius=RADIUS)
    run_frames(role_i, world_i)
    pos_msgs = [b for c, m, b in sent_i if m == int(MsgID.ACK_INTEREST_POS)]
    bytes_i = sum(len(b) for b in pos_msgs)
    assert pos_msgs, "interest stream produced no messages"
    assert bytes_b > 0
    assert bytes_i * 10 <= bytes_b, (bytes_i, bytes_b)


def test_interest_stream_contents_are_nearby_and_accurate():
    role, world, sent = make_role(interest_radius=RADIUS)
    run_frames(role, world, n_frames=2)
    k = role.kernel
    ext = world.config.extent
    quantum = ext / QMAX
    hosts = [k.store._hosts["NPC"], k.store._hosts["Player"]]
    # map conn -> session avatar position
    conn_pos = {}
    for sess in role.sessions.values():
        conn_pos[sess.conn_id] = np.asarray(
            k.get_property(sess.guid, "Position")
        )
    checked = 0
    for conn_id, msg_id, body in sent:
        if msg_id != int(MsgID.ACK_INTEREST_POS):
            continue
        base = MsgBase.decode(body)
        msg = InterestPosSync.decode(base.msg_data)
        heads = np.frombuffer(msg.svrid, np.int64)
        datas = np.frombuffer(msg.index, np.int64)
        qpos = np.frombuffer(msg.qpos, np.uint16).reshape(-1, 3)
        assert msg.count == len(heads) == len(qpos)
        avatar = conn_pos[conn_id]
        for h, d_, qp in zip(heads.tolist(), datas.tolist(), qpos.tolist()):
            # entity must actually BE near the avatar (within radius +
            # one tick of movement drift) and the dequantized position
            # must match the entity's device position to the quantum
            g = None
            for host in hosts:
                rows = np.flatnonzero((host.guid_head == h)
                                      & (host.guid_data == d_))
                if rows.size:
                    g = host.row_guid[int(rows[0])]
                    break
            assert g is not None
            true_pos = np.asarray(k.get_property(g, "Position"))
            deq = np.asarray(qp, np.float64) * float(msg.scale)
            # quantization error: half a quantum per axis + movement
            # between the synced frame and now
            move_per_tick = 2.0  # bench world speeds are small
            assert np.all(np.abs(deq[:2] - true_pos[:2])
                          <= quantum + 2 * move_per_tick)
            d = true_pos[:2] - avatar[:2]
            assert float(np.hypot(d[0], d[1])) <= RADIUS + 2 * move_per_tick
            checked += 1
    assert checked > 0


def test_far_entities_never_stream():
    """A session parked in an empty corner receives no interest traffic
    for the crowd (the broadcast lane would have sent it everything)."""
    role, world, sent = make_role(interest_radius=RADIUS)
    # move every NPC into the far corner, away from all avatars? cheaper:
    # park ONE extra session far outside every NPC's reach
    ident = Ident(svrid=99, index=777)
    sess = Session(ident=ident, conn_id=7777, account="corner")
    g = role.kernel.create_object("Player", {"Name": "corner"},
                                  scene=1, group=0)
    # beyond the grid: clipped into the border cell; park well inside a
    # corner that the uniform world still populates sparsely -> place at
    # a spot then verify against actual distances below
    role.kernel.set_property(g, "Position", (0.25, 0.25, 0.0))
    sess.guid = g
    role.sessions[ident_key(ident)] = sess
    role._guid_session[g] = ident_key(ident)
    run_frames(role, world, n_frames=2)
    k = role.kernel
    hosts = [k.store._hosts["NPC"], k.store._hosts["Player"]]
    for conn_id, msg_id, body in sent:
        if msg_id != int(MsgID.ACK_INTEREST_POS) or conn_id != 7777:
            continue
        base = MsgBase.decode(body)
        msg = InterestPosSync.decode(base.msg_data)
        heads = np.frombuffer(msg.svrid, np.int64)
        datas = np.frombuffer(msg.index, np.int64)
        for h, d_ in zip(heads.tolist(), datas.tolist()):
            gg = None
            for host in hosts:
                rows = np.flatnonzero((host.guid_head == h)
                                      & (host.guid_data == d_))
                if rows.size:
                    gg = host.row_guid[int(rows[0])]
                    break
            assert gg is not None
            p = np.asarray(k.get_property(gg, "Position"))
            d = float(np.hypot(p[0] - 0.25, p[1] - 0.25))
            assert d <= RADIUS + 4.0  # nearby only, never the far crowd


def _guids_received(sent, conn_id, start=0):
    got = set()
    for c, m, body in sent[start:]:
        if c != conn_id or m != int(MsgID.ACK_INTEREST_POS):
            continue
        msg = InterestPosSync.decode(MsgBase.decode(body).msg_data)
        heads = np.frombuffer(msg.svrid, np.int64)
        datas = np.frombuffer(msg.index, np.int64)
        got |= set(zip(heads.tolist(), datas.tolist()))
    return got


def _gones_received(sent, conn_id, start=0):
    gone = set()
    for c, m, body in sent[start:]:
        if c != conn_id or m != int(MsgID.ACK_INTEREST_POS):
            continue
        msg = InterestPosSync.decode(MsgBase.decode(body).msg_data)
        heads = np.frombuffer(msg.gone_svrid, np.int64)
        datas = np.frombuffer(msg.gone_index, np.int64)
        gone |= set(zip(heads.tolist(), datas.tolist()))
    return gone


def test_enter_view_resends_stationary_entities():
    """An entity that moved while unobserved and then STOPPED must still
    be streamed to an observer who later walks into range — and again on
    re-entry (the reference's OnObjectListEnter resend; round-4 advisor
    medium finding on the global delta gate)."""
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig

    world = GameWorld(WorldConfig(
        npc_capacity=64, player_capacity=64, extent=64.0,
        combat=False, movement=False, regen=False, middleware=False,
    ))
    world.start()
    world.scene.create_scene(1, width=64.0)
    role = GameRole(
        RoleConfig(6, 0, "EnterGame", "127.0.0.1", 0),
        backend="py", world=world, cross_server_sync=False,
        interest_radius=RADIUS,
    )
    sent = []
    role.server.send_raw = lambda c, m, b: (sent.append((c, m, b)), True)[1]
    k = role.kernel

    ident = Ident(svrid=99, index=1)
    sess = Session(ident=ident, conn_id=3001, account="walker")
    av = k.create_object("Player", {"Name": "walker"}, scene=1, group=0)
    k.set_property(av, "Position", (2.0, 2.0, 0.0))
    sess.guid = av
    role.sessions[ident_key(ident)] = sess
    role._guid_session[av] = ident_key(ident)

    npc = k.create_object("NPC", {}, scene=1, group=0)
    k.set_property(npc, "Position", (50.0, 50.0, 0.0))
    host = k.store._hosts["NPC"]
    row = k.store.row_of(npc)[1]
    npc_key = (int(host.guid_head[row]), int(host.guid_data[row]))

    dt, now = world.config.dt * 1.0001, 1000.0

    def frame():
        nonlocal now
        now += dt
        role.execute(now)

    frame()
    assert npc_key not in _guids_received(sent, 3001)

    # npc moves while unobserved, then stops
    k.set_property(npc, "Position", (52.0, 52.0, 0.0))
    frame()
    assert npc_key not in _guids_received(sent, 3001)

    # observer walks next to the (now stationary) npc -> must be streamed
    n0 = len(sent)
    k.set_property(av, "Position", (51.0, 51.0, 0.0))
    frame()
    assert npc_key in _guids_received(sent, 3001, n0)

    # walk away: npc leaves view -> explicit despawn via the gone list
    # (the stream is a delta; without this the client would render the
    # departed entity frozen in place forever)
    n1 = len(sent)
    k.set_property(av, "Position", (2.0, 2.0, 0.0))
    frame()
    assert npc_key not in _guids_received(sent, 3001, n1)
    assert npc_key in _gones_received(sent, 3001, n1)
    # ...then back -> re-entry resends
    n1b = len(sent)
    k.set_property(av, "Position", (51.0, 51.0, 0.0))
    frame()
    assert npc_key in _guids_received(sent, 3001, n1b)

    # stationary both sides -> nothing re-streams (per-session dedup,
    # and the idle gate skips the pipeline entirely)
    n2 = len(sent)
    frame()
    assert npc_key not in _guids_received(sent, 3001, n2)
    assert not any(m == int(MsgID.ACK_INTEREST_POS)
                   for _, m, _ in sent[n2:])

    # death inside view -> gone (create/destroy marks the class dirty)
    n3 = len(sent)
    k.destroy_object(npc)
    frame()
    assert npc_key in _gones_received(sent, 3001, n3)


def test_group_swap_without_movement_updates_visibility():
    """A zone change with NO Position diff (enter_scene/group swap) must
    re-run the interest pipeline: old-group observers get the entity in
    gone, and swapping back makes it visible again."""
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole, Session

    world = GameWorld(WorldConfig(
        npc_capacity=64, player_capacity=64, extent=64.0,
        combat=False, movement=False, regen=False, middleware=False,
    ))
    world.start()
    world.scene.create_scene(1, width=64.0)
    role = GameRole(
        RoleConfig(6, 0, "ZoneGame", "127.0.0.1", 0),
        backend="py", world=world, cross_server_sync=False,
        interest_radius=RADIUS,
    )
    sent = []
    role.server.send_raw = lambda c, m, b: (sent.append((c, m, b)), True)[1]
    k = role.kernel

    ident = Ident(svrid=99, index=1)
    sess = Session(ident=ident, conn_id=4001, account="zone")
    av = k.create_object("Player", {"Name": "zone"}, scene=1, group=0)
    k.set_property(av, "Position", (10.0, 10.0, 0.0))
    sess.guid = av
    role.sessions[ident_key(ident)] = sess
    role._guid_session[av] = ident_key(ident)

    npc = k.create_object("NPC", {}, scene=1, group=0)  # 0 = scene-wide
    k.set_property(npc, "Position", (12.0, 12.0, 0.0))
    host = k.store._hosts["NPC"]
    row = k.store.row_of(npc)[1]
    npc_key = (int(host.guid_head[row]), int(host.guid_data[row]))

    dt, now = world.config.dt * 1.0001, 1000.0

    def frame():
        nonlocal now
        now += dt
        role.execute(now)

    frame()
    assert npc_key in _guids_received(sent, 4001)

    # stationary npc swaps to a group the observer is not in
    n0 = len(sent)
    k.set_property(npc, "GroupID", 7)
    frame()
    assert npc_key in _gones_received(sent, 4001, n0)

    # ...and back: visible again, with no Position change anywhere
    n1 = len(sent)
    k.set_property(npc, "GroupID", 0)
    frame()
    assert npc_key in _guids_received(sent, 4001, n1)


def test_property_and_record_sync_respect_interest():
    """VERDICT r4 item 4: with a radius set, PUBLIC per-entity property
    diffs and record diffs reach only sessions whose avatars can see the
    entity (plus the owner) — with brute-force distance parity — and the
    bytes shrink vs the broadcast lane."""
    role_b, world_b, sent_b = make_role(interest_radius=None)
    role_i, world_i, sent_i = make_role(interest_radius=RADIUS)

    prop_ids = (int(MsgID.ACK_PROPERTY_INT),)

    def poke(role, world, sent):
        run_frames(role, world, n_frames=1)
        k = role.kernel
        host = k.store._hosts["NPC"]
        rows = np.flatnonzero(host.alloc_mask)[:5]
        n0 = len(sent)
        for r in rows:
            g = host.row_guid[int(r)]
            k.set_property(g, "HP", 55)  # public int, small diff
        now = run_frames(role, world, n_frames=1)
        return rows, n0

    rows_b, n_b = poke(role_b, world_b, sent_b)
    rows_i, n_i = poke(role_i, world_i, sent_i)

    bytes_b = sum(len(b) for c, m, b in sent_b[n_b:] if m in prop_ids)
    bytes_i = sum(len(b) for c, m, b in sent_i[n_i:] if m in prop_ids)
    assert bytes_b > 0
    assert bytes_i < bytes_b  # interest scope strictly cheaper

    # brute-force parity: every session that RECEIVED npc row r's HP is
    # within radius of it (+slack for the one frame of drift)
    k = role_i.kernel
    host = k.store._hosts["NPC"]
    spec = k.store.spec("NPC")
    cs = k.state.classes["NPC"]
    pos_np = np.asarray(cs.vec[:, spec.slots["Position"].col, :2])
    conn_avatar = {}
    for sess in role_i.sessions.values():
        if sess.guid is not None:
            conn_avatar[sess.conn_id] = np.asarray(
                k.get_property(sess.guid, "Position"))[:2]
    from noahgameframe_tpu.net.wire import ObjectPropertyInt

    for c, m, body in sent_i[n_i:]:
        if m not in prop_ids:
            continue
        base = MsgBase.decode(body)
        msg = ObjectPropertyInt.decode(base.msg_data)
        subject = msg.player_id
        r = np.flatnonzero((host.guid_head == subject.svrid)
                           & (host.guid_data == subject.index))
        if r.size == 0:
            continue  # a Player subject (owner lane) — skip
        p = pos_np[int(r[0])]
        av = conn_avatar.get(c)
        assert av is not None
        assert float(np.hypot(*(p - av))) <= RADIUS + 6.0, (
            "session received a property diff for an entity out of range")
