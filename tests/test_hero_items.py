"""Hero parity depth (stars, skills/talents, line-up, summons) and the
full item consume-process family (VERDICT r4 missing #3/#4).

Reference: NFCHeroModule.cpp (443 LoC) and the
NFC*ConsumeProcessModule family in NFServer/NFGameLogicPlugin/."""

from __future__ import annotations

import pytest

from noahgameframe_tpu.game import (
    GameWorld,
    ItemSubType,
    ItemType,
    PropertyGroup,
    WorldConfig,
)
from noahgameframe_tpu.game.hero import FIGHT_RECORD, HERO_RECORD


@pytest.fixture()
def world():
    w = GameWorld(WorldConfig(combat=False, movement=False, regen=False,
                              npc_capacity=64, player_capacity=8)).start()
    w.scene.create_scene(1)
    return w


@pytest.fixture()
def player(world):
    g = world.kernel.create_object("Player", {"Name": "H", "Account": "h"},
                                   scene=1, group=0)
    world.kernel.set_property(g, "Level", 10)
    return g


def define_heroes(world):
    e = world.kernel.elements
    e.add_element("Item", "hero_mage", {
        "ItemType": int(ItemType.CARD),
        "ATK_VALUE": 4, "MAXHP": 10,
        "Skill1": "fireball_1", "Talent1": "wisdom_1"})
    e.add_element("Skill", "fireball_1", {"AfterUpID": "fireball_2",
                                          "DamageValue": 10})
    e.add_element("Skill", "fireball_2", {"DamageValue": 20})
    e.add_element("Talent", "wisdom_1", {"AfterUpID": "wisdom_2"})
    e.add_element("Talent", "wisdom_2", {})


# -------------------------------------------------------------- hero depth


def test_star_up_caps(world, player):
    define_heroes(world)
    h = world.heroes
    h.max_star = 3
    row = h.add_hero(player, "hero_mage")
    assert h.hero_star(player, row) == 1
    assert h.hero_star_up(player, row)
    assert h.hero_star(player, row) == 2
    h.hero_star_up(player, row)
    h.hero_star_up(player, row)
    h.hero_star_up(player, row)
    assert h.hero_star(player, row) == 3  # capped
    assert not h.hero_star_up(player, 9)  # no such hero


def test_duplicate_card_stacks_a_star(world, player):
    define_heroes(world)
    h = world.heroes
    row = h.add_hero(player, "hero_mage")
    assert h.add_hero(player, "hero_mage") == row
    assert h.hero_star(player, row) == 2  # dup add -> star, not a 2nd row


def test_skill_and_talent_chains(world, player):
    """Skill/talent slots init from the hero config; upgrades walk the
    element AfterUpID chain and stop at the end (HeroSkillUp)."""
    define_heroes(world)
    h = world.heroes
    k = world.kernel
    row = h.add_hero(player, "hero_mage")
    assert str(k.store.record_get(k.state, player, HERO_RECORD, row,
                                  "Skill1")) == "fireball_1"
    assert h.hero_skill_up(player, row, 1)
    assert str(k.store.record_get(k.state, player, HERO_RECORD, row,
                                  "Skill1")) == "fireball_2"
    assert not h.hero_skill_up(player, row, 1)  # chain end
    assert not h.hero_skill_up(player, row, 2)  # empty slot
    assert not h.hero_skill_up(player, row, 9)  # bad index
    assert h.hero_talent_up(player, row, 1)
    assert str(k.store.record_get(k.state, player, HERO_RECORD, row,
                                  "Talent1")) == "wisdom_2"


def test_wear_skill_must_be_owned(world, player):
    define_heroes(world)
    h = world.heroes
    k = world.kernel
    row = h.add_hero(player, "hero_mage")
    assert not h.hero_wear_skill(player, row, "frostbolt")  # not owned
    assert h.hero_wear_skill(player, row, "fireball_1")
    assert str(k.store.record_get(k.state, player, HERO_RECORD, row,
                                  "FightSkill")) == "fireball_1"


def test_fight_lineup_positions_sum_stats(world, player):
    """Multiple battle positions: the FIGHTING_HERO fold sums every
    positioned hero's config stats x level (PlayerFightHero record)."""
    define_heroes(world)
    e = world.kernel.elements
    e.add_element("Item", "hero_tank", {"ItemType": int(ItemType.CARD),
                                        "ATK_VALUE": 1, "MAXHP": 50})
    h = world.heroes
    r1 = h.add_hero(player, "hero_mage")
    r2 = h.add_hero(player, "hero_tank")
    assert h.set_fight_hero(player, r1, pos=0)
    assert h.set_fight_hero(player, r2, pos=1)
    assert h.fight_hero(player, 0) == r1
    assert h.fight_hero(player, 1) == r2
    got = world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.FIGHTING_HERO)
    assert got == 4 + 1  # both level 1
    # leveling a positioned hero refreshes the fold
    h.add_hero_exp(player, r1, 200)  # level 1 -> 2
    got = world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.FIGHTING_HERO)
    assert got == 4 * 2 + 1
    # re-placing a position overwrites it
    assert h.set_fight_hero(player, r2, pos=0)
    assert h.fight_hero(player, 0) == r2
    assert not h.set_fight_hero(player, r1, pos=99)  # beyond the record


def test_summon_only_in_clone_scene(world, player):
    """CreateHero spawns the hero as an NPC (owner's camp, MasterID) in
    CLONE scenes only (NFCHeroModule.cpp:295-337)."""
    define_heroes(world)
    e = world.kernel.elements
    e.add_element("Scene", "2", {"SceneType": 1})  # clone scene config
    h = world.heroes
    k = world.kernel
    row = h.add_hero(player, "hero_mage")
    # scene 1 is a NORMAL scene: refuse
    assert h.create_hero(player, row) is None
    # move into the clone scene
    world.scene_process.enter(player, 2)
    npc = h.create_hero(player, row)
    assert npc is not None
    assert k.get_property(npc, "MasterID") == player
    assert str(k.get_property(npc, "ConfigID")) == "hero_mage"
    assert h.create_hero(player, row) is None  # already summoned
    assert h.destroy_hero(player, row)
    assert npc not in k.store.guid_map
    assert not h.destroy_hero(player, row)  # idempotent


def test_fight_hero_wire_handler(world):
    from noahgameframe_tpu.net.defines import MsgID
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole, Session
    from noahgameframe_tpu.net.transport import EV_MSG, NetEvent
    from noahgameframe_tpu.net.wire import (
        Ident,
        ReqSetFightHero,
        ident_key,
        wrap,
    )

    role = GameRole(
        RoleConfig(6, 0, "HeroGame", "127.0.0.1", 0),
        backend="py", world=world, cross_server_sync=False,
    )
    define_heroes(world)
    role.server.send_raw = lambda c, m, b: True
    k = role.kernel
    ident = Ident(svrid=9, index=5)
    sess = Session(ident=ident, conn_id=11, account="hh")
    g = k.create_object("Player", {"Name": "W"}, scene=1, group=0)
    sess.guid = g
    role.sessions[ident_key(ident)] = sess
    role._guid_session[g] = ident_key(ident)
    row = world.heroes.add_hero(g, "hero_mage")

    msg = ReqSetFightHero(heroid=Ident(svrid=0, index=row), fight_pos=1)
    role.server.dispatch.feed([
        NetEvent(EV_MSG, 11, int(MsgID.REQ_SET_FIGHT_HERO),
                 wrap(msg, player_id=ident))
    ])
    assert world.heroes.fight_hero(g, 1) == row


# ------------------------------------------------------- consume families


def test_equip_item_materializes_equip(world, player):
    e = world.kernel.elements
    e.add_element("Item", "sword_tok", {"ItemType": int(ItemType.EQUIP),
                                        "ATK_VALUE": 7})
    world.pack.create_item(player, "sword_tok", 1)
    assert world.items.use_item(player, "sword_tok")
    assert world.pack.item_count(player, "sword_tok") == 0
    assert list(world.pack.equips(player).values()) == ["sword_tok"]


def test_gem_socket_folds_stats_while_worn(world, player):
    e = world.kernel.elements
    e.add_element("Item", "sword_g", {"ItemType": int(ItemType.EQUIP),
                                      "ATK_VALUE": 7})
    e.add_element("Item", "ruby", {"ItemType": int(ItemType.GEM),
                                   "ATK_VALUE": 3})
    world.pack.create_item(player, "ruby", 2)
    row = world.pack.create_equip(player, "sword_g")
    # gem needs a target equip row
    assert not world.items.use_item(player, "ruby")
    assert world.items.use_item(player, "ruby", target=row)
    assert world.items.gems_of(player, row) == ["ruby"]
    # not worn yet: no stat contribution
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 0
    world.equip.wear(player, row)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 10  # 7 + 3
    # second gem stacks
    assert world.items.use_item(player, "ruby", target=row)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 13


def test_card_item_adds_hero_and_dup_stars(world, player):
    define_heroes(world)
    world.pack.create_item(player, "hero_mage", 2)
    assert world.items.use_item(player, "hero_mage")
    row = world.heroes.hero_row_of(player, "hero_mage")
    assert row is not None
    assert world.items.use_item(player, "hero_mage")  # dup card
    assert world.heroes.hero_star(player, row) == 2


def test_exp_item_targets_player_or_hero(world, player):
    define_heroes(world)
    e = world.kernel.elements
    e.add_element("Item", "tome", {"ItemType": int(ItemType.ITEM),
                                   "ItemSubType": int(ItemSubType.EXP),
                                   "AwardValue": 250})
    world.pack.create_item(player, "tome", 2)
    hero_row = world.heroes.add_hero(player, "hero_mage")
    # hero-targeted: 250 exp -> level 2 (200 spent, 50 left)
    assert world.items.use_item(player, "tome", target=hero_row)
    assert world.heroes.hero_level(player, hero_row) == 2
    # untargeted: player exp through the level module
    exp0 = int(world.kernel.get_property(player, "EXP"))
    assert world.items.use_item(player, "tome")
    assert int(world.kernel.get_property(player, "EXP")) != exp0 or \
        int(world.kernel.get_property(player, "Level")) > 10


def test_hp_water_revives_dead_player(world, player):
    """Reborn semantics: an HP water at 0 HP revives
    (NFCRebornItemConsumeProcessModule's intent)."""
    e = world.kernel.elements
    e.add_element("Item", "elixir", {"ItemType": int(ItemType.ITEM),
                                     "ItemSubType": int(ItemSubType.HP),
                                     "AwardValue": 40})
    k = world.kernel
    world.properties.set_group_value(player, "MAXHP",
                                     PropertyGroup.EFFECTVALUE, 100)
    k.set_property(player, "HP", 0)  # dead
    world.pack.create_item(player, "elixir", 1)
    assert world.items.use_item(player, "elixir")
    assert int(k.get_property(player, "HP")) == 40


def test_recycled_equip_row_does_not_inherit_gems(world, player):
    """Sockets live IN the record row, so deleting an equip and creating
    a new one on the recycled row must start gem-free (confirmed-repro
    finding from review: a host-side gem dict leaked across rows)."""
    e = world.kernel.elements
    e.add_element("Item", "axe", {"ItemType": int(ItemType.EQUIP),
                                  "ATK_VALUE": 7})
    e.add_element("Item", "shield", {"ItemType": int(ItemType.EQUIP),
                                     "ATK_VALUE": 1})
    e.add_element("Item", "ruby2", {"ItemType": int(ItemType.GEM),
                                    "ATK_VALUE": 3})
    world.pack.create_item(player, "ruby2", 2)
    row = world.pack.create_equip(player, "axe")
    assert world.items.use_item(player, "ruby2", target=row)
    assert world.items.use_item(player, "ruby2", target=row)
    world.pack.delete_equip(player, row)
    row2 = world.pack.create_equip(player, "shield")
    assert row2 == row  # store recycles the freed slot
    assert world.items.gems_of(player, row2) == []
    world.equip.wear(player, row2)
    assert world.properties.get_group_value(
        player, "ATK_VALUE", PropertyGroup.EQUIP) == 1  # shield only


def test_gems_survive_relog(world):
    """InlayInfo persists with the record through the data-agent path."""
    from noahgameframe_tpu.persist.agent import PlayerDataAgent
    from noahgameframe_tpu.persist.kv import MemoryKV

    agent = PlayerDataAgent(MemoryKV()).bind(world.kernel)
    k = world.kernel
    g = k.create_object("Player", {"Name": "G", "Account": "g"},
                        scene=1, group=0)
    e = world.kernel.elements
    e.add_element("Item", "blade2", {"ItemType": int(ItemType.EQUIP),
                                     "ATK_VALUE": 5})
    e.add_element("Item", "onyx", {"ItemType": int(ItemType.GEM),
                                   "ATK_VALUE": 2})
    world.pack.create_item(g, "onyx", 1)
    row = world.pack.create_equip(g, "blade2")
    assert world.items.use_item(g, "onyx", target=row)
    world.equip.wear(g, row)
    agent.save(g)
    k.destroy_object(g)
    g2 = k.create_object("Player", {"Name": "G", "Account": "g"},
                         scene=1, group=0)
    assert world.items.gems_of(g2, row) == ["onyx"]
    world.equip.refresh(g2)
    assert world.properties.get_group_value(
        g2, "ATK_VALUE", PropertyGroup.EQUIP) == 7


def test_resummon_after_external_destroy(world, player):
    """A summon killed from outside destroy_hero (clone release, combat
    death) must not block re-summoning."""
    define_heroes(world)
    e = world.kernel.elements
    e.add_element("Scene", "3", {"SceneType": 1})
    h = world.heroes
    k = world.kernel
    row = h.add_hero(player, "hero_mage")
    world.scene_process.enter(player, 3)
    npc = h.create_hero(player, row)
    assert npc is not None
    k.destroy_object(npc)  # external death
    npc2 = h.create_hero(player, row)
    assert npc2 is not None and npc2 != npc
