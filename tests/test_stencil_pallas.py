"""Pallas combat-fold kernel vs the XLA stencil fold: bit-identical
results (interpret mode on CPU), including tie-breaks and edge cells."""

import numpy as np
import pytest

from noahgameframe_tpu.game import GameWorld, WorldConfig
from noahgameframe_tpu.game.defines import PropertyGroup


def build(n, seed, use_pallas, attack_period_s=1.0 / 30.0):
    rng = np.random.RandomState(seed)
    extent = 40.0
    w = GameWorld(
        WorldConfig(
            npc_capacity=256, extent=extent, aoe_radius=5.0,
            attack_period_s=attack_period_s, movement=True, regen=False,
            middleware=False, seed=7,
        )
    )
    w.combat.use_pallas = use_pallas
    w.start()
    w.scene.create_scene(1, width=extent)
    k = w.kernel
    pos = rng.uniform(0, extent, (n, 2)).astype(np.float32)
    camps = rng.randint(0, 2, n)
    atks = rng.randint(0, 30, n)
    for i in range(n):
        g = k.create_object(
            "NPC",
            {"Position": (float(pos[i, 0]), float(pos[i, 1]), 0.0),
             "Camp": int(camps[i]), "HP": 500},
            scene=1,
        )
        w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.EFFECTVALUE, int(atks[i]))
        w.properties.set_group_value(g, "DEF_VALUE", PropertyGroup.EFFECTVALUE, 2)
        w.properties.set_group_value(g, "MAXHP", PropertyGroup.EFFECTVALUE, 500)
        w.properties.set_group_value(g, "MOVE_SPEED", PropertyGroup.EFFECTVALUE, 30000)
    w.combat.arm_all()
    return w


@pytest.mark.parametrize("seed", [3, 11])
def test_pallas_fold_matches_xla_fold(seed):
    a = build(120, seed, use_pallas=False)
    b = build(120, seed, use_pallas=True)
    for _ in range(6):
        a.tick()
        b.tick()
    ia = np.asarray(a.kernel.state.classes["NPC"].i32)
    ib = np.asarray(b.kernel.state.classes["NPC"].i32)
    np.testing.assert_array_equal(ia, ib)  # HP AND LastAttacker identical
    va = np.asarray(a.kernel.state.classes["NPC"].vec)
    vb = np.asarray(b.kernel.state.classes["NPC"].vec)
    np.testing.assert_array_equal(va, vb)


def test_pallas_fold_matches_xla_fold_asymmetric_buckets():
    """Staggered arming makes the attacker bucket SMALLER than the victim
    bucket (Ka < Kv) — the [Kv, Ka] pairwise broadcasts and tie-break
    reductions must stay bit-identical in that regime, not just at
    Ka == Kv."""
    a = build(150, 23, use_pallas=False, attack_period_s=0.2)
    b = build(150, 23, use_pallas=True, attack_period_s=0.2)
    cap = a.kernel.state.classes["NPC"].alive.shape[0]
    ka = a.combat.resolved_att_bucket(cap)
    kv = a.combat.resolved_bucket(cap)
    assert ka < kv, (ka, kv)
    for _ in range(8):  # > one full 6-tick period: every phase fires
        a.tick()
        b.tick()
    np.testing.assert_array_equal(
        np.asarray(a.kernel.state.classes["NPC"].i32),
        np.asarray(b.kernel.state.classes["NPC"].i32),
    )


# ------------------------------------------------ fused engine (NF_PALLAS=2)


def _combat_arrays(n, seed, width=6, cell_size=5.0, clump=None):
    """Random combat-shaped population; clump=(x0, x1) squeezes every
    position into that interval on both axes (siege shapes)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    extent = width * cell_size
    lo, hi = clump if clump is not None else (0.0, extent)
    pos = rng.uniform(lo, hi, (n, 2)).astype(np.float32)
    active = rng.rand(n) < 0.9
    attacking = (rng.rand(n) < 0.5) & active
    atk = rng.randint(0, 30, n).astype(np.float32)
    camp = rng.randint(1, 3, n).astype(np.float32)
    scene = rng.randint(1, 3, n).astype(np.float32)
    group = rng.randint(0, 2, n).astype(np.float32)
    eff = np.where(attacking, atk, 0.0).astype(np.float32)
    rows = np.arange(n, dtype=np.float32)
    vic_feats = jnp.asarray(
        np.stack([pos[:, 0], pos[:, 1], camp, scene, group], -1)
    )
    att_feats = jnp.asarray(
        np.stack([pos[:, 0], pos[:, 1], eff, camp, scene, group, rows], -1)
    )
    bank = jnp.asarray(
        np.stack([pos[:, 0], pos[:, 1], camp, scene, group, eff], -1)
    )
    return (
        jnp.asarray(pos), jnp.asarray(active), jnp.asarray(attacking),
        vic_feats, att_feats, bank,
    )


def _fused_vs_split(n, seed, bucket, sub_bucket, width=6, cell_size=5.0,
                    clump=None, radius=5.0):
    """Run both engines in interpret mode on CPU over the same random
    population and return everything a parity assert needs."""
    from noahgameframe_tpu.ops.stencil import (
        build_cell_slots_pair,
        build_cell_table_pair,
    )
    from noahgameframe_tpu.ops.stencil_pallas import (
        combat_fold_pallas,
        fused_neighborhood,
    )

    pos, active, attacking, vic_feats, att_feats, bank = _combat_arrays(
        n, seed, width, cell_size, clump
    )
    vt, at = build_cell_table_pair(
        pos, active, vic_feats, attacking, att_feats,
        cell_size, width, bucket, sub_bucket,
    )
    inc0, bestr0 = combat_fold_pallas(vt, at, radius, interpret=True)
    vs, ats = build_cell_slots_pair(
        pos, active, attacking, cell_size, width, bucket, sub_bucket
    )
    inc1, bestr1, nbr1 = fused_neighborhood(
        bank, vs, ats, radius, interpret=True
    )
    return (vt, at, inc0, bestr0), (vs, ats, inc1, bestr1, nbr1)


@pytest.mark.parametrize("binning", ["sort", "count"])
@pytest.mark.parametrize("seed", [3, 11])
def test_fused_interpret_parity(monkeypatch, binning, seed):
    """fused_neighborhood (interpret mode, CPU) is bit-identical to
    combat_fold_pallas over the split tables — same slot assignment,
    same stencil order, same tie-breaks — under both binning engines."""
    monkeypatch.setenv("NF_BINNING", binning)
    split, fused = _fused_vs_split(300, seed, bucket=16, sub_bucket=12)
    vt, at, inc0, bestr0 = split
    vs, ats, inc1, bestr1, _nbr = fused
    np.testing.assert_array_equal(np.asarray(vt.slot_of), np.asarray(vs.slot_of))
    np.testing.assert_array_equal(np.asarray(at.slot_of), np.asarray(ats.slot_of))
    np.testing.assert_array_equal(np.asarray(inc0), np.asarray(inc1))
    np.testing.assert_array_equal(np.asarray(bestr0), np.asarray(bestr1))


@pytest.mark.parametrize("binning", ["sort", "count"])
def test_fused_aoi_count_matches_brute_force(monkeypatch, binning):
    """The fused kernel's AOI occupancy plane equals a brute-force
    per-victim neighbor count (interest scope, self excluded) over the
    entities the table actually placed."""
    import jax.numpy as jnp

    from noahgameframe_tpu.ops.stencil import pull_slots

    monkeypatch.setenv("NF_BINNING", binning)
    width, cell_size, radius = 6, 5.0, 5.0
    n = 300
    pos, active, attacking, vic_feats, _af, bank = _combat_arrays(n, 7)
    _split, fused = _fused_vs_split(n, 7, bucket=16, sub_bucket=12)
    vs = fused[0]
    nbr = fused[4]
    nbr_rows = np.asarray(pull_slots(vs.slot_of, nbr, fill=0))
    posn = np.asarray(pos)
    scene = np.asarray(vic_feats[:, 3])
    group = np.asarray(vic_feats[:, 4])
    placed = np.asarray(vs.slot_of) < width * width * 16
    for i in np.flatnonzero(placed):
        d2 = ((posn[placed] - posn[i]) ** 2).sum(-1)
        scoped = (scene[placed] == scene[i]) & (
            (group[placed] == 0) | (group[placed] == group[i])
        )
        rows = np.arange(n)[placed]
        want = int(((d2 <= radius * radius) & scoped & (rows != i)).sum())
        assert nbr_rows[i] == want, i


@pytest.mark.parametrize("binning", ["sort", "count"])
def test_fused_siege_one_cell(monkeypatch, binning):
    """Degenerate occupancy: the whole population inside ONE cell, far
    over bucket capacity — overflow drops and fold results must match
    the split engine exactly (ROADMAP item 5b's siege shape)."""
    monkeypatch.setenv("NF_BINNING", binning)
    split, fused = _fused_vs_split(
        200, 13, bucket=8, sub_bucket=8, clump=(0.5, 4.5)
    )
    vt, at, inc0, bestr0 = split
    vs, ats, inc1, bestr1, _nbr = fused
    assert int(vs.dropped) == int(vt.dropped) > 0
    assert int(ats.dropped) == int(at.dropped)
    np.testing.assert_array_equal(np.asarray(vt.slot_of), np.asarray(vs.slot_of))
    np.testing.assert_array_equal(np.asarray(inc0), np.asarray(inc1))
    np.testing.assert_array_equal(np.asarray(bestr0), np.asarray(bestr1))


@pytest.mark.parametrize("binning", ["sort", "count"])
def test_fused_overflow_drop_parity(monkeypatch, binning):
    """Moderate overflow (small buckets, random spread): which rows drop
    is part of the engine contract — the fused path must inherit the
    split path's drops bit-for-bit, not just approximately."""
    monkeypatch.setenv("NF_BINNING", binning)
    split, fused = _fused_vs_split(400, 17, bucket=4, sub_bucket=4)
    vt, at, inc0, bestr0 = split
    vs, ats, inc1, bestr1, _nbr = fused
    assert int(vt.dropped) > 0
    assert int(vs.dropped) == int(vt.dropped)
    assert int(ats.dropped) == int(at.dropped)
    np.testing.assert_array_equal(np.asarray(inc0), np.asarray(inc1))
    np.testing.assert_array_equal(np.asarray(bestr0), np.asarray(bestr1))


def _digest_stream(use_pallas, ticks, n=200, seed=3):
    w = build(n, seed, use_pallas=use_pallas)
    k = w.kernel
    k.enable_digest()
    out = []
    for _ in range(ticks):
        k.tick()
        out.append(int(k.last_counters["state_digest"]) & 0xFFFFFFFF)
    return out


def _digest_after(use_pallas, ticks, n=200, seed=3):
    w = build(n, seed, use_pallas=use_pallas)
    k = w.kernel
    k.enable_digest()
    k.run_device(ticks)
    k.tick()
    return int(k.last_counters["state_digest"]) & 0xFFFFFFFF


def test_engine_digest_parity_24():
    """24 churn ticks: the world ends in the EXACT same state under all
    three engines (0 = XLA fold, 1 = Pallas fold, 2 = fused table-free)."""
    d0 = _digest_after(0, 24)
    d1 = _digest_after(1, 24)
    d2 = _digest_after(2, 24)
    assert d0 == d1 == d2


@pytest.mark.slow
def test_engine_digest_parity_120():
    d0 = _digest_after(0, 120)
    d1 = _digest_after(1, 120)
    d2 = _digest_after(2, 120)
    assert d0 == d1 == d2


def test_fused_replay_digest_stream_clean():
    """Per-tick digest STREAMS (not just the end state) are identical
    with the engine knob flipped — a replay of the same seed under
    NF_PALLAS=2 stays digest-clean at every tick."""
    assert _digest_stream(0, 12) == _digest_stream(2, 12)


def test_fused_vmem_fallback(monkeypatch):
    """A VMEM budget the tile can't fit downgrades engine 2 to the
    split path at trace time — same results, fallback metric bumped,
    no failure."""
    from noahgameframe_tpu.ops import stencil_pallas as sp

    ref = _digest_after(0, 12)
    monkeypatch.setenv("NF_PALLAS_VMEM_MB", "0.01")
    before = sp.fused_fallback_total()
    got = _digest_after(2, 12)
    assert got == ref
    assert sp.fused_fallback_total() > before
    fits, need, budget = sp.fused_fits_vmem(256, 8, 12, 12)
    assert not fits and need > budget


def test_fused_vmem_estimate_sane():
    """The host-side footprint model: a 20k world fits the default
    budget, a 1M-entity bank alone does not (the documented fallback
    regime for the unsharded big bench)."""
    from noahgameframe_tpu.ops.stencil_pallas import fused_fits_vmem

    fits_small, need_small, _ = fused_fits_vmem(20_000, 32, 36, 36)
    assert fits_small, need_small
    fits_big, need_big, _ = fused_fits_vmem(1_000_000, 395, 12, 6)
    assert not fits_big and need_big > need_small


def test_fused_soak_unexplained_clean():
    """Flipping the engine mid-run is a SANCTIONED retrace: the flip
    rides kernel.invalidate()'s generation bump, so the CostBook soak
    gate stays empty over the fused window."""
    w = build(150, 5, use_pallas=0)
    k = w.kernel
    k.enable_digest()
    k.run_device(6)
    mark = k.costbook.mark()
    w.combat.use_pallas = 2
    k.invalidate()  # engine choice is baked into the trace
    k.run_device(12)
    k.tick()
    assert k.costbook.unexplained_since(mark) == []


def test_resolved_engine_validation(monkeypatch):
    """Tri-state parsing: bools keep their historical meaning, unknown
    env values raise instead of silently running the default."""
    w = build(8, 1, use_pallas=None)
    c = w.combat
    for env, want in (("", 0), ("0", 0), ("1", 1), ("2", 2)):
        monkeypatch.setenv("NF_PALLAS", env)
        assert c.resolved_engine() == want
    monkeypatch.delenv("NF_PALLAS")
    assert c.resolved_engine() == 0
    monkeypatch.setenv("NF_PALLAS", "fused")
    with pytest.raises(ValueError):
        c.resolved_engine()
    monkeypatch.delenv("NF_PALLAS")
    c.use_pallas = True
    assert c.resolved_engine() == 1
    c.use_pallas = False
    assert c.resolved_engine() == 0
    c.use_pallas = 3
    with pytest.raises(ValueError):
        c.resolved_engine()


def test_pallas_fold_lane_aligned_matches(monkeypatch):
    """NF_PALLAS_ALIGN pads the lane (W) axis with zero-occupancy ghost
    cells for TPU lane alignment — results must stay bit-identical to
    the unpadded kernel (grid width 37 -> padded 128)."""
    monkeypatch.setenv("NF_PALLAS_ALIGN", "128")
    a = build(200, 31, use_pallas=False)
    b = build(200, 31, use_pallas=True)
    assert b.combat.width % 128 != 0  # the pad actually engages
    for _ in range(6):
        a.tick()
        b.tick()
    np.testing.assert_array_equal(
        np.asarray(a.kernel.state.classes["NPC"].i32),
        np.asarray(b.kernel.state.classes["NPC"].i32),
    )
