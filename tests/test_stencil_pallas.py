"""Pallas combat-fold kernel vs the XLA stencil fold: bit-identical
results (interpret mode on CPU), including tie-breaks and edge cells."""

import numpy as np
import pytest

from noahgameframe_tpu.game import GameWorld, WorldConfig
from noahgameframe_tpu.game.defines import PropertyGroup


def build(n, seed, use_pallas, attack_period_s=1.0 / 30.0):
    rng = np.random.RandomState(seed)
    extent = 40.0
    w = GameWorld(
        WorldConfig(
            npc_capacity=256, extent=extent, aoe_radius=5.0,
            attack_period_s=attack_period_s, movement=True, regen=False,
            middleware=False, seed=7,
        )
    )
    w.combat.use_pallas = use_pallas
    w.start()
    w.scene.create_scene(1, width=extent)
    k = w.kernel
    pos = rng.uniform(0, extent, (n, 2)).astype(np.float32)
    camps = rng.randint(0, 2, n)
    atks = rng.randint(0, 30, n)
    for i in range(n):
        g = k.create_object(
            "NPC",
            {"Position": (float(pos[i, 0]), float(pos[i, 1]), 0.0),
             "Camp": int(camps[i]), "HP": 500},
            scene=1,
        )
        w.properties.set_group_value(g, "ATK_VALUE", PropertyGroup.EFFECTVALUE, int(atks[i]))
        w.properties.set_group_value(g, "DEF_VALUE", PropertyGroup.EFFECTVALUE, 2)
        w.properties.set_group_value(g, "MAXHP", PropertyGroup.EFFECTVALUE, 500)
        w.properties.set_group_value(g, "MOVE_SPEED", PropertyGroup.EFFECTVALUE, 30000)
    w.combat.arm_all()
    return w


@pytest.mark.parametrize("seed", [3, 11])
def test_pallas_fold_matches_xla_fold(seed):
    a = build(120, seed, use_pallas=False)
    b = build(120, seed, use_pallas=True)
    for _ in range(6):
        a.tick()
        b.tick()
    ia = np.asarray(a.kernel.state.classes["NPC"].i32)
    ib = np.asarray(b.kernel.state.classes["NPC"].i32)
    np.testing.assert_array_equal(ia, ib)  # HP AND LastAttacker identical
    va = np.asarray(a.kernel.state.classes["NPC"].vec)
    vb = np.asarray(b.kernel.state.classes["NPC"].vec)
    np.testing.assert_array_equal(va, vb)


def test_pallas_fold_matches_xla_fold_asymmetric_buckets():
    """Staggered arming makes the attacker bucket SMALLER than the victim
    bucket (Ka < Kv) — the [Kv, Ka] pairwise broadcasts and tie-break
    reductions must stay bit-identical in that regime, not just at
    Ka == Kv."""
    a = build(150, 23, use_pallas=False, attack_period_s=0.2)
    b = build(150, 23, use_pallas=True, attack_period_s=0.2)
    cap = a.kernel.state.classes["NPC"].alive.shape[0]
    ka = a.combat.resolved_att_bucket(cap)
    kv = a.combat.resolved_bucket(cap)
    assert ka < kv, (ka, kv)
    for _ in range(8):  # > one full 6-tick period: every phase fires
        a.tick()
        b.tick()
    np.testing.assert_array_equal(
        np.asarray(a.kernel.state.classes["NPC"].i32),
        np.asarray(b.kernel.state.classes["NPC"].i32),
    )


def test_pallas_fold_lane_aligned_matches(monkeypatch):
    """NF_PALLAS_ALIGN pads the lane (W) axis with zero-occupancy ghost
    cells for TPU lane alignment — results must stay bit-identical to
    the unpadded kernel (grid width 37 -> padded 128)."""
    monkeypatch.setenv("NF_PALLAS_ALIGN", "128")
    a = build(200, 31, use_pallas=False)
    b = build(200, 31, use_pallas=True)
    assert b.combat.width % 128 != 0  # the pad actually engages
    for _ in range(6):
        a.tick()
        b.tick()
    np.testing.assert_array_equal(
        np.asarray(a.kernel.state.classes["NPC"].i32),
        np.asarray(b.kernel.state.classes["NPC"].i32),
    )
