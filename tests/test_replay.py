"""Deterministic flight recorder (ISSUE 4).

Covers the replay stack bottom-up: journal codec round-trips and
rotation, fail-closed framing under corruption (the on-disk sibling of
test_wire_fuzz's stream fuzz), on-device digest determinism, digest
bisection on synthetic streams, and — via scripts/replay_smoke.py — the
full record → replay → bisect e2e over a journaled chaos run.
"""

import importlib.util
import sys
import zlib
from pathlib import Path

import pytest

from noahgameframe_tpu.replay import (
    JournalError,
    JournalReader,
    JournalWriter,
    bisect_divergence,
    field_diff,
    read_ticks,
)
from noahgameframe_tpu.replay.bisect import first_divergence_linear
from noahgameframe_tpu.replay.journal import (
    HEADER,
    REC_CKPT,
    REC_EVENT,
    REC_META,
    REC_NOTE,
    REC_TICK,
    SEGMENT_MAGIC,
    MAX_RECORD_SIZE,
    SRC_SERVER,
    SRC_WORLD,
    decode_ckpt,
    decode_event,
    decode_json,
    decode_tick,
)

REPO = Path(__file__).resolve().parent.parent


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- journal codec
class TestJournalCodec:
    def test_round_trip_all_record_types(self, tmp_path):
        w = JournalWriter(tmp_path / "j", meta={"world_seed": 7})
        w.note({"kind": "chaos", "seed": 7})
        w.event(SRC_SERVER, 3, 42, 109, b"hello")
        w.event(SRC_WORLD, 3, -1, 210, b"")
        w.tick_mark(1, 0xDEADBEEF)
        w.checkpoint_mark(1)
        w.tick_mark(2, 2**31 + 5)  # digests are uint32: sign must not leak
        w.close()

        r = JournalReader(tmp_path / "j")
        assert r.meta == {"world_seed": 7}
        recs = list(r)
        kinds = [t for t, _ in recs]
        assert kinds == [REC_META, REC_NOTE, REC_EVENT, REC_EVENT,
                         REC_TICK, REC_CKPT, REC_TICK]
        assert decode_json(recs[1][1])["seed"] == 7
        assert decode_event(recs[2][1]) == (SRC_SERVER, 42, 3, 109, b"hello")
        assert decode_event(recs[3][1]) == (SRC_WORLD, -1, 3, 210, b"")
        assert decode_tick(recs[4][1]) == (1, 0xDEADBEEF)
        assert decode_ckpt(recs[5][1]) == 1
        assert decode_tick(recs[6][1]) == (2, (2**31 + 5) & 0xFFFFFFFF)
        assert read_ticks(tmp_path / "j") == {
            1: 0xDEADBEEF, 2: (2**31 + 5) & 0xFFFFFFFF
        }

    def test_rotation_at_tick_boundaries_only(self, tmp_path):
        w = JournalWriter(tmp_path / "j", segment_bytes=4096)
        body = bytes(300)
        for t in range(1, 31):
            # a fat event window, then the tick mark that may rotate
            for _ in range(3):
                w.event(SRC_SERVER, 3, 1, 7, body)
            w.tick_mark(t, t * 17)
        w.close()
        segs = sorted((tmp_path / "j").glob("seg-*.nfj"))
        assert len(segs) >= 2, "rotation never happened"
        assert w.segments_total == len(segs)
        assert w.ticks_total == 30
        # order survives the segment boundary, and every segment head
        # carries its self-describing META record
        ticks, metas = [], 0
        for rec_type, rec in JournalReader(tmp_path / "j"):
            if rec_type == REC_TICK:
                ticks.append(decode_tick(rec)[0])
            elif rec_type == REC_META:
                metas += 1
        assert ticks == list(range(1, 31))
        assert metas == len(segs)
        # rotation happens only right after a tick mark: every segment
        # except the newest ENDS with a complete REC_TICK frame
        for seg in segs[:-1]:
            last = None
            data = seg.read_bytes()
            off = len(SEGMENT_MAGIC)
            while off < len(data):
                rec_type, length, _ = HEADER.unpack_from(data, off)
                off += HEADER.size + length
                last = rec_type
            assert last == REC_TICK

    def test_writer_resumes_segment_numbering(self, tmp_path):
        w = JournalWriter(tmp_path / "j")
        w.tick_mark(1, 1)
        w.close()
        w2 = JournalWriter(tmp_path / "j")
        w2.tick_mark(2, 2)
        w2.close()
        # a second recording run must never clobber existing segments
        segs = sorted((tmp_path / "j").glob("seg-*.nfj"))
        assert len(segs) == 2
        assert read_ticks(tmp_path / "j") == {1: 1, 2: 2}


# ---------------------------------------------------------------- fuzz
# the on-disk sibling of test_wire_fuzz's framing section: a journal can
# be torn or bit-flipped at rest, and the reader must fail closed with
# JournalError — never crash, never silently skip input.
class TestJournalFuzz:
    @pytest.fixture()
    def journal(self, tmp_path):
        w = JournalWriter(tmp_path / "j", meta={"s": 1})
        for t in range(1, 9):
            w.event(SRC_SERVER, 3, 5, 11, bytes(range(64)))
            w.tick_mark(t, t * 31)
        w.close()
        return tmp_path / "j"

    @staticmethod
    def _seg(journal):
        return sorted(journal.glob("seg-*.nfj"))[0]

    @staticmethod
    def _assert_fails_closed(journal):
        with pytest.raises(JournalError):
            for _ in JournalReader(journal):
                pass

    def test_clean_journal_reads(self, journal):
        assert len(read_ticks(journal)) == 8

    def test_truncated_tail_mid_body(self, journal):
        seg = self._seg(journal)
        seg.write_bytes(seg.read_bytes()[:-7])
        self._assert_fails_closed(journal)

    def test_truncated_tail_mid_header(self, journal):
        seg = self._seg(journal)
        data = seg.read_bytes()
        seg.write_bytes(data + HEADER.pack(REC_TICK, 12, 0)[:5])
        self._assert_fails_closed(journal)

    def test_bit_flips_in_bodies_fail_crc(self, journal):
        import random

        seg = self._seg(journal)
        clean = seg.read_bytes()
        # locate every body byte by walking the valid frames, then flip
        # a sample of them: CRC32 must catch each one
        body_spans = []
        off = len(SEGMENT_MAGIC)
        while off < len(clean):
            _, length, _ = HEADER.unpack_from(clean, off)
            off += HEADER.size
            if length:
                body_spans.append((off, off + length))
            off += length
        rng = random.Random(5)
        flips = [rng.randrange(a, b) for a, b in body_spans for _ in (0,)]
        for pos in flips[:16]:
            mutated = bytearray(clean)
            mutated[pos] ^= 1 << rng.randrange(8)
            seg.write_bytes(bytes(mutated))
            self._assert_fails_closed(journal)
        seg.write_bytes(clean)

    def test_torn_mid_segment(self, journal):
        seg = self._seg(journal)
        data = seg.read_bytes()
        # cut inside the third record's body, keep a later-looking tail
        seg.write_bytes(data[: len(data) // 2 - 3])
        self._assert_fails_closed(journal)

    def test_bad_magic(self, journal):
        seg = self._seg(journal)
        data = bytearray(seg.read_bytes())
        data[0] ^= 0xFF
        seg.write_bytes(bytes(data))
        self._assert_fails_closed(journal)

    def test_unknown_record_type(self, journal):
        seg = self._seg(journal)
        seg.write_bytes(seg.read_bytes()
                        + HEADER.pack(99, 0, zlib.crc32(b"")))
        self._assert_fails_closed(journal)

    def test_oversize_length_is_corruption_not_allocation(self, journal):
        seg = self._seg(journal)
        seg.write_bytes(seg.read_bytes()
                        + HEADER.pack(REC_NOTE, MAX_RECORD_SIZE + 1, 0))
        self._assert_fails_closed(journal)

    def test_empty_directory_fails_closed(self, tmp_path):
        (tmp_path / "empty").mkdir()
        with pytest.raises(JournalError):
            JournalReader(tmp_path / "empty")
        with pytest.raises(JournalError):
            JournalReader(tmp_path / "missing")

    def test_corrupt_run_meta_fails_closed(self, journal):
        (journal / "journal.json").write_text("{not json")
        with pytest.raises(JournalError):
            JournalReader(journal)


# ------------------------------------------------------------- digest
def _tiny_world(seed=11):
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig

    w = GameWorld(WorldConfig(
        npc_capacity=16, player_capacity=4, seed=seed,
        combat=False, movement=False, regen=True, middleware=False,
        regen_period_s=0.1,
    )).start()
    w.seed_npcs(4, hp=50)
    return w


class TestStateDigest:
    def test_identical_runs_identical_digests(self):
        digests = []
        for _ in range(2):
            w = _tiny_world()
            k = w.kernel
            k.enable_digest()
            run = []
            for _t in range(5):
                k.execute()
                k.tick()
                run.append(k.last_counters["state_digest"] & 0xFFFFFFFF)
            digests.append(run)
        assert digests[0] == digests[1]
        # the world evolves (regen), so the digest stream must too
        assert len(set(digests[0])) > 1

    def test_digest_sees_single_cell_change(self):
        from noahgameframe_tpu.core.store import with_class

        w1, w2 = _tiny_world(), _tiny_world()
        for w in (w1, w2):
            w.kernel.enable_digest()
        cs = w2.kernel.state.classes["NPC"]
        w2.kernel.state = with_class(
            w2.kernel.state, "NPC",
            cs.replace(vec=cs.vec.at[0, 0, 0].add(1.0)),
        )
        outs = []
        for w in (w1, w2):
            w.kernel.execute()
            w.kernel.tick()
            outs.append(w.kernel.last_counters["state_digest"] & 0xFFFFFFFF)
        assert outs[0] != outs[1]

    def test_digest_not_in_metrics_totals(self):
        w = _tiny_world()
        k = w.kernel
        k.enable_digest()
        k.execute()
        k.tick()
        assert "state_digest" in k.last_counters
        assert "state_digest" not in k.counter_totals


# ------------------------------------------------------------- bisect
class TestBisect:
    @staticmethod
    def _streams(n, first_bad):
        a = {t: t * 7 for t in range(1, n + 1)}
        b = {t: (t * 7 if t < first_bad else t * 7 + 1)
             for t in range(1, n + 1)}
        return a, b

    def test_finds_exact_boundary(self):
        for first_bad in (2, 3, 57, 100):
            a, b = self._streams(100, first_bad)
            assert bisect_divergence(a, b) == first_bad
            assert first_divergence_linear(a, b) == first_bad

    def test_divergence_at_first_common_tick(self):
        a, b = self._streams(10, 1)
        assert bisect_divergence(a, b) == 1

    def test_no_divergence(self):
        a, _ = self._streams(50, 99)
        assert bisect_divergence(a, dict(a)) is None
        assert bisect_divergence(a, {}) is None

    def test_partial_overlap(self):
        # run B recorded from a later checkpoint: only the overlap counts
        a = {t: t for t in range(1, 101)}
        b = {t: (t if t < 80 else t + 1) for t in range(50, 121)}
        assert bisect_divergence(a, b) == 80

    def test_healed_divergence_after_boundary_raises(self):
        # diverged at 10, healed at 11, diverged again 12..32: the
        # forward persistence probes see the re-agreement and refuse
        a = {t: 0 for t in range(1, 33)}
        b = {t: (0 if t < 10 or t == 11 else 1) for t in range(1, 33)}
        with pytest.raises(ValueError):
            bisect_divergence(a, b)
        assert first_divergence_linear(a, b) == 10

    def test_pure_transient_blip_needs_linear_scan(self):
        # streams re-agree at the tail: bisect's persistence assumption
        # makes the blip invisible (documented) — linear finds it
        a = {t: 0 for t in range(1, 33)}
        b = dict(a)
        b[7] = 1
        assert bisect_divergence(a, b) is None
        assert first_divergence_linear(a, b) == 7

    def test_field_diff_names_bank_and_cells(self):
        from noahgameframe_tpu.core.store import with_class

        w1, w2 = _tiny_world(), _tiny_world()
        cs = w2.kernel.state.classes["NPC"]
        w2.kernel.state = with_class(
            w2.kernel.state, "NPC",
            cs.replace(vec=cs.vec.at[2, 0, 1].add(3.0)),
        )
        diff = field_diff(w1.kernel.state, w2.kernel.state)
        assert [d["key"] for d in diff] == ["c/NPC/vec"]
        assert diff[0]["count"] == 1
        cell = diff[0]["cells"][0]
        assert cell["index"] == (2, 0, 1)
        assert cell["b"] == pytest.approx(cell["a"] + 3.0)


# ----------------------------------------------------------- e2e
def test_record_replay_bisect_e2e(tmp_path):
    """The acceptance scenario: journal a 120-tick chaos run, replay it
    from its first checkpoint with bit-identical digests, then bisect a
    deliberately perturbed replay to the exact injected tick."""
    smoke = _load_script("replay_smoke")
    checks = smoke.run(tmp_path, seed=7)
    failed = [name for name, ok in checks.items() if not ok]
    assert not failed, f"replay smoke checks failed: {failed}"
