"""Measured-tuning promotion (scripts/decide_tuning.py): the harvest
queue's A/B captures elect the engine flags the driver bench runs with.
Wrong promotion logic would silently pessimize (or break) the round's
official benchmark, so the election rules are pinned here."""

import importlib.util
import json
import os
import sys


def _load(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "decide_tuning",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "decide_tuning.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.RUNS = str(tmp_path)
    return mod


def _w(tmp_path, name, ms=None, error=None):
    d = {"metric": "m", "detail": {"tick_ms": ms}}
    if error:
        d["error"] = error
    with open(os.path.join(str(tmp_path), name), "w") as f:
        json.dump(d, f)


def _run(mod, capsys):
    mod.main()
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1]) if out and out[-1].startswith("{") else None


def test_no_baseline_writes_nothing(tmp_path, capsys):
    mod = _load(tmp_path)
    mod.main()
    assert not os.path.exists(os.path.join(str(tmp_path), "tuning.json"))


def test_winner_must_beat_margin(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_radix.json", 98.0)   # within 3%: tie -> default
    _w(tmp_path, "r05_tpu_1m_pallas.json", 96.0)  # beats margin
    got = _run(mod, capsys)
    assert got["env"] == {"NF_PALLAS": "1"}


def test_best_radix_digit_wins(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_radix.json", 80.0)
    _w(tmp_path, "r05_tpu_1m_radix2.json", 70.0)
    got = _run(mod, capsys)
    assert got["env"] == {"NF_RADIX": "2"}


def test_aligned_pallas_promotes_align_flag(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_pallas.json", 90.0)
    _w(tmp_path, "r05_tpu_1m_pallas_aligned.json", 60.0)
    got = _run(mod, capsys)
    assert got["env"]["NF_PALLAS"] == "1"
    assert got["env"]["NF_PALLAS_ALIGN"] == "128"


def test_fused_pallas2_elected_when_fastest(tmp_path, capsys):
    """The r11 tri-state: the fused engine's capture beats both the
    baseline margin and the fold-only variants -> NF_PALLAS=2, and no
    ALIGN flag rides along (it belongs to the fold-only kernel)."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_pallas.json", 90.0)
    _w(tmp_path, "r05_tpu_1m_pallas_aligned.json", 85.0)
    _w(tmp_path, "r11_tpu_1m_pallas2.json", 70.0)
    got = _run(mod, capsys)
    assert got["env"]["NF_PALLAS"] == "2"
    assert "NF_PALLAS_ALIGN" not in got["env"]
    assert got["detail"]["pallas2_tick_ms"] == 70.0


def test_fused_pallas2_loses_to_faster_fold(tmp_path, capsys):
    """Fold-only still wins when it measures faster (e.g. a 1M world in
    the fused engine's VMEM-fallback regime measures ~baseline)."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_pallas.json", 80.0)
    _w(tmp_path, "r11_tpu_1m_pallas2.json", 99.5)  # fallback regime
    got = _run(mod, capsys)
    assert got["env"]["NF_PALLAS"] == "1"


def test_fused_pallas2_crash_capture_not_elected(tmp_path, capsys):
    """Crash-immunity, same contract as the NF_BINNING rules: an error
    payload (however fast its phantom tick_ms) never elects the engine."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r11_tpu_1m_pallas2.json", 5.0, error="mosaic OOM")
    got = _run(mod, capsys)
    assert "NF_PALLAS" not in got["env"]


def test_fused_pallas2_within_margin_keeps_default(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r11_tpu_1m_pallas2.json", 98.0)  # within 3%: tie -> off
    got = _run(mod, capsys)
    assert "NF_PALLAS" not in got["env"]


def test_verlet_skin_best_variant_wins(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r06_tpu_1m_verlet1.json", 90.0)
    _w(tmp_path, "r06_tpu_1m_verlet2.json", 70.0)
    _w(tmp_path, "r06_tpu_1m_verlet4.json", 98.0)  # within margin: loses
    got = _run(mod, capsys)
    assert got["env"] == {"NF_VERLET_SKIN": "2"}


def test_r06_baseline_preferred_over_r05(tmp_path, capsys):
    """A fresh r06 baseline supersedes the archived r05 one — electing
    against a stale baseline would promote phantom wins."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 200.0)
    _w(tmp_path, "r06_tpu_1m.json", 100.0)
    _w(tmp_path, "r06_tpu_1m_verlet2.json", 150.0)  # beats r05, not r06
    got = _run(mod, capsys)
    assert got["env"] == {}
    assert got["detail"]["baseline_tick_ms"] == 100.0


def test_error_payloads_are_ignored(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_1m_radix.json", 10.0, error="crashed")
    got = _run(mod, capsys)
    assert got["env"] == {}  # a 10x "win" from a crash payload is not real


def test_binning_count_elected_when_it_beats_margin(tmp_path, capsys):
    """The r07 A/B: count wins against its OWN pinned sort baseline."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_1m.json", 95.0)        # pinned NF_BINNING=sort
    _w(tmp_path, "r07_tpu_1m_count.json", 80.0)  # beats 95 * 0.97
    got = _run(mod, capsys)
    assert got["env"] == {"NF_BINNING": "count"}
    assert got["detail"]["binning_sort_tick_ms"] == 95.0
    assert got["detail"]["binning_count_tick_ms"] == 80.0


def test_binning_within_margin_keeps_sort(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_1m.json", 95.0)
    _w(tmp_path, "r07_tpu_1m_count.json", 93.0)  # within 3%: tie -> default
    got = _run(mod, capsys)
    assert "NF_BINNING" not in got["env"]


def test_binning_compares_against_round_baseline_when_r07_sort_missing(
        tmp_path, capsys):
    """No pinned r07 sort capture: fall back to the round baseline rather
    than electing against nothing (a crashed sort run must not hand the
    election to count by default)."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_1m_count.json", 90.0)
    got = _run(mod, capsys)
    assert got["env"] == {"NF_BINNING": "count"}
    assert got["detail"]["binning_sort_tick_ms"] == 100.0


def test_binning_error_capture_not_elected(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_1m_count.json", 10.0, error="oom")
    got = _run(mod, capsys)
    assert "NF_BINNING" not in got["env"]


def test_train8_elected_when_it_beats_100k_margin(tmp_path, capsys):
    """The r13 A/B: NF_TICK_TRAIN=8 wins against the same-shape 100k
    baseline (never the 1M one — wrong shape for the election)."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_100k.json", 20.0)
    _w(tmp_path, "r13_tpu_100k_train8.json", 15.0)  # beats 20 * 0.97
    got = _run(mod, capsys)
    assert got["env"] == {"NF_TICK_TRAIN": "8"}
    assert got["detail"]["train_base_100k_tick_ms"] == 20.0
    assert got["detail"]["train8_100k_tick_ms"] == 15.0


def test_train8_within_margin_keeps_single_ticks(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_100k.json", 20.0)
    _w(tmp_path, "r13_tpu_100k_train8.json", 19.6)  # within 3%: tie -> off
    got = _run(mod, capsys)
    assert "NF_TICK_TRAIN" not in got["env"]


def test_train8_needs_a_100k_baseline(tmp_path, capsys):
    """No 100k capture at all: the train election does NOT fall back to
    the 1M baseline — a cross-shape 'win' would be phantom."""
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r13_tpu_100k_train8.json", 5.0)
    got = _run(mod, capsys)
    assert "NF_TICK_TRAIN" not in got["env"]


def test_train8_falls_back_to_v2_baseline(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r05_tpu_100k_v2.json", 20.0)
    _w(tmp_path, "r13_tpu_100k_train8.json", 15.0)
    got = _run(mod, capsys)
    assert got["env"] == {"NF_TICK_TRAIN": "8"}


def test_train8_error_capture_not_elected(tmp_path, capsys):
    mod = _load(tmp_path)
    _w(tmp_path, "r05_tpu_1m.json", 100.0)
    _w(tmp_path, "r07_tpu_100k.json", 20.0)
    _w(tmp_path, "r13_tpu_100k_train8.json", 1.0, error="tunnel died")
    got = _run(mod, capsys)
    assert "NF_TICK_TRAIN" not in got["env"]


def test_bench_applies_tuning_env(tmp_path, monkeypatch):
    """bench.py's loader: setdefault semantics (explicit env wins)."""
    runs = tmp_path / "bench_runs"
    runs.mkdir()
    (runs / "tuning.json").write_text(
        json.dumps({"env": {"NF_RADIX": "2", "NF_PALLAS": "1"}})
    )
    monkeypatch.setenv("NF_PALLAS", "0")  # operator override
    monkeypatch.delenv("NF_RADIX", raising=False)
    applied = {}
    with open(runs / "tuning.json") as f:
        for k, v in (json.load(f).get("env") or {}).items():
            if os.environ.setdefault(k, str(v)) == str(v):
                applied[k] = str(v)
    assert applied == {"NF_RADIX": "2"}
    assert os.environ["NF_PALLAS"] == "0"
