"""KV-backed social persistence: mail/rank/guild survive a process kill
WITHOUT a whole-world checkpoint (VERDICT r4 item 10; reference
NFServer/NFDataAgent_NosqlPlugin semantics)."""

from __future__ import annotations

import pytest

from noahgameframe_tpu.game import GameWorld, WorldConfig
from noahgameframe_tpu.persist import MemoryKV, SocialDataAgent
from noahgameframe_tpu.persist.agent import PlayerDataAgent


def make_world():
    w = GameWorld(WorldConfig(combat=False, movement=False, regen=False,
                              npc_capacity=64, player_capacity=8)).start()
    w.scene.create_scene(1)
    return w


def bind(world, kv):
    return SocialDataAgent(kv).bind(
        world.kernel, mail=world.mail, rank=world.rank, guilds=world.guilds)


def make_player(world, account, name):
    g = world.kernel.create_object(
        "Player", {"Name": name, "Account": account}, scene=1, group=0)
    return g


def test_mail_survives_process_kill():
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    mid = w1.mail.send("alice", "system", "Welcome", "hi", gold=25,
                       items={"potion": 2})
    w1.mail.send("bob", "system", "Other")
    # "kill" the process: a brand-new world over the same KV
    w2 = make_world()
    bind(w2, kv)
    box = w2.mail.mailbox("alice")
    assert [m.title for m in box] == ["Welcome"]
    assert box[0].gold == 25 and box[0].items == {"potion": 2}
    # ids keep advancing (no reuse after reload)
    nid = w2.mail.send("alice", "system", "Second")
    assert nid > mid
    # draw state writes through too
    e = w2.kernel.elements
    e.add_element("Item", "potion", {"ItemType": 2})
    p = make_player(w2, "alice", "Alice")
    assert w2.mail.draw("alice", mid, p)
    w3 = make_world()
    bind(w3, kv)
    assert w3.mail.mailbox("alice")[0].drawn


def test_rank_survives_process_kill():
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    w1.rank.update("level", "alice", 30)
    w1.rank.update("level", "bob", 40)
    w1.rank.update("power", "alice", 900)
    w1.rank.remove("power", "alice")

    w2 = make_world()
    bind(w2, kv)
    assert w2.rank.top("level") == [("bob", 40), ("alice", 30)]
    assert w2.rank.score("power", "alice") is None


def test_guild_survives_process_kill_and_relinks_members():
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    lead = make_player(w1, "lead", "Lead")
    mate = make_player(w1, "mate", "Mate")
    gid = w1.guilds.create_guild(lead, "Axiom")
    assert gid is not None
    assert w1.guilds.join(gid, mate)
    # logout of both members dissolves the live entity, but durable
    # membership (accounts) must survive
    w1.kernel.destroy_object(mate)
    w1.kernel.destroy_object(lead)
    assert w1.guilds.find_by_name("Axiom") is None  # live roster empty

    # fresh process: members log back in and re-link by account
    w2 = make_world()
    bind(w2, kv)
    mate2 = make_player(w2, "mate", "Mate")
    info = w2.guilds.find_by_name("Axiom")
    assert info is not None  # first returning member resurrects it
    assert mate2 in info.members
    lead2 = make_player(w2, "lead", "Lead")
    info = w2.guilds.find_by_name("Axiom")
    assert lead2 in info.members
    assert info.leader == lead2  # saved leader reclaims leadership
    from noahgameframe_tpu.core.datatypes import Guid

    assert w2.kernel.get_property(mate2, "GuildID") == info.group_id


def test_voluntary_leave_drops_durable_membership():
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    lead = make_player(w1, "lead", "Lead")
    mate = make_player(w1, "mate", "Mate")
    gid = w1.guilds.create_guild(lead, "Axiom")
    w1.guilds.join(gid, mate)
    assert w1.guilds.leave(mate)  # walks out on purpose

    w2 = make_world()
    bind(w2, kv)
    make_player(w2, "mate", "Mate")
    assert w2.guilds.find_by_name("Axiom") is None  # mate is not a member
    make_player(w2, "lead", "Lead")
    info = w2.guilds.find_by_name("Axiom")
    assert info is not None and len(info.members) == 1


def test_disband_deletes_the_record():
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    lead = make_player(w1, "lead", "Lead")
    w1.guilds.create_guild(lead, "Axiom")
    assert w1.guilds.disband(lead)

    w2 = make_world()
    bind(w2, kv)
    make_player(w2, "lead", "Lead")
    assert w2.guilds.find_by_name("Axiom") is None
    assert kv.keys("guild:*") == []


def test_social_kv_coexists_with_player_blobs():
    """Same KV can hold player blobs (obj:) and social keys without
    collision — one Redis, many agents, like the reference."""
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    PlayerDataAgent(kv).bind(w1.kernel)
    p = make_player(w1, "carol", "Carol")
    w1.kernel.set_property(p, "Level", 12)
    w1.mail.send("carol", "system", "Hello")
    w1.kernel.destroy_object(p)  # agent saves blob on destroy

    w2 = make_world()
    bind(w2, kv)
    PlayerDataAgent(kv).bind(w2.kernel)
    p2 = make_player(w2, "carol", "Carol")
    assert int(w2.kernel.get_property(p2, "Level")) == 12
    assert [m.title for m in w2.mail.mailbox("carol")] == ["Hello"]


def test_dormant_guild_name_not_claimable_by_strangers():
    """A guild whose members are all offline (entity dissolved) still
    owns its name durably: a stranger cannot create 'Axiom' and absorb
    the dormant record's members (review finding)."""
    kv = MemoryKV()
    w1 = make_world()
    bind(w1, kv)
    lead = make_player(w1, "lead", "Lead")
    w1.guilds.create_guild(lead, "Axiom")
    w1.kernel.destroy_object(lead)  # guild entity dissolves, record stays

    stranger = make_player(w1, "stranger", "Stranger")
    assert w1.guilds.create_guild(stranger, "Axiom") is None
    assert w1.guilds.create_guild(stranger, "Other") is not None

    # the rightful leader returns and gets their guild back, alone
    lead2 = make_player(w1, "lead", "Lead")
    info = w1.guilds.find_by_name("Axiom")
    assert info is not None
    assert info.leader == lead2
    assert stranger not in info.members
