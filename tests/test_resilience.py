"""Fault-injection + supervised recovery (ISSUE 2).

Covers the resilience stack bottom-up: RetryPolicy's backoff math, the
chaos layer's determinism and partition scheduling, atomic checkpoints
with the torn-pair guard, the master's heartbeat-lease FSM, and — via
scripts/chaos_smoke.py — the full kill/revive e2e over the five-role
cluster under an active FaultPlan.
"""

import importlib.util
import sys
import time as _time
from pathlib import Path

import numpy as np
import pytest

from noahgameframe_tpu.net.chaos import (
    ChaosDirector,
    FaultPlan,
    FaultyTransport,
    LinkFaults,
)
from noahgameframe_tpu.net.defines import (
    RECONNECT_CAP_SECONDS,
    RECONNECT_SECONDS,
    ServerState,
    ServerType,
)
from noahgameframe_tpu.net.module import NetClientModule
from noahgameframe_tpu.net.retry import RetryPolicy
from noahgameframe_tpu.net.transport import EV_CONNECTED, EV_MSG, NetEvent

REPO = Path(__file__).resolve().parent.parent


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ RetryPolicy
class TestRetryPolicy:
    def test_base_is_the_old_reconnect_constant(self):
        # the fixed 10 s timer became the backoff base: configs that
        # tuned RECONNECT_SECONDS keep their first-retry behavior
        p = RetryPolicy(jitter=0.0)
        assert p.base == RECONNECT_SECONDS
        assert p.delay(1) == RECONNECT_SECONDS
        assert NetClientModule().retry.base == RECONNECT_SECONDS

    def test_exponential_growth_and_cap(self):
        p = RetryPolicy(base=1.0, cap=8.0, factor=2.0, jitter=0.0)
        assert [p.delay(n) for n in (1, 2, 3, 4, 5, 99)] == [
            1.0, 2.0, 4.0, 8.0, 8.0, 8.0
        ]
        assert RetryPolicy(jitter=0.0).delay(99) == RECONNECT_CAP_SECONDS

    def test_jitter_deterministic_and_bounded(self):
        p = RetryPolicy(base=1.0, cap=100.0, jitter=0.25, seed=3)
        for attempt in (1, 2, 5):
            d = p.delay(attempt, key=7)
            assert d == p.delay(attempt, key=7)  # reproducible
            nominal = 2.0 ** (attempt - 1)
            assert 0.75 * nominal <= d <= 1.25 * nominal
        # distinct keys de-sync (the thundering-herd fix)
        assert p.delay(3, key=1) != p.delay(3, key=2)

    def test_cap_bounds_jittered_delay(self):
        p = RetryPolicy(base=10.0, cap=10.0, jitter=0.25, seed=0)
        assert all(p.delay(n, key=k) <= 10.0
                   for n in range(1, 8) for k in range(5))


# ----------------------------------------------------------- chaos layer
class _FakeInner:
    """Scriptable transport double: records sends, replays queued events."""

    def __init__(self):
        self.sent = []
        self.queue = []
        self.disconnects = 0

    def send_msg(self, msg_id, body):
        self.sent.append((msg_id, bytes(body)))
        return True

    def poll(self):
        out, self.queue = self.queue, []
        return out

    def disconnect(self):
        self.disconnects += 1

    def close(self):
        pass


def _run_sequence(seed):
    """Push a fixed message schedule through a fresh FaultyTransport."""
    plan = FaultPlan(seed=seed, links={
        "link": LinkFaults(drop=0.2, dup=0.2, delay=0.2, delay_polls=2,
                           truncate=0.15, corrupt=0.15),
    })
    director = ChaosDirector(plan)
    inner = _FakeInner()
    t = director.wrap(inner, "link.a->1")
    delivered_in = []
    for i in range(60):
        t.send_msg(i, bytes([i % 256]) * (4 + i % 9))
        inner.queue.append(NetEvent(EV_MSG, 0, 1000 + i, b"pong" * (1 + i % 3)))
        delivered_in.extend((ev.msg_id, ev.body) for ev in t.poll())
    for _ in range(5):  # drain delayed traffic
        delivered_in.extend((ev.msg_id, ev.body) for ev in t.poll())
    return director, inner.sent, delivered_in


class TestFaultyTransport:
    def test_same_seed_same_fault_sequence(self):
        d1, out1, in1 = _run_sequence(seed=42)
        d2, out2, in2 = _run_sequence(seed=42)
        assert d1.logs == d2.logs  # byte-identical fault schedule
        assert d1.counts == d2.counts
        assert out1 == out2  # delivered bytes identical both directions
        assert in1 == in2
        assert d1.total() > 0  # the plan actually fired

    def test_different_seed_different_sequence(self):
        d1, out1, _ = _run_sequence(seed=1)
        d2, out2, _ = _run_sequence(seed=2)
        assert d1.logs != d2.logs or out1 != out2

    def test_counts_survive_redial(self):
        # the director owns the budget; a fresh wrapper (reconnect dial)
        # keeps accumulating into the same per-link counters
        plan = FaultPlan(links={"l": LinkFaults(drop=1.0)})
        director = ChaosDirector(plan)
        t1 = director.wrap(_FakeInner(), "l.x->1")
        t1.send_msg(1, b"a")
        t2 = director.wrap(_FakeInner(), "l.x->1")
        t2.send_msg(2, b"b")
        assert director.counts["l.x->1"]["drop_out"] == 2

    def test_partition_window_heals(self):
        plan = FaultPlan(links={
            "l": LinkFaults(partitions=((2, 5, "out"),)),
        })
        inner = _FakeInner()
        t = ChaosDirector(plan).wrap(inner, "l.x->1")
        for _ in range(8):
            t.poll()  # ticks 1..8
            t.send_msg(7, b"hi")
        # out-partition covers ticks 2,3,4 -> exactly 3 swallowed sends
        assert len(inner.sent) == 5
        assert t.counts["partition_out"] == 3

    def test_in_partition_blocks_messages_not_connects(self):
        plan = FaultPlan(links={
            "l": LinkFaults(partitions=((0, 100, "in"),)),
        })
        inner = _FakeInner()
        t = ChaosDirector(plan).wrap(inner, "l.x->1")
        inner.queue = [NetEvent(EV_CONNECTED, 0),
                       NetEvent(EV_MSG, 0, 5, b"x")]
        kinds = [ev.kind for ev in t.poll()]
        assert kinds == [EV_CONNECTED]  # socket events pass, payload doesn't
        assert t.counts["partition_in"] == 1

    def test_refuse_turns_connect_into_disconnect(self):
        from noahgameframe_tpu.net.transport import EV_DISCONNECTED

        plan = FaultPlan(links={"l": LinkFaults(refuse=1.0)})
        inner = _FakeInner()
        t = ChaosDirector(plan).wrap(inner, "l.x->1")
        inner.queue = [NetEvent(EV_CONNECTED, 0)]
        assert [ev.kind for ev in t.poll()] == [EV_DISCONNECTED]
        assert inner.disconnects == 1

    def test_refuse_first_is_deterministic_across_redials(self):
        from noahgameframe_tpu.net.transport import EV_DISCONNECTED

        plan = FaultPlan(links={"l": LinkFaults(refuse_first=2)})
        director = ChaosDirector(plan)
        kinds = []
        for _ in range(4):  # each dial = fresh inner + fresh wrapper
            inner = _FakeInner()
            t = director.wrap(inner, "l.x->1")
            inner.queue = [NetEvent(EV_CONNECTED, 0)]
            kinds.extend(ev.kind for ev in t.poll())
        # exactly the first two connects refused, then the link heals
        assert kinds == [EV_DISCONNECTED, EV_DISCONNECTED,
                         EV_CONNECTED, EV_CONNECTED]
        assert director.counts["l.x->1"]["refuse"] == 2

    def test_delayed_messages_arrive_in_order(self):
        plan = FaultPlan(links={"l": LinkFaults(delay=1.0, delay_polls=2)})
        inner = _FakeInner()
        t = ChaosDirector(plan).wrap(inner, "l.x->1")
        t.send_msg(1, b"first")
        t.send_msg(2, b"second")
        t.poll()
        assert inner.sent == []  # still held
        t.poll()
        assert [m for m, _ in inner.sent] == [1, 2]

    def test_unmatched_link_gets_default(self):
        plan = FaultPlan(links={"proxy5.games": LinkFaults(drop=1.0)})
        assert plan.for_link("proxy5.games->6").drop == 1.0
        assert not plan.for_link("game6.world->7").any()


# ----------------------------------------------- checkpoint atomicity
@pytest.fixture(scope="module")
def smoke():
    return _load_script("chaos_smoke")


class TestAtomicCheckpoint:
    def test_save_twice_and_torn_guard(self, smoke, tmp_path):
        import json

        from noahgameframe_tpu.persist.checkpoint import _flatten_state

        w = smoke.build_world(seed=11)
        path = tmp_path / "ckpt"
        w.save(path)
        w.tick()
        w.save(path)  # second save exercises the rename-aside swap
        leftovers = [p for p in tmp_path.iterdir() if p.name != "ckpt"]
        assert leftovers == []  # no temp/old dirs survive
        # round-trip into a fresh world
        w2 = smoke.build_world(seed=12)  # different seed: load must win
        w2.load(path)
        a = _flatten_state(w.kernel.state)
        b = _flatten_state(w2.kernel.state)
        assert set(a) == set(b)
        for key in a:
            assert np.array_equal(a[key], b[key]), key
        # torn pair: meta claiming a different tick than the arrays
        meta_p = path / "meta.json"
        meta = json.loads(meta_p.read_text())
        meta["array_tick"] = int(meta["array_tick"]) + 1
        meta_p.write_text(json.dumps(meta))
        with pytest.raises(ValueError, match="torn checkpoint"):
            smoke.build_world(seed=12).load(path)


# ------------------------------------------------- master lease FSM
class TestMasterLeases:
    def _report(self, sid=6, stype=ServerType.GAME):
        from noahgameframe_tpu.net.wire import ServerInfoReport

        return ServerInfoReport(
            server_id=sid, server_name=b"G", server_ip=b"127.0.0.1",
            server_port=1, server_max_online=10, server_cur_count=0,
            server_state=int(ServerState.NORMAL), server_type=int(stype),
        )

    def test_up_suspect_down_recover(self):
        from noahgameframe_tpu.net.roles.base import RoleConfig
        from noahgameframe_tpu.net.roles.master import MasterRole

        m = MasterRole(
            RoleConfig(1, int(ServerType.MASTER), "M", "127.0.0.1", 0),
            lease_suspect_seconds=1.0, lease_down_seconds=2.0,
        )
        try:
            m._upsert(self._report(), -1)
            t0 = _time.monotonic()
            reg = m.telemetry.registry

            def lease():
                return m.servers_status()["servers"]["game"][0]["lease"]

            m._sweep_leases(t0 + 0.5)
            assert lease() == "UP"
            m._sweep_leases(t0 + 1.5)
            assert lease() == "SUSPECT"
            assert reg.value("nf_lease_expirations_total", role="game") == 0
            m._sweep_leases(t0 + 2.5)
            assert lease() == "DOWN"
            assert reg.value("nf_lease_expirations_total", role="game") == 1
            # DOWN marks the stored report CRASH (routed lists skip it)
            entry = m.registry[int(ServerType.GAME)][6]
            assert entry.report.server_state == int(ServerState.CRASH)
            # age is rendered for the dashboard
            status = m.servers_status()["servers"]["game"][0]
            assert status["last_seen_age_s"] >= 0.0
            # a fresh report is a recovery
            m._upsert(self._report(), -1)
            assert lease() == "UP"
            assert reg.value("nf_lease_recoveries_total", role="game") == 1
        finally:
            m.shut()

    def test_down_world_leaves_login_routing_list(self):
        from noahgameframe_tpu.net.roles.base import RoleConfig
        from noahgameframe_tpu.net.roles.master import MasterRole

        m = MasterRole(
            RoleConfig(1, int(ServerType.MASTER), "M", "127.0.0.1", 0),
            lease_suspect_seconds=1.0, lease_down_seconds=2.0,
        )
        try:
            m._upsert(self._report(sid=7, stype=ServerType.WORLD), -1)
            assert len(m._world_reports().server_list) == 1
            m._sweep_leases(_time.monotonic() + 3.0)
            assert len(m._world_reports().server_list) == 0
        finally:
            m.shut()


# ----------------------------------------------------------- e2e
def test_chaos_kill_revive_e2e(smoke, tmp_path):
    """The acceptance scenario: deterministic seed, active FaultPlan,
    kill mid-tick, revive from the atomic checkpoint, DOWN->UP at the
    master, state equal to the fault-free control, counters nonzero."""
    checks = smoke.run(tmp_path, seed=7)
    failed = [name for name, ok in checks.items() if not ok]
    assert not failed, f"chaos smoke checks failed: {failed}"
