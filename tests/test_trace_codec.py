"""Frame-observatory units (ISSUE 7): trace codec fuzz, stage clock
accounting, clock-offset estimation, multi-process trace merge.

The codec section mirrors tests/test_wire_fuzz.py's contract: a header
that arrives torn, oversized, or version-skewed must raise TraceError —
never crash a role, never yield a half-parsed context.  The e2e flow
lives in tests/test_pipeline.py.
"""

import random

import pytest

from noahgameframe_tpu.telemetry.pipeline import (
    TRACE_SIZE,
    TRACE_VERSION,
    ClockSync,
    StageClock,
    TraceContext,
    TraceError,
    decode_trace,
    encode_trace,
    merge_chrome_traces,
)
from noahgameframe_tpu.telemetry.registry import MetricsRegistry


# ----------------------------------------------------------------- codec
class TestTraceCodec:
    def test_round_trip_all_fields(self):
        ctx = TraceContext(
            tick=(1 << 63) + 5, game_id=6, seq=0xFFFFFFFF,
            t_encode_ns=123456789, proxy_in_ns=1, proxy_out_ns=2,
            client_recv_ns=3, flags=0x7F,
        )
        buf = encode_trace(ctx)
        assert len(buf) == TRACE_SIZE
        assert decode_trace(buf) == ctx

    def test_every_truncation_fails_closed(self):
        buf = encode_trace(TraceContext(tick=1, game_id=2, seq=3,
                                        t_encode_ns=4))
        for n in range(TRACE_SIZE):
            with pytest.raises(TraceError):
                decode_trace(buf[:n])

    def test_oversize_fails_closed(self):
        buf = encode_trace(TraceContext(tick=1, game_id=2, seq=3,
                                        t_encode_ns=4))
        for extra in (1, 7, 64):
            with pytest.raises(TraceError):
                decode_trace(buf + bytes(extra))

    def test_unknown_version_fails_closed(self):
        buf = bytearray(encode_trace(
            TraceContext(tick=1, game_id=2, seq=3, t_encode_ns=4)))
        for v in range(256):
            if v == TRACE_VERSION:
                continue
            buf[0] = v
            with pytest.raises(TraceError):
                decode_trace(bytes(buf))

    def test_random_garbage_never_crashes(self):
        rng = random.Random(11)
        for _ in range(200):
            blob = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 2 * TRACE_SIZE)))
            try:
                decode_trace(blob)
            except TraceError:
                pass  # the only acceptable failure mode

    def test_body_bitflips_round_trip_or_fail_closed(self):
        # past the version byte every value is opaque payload: a flip
        # must still decode (to different stamps) or raise — no crash
        clean = encode_trace(TraceContext(tick=9, game_id=8, seq=7,
                                          t_encode_ns=6))
        rng = random.Random(5)
        for _ in range(64):
            buf = bytearray(clean)
            buf[rng.randrange(1, TRACE_SIZE)] ^= 1 << rng.randrange(8)
            try:
                decode_trace(bytes(buf))
            except TraceError:
                pass


# ------------------------------------------------------------ stage clock
class TestStageClock:
    def test_waterfall_sums_to_wall_exactly(self):
        sc = StageClock()
        sc.frame_begin(7)
        with sc.stage("tick"):
            pass
        with sc.stage("encode"):
            with sc.stage("send"):
                pass
        last = sc.frame_end()
        assert sc.last_tick == 7
        assert sum(last.values()) == sc.last_wall_ns
        assert "other" in last and last["other"] >= 0

    def test_nested_child_time_is_exclusive(self):
        import time

        sc = StageClock()
        sc.frame_begin(1)
        with sc.stage("encode"):
            with sc.stage("send"):
                time.sleep(0.02)
        sc.frame_end()
        # "send" held the sleep; "encode" keeps only its own bookkeeping
        assert sc.last["send"] >= 15_000_000
        assert sc.last["encode"] < sc.last["send"]

    def test_add_ns_charges_innermost_parent(self):
        sc = StageClock()
        sc.frame_begin(1)
        with sc.stage("encode"):
            sc.add_ns("send", 5_000_000)
        sc.frame_end()
        assert sc.last["send"] == 5_000_000
        # the manual charge was subtracted from the enclosing stage
        assert sc.last["encode"] < 5_000_000
        assert sum(sc.last.values()) == sc.last_wall_ns

    def test_histograms_and_stats(self):
        reg = MetricsRegistry()
        sc = StageClock(reg)
        for t in range(4):
            sc.frame_begin(t)
            with sc.stage("tick"):
                pass
            sc.frame_end()
        assert sc.frames == 4
        stats = sc.stats()
        assert "tick" in stats and "other" in stats
        assert set(stats["tick"]) == {"p50_ms", "p95_ms", "mean_ms"}
        assert "nf_stage_tick_seconds" in reg.exposition()


# ------------------------------------------------------------- clock sync
class TestClockSync:
    def test_min_filter_converges_on_offset_plus_min_delay(self):
        rng = random.Random(3)
        cs = ClockSync(window=64)
        offset, min_delay, max_delay = 5_000_000, 1_000, 900_000
        for i in range(64):
            sent = i * 10_000_000
            delay = rng.randrange(min_delay, max_delay)
            cs.update("game6", sent, sent + offset + delay)
        est = cs.offset_ns("game6")
        assert offset + min_delay <= est <= offset + max_delay
        # with enough samples the min filter sheds most of the jitter
        assert est < offset + max_delay // 2

    def test_negative_offsets_survive(self):
        cs = ClockSync()
        cs.update("proxy5", 1_000_000, 200_000)  # receiver clock behind
        assert cs.offset_ns("proxy5") == -800_000
        assert cs.offsets() == {"proxy5": -800_000}

    def test_window_slides(self):
        cs = ClockSync(window=4)
        for d in (50, 40, 30, 20, 10):
            cs.update("k", 0, d)
        assert cs.offset_ns("k") == 10
        for d in (100, 100, 100, 100):
            cs.update("k", 0, d)
        # the old minimum aged out of the 4-sample window
        assert cs.offset_ns("k") == 100

    def test_unknown_key(self):
        assert ClockSync().offset_ns("nope") is None


# ------------------------------------------------------------- trace merge
class TestChromeTraceMerge:
    @staticmethod
    def _doc(pid, ts):
        return {"traceEvents": [
            {"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
             "args": {"name": f"role{pid}"}},
            {"ph": "X", "pid": pid, "tid": 1, "name": "tick",
             "ts": ts, "dur": 5.0},
        ]}

    def test_merge_applies_offsets_and_keeps_pids(self):
        merged = merge_chrome_traces(
            [self._doc(1, 100.0), self._doc(2, 100.0)],
            offsets_us=[0.0, 250.0],
        )
        evs = merged["traceEvents"]
        assert merged["displayTimeUnit"] == "ms"
        assert {e["pid"] for e in evs} == {1, 2}
        xs = {e["pid"]: e["ts"] for e in evs if e["ph"] == "X"}
        assert xs == {1: 100.0, 2: 350.0}

    def test_metadata_events_never_shift(self):
        merged = merge_chrome_traces([self._doc(3, 10.0)],
                                     offsets_us=[999.0])
        meta = [e for e in merged["traceEvents"] if e["ph"] == "M"]
        assert meta and all("ts" not in e for e in meta)

    def test_merge_without_offsets(self):
        merged = merge_chrome_traces([self._doc(1, 7.0), self._doc(2, 8.0)])
        xs = sorted(e["ts"] for e in merged["traceEvents"]
                    if e["ph"] == "X")
        assert xs == [7.0, 8.0]

    def test_input_docs_not_mutated(self):
        doc = self._doc(1, 50.0)
        merge_chrome_traces([doc], offsets_us=[100.0])
        assert doc["traceEvents"][1]["ts"] == 50.0

    def test_span_tracer_round_trip_merge(self):
        from noahgameframe_tpu.telemetry.tracing import SpanTracer

        a, b = SpanTracer(enabled=True), SpanTracer(enabled=True)
        with a.span("game.tick"):
            pass
        with b.span("proxy.relay"):
            pass
        off = (b.epoch_ns - a.epoch_ns) / 1e3  # same-clock alignment
        merged = merge_chrome_traces(
            [a.chrome_trace(pid=1), b.chrome_trace(pid=2)],
            offsets_us=[0.0, off],
        )
        names = {e["name"] for e in merged["traceEvents"]}
        assert {"game.tick", "proxy.relay"} <= names
        assert {e["pid"] for e in merged["traceEvents"]} == {1, 2}
