"""NF_BINNING=count parity + guard rails (the counting-sort tentpole).

The contract: the count engine (histogram + bounded scatter-min ranks +
scatter, ops/stencil.py) is BIT-IDENTICAL to the stable-argsort engine —
payload, slot_of and dropped, including WHICH rows overflow to the dump
slot — across the full matrix NF_BINNING x NF_RADIX x Verlet skin, over
degenerate occupancies, and through a whole fused 24/120-tick world run
(state_digest equality).  Plus two lint-style guards: the counting build
path contains no sort/argsort call, and nothing outside
stencil.binning_mode() reads the env var."""

import ast
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from noahgameframe_tpu.ops import stencil
from noahgameframe_tpu.ops.stencil import (
    BINNING_MODES,
    binning_mode,
    build_cell_table,
    build_cell_table_pair,
)
from noahgameframe_tpu.ops.verlet import (
    full_table,
    init_cache,
    refresh,
    sub_table,
)

PKG = Path(__file__).resolve().parent.parent / "noahgameframe_tpu"


# --------------------------------------------------------------- fixtures

def _case(seed, n, width, cell, p_active=0.85, p_sub=0.3):
    rng = np.random.default_rng(seed)
    pos = jnp.asarray(rng.uniform(0, width * cell, (n, 2)).astype(np.float32))
    active = jnp.asarray(rng.random(n) < p_active)
    sub = jnp.asarray(rng.random(n) < p_sub) & active
    feats = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    sfeats = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
    # pair-builder positional order: (pos, active, features, sub_mask,
    # sub_features) — splat-ready
    return pos, active, feats, sub, sfeats


def _set_mode(monkeypatch, mode, radix=0):
    if mode == "sort":
        monkeypatch.delenv("NF_BINNING", raising=False)
    else:
        monkeypatch.setenv("NF_BINNING", mode)
    if radix:
        monkeypatch.setenv("NF_RADIX", str(radix))
    else:
        monkeypatch.delenv("NF_RADIX", raising=False)


def _np_tables(tables):
    out = []
    for t in tables:
        out.append((np.asarray(t.payload), np.asarray(t.slot_of),
                    int(t.dropped)))
    return out


def _assert_tables_equal(a, b, label=""):
    for (pa, sa, da), (pb, sb, db) in zip(_np_tables(a), _np_tables(b)):
        np.testing.assert_array_equal(pa, pb, err_msg=f"{label} payload")
        np.testing.assert_array_equal(sa, sb, err_msg=f"{label} slot_of")
        assert da == db, f"{label} dropped {da} != {db}"


# ------------------------------------------------- pair-builder bit parity

@pytest.mark.parametrize("radix", [0, 1, 2])
@pytest.mark.parametrize("bucket,sub_bucket", [(16, 8), (4, 2), (1, 1)])
def test_pair_matrix_bit_identical(monkeypatch, radix, bucket, sub_bucket):
    """build_cell_table_pair: count == sort(+radix variants) bit-for-bit,
    including the forced-overflow (1, 1) geometry where MOST rows drop —
    both engines must keep the same (smallest-row-id) winners."""
    case = _case(7, 311, 8, 4.0)
    _set_mode(monkeypatch, "sort", radix)
    ref = build_cell_table_pair(*case, 4.0, 8, bucket, sub_bucket)
    _set_mode(monkeypatch, "count")
    got = build_cell_table_pair(*case, 4.0, 8, bucket, sub_bucket)
    _assert_tables_equal(ref, got, f"radix={radix} bucket={bucket}")


def test_single_table_bit_identical(monkeypatch):
    pos, active, feats, _sub, _sf = _case(3, 257, 8, 4.0)
    _set_mode(monkeypatch, "sort")
    ref = build_cell_table(pos, active, feats, 4.0, 8, 12)
    _set_mode(monkeypatch, "count")
    got = build_cell_table(pos, active, feats, 4.0, 8, 12)
    _assert_tables_equal([ref], [got], "single")


@pytest.mark.parametrize("name,case_kw", [
    ("all_inactive", dict(p_active=0.0)),
    ("all_active", dict(p_active=1.0, p_sub=1.0)),
    ("sub_empty", dict(p_sub=0.0)),
])
def test_degenerate_masks_bit_identical(monkeypatch, name, case_kw):
    case = _case(11, 200, 8, 4.0, **case_kw)
    _set_mode(monkeypatch, "sort")
    ref = build_cell_table_pair(*case, 4.0, 8, 8, 4)
    _set_mode(monkeypatch, "count")
    got = build_cell_table_pair(*case, 4.0, 8, 8, 4)
    _assert_tables_equal(ref, got, name)


def test_all_one_cell_and_one_overfull_cell(monkeypatch):
    """Worst-case occupancy skew: every entity in a single cell (every
    other cell empty), then one packed cell among a uniform field.  The
    scatter-min rounds must rank exactly the bucket smallest row ids."""
    n, width, cell = 300, 8, 4.0
    rng = np.random.default_rng(13)
    active = jnp.ones(n, bool)
    sub = jnp.asarray(rng.random(n) < 0.4)
    feats = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
    sfeats = feats[:, :1]

    one_cell = jnp.broadcast_to(
        jnp.float32([cell * 2.5, cell * 2.5]), (n, 2)
    )
    packed = jnp.asarray(
        rng.uniform(0, width * cell, (n, 2)).astype(np.float32)
    ).at[: n // 2].set(jnp.float32([cell * 5.5, cell * 5.5]))

    for label, pos in (("one_cell", one_cell), ("packed", packed)):
        _set_mode(monkeypatch, "sort")
        ref = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                    cell, width, 8, 4)
        assert int(ref[0].dropped) > 0, f"{label}: no overflow exercised"
        _set_mode(monkeypatch, "count")
        got = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                    cell, width, 8, 4)
        _assert_tables_equal(ref, got, label)


def test_rect_grid_precomputed_cells_bit_identical(monkeypatch):
    """The spatial slab path: precomputed cell ids over a rectangular
    [height, width] grid (cell=..., height=...) through both engines."""
    h, w, cell = 4, 8, 4.0
    n = 220
    rng = np.random.default_rng(17)
    pos = jnp.asarray(
        np.c_[rng.uniform(0, w * cell, n), rng.uniform(0, h * cell, n)]
        .astype(np.float32)
    )
    active = jnp.asarray(rng.random(n) < 0.9)
    sub = jnp.asarray(rng.random(n) < 0.3) & active
    feats = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
    sfeats = feats
    cx = jnp.clip((pos[:, 0] / cell).astype(jnp.int32), 0, w - 1)
    cy = jnp.clip((pos[:, 1] / cell).astype(jnp.int32), 0, h - 1)
    cid = cy * w + cx
    _set_mode(monkeypatch, "sort")
    ref = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                cell, w, 6, 4, cell=cid, height=h)
    _set_mode(monkeypatch, "count")
    got = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                cell, w, 6, 4, cell=cid, height=h)
    _assert_tables_equal(ref, got, "rect")


def test_fuzz_overflow_sweep(monkeypatch):
    """Random densities x tiny buckets: whatever drops, BOTH engines drop
    the same rows (slot_of equality is the strong form of that claim)."""
    for seed in range(6):
        rng = np.random.default_rng(100 + seed)
        n = int(rng.integers(16, 400))
        width = int(rng.integers(2, 10))
        bucket = int(rng.integers(1, 6))
        sub_bucket = int(rng.integers(1, bucket + 1))
        case = _case(seed, n, width, 4.0,
                     p_active=float(rng.uniform(0.1, 1.0)),
                     p_sub=float(rng.uniform(0.0, 1.0)))
        _set_mode(monkeypatch, "sort")
        ref = build_cell_table_pair(*case, 4.0, width, bucket, sub_bucket)
        _set_mode(monkeypatch, "count")
        got = build_cell_table_pair(*case, 4.0, width, bucket, sub_bucket)
        _assert_tables_equal(ref, got, f"fuzz seed={seed}")


# --------------------------------------------------- verlet cache parity

@pytest.mark.parametrize("skin", [0.0, 2.0])
def test_verlet_tables_cross_engine(monkeypatch, skin):
    """A cache anchored under count reproduces the sort-engine pair
    builder through full_table/sub_table — rebuild arm AND the reuse
    replay both land on identical tables."""
    n, width, cell = 257, 8, 4.0
    pos, active, feats, sub, sfeats = _case(5, n, width, cell)
    _set_mode(monkeypatch, "sort")
    ref = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                cell, width, 12, 8)
    _set_mode(monkeypatch, "count")
    cache, rebuilt = refresh(
        init_cache(n), pos, active, cell, width, 12, skin
    )
    assert int(rebuilt) == 1
    got_full = full_table(cache, feats, active, width * width, cell,
                          width, 12)
    got_sub = sub_table(cache, sub, sfeats, width * width, cell, width, 8)
    _assert_tables_equal(ref, (got_full, got_sub), f"verlet skin={skin}")


def test_verlet_reuse_tick_count_engine(monkeypatch):
    """Reuse branch under count: after sub-skin drift, sub_table with a
    fresh mask equals the pair builder run against the ANCHOR binning."""
    _set_mode(monkeypatch, "count")
    rng = np.random.default_rng(9)
    n, width, cell = 181, 8, 4.0
    pos0 = jnp.asarray(
        rng.uniform(1, width * cell - 1, (n, 2)).astype(np.float32)
    )
    active = jnp.ones(n, bool)
    cache, _ = refresh(init_cache(n), pos0, active, cell, width, 12, 2.0)
    pos1 = pos0 + jnp.asarray(
        rng.uniform(-0.4, 0.4, (n, 2)).astype(np.float32)
    )
    cache, rebuilt = refresh(cache, pos1, active, cell, width, 12, 2.0)
    assert int(rebuilt) == 0
    sub = jnp.asarray(rng.random(n) < 0.25)
    sfeats = jnp.asarray(rng.normal(size=(n, 2)).astype(np.float32))
    got = sub_table(cache, sub, sfeats, width * width, cell, width, 8)
    _, ref = build_cell_table_pair(
        pos0, active, jnp.zeros((n, 1), jnp.float32), sub, sfeats,
        cell, width, 12, 8,
    )
    np.testing.assert_array_equal(np.asarray(ref.payload),
                                  np.asarray(got.payload))


# ------------------------------------------------ fused world-run digests

def _digest_world(skin, ticks):
    from noahgameframe_tpu.game.world import GameWorld, WorldConfig

    w = GameWorld(WorldConfig(
        npc_capacity=2048, extent=96.0, seed=11, middleware=False,
        aoi_bucket=64, verlet_skin=skin,
    ))
    w.start()
    w.scene.create_scene(1, width=96.0)
    w.seed_npcs(2000)
    k = w.kernel
    k.enable_digest()
    k.run_device(ticks)
    k.tick()
    return k.last_counters["state_digest"] & 0xFFFFFFFF


@pytest.mark.parametrize("skin", [None, 2.0])
def test_fused_run_digest_parity_24(monkeypatch, skin):
    """24 fused device ticks (with and without the Verlet cache): the
    count-engine world ends in the EXACT state the sort-engine world
    does — one digest covers every leaf of the class banks."""
    _set_mode(monkeypatch, "sort")
    ref = _digest_world(skin, 24)
    _set_mode(monkeypatch, "count")
    got = _digest_world(skin, 24)
    assert ref == got


@pytest.mark.slow
@pytest.mark.parametrize("skin", [None, 2.0])
def test_fused_run_digest_parity_120(monkeypatch, skin):
    _set_mode(monkeypatch, "sort")
    ref = _digest_world(skin, 120)
    _set_mode(monkeypatch, "count")
    got = _digest_world(skin, 120)
    assert ref == got


# ------------------------------------------------------------ guard rails

def test_binning_mode_validation(monkeypatch):
    monkeypatch.delenv("NF_BINNING", raising=False)
    assert binning_mode() == "sort"
    monkeypatch.setenv("NF_BINNING", "")
    assert binning_mode() == "sort"
    monkeypatch.setenv("NF_BINNING", "  ")
    assert binning_mode() == "sort"
    monkeypatch.setenv("NF_BINNING", "count")
    assert binning_mode() == "count"
    for bad in ("Count", "radix", "cuont"):
        monkeypatch.setenv("NF_BINNING", bad)
        with pytest.raises(ValueError, match="NF_BINNING"):
            binning_mode()


def test_dispatch_covers_every_mode(monkeypatch):
    """Every value in BINNING_MODES must build real tables through BOTH
    entry points — a mode added to the tuple without a dispatch arm (or
    vice versa) fails loudly here, not silently at 3am on a chip."""
    pos, active, feats, sub, sfeats = _case(2, 64, 4, 4.0)
    for mode in BINNING_MODES:
        monkeypatch.setenv("NF_BINNING", mode)
        t = build_cell_table(pos, active, feats, 4.0, 4, 8)
        assert t.payload.shape[0] == 4 * 4 * 8 + 1
        pair = build_cell_table_pair(pos, active, feats, sub, sfeats,
                                     4.0, 4, 8, 4)
        assert pair[1].bucket == 4
    # unknown values must raise at the dispatch, not fall through
    monkeypatch.setenv("NF_BINNING", "bogus")
    with pytest.raises(ValueError, match="NF_BINNING"):
        build_cell_table(pos, active, feats, 4.0, 4, 8)
    with pytest.raises(ValueError, match="NF_BINNING"):
        build_cell_table_pair(pos, active, feats, sub, sfeats, 4.0, 4, 8, 4)


# The counting build path must stay sort-free — that IS the optimisation.
_COUNT_PATH_FNS = (
    "_cell_counts",
    "_counting_ranks",
    "_counting_slots",
    "_build_pair_counting",
    "table_from_slots",
    "_cell_keys",
)


def _function_defs(tree):
    return {
        node.name: node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _dotted(node):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def test_counting_path_contains_no_sort():
    src = (PKG / "ops" / "stencil.py").read_text()
    defs = _function_defs(ast.parse(src))
    missing = [f for f in _COUNT_PATH_FNS if f not in defs]
    assert not missing, f"count-path functions renamed? {missing}"
    offenses = []
    for fname in _COUNT_PATH_FNS:
        for node in ast.walk(defs[fname]):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted and "sort" in dotted.lower():
                offenses.append(f"{fname}:{node.lineno}: {dotted}()")
    assert not offenses, "\n".join(offenses)


def test_env_read_only_inside_binning_mode():
    """NF_BINNING is read in exactly one place: stencil.binning_mode().
    Any other read (os.environ.get / os.getenv / os.environ[...] with the
    literal or with ENV_BINNING) would fork the dispatch and let the two
    sites disagree mid-trace."""

    def _mentions_env(node):
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Constant) and a.value == "NF_BINNING":
                return True
            if isinstance(a, ast.Name) and a.id == "ENV_BINNING":
                return True
        return False

    offenses = []
    for path in sorted(PKG.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        # map node -> enclosing function name
        enclosing = {}
        for fn in ast.walk(tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in ast.walk(fn):
                    enclosing.setdefault(child, fn.name)
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func) or ""
                if dotted.endswith(("environ.get", "getenv")) and \
                        _mentions_env(node):
                    hit = dotted
            elif isinstance(node, ast.Subscript):
                dotted = _dotted(node.value) or ""
                sl = node.slice
                if dotted.endswith("environ") and isinstance(
                        sl, (ast.Constant, ast.Name)):
                    v = sl.value if isinstance(sl, ast.Constant) else None
                    nm = sl.id if isinstance(sl, ast.Name) else None
                    if v == "NF_BINNING" or nm == "ENV_BINNING":
                        hit = dotted + "[...]"
            if hit is None:
                continue
            fn = enclosing.get(node)
            if path.name == "stencil.py" and fn == "binning_mode":
                continue
            offenses.append(
                f"{path.relative_to(PKG.parent)}:{node.lineno}: {hit}"
            )
    assert not offenses, "\n".join(offenses)
    # and the sanctioned read must actually exist (the guard is useless
    # if a refactor moves the read and nothing asserts where it went)
    assert stencil.ENV_BINNING == "NF_BINNING"


def test_sub_overflow_independent_of_full(monkeypatch):
    """A row that overflows the FULL table can still hold a valid SUB
    slot (the subset re-ranks independently) — in both engines."""
    n = 40
    pos = jnp.broadcast_to(jnp.float32([2.0, 2.0]), (n, 2))  # one cell
    active = jnp.ones(n, bool)
    # sub members are the LAST rows: all overflow the size-4 full table,
    # but the first 4 of them fit the size-4 sub table
    sub = jnp.arange(n) >= n - 8
    feats = jnp.asarray(np.arange(n * 2, dtype=np.float32).reshape(n, 2))
    for mode in BINNING_MODES:
        monkeypatch.setenv("NF_BINNING", mode)
        full, subt = build_cell_table_pair(
            pos, active, feats, sub, feats, 4.0, 4, 4, 4
        )
        assert int(full.dropped) == n - 4
        assert int(subt.dropped) == 4  # 8 members, 4 slots
        # the sub winners are the 4 smallest row ids AMONG sub members
        placed = np.asarray(subt.slot_of[sub])
        dump = 4 * 4 * 4
        assert (np.sort(placed[placed < dump]) ==
                np.asarray(subt.slot_of)[n - 8:n - 4]).all()
