"""Generated C++ client SDK: compile with g++ and round-trip real bytes
against the Python codec (the verifiable Cocos-style client binding —
reference ships NFClient/ C++/C# SDKs speaking the same frames)."""

import shutil
import struct
import subprocess
from pathlib import Path

import pytest

import noahgameframe_tpu.net.wire as wire
import noahgameframe_tpu.net.wire_families as families
from noahgameframe_tpu.net.wire import Message
from noahgameframe_tpu.tools.emit_cpp_sdk import emit_header

# representative classes: envelope, nested/repeated sync messages, enums,
# floats/doubles, every scalar family
CASES = [
    wire.Ident,
    wire.MsgBase,
    wire.ObjectPropertyList,
    wire.ObjectRecordList,
    wire.RecordAddRowStruct,
    wire.ObjectRecordSwap,
    wire.ReqAccountLogin,
    wire.ServerInfoReport,
    wire.ReqAckPlayerMove,
    wire.AckConnectWorldResult,
    families.PackMysqlParam,
    families.PackSURLParam,
    families.ReqBuildOperate,
    families.BulletEvents,
    families.CameraControlEvents,
]


class Gen:
    """Deterministic field filler (protoc-free variant of the one in
    test_wire_protoc.py — enums just get small ints here)."""

    def __init__(self):
        self.n = 0

    def value(self, ftype):
        self.n += 1
        i = self.n
        if isinstance(ftype, tuple):
            return [self.value(ftype[1]) for _ in range(2)]
        if isinstance(ftype, type) and issubclass(ftype, Message):
            return self.message(ftype)
        return {
            "int32": [5, -3, 0, 1 << 28][i % 4],
            "int64": [9, -1, 1 << 40][i % 3],
            "uint64": [0, 7, (1 << 62) + 3][i % 3],
            "bool": bool(i % 2),
            "enum": i % 3,
            "float": [0.5, -2.25, 100.125][i % 3],
            "double": [1.5, -3.25e10][i % 2],
            "bytes": f"b{i}".encode(),
            "string": f"s{i}",
        }[ftype]

    def message(self, cls):
        return cls(**{f[1]: self.value(f[2]) for f in cls.FIELDS})


def driver_cpp() -> str:
    """main.cpp: read framed stream on stdin (msg_id = case index),
    decode -> re-encode -> frame to stdout."""
    cases = "\n".join(
        f"        case {i}: {{ nfmsg::{c.__name__} m; "
        f"if (!m.Decode(body.data(), body.size())) return 2; "
        f"out2 = m.Encode(); break; }}"
        for i, c in enumerate(CASES)
    )
    return (
        '#include "nfmsg.hpp"\n'
        "#include <cstdio>\n"
        "#include <iostream>\n"
        "#include <iterator>\n"
        "int main() {\n"
        "    std::string in((std::istreambuf_iterator<char>(std::cin)),\n"
        "                   std::istreambuf_iterator<char>());\n"
        "    std::string out;\n"
        "    size_t off = 0; uint16_t id; std::string body;\n"
        "    nfmsg::UnframeResult ur;\n"
        "    while ((ur = nfmsg::unframe(in, off, id, body)) == nfmsg::UNFRAME_OK) {\n"
        "        std::string out2;\n"
        "        switch (id) {\n"
        f"{cases}\n"
        "        default: return 3;\n"
        "        }\n"
        "        nfmsg::frame(out, id, out2);\n"
        "    }\n"
        "    if (ur == nfmsg::UNFRAME_ERROR) return 5;\n"
        "    if (off != in.size()) return 4;\n"
        "    fwrite(out.data(), 1, out.size(), stdout);\n"
        "    return 0;\n"
        "}\n"
    )


@pytest.fixture(scope="module")
def sdk_bin(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("g++ unavailable")
    d = tmp_path_factory.mktemp("cppsdk")
    (d / "nfmsg.hpp").write_text(emit_header())
    (d / "main.cc").write_text(driver_cpp())
    exe = d / "roundtrip"
    r = subprocess.run(
        ["g++", "-std=c++11", "-O1", "-Wall", "-Werror",
         "-I", str(d), str(d / "main.cc"), "-o", str(exe)],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    return exe


def frame(msg_id: int, body: bytes) -> bytes:
    return struct.pack(">HI", msg_id, len(body) + 6) + body


def test_cpp_roundtrip_byte_identical(sdk_bin):
    gen = Gen()
    stream = b""
    originals = []
    for i, cls in enumerate(CASES):
        m = gen.message(cls)
        originals.append(m.encode())
        stream += frame(i, originals[-1])
    r = subprocess.run([str(sdk_bin)], input=stream, capture_output=True)
    assert r.returncode == 0, r.returncode
    assert r.stdout == stream, "C++ decode->encode is not byte-identical"


def test_cpp_tolerates_unknown_fields(sdk_bin):
    # Ident bytes + an unknown field tag 15 (varint): C++ must skip it
    # and re-encode only the known fields
    base = wire.Ident(svrid=4, index=2).encode()
    extra = base + bytes([15 << 3 | 0, 42])
    r = subprocess.run(
        [str(sdk_bin)], input=frame(0, extra), capture_output=True
    )
    assert r.returncode == 0
    assert r.stdout == frame(0, base)


def test_cpp_rejects_truncated_body(sdk_bin):
    body = wire.MsgBase(msg_data=b"x" * 40).encode()[:-7]
    r = subprocess.run(
        [str(sdk_bin)], input=frame(1, body), capture_output=True
    )
    assert r.returncode == 2  # decode failure reported, no crash


def test_cpp_wire_type_mismatch_stays_aligned(sdk_bin):
    """A known tag carrying the wrong wire type is skipped like an
    unknown field; later fields still decode."""
    # Ident: tag1 as length-delimited junk (wrong, declared varint),
    # then tag2 correct
    body = bytes([1 << 3 | 2, 3]) + b"xyz" + wire.Ident(index=7).encode()
    r = subprocess.run([str(sdk_bin)], input=frame(0, body), capture_output=True)
    assert r.returncode == 0
    assert r.stdout == frame(0, wire.Ident(index=7).encode())


def test_cpp_varint_overlong_rejected(sdk_bin):
    body = b"\x80" * 11 + b"\x01"
    r = subprocess.run([str(sdk_bin)], input=frame(0, body), capture_output=True)
    assert r.returncode == 2  # decode failure, not UB/garbage


def test_cpp_decode_resets_reused_object(sdk_bin, tmp_path):
    """Decode clears prior state (protobuf Parse semantics): reusing one
    message object across frames must not accumulate repeated fields."""
    import textwrap

    d = sdk_bin.parent
    src = d / "reuse.cc"
    src.write_text(textwrap.dedent('''
        #include "nfmsg.hpp"
        #include <cstdio>
        int main() {
            nfmsg::ObjectPropertyList m;
            nfmsg::ObjectPropertyList src;
            nfmsg::PropertyInt p; p.property_name = "HP";
            p.has_property_name = true; p.data = 5; p.has_data = true;
            src.property_int_list.push_back(p);
            std::string s = src.Encode();
            m.Decode(s.data(), s.size());
            m.Decode(s.data(), s.size());
            printf("%zu\\n", m.property_int_list.size());
            return 0;
        }
    '''))
    exe = d / "reuse"
    r = subprocess.run(["g++", "-std=c++11", "-I", str(d), str(src), "-o", str(exe)],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr[-2000:]
    out = subprocess.run([str(exe)], capture_output=True, text=True)
    assert out.stdout.strip() == "1"


def test_cpp_corrupt_header_is_error_not_stall(sdk_bin):
    bad = struct.pack(">HI", 0, 3)  # total < 6: protocol error
    r = subprocess.run([str(sdk_bin)], input=bad + b"xxxx", capture_output=True)
    assert r.returncode == 5  # surfaced as error, not an infinite wait
