"""Game-day drill engine (ISSUE 11): campaigns, runner, invariants.

Three layers, cheapest first:

- Campaign/Step units: build-time validation, stable ordering, JSON-safe
  description of live kwargs (callables, fault dataclasses).
- DrillRunner over a *forged* cluster (SimpleNamespace stand-ins + its
  own MetricsRegistry): step firing, action dispatch, telemetry, the
  violation cap.  Invariants read cluster state defensively by design,
  so each one also gets a seeded *violation* test — a forged cluster in
  a state the invariant must reject.  These tests fail if the invariant
  is disabled (returns []), which is exactly the regression they guard.
- ChaosDirector campaign primitives (ISSUE 11 satellites): store-phase
  exposure, live re-arming with consumed budgets, idempotent heal, and
  the re-wrap guard.
- The flagship game-day itself: short mode in tier-1, the full
  40-session campaign marked ``slow``.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path
from types import SimpleNamespace

import pytest

from noahgameframe_tpu.drill import (
    BoundedFailoverLag,
    Campaign,
    ConsistentCounters,
    DrillContext,
    DrillRunner,
    LegalLeaseTransitions,
    MonotoneWatermarks,
    NoSilentDrop,
    OrderedReplay,
    RoomIsolation,
    Step,
    default_invariants,
    merged,
)
from noahgameframe_tpu.net.chaos import (
    ChaosDirector,
    FaultPlan,
    LinkFaults,
    StoreFaultError,
    StoreFaults,
)
from noahgameframe_tpu.net.defines import SwitchNoticeCode
from noahgameframe_tpu.net.failover import ParkingBuffer
from noahgameframe_tpu.telemetry.registry import MetricsRegistry

REPO = Path(__file__).resolve().parent.parent


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------- Campaign
class TestCampaign:
    def test_builder_sorts_by_tick_stable_within_tick(self):
        c = (Campaign("t", seed=3)
             .add(5, "note", label="second-at-5")
             .add(1, "note", label="early")
             .add(5, "note", label="third-at-5"))
        assert [s.label for s in c.steps] == [
            "early", "second-at-5", "third-at-5"]
        assert c.horizon == 5
        assert len(c) == 3
        assert c.seed == 3

    def test_negative_tick_rejected(self):
        with pytest.raises(ValueError, match="at_tick"):
            Campaign("t").add(-1, "note")
        with pytest.raises(ValueError, match="at_tick"):
            Campaign("t", steps=[Step(-2, "note")])

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown action"):
            Campaign("t").add(0, "reboot_datacenter")
        with pytest.raises(ValueError, match="'call'"):
            Campaign("t", steps=[Step(0, "frobnicate")])

    def test_describe_is_json_safe_with_live_kwargs(self):
        # kwargs hold exactly what real campaigns carry: a fault
        # dataclass, a world factory, a plain scalar
        c = (Campaign("t", seed=7)
             .add(5, "store_faults", pattern="game6.store",
                  faults=StoreFaults(fail_first=3))
             .add(9, "call", fn=lambda r: None)
             .add(2, "kill_role", role="Game1", hard=True))
        desc = c.describe()
        json.dumps(desc)  # must not raise
        assert desc["name"] == "t" and desc["seed"] == 7
        assert desc["horizon"] == 9
        by_action = {s["action"]: s for s in desc["steps"]}
        assert by_action["store_faults"]["kwargs"]["faults"][
            "fail_first"] == 3
        assert by_action["call"]["kwargs"]["fn"].startswith("<callable")
        assert by_action["kill_role"]["kwargs"] == {
            "role": "Game1", "hard": True}

    def test_merged_shifts_offsets_and_defaults_labels(self):
        outage = Campaign("outage").add(0, "note").add(4, "heal")
        kill = Campaign("kill").add(0, "kill_role", role="Game1",
                                    label="boom")
        c = merged("gameday", 7, (10, outage), (12, kill))
        assert [(s.at_tick, s.action, s.label) for s in c.steps] == [
            (10, "note", "outage:note"),
            (12, "kill_role", "boom"),
            (14, "heal", "outage:heal"),
        ]
        assert c.seed == 7 and c.horizon == 14


# ------------------------------------------------------------ DrillRunner
class _AlwaysViolates:
    name = "always"

    def check(self, ctx):
        return ["forged breach"]


class _NeverViolates:
    name = "never"

    def check(self, ctx):
        return []


def _fake_cluster(log):
    """Minimal dispatch target: records every call the runner makes."""
    role = SimpleNamespace(
        config=SimpleNamespace(name="Game1"),
        checkpoint_now=lambda: log.append(("checkpoint", "Game1")),
    )
    chaos = SimpleNamespace(
        heal=lambda pattern: log.append(("heal", pattern)),
        set_store_faults=lambda p, f: log.append(("store_faults", p, f)),
        set_link_faults=lambda p, f: log.append(("link_faults", p, f)),
    )
    return SimpleNamespace(
        execute=lambda: log.append(("pump",)),
        kill_role=lambda role, hard: log.append(("kill", role, hard)),
        revive_role=lambda name, world, resume: log.append(
            ("revive", name, world, resume)),
        chaos=chaos,
        roles=[role],
    )


class TestRunnerActions:
    def test_steps_fire_at_their_tick_before_the_pump(self):
        log = []
        c = (Campaign("t")
             .add(0, "note", label="start")
             .add(2, "kill_role", role="Game1", hard=True)
             .add(2, "heal", pattern="game6")
             .add(4, "call", fn=lambda r: log.append(("call", r.tick))))
        r = DrillRunner(_fake_cluster(log), c, invariants=[],
                        registry=MetricsRegistry())
        for _ in range(5):
            r.step_once()
        assert log == [
            ("pump",),                       # tick 0: note is a no-op
            ("pump",),                       # tick 1
            ("kill", "Game1", True),         # tick 2: both due steps...
            ("heal", "game6"),               # ...fire before the pump
            ("pump",),
            ("pump",),                       # tick 3
            ("call", 4),                     # tick 4
            ("pump",),
        ]
        assert r.steps_remaining == 0
        assert [a["label"] or a["action"] for a in r.actions_fired] == [
            "start", "kill_role", "heal", "call"]
        assert [a["tick"] for a in r.actions_fired] == [0, 2, 2, 4]

    def test_all_dispatch_arms_and_telemetry(self):
        log = []
        reg = MetricsRegistry()
        factory_built = []

        def factory():
            factory_built.append(1)
            return "fresh-world"

        c = (Campaign("t")
             .add(0, "store_faults", pattern="game6.store",
                  faults=StoreFaults(fail_first=1))
             .add(0, "link_faults", pattern="proxy5",
                  faults=LinkFaults(dup=0.5))
             .add(1, "checkpoint", role="Game1")
             .add(2, "revive_role", name="Game1",
                  world_factory=factory, resume=True))
        r = DrillRunner(_fake_cluster(log), c, invariants=[], registry=reg)
        for _ in range(3):
            r.step_once()
        kinds = [e[0] for e in log]
        assert kinds == ["store_faults", "link_faults", "pump",
                         "checkpoint", "pump", "revive", "pump"]
        # the factory is only called when the step fires, and its world
        # is what reaches revive_role
        assert factory_built == [1]
        assert log[5] == ("revive", "Game1", "fresh-world", True)
        assert reg.value("nf_drill_ticks_total") == 3.0
        assert reg.value("nf_drill_actions_total",
                         action="store_faults") == 1.0
        assert reg.value("nf_drill_actions_total",
                         action="revive_role") == 1.0

    def test_violation_cap_keeps_counting_past_the_cap(self):
        reg = MetricsRegistry()
        r = DrillRunner(_fake_cluster([]), Campaign("t"),
                        invariants=[_AlwaysViolates(), _NeverViolates()],
                        registry=reg, max_violations=5)
        for _ in range(9):
            r.step_once()
        assert len(r.violations) == 5          # stored verbatim: capped
        rep = r.report()
        assert not rep.clean
        assert rep.checks == {"always": 9, "never": 9}
        # ...but the tally and the counter never stop
        assert reg.value("nf_drill_invariant_violations_total",
                         invariant="always") == 9.0
        assert reg.value("nf_drill_invariant_checks_total",
                         invariant="never") == 9.0
        assert r.status()["invariant_violations"] == {"always": 9}

    def test_status_block_is_json_safe(self):
        c = (Campaign("gameday", seed=7)
             .add(3, "kill_role", role="Game1", hard=True)
             .add(8, "call", fn=lambda r: None))
        r = DrillRunner(_fake_cluster([]), c, invariants=[],
                        registry=MetricsRegistry())
        r.step_once()
        st = r.status()
        json.dumps(st)  # /json mounts this verbatim
        assert st["campaign"] == "gameday" and st["seed"] == 7
        assert st["tick"] == 1 and st["horizon"] == 8
        assert st["actions_fired"] == 0 and st["steps_remaining"] == 2
        assert st["next_step"]["at_tick"] == 3

    def test_report_round_trips_through_json(self):
        c = Campaign("t").add(0, "call", fn=lambda r: None)
        r = DrillRunner(_fake_cluster([]), c,
                        invariants=[_AlwaysViolates()],
                        registry=MetricsRegistry())
        r.step_once()
        rep = r.report()
        blob = json.dumps(rep.to_dict())
        back = json.loads(blob)
        assert back["clean"] is False
        assert back["invariant_violations"] == {"always": 1}
        assert back["violations"][0] == {
            "invariant": "always", "tick": 0, "detail": "forged breach"}

    def test_default_invariants_is_the_full_library(self):
        names = {i.name for i in default_invariants()}
        assert names == {
            "no_silent_drop", "legal_lease_transitions",
            "monotone_watermarks", "bounded_failover_lag",
            "ordered_replay", "consistent_counters",
        }


# ------------------------------------- seeded violations, one per checker
def _ctx(cluster, tick=0, now=0.0):
    return DrillContext(cluster=cluster, tick=tick, now=now)


def _proxy(parking=None, live=(6,), conn_info=None, notices=None,
           conn_notices=None):
    return SimpleNamespace(
        parking=parking if parking is not None else ParkingBuffer(),
        games=SimpleNamespace(servers={int(g): object() for g in live}),
        _conn_info=dict(conn_info or {}),
        notice_counts=dict(notices or {}),
        conn_notices=dict(conn_notices or {}),
    )


class TestNoSilentDrop:
    def test_dropped_frames_without_notice_violate(self):
        pb = ParkingBuffer(max_frames=1, deadline_s=60.0)
        pb.park("c1", 3, b"a", now=0.0)
        pb.park("c1", 3, b"b", now=0.0)  # overflow: oldest dropped
        assert pb.dropped_overflow == 1
        inv = NoSilentDrop()
        cluster = SimpleNamespace(proxy=_proxy(parking=pb))
        out = inv.check(_ctx(cluster))
        assert out and "zero DROPPED notices" in out[0]
        # same drop WITH a notice pushed: clean
        inv2 = NoSilentDrop()
        cluster.proxy.notice_counts = {int(SwitchNoticeCode.DROPPED): 1}
        assert inv2.check(_ctx(cluster)) == []

    def test_unbound_session_past_grace_violates(self):
        inv = NoSilentDrop(grace_samples=3)
        cluster = SimpleNamespace(proxy=_proxy(
            live=(16,), conn_info={"c9": {"game_id": 6}}))
        assert inv.check(_ctx(cluster)) == []      # streak 1
        assert inv.check(_ctx(cluster)) == []      # streak 2
        out = inv.check(_ctx(cluster))             # streak 3 = grace
        assert out and "no switch notice" in out[0]
        # a notice (any code) on that conn silences the clause
        cluster.proxy.conn_notices = {"c9": [int(SwitchNoticeCode.REHOMING)]}
        assert NoSilentDrop(grace_samples=1).check(_ctx(cluster)) == []


class TestLegalLeaseTransitions:
    def _master(self, lease):
        return SimpleNamespace(
            lease_suspect_seconds=1.0, lease_down_seconds=2.0,
            registry={6: {6: SimpleNamespace(lease=lease)}})

    def test_up_to_down_with_tight_sampling_violates(self):
        inv = LegalLeaseTransitions()
        m = self._master("UP")
        cluster = SimpleNamespace(master=m)
        assert inv.check(_ctx(cluster, now=0.0)) == []   # baseline
        m.registry[6][6].lease = "DOWN"
        out = inv.check(_ctx(cluster, now=0.01))         # gap << window
        assert out and "UP->DOWN" in out[0]

    def test_legal_path_is_clean(self):
        inv = LegalLeaseTransitions()
        m = self._master("UP")
        cluster = SimpleNamespace(master=m)
        for i, lease in enumerate(
                ["UP", "SUSPECT", "DOWN", "UP", "SUSPECT", "UP"]):
            m.registry[6][6].lease = lease
            assert inv.check(_ctx(cluster, now=0.01 * i)) == [], lease

    def test_coarse_gap_tolerates_skipped_suspect(self):
        inv = LegalLeaseTransitions()
        m = self._master("UP")
        cluster = SimpleNamespace(master=m)
        inv.check(_ctx(cluster, now=0.0))
        m.registry[6][6].lease = "DOWN"
        # gap 5 s > the 1 s SUSPECT window: the sampler stalled through
        # the intermediate state, the machine did not
        assert inv.check(_ctx(cluster, now=5.0)) == []

    def test_previous_gap_also_excuses_the_jump(self):
        # regression for the pass-structure timing: the master sweeps at
        # the TOP of a pump pass, the drill samples at the BOTTOM — a
        # stall late in pass N lands in the N-1→N sample gap while the
        # lease jump only shows at sweep N+1, one sample later
        inv = LegalLeaseTransitions()
        m = self._master("UP")
        cluster = SimpleNamespace(master=m)
        inv.check(_ctx(cluster, now=0.0))
        inv.check(_ctx(cluster, now=5.0))    # the big gap, lease still UP
        m.registry[6][6].lease = "DOWN"
        assert inv.check(_ctx(cluster, now=5.01)) == []
        # but TWO samples later the excuse has expired
        m.registry[6][6].lease = "UP"
        inv.check(_ctx(cluster, now=5.02))
        m.registry[6][6].lease = "DOWN"
        out = inv.check(_ctx(cluster, now=5.03))
        assert out and "UP->DOWN" in out[0]


class TestMonotoneWatermarks:
    def _cluster(self, seq, tick):
        wal = SimpleNamespace(flushed_seq=seq, flushed_tick=tick)
        game = SimpleNamespace(persist=SimpleNamespace(name="Game1",
                                                       wal=wal))
        return SimpleNamespace(games=[game]), wal

    def test_seq_regression_violates(self):
        inv = MonotoneWatermarks()
        cluster, wal = self._cluster(5, 10)
        assert inv.check(_ctx(cluster)) == []
        wal.flushed_seq = 3
        out = inv.check(_ctx(cluster))
        assert out and "moved backwards" in out[0]

    def test_tick_regression_at_equal_seq_violates(self):
        inv = MonotoneWatermarks()
        cluster, wal = self._cluster(5, 10)
        inv.check(_ctx(cluster))
        wal.flushed_tick = 9
        assert inv.check(_ctx(cluster))

    def test_disappear_then_restart_below_watermark_is_caught(self):
        # a killed role's key vanishes; the baseline must survive so a
        # revived pipeline restarting low is caught on first report
        probe = {"store:g1": (7, 40)}
        inv = MonotoneWatermarks(store_probe=lambda: dict(probe))
        cluster = SimpleNamespace(games=[])
        assert inv.check(_ctx(cluster)) == []
        probe.clear()                                   # role killed
        assert inv.check(_ctx(cluster)) == []
        probe["store:g1"] = (2, 5)                      # revived too low
        out = inv.check(_ctx(cluster))
        assert out and "7:40 -> 2:5" in out[0]

    def test_advancing_marks_are_clean(self):
        inv = MonotoneWatermarks()
        cluster, wal = self._cluster(1, 1)
        for seq in range(1, 5):
            wal.flushed_seq, wal.flushed_tick = seq, seq * 3
            assert inv.check(_ctx(cluster)) == []


class TestBoundedFailoverLag:
    def _cluster(self, lag):
        driver = SimpleNamespace(deadline_s=2.0, lag=lambda now: lag)
        return SimpleNamespace(world=SimpleNamespace(failover=driver))

    def test_lag_past_deadline_plus_slack_violates(self):
        inv = BoundedFailoverLag(slack_s=0.5)
        out = inv.check(_ctx(self._cluster(lag=2.6)))
        assert out and "exceeds deadline" in out[0]

    def test_lag_within_bound_is_clean(self):
        inv = BoundedFailoverLag(slack_s=0.5)
        assert inv.check(_ctx(self._cluster(lag=2.4))) == []


class TestOrderedReplay:
    def test_scrambled_replay_violates_once(self):
        # drive the REAL ParkingBuffer's seq audit: park in order,
        # scramble the queue behind its back, replay
        pb = ParkingBuffer(max_frames=16, deadline_s=60.0)
        for i in range(3):
            pb.park("c1", 3, b"m%d" % i, now=0.0)
        pb._q["c1"].rotate(1)  # last frame now replays first
        sent = []
        pb.replay("c1", lambda mid, body: sent.append(body) or True)
        assert pb.order_violations > 0
        inv = OrderedReplay()
        cluster = SimpleNamespace(proxy=SimpleNamespace(parking=pb))
        out = inv.check(_ctx(cluster))
        assert out and "out of per-session" in out[0]
        # watermark: the same breach is not re-reported next sample
        assert inv.check(_ctx(cluster)) == []

    def test_in_order_replay_is_clean(self):
        pb = ParkingBuffer(max_frames=16, deadline_s=60.0)
        for i in range(3):
            pb.park("c1", 3, b"m%d" % i, now=0.0)
        pb.replay("c1", lambda mid, body: True)
        assert pb.order_violations == 0
        inv = OrderedReplay()
        cluster = SimpleNamespace(proxy=SimpleNamespace(parking=pb))
        assert inv.check(_ctx(cluster)) == []


class _FakeReg:
    """value()-only registry stand-in so counters can be *forged*
    (a real Counter cannot go backwards, which is the point of the
    busy-monotone clause)."""

    def __init__(self, **vals):
        self.vals = dict(vals)

    def value(self, name, **labels):
        return float(self.vals.get(name, 0.0))


def _counters_cluster(reg, pending=0, parking=None):
    driver = SimpleNamespace(pending_count=lambda: pending)
    world = SimpleNamespace(failover=driver,
                            telemetry=SimpleNamespace(registry=reg))
    proxy = SimpleNamespace(
        parking=parking if parking is not None else ParkingBuffer())
    return SimpleNamespace(world=world, proxy=proxy)


class TestConsistentCounters:
    def test_unbalanced_failover_bank_violates(self):
        reg = _FakeReg(nf_failover_initiated_total=3.0,
                       nf_failover_completed_total=1.0,
                       nf_failover_deadline_exceeded_total=0.0)
        out = ConsistentCounters().check(
            _ctx(_counters_cluster(reg, pending=1)))
        assert out and "failover bank not conserved" in out[0]

    def test_balanced_bank_is_clean(self):
        reg = _FakeReg(nf_failover_initiated_total=3.0,
                       nf_failover_completed_total=2.0,
                       nf_failover_deadline_exceeded_total=0.0)
        assert ConsistentCounters().check(
            _ctx(_counters_cluster(reg, pending=1))) == []

    def test_parking_bank_not_conserved_violates(self):
        pb = ParkingBuffer(max_frames=16, deadline_s=60.0)
        pb.park("c1", 3, b"x", now=0.0)
        pb.parked_total += 1  # forge a leak: one frame unaccounted for
        out = ConsistentCounters().check(
            _ctx(_counters_cluster(_FakeReg(), parking=pb)))
        assert out and "parking bank not conserved" in out[0]

    def test_busy_counter_going_backwards_violates(self):
        reg = _FakeReg(nf_failover_busy_total=5.0)
        inv = ConsistentCounters()
        cluster = _counters_cluster(reg)
        assert inv.check(_ctx(cluster)) == []
        reg.vals["nf_failover_busy_total"] = 3.0
        out = inv.check(_ctx(cluster))
        assert out and "busy_total went backwards" in out[0]


# ------------------------------------ chaos campaign primitives (sat 2+3)
class _Backend:
    """Write-behind StoreBackend seam stand-in."""

    def __init__(self):
        self.data = {}

    def write(self, key, blob):
        self.data[key] = blob

    def delete(self, key):
        self.data.pop(key, None)

    def ping(self):
        return True


class TestChaosCampaignPrimitives:
    def test_store_phase_exposes_op_clock_and_budgets(self):
        d = ChaosDirector(FaultPlan(
            seed=7, stores={"game6.store": StoreFaults(fail_first=2)}))
        store = d.wrap_store(_Backend(), "game6.store")
        for _ in range(2):
            with pytest.raises(StoreFaultError):
                store.write("k", b"v")
        store.write("k", b"v")  # budget consumed: third call lands
        ph = d.store_phase()["game6.store"]
        assert ph["ops_seen"] == 3
        assert ph["fails_injected"] == 2
        assert ph["fail_first_remaining"] == 0
        assert ph["down_active"] is None and ph["down_upcoming"] == []
        # status() carries the phase block (this is what /json mounts)
        assert d.status()["store_phase"]["game6.store"][
            "ops_seen"] == 3

    def test_store_phase_tracks_down_windows(self):
        d = ChaosDirector(FaultPlan(
            seed=7, stores={"game6.store": StoreFaults(down=((2, 4),))}))
        store = d.wrap_store(_Backend(), "game6.store")
        store.write("a", b"1")
        store.write("b", b"2")
        ph = d.store_phase()["game6.store"]
        assert ph["down_active"] == [2, 4]      # op clock sits at 2
        assert ph["down_remaining_ops"] == 2
        for _ in range(2):
            with pytest.raises(StoreFaultError):
                store.write("c", b"3")
        store.write("c", b"3")                   # window passed
        ph = d.store_phase()["game6.store"]
        assert ph["downs_hit"] == 2
        assert ph["down_active"] is None and ph["down_upcoming"] == []

    def test_set_store_faults_rearms_live_wrappers(self):
        d = ChaosDirector(FaultPlan(seed=7))
        store = d.wrap_store(_Backend(), "game6.store")
        store.write("k", b"v")  # no faults armed yet
        assert d.set_store_faults("game6.store",
                                  StoreFaults(fail_first=1)) == 1
        with pytest.raises(StoreFaultError):
            store.write("k", b"v")  # live wrapper re-armed immediately
        # the plan was upserted too: a future re-wrap sees the faults
        assert d.plan.stores["game6.store"].fail_first == 1

    def test_heal_is_idempotent(self):
        d = ChaosDirector(FaultPlan(
            seed=7,
            links={"proxy5.games": LinkFaults(dup=0.5)},
            stores={"game6.store": StoreFaults(fail_first=5)}))
        t = d.wrap(SimpleNamespace(), "proxy5.games->6")
        s = d.wrap_store(_Backend(), "game6.store")
        assert t.faults.any() and s.faults.any()
        assert d.heal("game6.store") == 1   # the store link went clean
        assert not s.faults.any()
        assert t.faults.any()               # pattern-scoped: link kept
        assert d.heal("game6.store") == 0   # idempotent: nothing left
        assert d.heal() == 1                # the transport link
        assert not t.faults.any()
        assert d.heal() == 0
        assert not d.plan.links and not d.plan.stores

    def test_rewrap_does_not_nest_or_reset(self):
        d = ChaosDirector(FaultPlan(
            seed=7, stores={"game6.store": StoreFaults(fail_first=1)}))
        backend = _Backend()
        s1 = d.wrap_store(backend, "game6.store")
        with pytest.raises(StoreFaultError):
            s1.write("k", b"v")
        # revive_role re-runs the chaos hookup on the same pipeline: the
        # guard unwraps instead of nesting, so the shared op clock is
        # not double-advanced
        s2 = d.wrap_store(s1, "game6.store")
        assert s2.inner is backend
        s2.write("k", b"v")  # budget already consumed on the shared counts
        assert d.store_phase()["game6.store"]["ops_seen"] == 2

    def test_consumed_budget_survives_heal_and_rearm(self):
        # the ISSUE 11 satellite: heal() + later re-arm must NOT
        # resurrect a consumed first-N window
        d = ChaosDirector(FaultPlan(
            seed=7, stores={"game6.store": StoreFaults(fail_first=1)}))
        store = d.wrap_store(_Backend(), "game6.store")
        with pytest.raises(StoreFaultError):
            store.write("k", b"v")
        d.heal("game6.store")
        store.write("k", b"v")
        # re-arm the SAME schedule; the fail budget lives in the shared
        # counts, so nothing fires again
        d.set_store_faults("game6.store", StoreFaults(fail_first=1))
        store.write("k", b"v")
        # and a fresh re-wrap (revive path) continues, not restarts
        store2 = d.wrap_store(_Backend(), "game6.store")
        store2.write("k", b"v")
        assert d.store_phase()["game6.store"]["fails_injected"] == 1

    def test_set_link_faults_rearms_live_transports(self):
        d = ChaosDirector(FaultPlan(seed=7))
        t = d.wrap(SimpleNamespace(), "proxy5.games->6")
        assert not t.faults.any()
        assert d.set_link_faults("proxy5.games", LinkFaults(dup=0.9)) == 1
        assert t.faults.dup == 0.9
        assert d.plan.links["proxy5.games"].dup == 0.9


# ----------------------------------------------------- the flagship drill
@pytest.fixture(scope="module")
def gameday():
    return _load_script("gameday_smoke")


class TestGamedayE2E:
    def test_gameday_short_campaign(self, gameday, tmp_path):
        # tier-1 sized: 6 sessions, 3 chats — same campaign shape
        # (store outage ⊃ kill ⊃ surge, heal, revive), ~20 s
        checks = gameday.run(tmp_path, seed=7, sessions=6, chats=3)
        failed = [name for name, ok in checks.items() if not ok]
        assert not failed

    @pytest.mark.slow
    def test_gameday_full_campaign(self, gameday, tmp_path):
        checks = gameday.run(tmp_path, seed=7, sessions=40, chats=5,
                             out_path=tmp_path / "r07_gameday.json")
        failed = [name for name, ok in checks.items() if not ok]
        assert not failed
        blob = json.loads((tmp_path / "r07_gameday.json").read_text())
        assert blob["metric"] == "gameday_sessions_rehomed_per_sec"
        assert blob["detail"]["replay_ok"] is True
        assert blob["detail"]["drill_clean"] is True


# --------------------------------------- the elastic-mesh flagship drill
@pytest.fixture(scope="module")
def reshard():
    return _load_script("reshard_smoke")


class TestReshardE2E:
    def test_reshard_short_campaign(self, reshard, tmp_path):
        # tier-1 sized: 4 sessions, 2 chats — same campaign shape
        # (grow 2→4 under surge, drain a device, heal), ~30 s
        checks = reshard.run(tmp_path, seed=7, sessions=4, chats=2)
        failed = [name for name, ok in checks.items() if not ok]
        assert not failed

    @pytest.mark.slow
    def test_reshard_full_campaign(self, reshard, tmp_path):
        checks = reshard.run(tmp_path, seed=7, sessions=12, chats=4,
                             out_path=tmp_path / "r10_reshard.json")
        failed = [name for name, ok in checks.items() if not ok]
        assert not failed
        blob = json.loads((tmp_path / "r10_reshard.json").read_text())
        assert blob["metric"] == "reshard_gameday_exodus_ticks"
        assert blob["detail"]["drill_clean"] is True


# --------------------------------- many-worlds room actions + isolation
def _room_cluster(log):
    """Forged cluster whose game role records room-action dispatch."""
    role = SimpleNamespace(
        config=SimpleNamespace(name="Game1"),
        create_room=lambda seed, room_id, control: log.append(
            ("create_room", seed, room_id, control)),
        destroy_room=lambda rid: log.append(("destroy_room", rid)),
        rehome_room=lambda rid: log.append(("rehome_room", rid)),
    )
    return SimpleNamespace(
        execute=lambda: log.append(("pump",)),
        chaos=None,
        roles=[role],
    )


class TestRoomActions:
    def test_room_actions_dispatch_with_kwargs(self):
        log = []
        c = (Campaign("t")
             .add(0, "create_room", role="Game1", seed=7, room_id=3,
                  control=True)
             .add(1, "rehome_room", role="Game1", room_id=3)
             .add(2, "destroy_room", role="Game1", room_id=3))
        r = DrillRunner(_room_cluster(log), c, invariants=[],
                        registry=MetricsRegistry())
        for _ in range(3):
            r.step_once()
        assert log == [
            ("create_room", 7, 3, True), ("pump",),
            ("rehome_room", 3), ("pump",),
            ("destroy_room", 3), ("pump",),
        ]

    def test_room_actions_are_builtin(self):
        for action in ("create_room", "destroy_room", "rehome_room"):
            Campaign("t").add(0, action, role="Game1", room_id=1)


def _room_game(digests, controls=(1,), rooms=None, tick=5, calls=None):
    """Forged game role hosting a rooms-directory stand-in.

    ``digests`` maps room_id -> (live, want)."""
    calls = calls if calls is not None else []

    def digest(rid):
        calls.append(("digest", rid))
        return digests[rid][0]

    directory = SimpleNamespace(
        controls={rid: object() for rid in controls},
        rooms=dict(rooms if rooms is not None
                   else {rid: rid for rid in controls}),
        batch=SimpleNamespace(tick_count=tick),
        digest=digest,
        control_digest=lambda rid: digests[rid][1],
    )
    return SimpleNamespace(config=SimpleNamespace(name="Game1"),
                           rooms=directory)


class TestRoomIsolation:
    def test_divergent_room_violates(self):
        game = _room_game({1: (0xAA, 0xAA), 2: (0xDEAD, 0xBEEF)},
                          controls=(1, 2))
        inv = RoomIsolation()
        out = inv.check(_ctx(SimpleNamespace(games=[game])))
        assert len(out) == 1 and "room 2" in out[0]
        assert "cross-room leak" in out[0]

    def test_lockstep_rooms_are_clean_and_roomless_games_skipped(self):
        game = _room_game({1: (0x5150, 0x5150)})
        bare = SimpleNamespace(config=SimpleNamespace(name="Game2"))
        inv = RoomIsolation()
        assert inv.check(_ctx(SimpleNamespace(games=[game, bare]))) == []

    def test_static_batch_is_not_redigested(self):
        calls = []
        game = _room_game({1: (7, 7)}, calls=calls)
        inv = RoomIsolation()
        inv.check(_ctx(SimpleNamespace(games=[game]), tick=0))
        inv.check(_ctx(SimpleNamespace(games=[game]), tick=1))
        assert calls == [("digest", 1)]  # tick_count never moved
        game.rooms.batch.tick_count = 6
        inv.check(_ctx(SimpleNamespace(games=[game]), tick=2))
        assert calls == [("digest", 1), ("digest", 1)]

    def test_sample_every_gates_drill_ticks(self):
        calls = []
        game = _room_game({1: (7, 7)}, calls=calls)
        inv = RoomIsolation(sample_every=4)
        for t in range(4):
            game.rooms.batch.tick_count = 5 + t
            inv.check(_ctx(SimpleNamespace(games=[game]), tick=t))
        assert calls == [("digest", 1)]  # only drill tick 0 sampled

    def test_destroyed_room_with_straggler_control_skipped(self):
        calls = []
        game = _room_game({1: (1, 2)}, controls=(1,), rooms={},
                          calls=calls)
        inv = RoomIsolation()
        assert inv.check(_ctx(SimpleNamespace(games=[game]))) == []
        assert calls == []
