"""Observed tick trains (ISSUE 20): K ticks per dispatch, zero lost history.

``NF_TICK_TRAIN=K`` compiles a ``lax.scan`` over K kernel ticks into ONE
dispatch, scan-stacking every host-consumed per-tick lane ``[K, ...]``
(the ``TRAIN_LANE_SPEC`` contract in ``kernel/kernel.py``).  The spine:

1. digest parity — ``Kernel.train`` over 120 ticks is bit-identical,
   tick by tick, to a single-ticking control for K ∈ {1, 4, 8} and a
   ragged K=7 (120 = 17·7 + 1: the tail rides the plain step);
2. the sharded and many-worlds engines reproduce the same digests
   through their own train dispatches;
3. per-lane host fan-out — an in-trace death at a chosen mid-train tick
   is attributed to EXACTLY that tick's lane (the post-train alive scan
   would pin it to the train's last tick), fires its destroy event once,
   and frees the row;
4. a journaled ``GameRole`` run with ``tick_train=4`` writes one mark
   per stacked frame from the in-lane tick/digest stamps, declares the
   staleness contract in the run meta, and replays digest-clean with
   the knob OFF (one real tick per mark);
5. soak hygiene — train dispatch accounting is exact (⌈n/K⌉), a
   mid-soak ``invalidate()`` is a sanctioned generation bump
   (``unexplained_since`` stays empty), and ``configure_train``
   re-pins K without an unexplained retrace;
6. the trace-time ``_assert_train_lanes`` gate and the StageClock
   per-tick amortization hold up under direct prodding.

``RoomBatch.run``'s refreshed ``last_counters`` regression rides along
(the fused loop used to return the pre-run snapshot).

Tier-1 runs the combined kernel contract test, death attribution, the
rooms run() regression, the role journal/election pair and the
plumbing checks (~80 s); the per-K parity matrix, the invalidate soak
and the sharded/rooms engine parities are ``slow`` (each is its own
world build + scan compile against a shared 1500 s tier-1 wall).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from noahgameframe_tpu.core.store import with_class
from noahgameframe_tpu.game import GameWorld
from noahgameframe_tpu.game.world import WorldConfig
from noahgameframe_tpu.kernel.kernel import (
    TRAIN_LANE_SPEC,
    ObjectEvent,
    _assert_train_lanes,
)
from noahgameframe_tpu.kernel.module import Phase

TICKS = 120


def _recipe(seed=7):
    w = GameWorld(WorldConfig(npc_capacity=32, player_capacity=8,
                              extent=64.0, seed=seed, middleware=False,
                              combat=True, movement=True, regen=True,
                              verlet_skin=2.0))
    w.start()
    w.scene.create_scene(1, width=64.0)
    w.seed_npcs(16, rng=np.random.default_rng(seed + 100))
    w.kernel.enable_digest()
    return w


@pytest.fixture(scope="module")
def control_digests():
    """120 per-tick digests from a single-ticking control world."""
    w = _recipe()
    return [w.kernel.tick().counters["state_digest"] for _ in range(TICKS)]


# ------------------------------------------------------- kernel parity
#
# Tier-1 runs ONE kernel world through the whole contract (parity,
# reconfigure, ragged tail, dispatch accounting, CostBook hygiene) —
# the per-K matrix and the invalidate soak are `slow`: each K is its
# own world build + scan compile (~15 s apiece) and the tier-1 wall
# budget is shared with the rest of the suite.

def test_kernel_train_parity_reconfigure_and_ragged(control_digests):
    """120 ticks bit-identical to the control through a mid-run K
    change (4 -> 7): 10 whole K=4 trains, then 11 K=7 trains + 3
    ragged singles.  The reconfigure drops only the train executable
    (a NEW costbook entry, nothing unexplained), and the in-lane tick
    stamps are the per-tick identity the journal marks use."""
    w = _recipe()
    kern = w.kernel
    kern.configure_train(4)
    outs = kern.train(40)
    mark = kern.costbook.mark()
    kern.configure_train(7)
    outs += kern.train(80)
    assert len(outs) == TICKS
    assert [o.counters["state_digest"] for o in outs] == control_digests
    assert [o.counters["tick"] for o in outs] == list(range(1, TICKS + 1))
    assert kern.tick_count == TICKS
    assert kern.train_dispatches == 40 // 4 + 80 // 7
    assert kern.train_ticks == 40 + 77
    assert kern.train_fetch_bytes > 0
    assert kern.costbook.unexplained_since(mark) == []


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 4, 8, 7])
def test_kernel_train_digest_parity(k, control_digests):
    """train(120) is bit-identical tick-by-tick to the control for
    whole trains (K | 120) and ragged tails (K=7: 17 trains + 1 step)."""
    w = _recipe()
    kern = w.kernel
    kern.configure_train(k)
    outs = kern.train(TICKS)
    assert len(outs) == TICKS
    assert [o.counters["state_digest"] for o in outs] == control_digests
    # in-lane tick stamps are the per-tick identity the journal marks use
    assert [o.counters["tick"] for o in outs] == list(range(1, TICKS + 1))
    assert kern.tick_count == TICKS
    assert kern.train_dispatches == TICKS // k
    assert kern.train_ticks == (TICKS // k) * k
    if k > 1:
        assert kern.train_fetch_bytes > 0


@pytest.mark.slow
def test_train_soak_mid_invalidate_unexplained_clean(control_digests):
    """An invalidate() mid-soak retraces the train under a sanctioned
    generation bump: unexplained_since(mark) stays empty and parity
    holds through the retrace."""
    w = _recipe()
    kern = w.kernel
    kern.configure_train(4)
    digs = [o.counters["state_digest"] for o in kern.train(8)]  # warm
    mark = kern.costbook.mark()
    digs += [o.counters["state_digest"] for o in kern.train(52)]
    kern.invalidate()
    digs += [o.counters["state_digest"] for o in kern.train(60)]
    assert digs == control_digests
    assert kern.costbook.unexplained_since(mark) == []
    assert kern.train_dispatches == TICKS // 4


# -------------------------------------------------- death attribution

def _kill_phase(row, at_tick):
    """In-trace device kill: clear NPC `row`'s alive bit so the death
    lands in output tick `at_tick` (ctx.tick is pre-increment)."""
    def fn(state, ctx):
        cs = state.classes["NPC"]
        hit = ctx.tick == (at_tick - 1)
        alive = cs.alive.at[row].set(
            jnp.where(hit, False, cs.alive[row]))
        return with_class(state, "NPC", cs.replace(alive=alive))
    return fn


def test_train_death_attributed_to_exact_lane():
    """A device kill at tick 6 (lane 1 of the second K=4 train) shows in
    exactly that lane's died mask, frees the row once, and fires the
    destroy hook with the tick-6 guid — the post-train alive scan
    could only have blamed tick 8."""
    wt = _recipe()
    kt = wt.kernel
    row = 0
    guid_t = kt.store._hosts["NPC"].row_guid[row]
    kt.set_phases(list(kt._composed)
                  + [Phase("test.kill", _kill_phase(row, 6), order=999)])

    live_before = kt.store.live_count("NPC")
    kt.configure_train(4)
    destroyed = []
    kt.register_class_event(
        lambda g, cn, ev: destroyed.append((g, int(ev))), "NPC")
    outs_t = kt.train(8)
    died_lanes = [i for i, o in enumerate(outs_t)
                  if np.asarray(o.died["NPC"]).any()]
    assert died_lanes == [5]  # tick 6, not the train boundary at tick 8
    assert np.flatnonzero(np.asarray(outs_t[5].died["NPC"])).tolist() == [row]
    assert guid_t not in kt.store.guid_map
    assert [d for d in destroyed if d[1] == int(ObjectEvent.DESTROY)] \
        == [(guid_t, int(ObjectEvent.DESTROY))]
    assert kt.store.live_count("NPC") == live_before - 1


# ------------------------------------------------------ other engines
#
# The sharded/rooms train parities are `slow` (each is ~20-40 s of
# virtual-device compiles): tier-1 keeps the rooms run() regression
# below, and the committed bench artifact (`bench_runs/
# r13_train_cpu.json`) re-proves rooms train parity over 120 ticks at
# 256 rooms on every regeneration.

@pytest.mark.slow
def test_sharded_train_digest_parity(control_digests):
    from noahgameframe_tpu.parallel.shard import ShardedKernel

    w = _recipe()
    sk = ShardedKernel(w.kernel, n_devices=8)
    sk.place()
    sk.configure_train(4)
    outs = sk.train(30)  # 7 trains + 2 ragged singles
    assert [o.counters["state_digest"] for o in outs] == control_digests[:30]
    assert w.kernel.train_dispatches == 7


@pytest.mark.slow
def test_rooms_train_digest_parity():
    from noahgameframe_tpu.parallel.mesh import ROOMS_AXIS, make_mesh
    from noahgameframe_tpu.parallel.rooms import RoomBatch, RoomBinPacker

    mesh = make_mesh(8, axis=ROOMS_AXIS)
    w = _recipe()
    w.kernel._ensure_aux()

    def build():
        batch = RoomBatch(w.kernel, 16, mesh=mesh)
        packer = RoomBinPacker(batch.capacity, n_blocks=8)
        for i in range(16):
            batch.admit(packer.alloc(), w.kernel.state.replace(
                rng=jax.random.PRNGKey(50 + i)))
        return batch

    b_train, b_ctl = build(), build()
    b_train.configure_train(4)
    lanes = b_train.train(10)  # [10, R, L]: 2 trains + 2 ragged singles
    assert lanes.shape[0] == 10
    assert b_train.train_dispatches == 2
    assert b_train.tick_count == 10
    ctl = [b_ctl.tick() for _ in range(10)]
    for i in range(10):
        c = b_train.kernel.decode_counters(lanes[i])
        assert np.array_equal(c["state_digest"], ctl[i]["state_digest"]), i
        assert np.array_equal(c["tick"], ctl[i]["tick"]), i


def test_rooms_run_refreshes_last_counters():
    """Regression (this PR): the fused run() used to leave last_counters
    at the pre-run snapshot; it must return the FINAL tick's decoded
    row, and run(0) is a no-op.  Single batch: a stale snapshot would
    carry tick stamp 1 (and the tick-1 digests) after run(5)."""
    from noahgameframe_tpu.parallel.mesh import ROOMS_AXIS, make_mesh
    from noahgameframe_tpu.parallel.rooms import RoomBatch, RoomBinPacker

    mesh = make_mesh(8, axis=ROOMS_AXIS)
    w = _recipe()
    w.kernel._ensure_aux()
    batch = RoomBatch(w.kernel, 16, mesh=mesh)
    packer = RoomBinPacker(batch.capacity, n_blocks=8)
    for i in range(16):
        batch.admit(packer.alloc(), w.kernel.state.replace(
            rng=jax.random.PRNGKey(50 + i)))

    c1 = batch.tick()
    assert np.asarray(c1["tick"]).tolist() == [1] * 16
    got = batch.run(5)
    assert np.asarray(got["tick"]).tolist() == [6] * 16
    assert not np.array_equal(got["state_digest"], c1["state_digest"])
    before = batch.tick_count
    again = batch.run(0)
    assert batch.tick_count == before
    assert np.array_equal(again["state_digest"], got["state_digest"])
    assert np.array_equal(again["tick"], got["tick"])


# --------------------------------------------- role journal + replay

def test_role_train_journal_replays_clean(tmp_path):
    """A serving role with tick_train=4 journals one mark PER stacked
    frame (from in-lane tick/digest stamps), declares the K-1 staleness
    contract in the run meta, moves the train metrics, and an offline
    replay with the knob OFF is digest-clean."""
    from noahgameframe_tpu.net.defines import ServerType
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole
    from noahgameframe_tpu.replay import (
        make_offline_role,
        read_ticks,
        replay_journal,
    )
    from noahgameframe_tpu.replay.journal import JournalReader

    def build_world(seed=11):
        w = GameWorld(WorldConfig(npc_capacity=32, player_capacity=8,
                                  extent=64.0, seed=seed, middleware=False,
                                  combat=True, movement=True, regen=True,
                                  verlet_skin=2.0)).start()
        if 1 not in w.scene.scenes:
            w.scene.create_scene(1, width=64.0)
        w.seed_npcs(16, rng=np.random.default_rng(seed + 100))
        return w

    jdir = tmp_path / "journal"
    role = GameRole(
        RoleConfig(6, int(ServerType.GAME), "TrainTest", "127.0.0.1", 0,
                   targets=[]),
        backend="auto", world=build_world(), tick_train=4,
        journal_dir=jdir,
    )
    role.server.send_raw = lambda _conn, _msg, _body: True
    assert role.tick_train == 4
    role.kernel.enable_digest()
    dt = role.game_world.config.dt
    now = 1000.0
    for _ in range(6):  # 6 train frames = 24 journaled ticks
        now += dt + 1e-6
        role.execute(now=now)
    assert role.kernel.tick_count == 24
    reg = role.telemetry.registry
    assert reg.value("nf_train_dispatches_total") == 6
    assert reg.value("nf_train_ticks_total") == 24
    assert reg.value("nf_train_fetch_bytes_total") > 0
    role.shut()

    assert len(read_ticks(jdir)) == 24
    meta = JournalReader(jdir).meta
    assert meta["tick_train"] == 4
    assert meta["serve_staleness_ticks"] == 3

    role2 = make_offline_role(world=build_world())
    role2.kernel.enable_digest()
    try:
        rep = replay_journal(jdir, role=role2)
        assert rep.ticks_replayed == 24
        assert rep.ok
        assert role2.telemetry.registry.value(
            "nf_replay_divergences_total") == 0
    finally:
        role2.shut()


def test_role_train_election_yields_to_overlap():
    """tick_train needs the whole frame budget in one dispatch;
    serve_overlap needs a host window between ticks.  Overlap wins."""
    from noahgameframe_tpu.net.defines import ServerType
    from noahgameframe_tpu.net.roles.base import RoleConfig
    from noahgameframe_tpu.net.roles.game import GameRole

    w = GameWorld(WorldConfig(npc_capacity=32, player_capacity=8,
                              extent=64.0, seed=3, middleware=False,
                              combat=False, movement=False,
                              regen=True)).start()
    role = GameRole(
        RoleConfig(6, int(ServerType.GAME), "Overlap", "127.0.0.1", 0,
                   targets=[]),
        backend="auto", world=w, interest_radius=8.0,
        serve_batch=True, serve_overlap=True, tick_train=8,
    )
    try:
        assert role.tick_train == 0
        assert role.serve_overlap
    finally:
        role.shut()


# ------------------------------------------------- contract plumbing

def test_assert_train_lanes_gates_both_directions():
    ok = {name: None for name in TRAIN_LANE_SPEC}
    _assert_train_lanes(ok)  # exact coverage: quiet
    with pytest.raises(AssertionError, match="unlisted.*aggro"):
        _assert_train_lanes({**ok, "aggro": None})
    short = dict(ok)
    del short["died"]
    with pytest.raises(AssertionError, match="stale.*died"):
        _assert_train_lanes(short)


def test_stage_clock_train_scale_amortizes_histogram_only():
    from noahgameframe_tpu.telemetry.pipeline import StageClock
    from noahgameframe_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    sc = StageClock(registry=reg)
    sc.frame_begin(0)
    sc.add_ns("tick", 8_000_000)  # one 8ms span covering a K=8 train
    sc.set_scale("tick", 8)
    sc.frame_end()
    h = sc._hists["tick"]
    assert h.count == 1
    assert h.sum == pytest.approx(0.001)  # banked PER-TICK: 8ms / 8
    assert sc.last["tick"] == 8_000_000  # waterfall stays exact
    # the divisor is per-frame state: the next plain frame banks 1:1
    sc.frame_begin(1)
    sc.add_ns("tick", 2_000_000)
    sc.frame_end()
    assert h.sum == pytest.approx(0.003)
