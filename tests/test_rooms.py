"""Many-worlds room engine (ISSUE 19): batched rooms are bit-identical
to independent single-room worlds.

The correctness spine, exercised once by a module-scoped scenario and
asserted piecewise:

1. K rooms admitted into one vmapped batch and ticked together digest
   bit-identically, per room, to K lockstep single-room control worlds
   (24 combat+movement+regen ticks in tier-1; the 120-tick churn soak
   is ``slow``-marked);
2. churn — destroy, create into the recycled slot, re-home mid-combat —
   triggers ZERO unexplained recompiles after the warm-up mark (one
   compile per CostBook entry serves every slot, because slot indices
   are traced scalars) and zero dropped rows;
3. re-homing is slot-invariant: the blob walk excludes device placement
   so the digest is unchanged by the move itself, and parity with the
   control holds through subsequent ticks;
4. the cross-engine door: a plain single-world snapshot packs into a
   room blob, admits into a batch slot, and both engines advance to the
   same bytes;
5. growing the batch is a sanctioned generation bump — the retrace is
   explained, and parity survives the widening;
6. blobs fail closed: frame CRC corruption and CRC-valid payload
   tampering (caught by the embedded room digest) are both rejected.

Host-only pieces (bin packer policies, slot exhaustion) need no jax.
"""

import jax
import numpy as np
import pytest

from noahgameframe_tpu.game import GameWorld
from noahgameframe_tpu.game.world import WorldConfig
from noahgameframe_tpu.parallel.rooms import (
    _LEAF_HEADER,
    _ROOM_HEADER,
    RoomBinPacker,
    RoomDirectory,
    RoomSlotsFull,
    pack_room_blob,
    room_digest,
    unpack_room_blob,
)
from noahgameframe_tpu.persist.rowblob import (
    RowBlobError,
    frame_blob,
    unframe_blob,
)


def _recipe(seed):
    w = GameWorld(WorldConfig(npc_capacity=48, player_capacity=8,
                              extent=48.0, seed=seed, middleware=False,
                              combat=True, movement=True, regen=True,
                              verlet_skin=2.0))
    w.start()
    w.scene.create_scene(1, width=48.0)
    w.seed_npcs(16, rng=np.random.default_rng(seed + 100))
    return w


@pytest.fixture(scope="module")
def scenario():
    """One end-to-end choreography; tests assert on the recording."""
    rec = {}
    d = RoomDirectory(_recipe, capacity=8, template_seed=0)
    rooms = [d.create_room(seed=s, control=True) for s in (1, 2, 3)]
    rec["slots0"] = {r: d.slot_of(r) for r in rooms}

    # warm-up compiles every CostBook entry once (admit via create,
    # step/run, extract via digest), then the no-recompile gate arms
    d.run(2)
    d.digest(rooms[0])
    mark = d.batch.costbook.mark()

    d.run(22)  # 24 ticks total — mid-combat by construction
    rec["parity_24"] = {r: (d.digest(r), d.control_digest(r))
                       for r in rooms}

    # churn: destroy room 2, create room 4 (must recycle the slot),
    # then re-home room 1 to a fresh slot mid-combat
    freed = d.destroy_room(rooms[1])
    r4 = d.create_room(seed=9, control=True)
    rec["freed_slot"], rec["recycled_slot"] = freed, d.slot_of(r4)
    src, dst = d.rehome_room(rooms[0])
    rec["rehome"] = (src, dst)
    rec["parity_after_rehome"] = (d.digest(rooms[0]),
                                  d.control_digest(rooms[0]))

    d.run(12)
    live = [rooms[0], rooms[2], r4]
    rec["parity_churn"] = {r: (d.digest(r), d.control_digest(r))
                           for r in live}
    rec["unexplained"] = d.batch.costbook.unexplained_since(mark)
    rec["loads"] = {r: int(np.asarray(
        d.batch.extract(d.slot_of(r)).classes["NPC"].alive).sum())
        for r in live}
    rec["status"] = d.status()

    # grow: sanctioned retrace, parity survives the widening
    mark2 = d.batch.costbook.mark()
    d.grow(16)
    d.run(3)
    rec["grow_unexplained"] = d.batch.costbook.unexplained_since(mark2)
    rec["parity_grow"] = {r: (d.digest(r), d.control_digest(r))
                          for r in live}

    # cross-engine door: single world snapshot -> batch slot, advance 7
    # (batch.run skews the other rooms past their controls, so this
    # segment runs last; the template is copied to host before the
    # donated device buffers are consumed by the final run)
    w = _recipe(77)
    w.kernel._ensure_aux()
    w.kernel.run_device(5, reconcile=False)
    blob = pack_room_blob(w.kernel.state, w.kernel.store.class_order)
    rec["blob"] = blob
    rec["template"] = (
        jax.tree.map(lambda a: np.asarray(a).copy(), w.kernel.state),
        w.kernel.store.class_order)
    slot = d.packer.alloc()
    d.batch.admit_blob(slot, blob)
    d.batch.run(7)
    w.kernel.run_device(7, reconcile=False)
    rec["snapshot_parity"] = (
        d.batch.digest(slot),
        room_digest(w.kernel.state, w.kernel.store.class_order))
    d.packer.free(slot)
    return rec


def test_batched_rooms_match_single_room_controls(scenario):
    for r, (live, want) in scenario["parity_24"].items():
        assert live == want, f"room {r} diverged at tick 24"


def test_destroy_recycles_the_slot(scenario):
    assert scenario["recycled_slot"] == scenario["freed_slot"]


def test_rehome_mid_combat_is_slot_invariant(scenario):
    src, dst = scenario["rehome"]
    assert src != dst
    live, want = scenario["parity_after_rehome"]
    assert live == want, "the move itself changed the room's bytes"


def test_parity_survives_churn(scenario):
    for r, (live, want) in scenario["parity_churn"].items():
        assert live == want, f"room {r} diverged after churn"


def test_churn_causes_zero_unexplained_recompiles(scenario):
    assert scenario["unexplained"] == [], scenario["unexplained"]


def test_zero_dropped_rows_across_rehomes(scenario):
    # every surviving room still carries its 16 seeded npcs (combat in
    # these short runs wounds but does not kill) — nothing stranded
    assert all(n == 16 for n in scenario["loads"].values()), \
        scenario["loads"]


def test_occupancy_status_is_consistent(scenario):
    st = scenario["status"]
    assert st["active"] == len(st["occupancy"]) == 3
    assert st["capacity"] - st["active"] == st["slots_free"]
    assert st["destroyed"] == 1 and st["rehomed"] == 1


def test_cross_engine_snapshot_load(scenario):
    live, want = scenario["snapshot_parity"]
    assert live == want


def test_grow_is_sanctioned_and_preserves_parity(scenario):
    assert scenario["grow_unexplained"] == []
    for r, (live, want) in scenario["parity_grow"].items():
        assert live == want, f"room {r} diverged across grow"


def test_blob_roundtrip_and_fail_closed(scenario):
    blob = scenario["blob"]
    state, class_order = scenario["template"]
    back = unpack_room_blob(blob, state, class_order)
    assert room_digest(back, class_order) == room_digest(state,
                                                         class_order)
    # frame CRC catches a flipped byte
    corrupt = bytearray(blob)
    corrupt[len(corrupt) // 2] ^= 0xFF
    with pytest.raises(RowBlobError):
        unpack_room_blob(bytes(corrupt), state, class_order)
    # CRC-valid tampering (re-framed) is caught by the embedded digest:
    # flip the low byte of the first leaf's DATA (the tick scalar) so
    # every structural check still passes
    payload = bytearray(unframe_blob(blob, allow_legacy=False))
    tick = np.asarray(state.tick)
    off = (_ROOM_HEADER.size + _LEAF_HEADER.size
           + len("tick") + len(tick.dtype.str))
    payload[off] ^= 0x01
    with pytest.raises(RowBlobError, match="digest"):
        unpack_room_blob(frame_blob(bytes(payload)), state, class_order)


# -- host-only: the bin packer ----------------------------------------------


def test_packer_least_loaded_spreads_across_blocks():
    p = RoomBinPacker(8, n_blocks=4)
    slots = [p.alloc(load=1.0) for _ in range(4)]
    assert sorted(p.block_of(s) for s in slots) == [0, 1, 2, 3]
    p.set_load(slots[2], 9.0)
    nxt = p.alloc(load=1.0)
    assert p.block_of(nxt) != p.block_of(slots[2])


def test_packer_first_fit_fills_in_order():
    p = RoomBinPacker(4, n_blocks=2, policy="first-fit")
    assert [p.alloc() for _ in range(4)] == [0, 1, 2, 3]


def test_packer_exhaustion_and_recycle():
    p = RoomBinPacker(2)
    a, b = p.alloc(), p.alloc()
    with pytest.raises(RoomSlotsFull) as ei:
        p.alloc()
    assert ei.value.capacity == 2
    p.free(a)
    assert p.alloc() == a
    assert b == 1


def test_packer_grow_keeps_assignments():
    p = RoomBinPacker(2, n_blocks=2)
    a = p.alloc(load=3.0)
    p.grow(8, n_blocks=4)
    assert p.capacity == 8 and p.used[a]
    with pytest.raises(ValueError):
        p.grow(4)


@pytest.mark.slow
def test_long_churn_soak_stays_bit_identical():
    """120 ticks with churn every 24: create/destroy/re-home mid-run,
    digest parity for every surviving room, zero unexplained."""
    d = RoomDirectory(_recipe, capacity=8, template_seed=0)
    rooms = [d.create_room(seed=s, control=True) for s in (1, 2)]
    d.run(2)
    d.digest(rooms[0])
    src, dst = d.rehome_room(rooms[0])  # warm the re-home path too
    mark = d.batch.costbook.mark()
    next_seed = 10
    for phase in range(5):
        d.run(24)
        if phase % 2 == 0:
            rid = d.create_room(seed=next_seed, control=True)
            rooms.append(rid)
            next_seed += 1
        else:
            d.destroy_room(rooms.pop(0))
            d.rehome_room(rooms[0])
        for r in rooms:
            assert d.digest(r) == d.control_digest(r), \
                f"room {r} diverged at phase {phase}"
    assert d.batch.costbook.unexplained_since(mark) == []
