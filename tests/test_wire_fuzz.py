"""Randomized encode/decode round-trips over the ENTIRE wire surface.

test_wire_protoc.py proves byte-compatibility against protoc for the
reference message set; this fuzz proves the codec itself is symmetric
for every one of the ~96 declared messages (wire.py + wire_families.py),
including deep nesting and repeated fields, across random values and
boundary ints.  Any field a decode drops or mangles fails the equality
check."""

import random
import zlib

import pytest

from noahgameframe_tpu.tools.emit_cpp_sdk import _collect, _is_msg

BOUNDARY_INTS = [0, 1, -1, 127, 128, 2**31 - 1, -(2**31), 2**53, 5]


def _rand_scalar(t: str, rng: random.Random):
    if t in ("int32", "enum"):
        v = rng.choice([0, 1, -1, 127, 2**31 - 1, -(2**31), rng.randint(-9999, 9999)])
        return int(v)
    if t == "int64":
        return rng.choice(BOUNDARY_INTS + [rng.randint(-(2**53), 2**53)])
    if t == "uint64":
        return rng.choice([0, 1, 2**63, 2**64 - 1, rng.randint(0, 2**53)])
    if t == "bool":
        return rng.random() < 0.5
    if t == "float":
        import struct

        # round-trippable f32 values only
        return struct.unpack("<f", struct.pack("<f", rng.uniform(-1e6, 1e6)))[0]
    if t == "double":
        return rng.uniform(-1e12, 1e12)
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 12)))


def _fill(cls, rng: random.Random, depth: int = 0):
    msg = cls()
    for _tag, fname, ftype, _default in cls.FIELDS:
        if isinstance(ftype, tuple):
            inner = ftype[1]
            n = rng.randrange(0, 3 if depth < 2 else 1)
            vals = [
                _fill(inner, rng, depth + 1) if _is_msg(inner)
                else _rand_scalar(inner, rng)
                for _ in range(n)
            ]
            setattr(msg, fname, vals)
        elif _is_msg(ftype):
            if rng.random() < 0.8 and depth < 3:
                setattr(msg, fname, _fill(ftype, rng, depth + 1))
        else:
            if rng.random() < 0.85:
                setattr(msg, fname, _rand_scalar(ftype, rng))
    return msg


@pytest.mark.parametrize("cls", _collect(), ids=lambda c: c.__name__)
def test_roundtrip_fuzz(cls):
    rng = random.Random(zlib.crc32(cls.__name__.encode()))
    for _ in range(8):
        m = _fill(cls, rng)
        raw = m.encode()
        back = cls.decode(raw)
        assert m == back, (cls.__name__, raw.hex())


# ---------------------------------------------------------------- framing
# the chaos layer (net/chaos.py) duplicates, truncates, and corrupts
# message *bodies*; this section pins the framing layer's contract under
# the stream-level equivalents: dup/re-chunked/short streams never crash
# the decoder, and garbage headers fail ONLY with ProtocolError.

from noahgameframe_tpu.net.framing import (  # noqa: E402
    FrameDecoder,
    HEAD_LENGTH,
    ProtocolError,
    pack_frame,
)


def _frames(rng, n=20):
    return [
        (rng.randrange(1, 1000),
         bytes(rng.randrange(256) for _ in range(rng.randrange(0, 64))))
        for _ in range(n)
    ]


def test_frame_duplicates_decode_twice():
    rng = random.Random(1)
    frames = _frames(rng)
    dec = FrameDecoder()
    stream = b"".join(pack_frame(m, b) + pack_frame(m, b) for m, b in frames)
    got = dec.feed(stream)
    want = [f for pair in zip(frames, frames) for f in pair]
    assert got == want


def test_frame_random_chunking_identical():
    rng = random.Random(2)
    frames = _frames(rng)
    stream = b"".join(pack_frame(m, b) for m, b in frames)
    for trial in range(5):
        r = random.Random(100 + trial)
        dec = FrameDecoder()
        got, i = [], 0
        while i < len(stream):
            j = min(len(stream), i + r.randrange(1, 17))
            got.extend(dec.feed(stream[i:j]))
            i = j
        assert got == frames, f"chunking trial {trial}"


def test_frame_truncated_tail_pends_without_crash():
    rng = random.Random(3)
    frames = _frames(rng, n=5)
    stream = b"".join(pack_frame(m, b) for m, b in frames)
    # cut mid-final-frame: everything complete decodes, the tail pends
    cut = len(stream) - len(frames[-1][1]) // 2 - 1
    dec = FrameDecoder()
    assert dec.feed(stream[:cut]) == frames[:-1]
    # the rest of the bytes complete the pending frame
    assert dec.feed(stream[cut:]) == frames[-1:]


def test_frame_corrupt_headers_raise_protocol_error_only():
    rng = random.Random(4)
    for _ in range(200):
        n = rng.randrange(HEAD_LENGTH, 64)
        garbage = bytes(rng.randrange(256) for _ in range(n))
        dec = FrameDecoder()
        try:
            dec.feed(garbage)
        except ProtocolError:
            pass  # the one sanctioned failure mode
        # anything else (struct.error, IndexError, …) fails the test
