"""Model-based randomized test of the SoA entity store.

Runs a few hundred random operations (create/destroy with recycling,
typed property set/get, record add/set/remove/swap) against a plain
Python dict model and checks full agreement after every op batch — the
store is the foundation every layer sits on, so its contract gets the
adversarial treatment, not just example-based tests.

Reference semantics being modeled: NFCKernelModule object map +
NFCProperty/NFCRecord (NFCRecord::AddRow fills the first unused slot
and writes every cell; SwapRowInfo exchanges contents + used flags)."""

import random

import numpy as np
import pytest

from noahgameframe_tpu.core import StoreConfig
from noahgameframe_tpu.core.store import EntityStore

from fixtures import base_registry

PROPS = {
    "Level": ("int", lambda r: r.randint(-5, 99)),
    "EXP": ("int", lambda r: r.randint(0, 10_000)),
    "Name": ("string", lambda r: f"n{r.randint(0, 30)}"),
    "MoveSpeed": ("float", lambda r: float(np.float32(r.uniform(-5, 5)))),
    "Position": (
        "vector3",
        lambda r: tuple(float(np.float32(r.uniform(0, 64))) for _ in range(3)),
    ),
}
REC = "PlayerHero"
REC_COLS = {
    "ConfigID": lambda r: f"cfg{r.randint(0, 9)}",
    "Level": lambda r: r.randint(0, 60),
    "Exp": lambda r: r.randint(0, 999),
}


@pytest.mark.parametrize("seed", [5, 17])
def test_store_agrees_with_model(seed):
    rng = random.Random(seed)
    store = EntityStore(base_registry(), StoreConfig(default_capacity=32))
    state = store.init_state(0)
    live = {}  # guid -> {"props": {...}, "rec": {rec_row: {...} or None}}

    def check():
        assert store.live_count("Player") == len(live)
        for g, m in live.items():
            for pname, want in m["props"].items():
                got = store.get_property(state, g, pname)
                assert got == want, (g, pname, got, want)
            for rr, cells in m["rec"].items():
                for tag, want in cells.items():
                    got = store.record_get(state, g, REC, rr, tag)
                    assert got == want, (g, rr, tag, got, want)

    for step in range(300):
        op = rng.random()
        if op < 0.25 or not live:
            if len(live) >= 30:
                continue
            vals = {p: gen(rng) for p, (_t, gen) in PROPS.items()
                    if rng.random() < 0.7}
            state, guids, _rows = store.create_many(
                state, "Player", 1, values={p: [v] for p, v in vals.items()}
            )
            g = guids[0]
            defaults = {"Level": 0, "EXP": 0, "Name": "", "MoveSpeed": 0.0,
                        "Position": (0.0, 0.0, 0.0)}
            live[g] = {"props": {**defaults, **vals}, "rec": {}}
        elif op < 0.35:
            g = rng.choice(list(live))
            state = store.destroy_object(state, g)
            del live[g]
        elif op < 0.65:
            g = rng.choice(list(live))
            pname = rng.choice(list(PROPS))
            v = PROPS[pname][1](rng)
            state = store.set_property(state, g, pname, v)
            live[g]["props"][pname] = v
        elif op < 0.8:
            g = rng.choice(list(live))
            m = live[g]["rec"]
            if len(m) >= 8:
                continue
            cells = {t: gen(rng) for t, gen in REC_COLS.items()
                     if rng.random() < 0.8}
            state, rr = store.record_add_row(state, g, REC, cells)
            full = {"GUID": None, "ConfigID": "", "Level": 0, "Exp": 0}
            full.update(cells)
            full.pop("GUID")  # object cells compare via handles; skip
            m[rr] = full
        elif op < 0.9:
            g = rng.choice(list(live))
            m = live[g]["rec"]
            if not m:
                continue
            rr = rng.choice(list(m))
            if rng.random() < 0.5:
                state = store.record_remove_row(state, g, REC, rr)
                del m[rr]
            else:
                tag = rng.choice(list(REC_COLS))
                v = REC_COLS[tag](rng)
                state = store.record_set(state, g, REC, rr, tag, v)
                m[rr][tag] = v
        else:
            g = rng.choice(list(live))
            m = live[g]["rec"]
            a, b = rng.randrange(8), rng.randrange(8)
            state = store.record_swap_rows(state, g, REC, a, b)
            ra, rb = m.pop(a, None), m.pop(b, None)
            if rb is not None:
                m[a] = rb
            if ra is not None:
                m[b] = ra
        if step % 25 == 0:
            check()
    check()
