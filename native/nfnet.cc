// nfnet: native epoll TCP runtime for the noahgameframe_tpu network edge.
//
// TPU-native replacement for the reference's libevent stack
// (NFComm/NFNet/NFCNet.cpp): same pump contract (poll once per main-loop
// tick, no threads touch game state), same 6-byte frame layout
// (big-endian u16 msgID + u32 total size incl. header,
// NFComm/NFNet/NFINet.h:168-233), exposed through a flat C API consumed
// from Python via ctypes (no pybind11 in the image).
//
// Event model: poll() performs all ready I/O and stages an event list
// (CONNECTED / DISCONNECTED / MSG) that the caller walks with accessor
// functions; bodies live in an arena valid until the next poll().

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kHeadLen = 6;
constexpr uint32_t kMaxFrame = 64u * 1024u * 1024u;
constexpr int kEvConnected = 1;
constexpr int kEvDisconnected = 2;
constexpr int kEvMsg = 3;

struct Event {
  int kind;
  int conn_id;
  int msg_id;
  size_t body_off;
  uint32_t body_len;
};

struct Conn {
  int fd = -1;
  bool connecting = false;
  std::string inbuf;
  std::string outbuf;
};

int set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags < 0 ? -1 : fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

}  // namespace

struct nfnet {
  int epfd = -1;
  int listen_fd = -1;  // servers only
  int listen_port = 0;
  std::string client_host;  // clients only
  int client_port = 0;
  int next_id = 1;
  std::unordered_map<int, Conn> conns;
  std::unordered_map<int, int> fd2id;
  std::vector<Event> events;
  std::string arena;  // MSG bodies for the current event batch

  ~nfnet() {
    for (auto& kv : conns) close(kv.second.fd);
    if (listen_fd >= 0) close(listen_fd);
    if (epfd >= 0) close(epfd);
  }

  void watch(int fd, bool want_write) {
    epoll_event ev{};
    ev.events = EPOLLIN | (want_write ? static_cast<uint32_t>(EPOLLOUT) : 0u);
    ev.data.fd = fd;
    epoll_ctl(epfd, EPOLL_CTL_MOD, fd, &ev);
  }

  int add_conn(int fd, bool connecting) {
    int id = next_id++;
    Conn& c = conns[id];
    c.fd = fd;
    c.connecting = connecting;
    fd2id[fd] = id;
    epoll_event ev{};
    ev.events = EPOLLIN | (connecting ? static_cast<uint32_t>(EPOLLOUT) : 0u);
    ev.data.fd = fd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
    return id;
  }

  void drop_conn(int id, bool notify) {
    auto it = conns.find(id);
    if (it == conns.end()) return;
    epoll_ctl(epfd, EPOLL_CTL_DEL, it->second.fd, nullptr);
    fd2id.erase(it->second.fd);
    close(it->second.fd);
    conns.erase(it);
    if (notify) events.push_back({kEvDisconnected, id, 0, 0, 0});
  }

  void extract_frames(int id, Conn& c) {
    size_t off = 0;
    const std::string& buf = c.inbuf;
    while (buf.size() - off >= kHeadLen) {
      uint16_t msg_id;
      uint32_t total;
      memcpy(&msg_id, buf.data() + off, 2);
      memcpy(&total, buf.data() + off + 2, 4);
      msg_id = ntohs(msg_id);
      total = ntohl(total);
      if (total < kHeadLen || total > kMaxFrame) {
        drop_conn(id, true);
        return;
      }
      if (buf.size() - off < total) break;
      uint32_t body_len = total - kHeadLen;
      events.push_back({kEvMsg, id, msg_id, arena.size(), body_len});
      arena.append(buf, off + kHeadLen, body_len);
      off += total;
    }
    if (off) c.inbuf.erase(0, off);
  }

  void pump_conn(int id, uint32_t evmask) {
    auto it = conns.find(id);
    if (it == conns.end()) return;
    Conn& c = it->second;
    if (c.connecting && (evmask & (EPOLLOUT | EPOLLERR | EPOLLHUP))) {
      int err = 0;
      socklen_t len = sizeof(err);
      getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &len);
      if (err != 0 || (evmask & (EPOLLERR | EPOLLHUP))) {
        drop_conn(id, true);
        return;
      }
      c.connecting = false;
      events.push_back({kEvConnected, id, 0, 0, 0});
      watch(c.fd, !c.outbuf.empty());
    }
    if (evmask & EPOLLIN) {
      char tmp[256 * 1024];
      for (;;) {
        ssize_t n = recv(c.fd, tmp, sizeof(tmp), 0);
        if (n > 0) {
          c.inbuf.append(tmp, static_cast<size_t>(n));
          if (static_cast<size_t>(n) < sizeof(tmp)) break;
        } else if (n == 0) {
          drop_conn(id, true);
          return;
        } else {
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          drop_conn(id, true);
          return;
        }
      }
      extract_frames(id, c);
      if (conns.find(id) == conns.end()) return;  // dropped on bad frame
    }
    if ((evmask & EPOLLOUT) && !c.connecting && !c.outbuf.empty()) {
      ssize_t n = send(c.fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
      if (n > 0) {
        c.outbuf.erase(0, static_cast<size_t>(n));
      } else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
        drop_conn(id, true);
        return;
      }
      watch(c.fd, !c.outbuf.empty());
    }
    if (evmask & (EPOLLERR | EPOLLHUP)) drop_conn(id, true);
  }
};

extern "C" {

nfnet* nfnet_server_create(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return nullptr;
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      listen(fd, 512) < 0 || set_nonblock(fd) < 0) {
    close(fd);
    return nullptr;
  }
  sockaddr_in bound{};
  socklen_t blen = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen);

  nfnet* h = new nfnet();
  h->epfd = epoll_create1(0);
  h->listen_fd = fd;
  h->listen_port = ntohs(bound.sin_port);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = fd;
  epoll_ctl(h->epfd, EPOLL_CTL_ADD, fd, &ev);
  return h;
}

nfnet* nfnet_client_create(const char* host, int port) {
  nfnet* h = new nfnet();
  h->epfd = epoll_create1(0);
  h->client_host = host;
  h->client_port = port;
  return h;
}

// Begin a non-blocking connect; CONNECTED/DISCONNECTED arrives via poll.
// Returns the conn id, or -1.
int nfnet_client_connect(nfnet* h) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  set_nonblock(fd);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(h->client_port));
  if (inet_pton(AF_INET, h->client_host.c_str(), &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  int rc = connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc < 0 && errno != EINPROGRESS) {
    close(fd);
    return -1;
  }
  return h->add_conn(fd, rc != 0);
}

int nfnet_server_port(nfnet* h) { return h->listen_port; }
int nfnet_num_conns(nfnet* h) { return static_cast<int>(h->conns.size()); }

int nfnet_poll(nfnet* h) {
  h->events.clear();
  h->arena.clear();
  epoll_event evs[256];
  int n = epoll_wait(h->epfd, evs, 256, 0);
  for (int i = 0; i < n; ++i) {
    int fd = evs[i].data.fd;
    if (fd == h->listen_fd) {
      for (;;) {
        int cfd = accept(h->listen_fd, nullptr, nullptr);
        if (cfd < 0) break;
        set_nonblock(cfd);
        int one = 1;
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        int id = h->add_conn(cfd, false);
        h->events.push_back({kEvConnected, id, 0, 0, 0});
      }
    } else {
      auto it = h->fd2id.find(fd);
      if (it != h->fd2id.end()) h->pump_conn(it->second, evs[i].events);
    }
  }
  return static_cast<int>(h->events.size());
}

int nfnet_event_kind(nfnet* h, int i) { return h->events[i].kind; }
int nfnet_event_conn(nfnet* h, int i) { return h->events[i].conn_id; }
int nfnet_event_msgid(nfnet* h, int i) { return h->events[i].msg_id; }

const char* nfnet_event_body(nfnet* h, int i, uint32_t* len) {
  *len = h->events[i].body_len;
  return h->arena.data() + h->events[i].body_off;
}

int nfnet_send(nfnet* h, int conn_id, int msg_id, const char* data,
               uint32_t len) {
  auto it = h->conns.find(conn_id);
  if (it == h->conns.end()) return 0;
  Conn& c = it->second;
  char head[kHeadLen];
  uint16_t mid = htons(static_cast<uint16_t>(msg_id));
  uint32_t total = htonl(len + kHeadLen);
  memcpy(head, &mid, 2);
  memcpy(head + 2, &total, 4);
  c.outbuf.append(head, kHeadLen);
  c.outbuf.append(data, len);
  if (!c.connecting) {
    // opportunistic immediate flush, then epoll for the rest
    ssize_t n = send(c.fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
    if (n > 0) c.outbuf.erase(0, static_cast<size_t>(n));
    h->watch(c.fd, !c.outbuf.empty());
  }
  return 1;
}

void nfnet_close_conn(nfnet* h, int conn_id) { h->drop_conn(conn_id, false); }
void nfnet_destroy(nfnet* h) { delete h; }

}  // extern "C"
