"""Tutorial 1 — plugin & module lifecycle.

Mirrors the reference's Tutorial1 (`Tutorial/Tutorial1/HelloWorld1.cpp`):
a plugin registers one module; the plugin manager drives the 9-phase
lifecycle (awake → init → after_init → check_config → ready_execute →
execute… → before_shut → shut) and the module logs each phase.

Run:  python examples/tutorial1_lifecycle.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.kernel import Module, Plugin, PluginManager


class HelloWorldModule(Module):
    name = "HelloWorldModule"

    def awake(self) -> None:
        print("HelloWorld awake")

    def init(self) -> None:
        print("HelloWorld init")

    def after_init(self) -> None:
        print("HelloWorld after_init")

    def ready_execute(self) -> None:
        print("HelloWorld ready_execute")

    def execute(self) -> None:
        print(f"HelloWorld execute (frame {self.pm.frame})")

    def before_shut(self) -> None:
        print("HelloWorld before_shut")

    def shut(self) -> None:
        print("HelloWorld shut")


def create_plugin(pm: PluginManager) -> Plugin:
    """The DllStartPlugin/CREATE_PLUGIN equivalent: a module exposing
    create_plugin() is loadable from a Plugin.xml manifest too."""
    m = HelloWorldModule()
    m.pm = pm
    return Plugin("HelloWorldPlugin", [m])


def main() -> None:
    pm = PluginManager(app_id=1, app_name="Tutorial1")
    pm.register_plugin(create_plugin(pm))
    pm.start()
    pm.run(3)
    pm.shutdown()
    print("tutorial1 done")


if __name__ == "__main__":
    main()
