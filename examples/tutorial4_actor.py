"""Tutorial 4 — the actor model: offload work, marshal results back.

Mirrors the reference's Tutorial4 blurb ("use multiple cpus"): spawn an
actor with a component, post messages from the main loop, and receive
results back on the main thread during `execute()` — game state is only
ever touched from the main loop.

Run:  python examples/tutorial4_actor.py
"""

from __future__ import annotations

import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.kernel import ActorComponent, ActorModule

MSG_HEAVY_MATH = 1


def main() -> None:
    actors = ActorModule(threads=2)

    comp = ActorComponent()

    def heavy_math(_msg_id: int, n: int) -> int:
        time.sleep(0.01)  # pretend this is expensive IO / crunching
        return sum(i * i for i in range(n))

    comp.on(MSG_HEAVY_MATH, heavy_math)
    actor_id = actors.require_actor(comp)

    main_thread = threading.get_ident()
    results = []

    def on_done(aid: int, msg_id: int, result) -> None:
        assert threading.get_ident() == main_thread, "must run on main loop"
        results.append(result)
        print(f"  result from actor {aid}: {result}")

    print("posting 3 jobs to the actor…")
    for n in (10, 100, 1000):
        actors.send_to_actor(actor_id, MSG_HEAVY_MATH, n, on_done)

    # the main loop: pump until all results are marshalled back
    while len(results) < 3:
        actors.execute()
        time.sleep(0.001)

    actors.shut()
    print("tutorial4 done")


if __name__ == "__main__":
    main()
