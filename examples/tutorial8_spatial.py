"""Tutorial 8 — spatial slab sharding: migration and halos.

Tutorial 5 shards the ENTITY axis and lets XLA partition the cell-table
sort into cross-shard collectives.  This tutorial shows the second
strategy (`parallel/spatial.py`): partition SPACE into per-shard slabs,
keep the sort shard-local, exchange one dense attacker halo plane with
each neighbor via `lax.ppermute`, and MIGRATE entities between shard
banks when their cell crosses a slab boundary — the compiled-collective
analog of the reference re-homing a player to another game server
through the World relay (NFCGSSwichServerModule / NFCWorldNet_Server).

Runs on a virtual 4-device CPU mesh so it works anywhere:

Run:  python examples/tutorial8_spatial.py
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from noahgameframe_tpu.parallel import SpatialGeom, SpatialWorld


def main() -> None:
    geom = SpatialGeom(
        extent=128.0, cell_size=4.0, width=32, n_shards=4,
        bucket=24, att_bucket=24, radius=4.0, mig_budget=256,
        speed=1.5, attack_period=3,
        regen_per_tick=1, hp_max=80, respawn_ticks=10,
    )
    rng = np.random.default_rng(7)
    n = 2000
    world = SpatialWorld(geom)
    world.place(
        rng.uniform(1.0, geom.extent - 1.0, (n, 2)).astype(np.float32),
        np.full(n, 80, np.int32),
        rng.integers(5, 20, n).astype(np.int32),
        (np.arange(n) % 2).astype(np.int32),
    )
    print(f"{n} entities over {geom.n_shards} slabs "
          f"({geom.slab_h} cell rows each), bank={world.bank_size}")

    for burst in range(5):
        world.step(10)
        mig, over, drop, misp, vdrop, adrop = world.stats_last.sum(axis=0)
        got = world.gather()
        dead = sum(1 for _, (_, _, h) in got.items() if h == 0)
        print(
            f"tick {world.tick_count:3d}: migrated={mig:4d}/tick "
            f"dead={dead:4d} overflow={over + drop + misp + vdrop + adrop}"
        )

    # every entity still exists exactly once, wherever it wandered
    assert len(world.gather()) == n
    print("population conserved across all migrations - OK")


if __name__ == "__main__":
    main()
