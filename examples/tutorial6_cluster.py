"""Tutorial 6: the five-role cluster, end to end, in one process.

Boots master/login/world/proxy/game on loopback sockets (LocalCluster),
then drives a real client through the full reference login pipeline —
login -> world list -> select world -> proxy connect-key -> select game
server -> create role -> enter game — and finally moves and chats, with
the client's object mirror converging from the server's per-frame
property sync (the §3.3 spine).

Reference parity: the _Out/Tester rund_*.sh bring-up plus the
NFClient login flow (NFCLoginNet_ServerModule::OnLoginProcess,
NFCProxyServerNet_ServerModule::OnConnectKeyProcess,
NFCGameServerNet_ServerModule::OnClienEnterGameProcess).

Run:  python examples/tutorial6_cluster.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

# a control-plane demo: tiny worlds, lots of socket pumping — the CPU
# backend starts instantly and never contends for the one shared chip
jax.config.update("jax_platforms", "cpu")

from noahgameframe_tpu.client import GameClient
from noahgameframe_tpu.game import GameWorld, WorldConfig
from noahgameframe_tpu.net.roles import LocalCluster


def pump(cluster, client, cond, timeout=10.0):
    ok = cluster.pump_until(cond, extra=client.execute, timeout=timeout)
    if not ok:
        raise TimeoutError(f"cluster timed out waiting for {cond}")


def main() -> None:
    world = GameWorld(
        WorldConfig(combat=False, movement=False, regen=True,
                    npc_capacity=64, player_capacity=16)
    ).start()
    cluster = LocalCluster(http_port=0, game_world=world)
    cluster.start(timeout=20.0)
    print("cluster up:", sorted(cluster.master.servers_status()["servers"]))

    c = GameClient("tutorial6")
    c.connect("127.0.0.1", cluster.login.config.port)
    pump(cluster, c, lambda: c.connected)
    c.login()
    pump(cluster, c, lambda: c.logged_in)
    c.request_world_list()
    pump(cluster, c, lambda: c.worlds)
    c.connect_world(c.worlds[0].server_id)
    pump(cluster, c, lambda: c.world_grant is not None)
    c.connect_proxy()
    pump(cluster, c, lambda: c.connected)
    c.verify_key()
    pump(cluster, c, lambda: c.key_verified)
    c.select_server(cluster.game.config.server_id)
    pump(cluster, c, lambda: c.server_selected)
    c.create_role("Hero6")
    pump(cluster, c, lambda: c.roles)
    c.enter_game("Hero6")
    pump(cluster, c, lambda: c.entered)
    print("entered game; avatar guid:", c.player_guid)

    # move: the server's per-frame diff flush lands in the client mirror
    key = (c.player_guid.svrid, c.player_guid.index)
    c.move_to(12.0, 34.0, 0.0)
    pump(cluster, c, lambda: (
        key in c.objects
        and c.objects[key].properties.get("Position", (0, 0, 0))[0] == 12.0
    ))
    print("mirror position:", c.objects[key].properties["Position"])

    c.chat("hello from tutorial 6")
    pump(cluster, c, lambda: c.chat_log)
    print("chat echoed:", c.chat_log[-1][1])

    cluster.shut()
    print("tutorial6 done")


if __name__ == "__main__":
    main()
