"""Tutorial 5 — shard one world over a device mesh.

The reference scales by running more server processes and splitting
players across them by consistent hash (SURVEY §2.5).  The TPU build
scales the SAME world over more chips instead: every entity bank shards
its capacity axis across a `jax.sharding.Mesh`, the compiled tick runs
SPMD, and XLA inserts the cross-shard collectives (combat reads across
shard boundaries through the cell table — no relay server, no resharding
logic in user code).

This tutorial runs on a virtual 4-device CPU mesh so it works anywhere:

Run:  python examples/tutorial5_sharded_world.py
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from noahgameframe_tpu.game import GameWorld, WorldConfig
from noahgameframe_tpu.parallel import ShardedKernel


def main() -> None:
    n_dev = min(4, len(jax.devices()))
    world = GameWorld(
        WorldConfig(npc_capacity=1024, player_capacity=64, extent=128.0,
                    attack_period_s=0.2, middleware=False)
    )
    world.start()
    world.scene.create_scene(1, width=128.0)
    world.seed_npcs(800, camps=2)

    sk = ShardedKernel(world.kernel, n_devices=n_dev)
    sk.place()  # move the world onto the mesh
    print(f"mesh: {sk.mesh.shape} over {n_dev} devices")

    npc = world.kernel.state.classes["NPC"]
    print("i32 bank sharding:", npc.i32.sharding)

    sk.run_device(60)  # fused 60-tick SPMD loop, zero host syncs

    hp = np.asarray(world.kernel.store.column(world.kernel.state, "NPC", "HP"))
    alive = np.asarray(world.kernel.state.classes["NPC"].alive)
    print(f"alive: {alive.sum()}  damaged: {(hp[alive] < 100).sum()} "
          f"(combat crossed shard boundaries)")
    assert (hp[alive] < 100).any()
    print("done")


if __name__ == "__main__":
    main()
