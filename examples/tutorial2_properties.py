"""Tutorial 2 — data-driven entities: properties, records, elements.

Mirrors the reference's Tutorial2: define a class schema, create an
object, read/write typed properties and table records, seed from element
config.  Here the schema compiles to device SoA banks, but the host API
keeps the reference's shape (`SetPropertyInt`/`GetPropertyInt` become
`set_property`/`get_property`).

Run:  python examples/tutorial2_properties.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from noahgameframe_tpu.core.schema import ClassDef, ClassRegistry, prop, record
from noahgameframe_tpu.core.store import StoreConfig
from noahgameframe_tpu.kernel import Kernel, Plugin, PluginManager


def build_registry() -> ClassRegistry:
    reg = ClassRegistry()
    reg.define(ClassDef(
        name="IObject",
        properties=[
            prop("ID", "string", private=True),
            prop("SceneID", "int", private=True),
            prop("GroupID", "int", private=True),
        ],
    ))
    reg.define(ClassDef(
        name="Knight",
        parent="IObject",
        properties=[
            prop("Name", "string", public=True, save=True),
            prop("HP", "int", public=True, save=True),
            prop("Speed", "float", public=True),
            prop("Home", "vector3", private=True),
        ],
        records=[record("KillLog", 8, [("Victim", "string"), ("Count", "int")],
                        private=True)],
    ))
    return reg


def main() -> None:
    kernel = Kernel(build_registry(), StoreConfig(default_capacity=16))
    pm = PluginManager(app_name="Tutorial2")
    pm.register_plugin(Plugin("KernelPlugin", [kernel]))
    pm.start()

    g = kernel.create_object("Knight", {"Name": "Lancelot", "HP": 120,
                                        "Speed": 1.5, "Home": (1.0, 2.0, 0.0)})
    print("Name:", kernel.get_property(g, "Name"))
    print("HP:", kernel.get_property(g, "HP"))
    kernel.set_property(g, "HP", 95)
    print("HP after hit:", kernel.get_property(g, "HP"))
    print("Home:", kernel.get_property(g, "Home"))

    # records: AddRow / SetInt / FindRowsByTag parity
    store = kernel.store
    kernel.state, row = store.record_add_row(
        kernel.state, g, "KillLog", {"Victim": "goblin", "Count": 3})
    kernel.state = store.record_set(kernel.state, g, "KillLog", row, "Count", 4)
    print("KillLog[goblin] =", store.record_get(
        kernel.state, g, "KillLog", row, "Count"))
    print("rows for goblin:", store.record_find_rows(
        kernel.state, g, "KillLog", "Victim", "goblin"))

    # property-change subscription (per-write host callbacks)
    kernel.register_property_event(
        "Knight", "HP",
        lambda cname, pname, rows: print(f"HP changed on rows {rows}"))
    kernel.set_property(g, "HP", 90)
    pm.run(1)
    pm.shutdown()
    print("tutorial2 done")


if __name__ == "__main__":
    main()
