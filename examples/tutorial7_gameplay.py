"""Tutorial 7: the gameplay middleware in one sitting — items, gems,
hero line-up, SLG city building, and social persistence.

Builds a standard GameWorld, then walks the round-5 gameplay surface:

1. consume-process families: an equip token materializes into the bag,
   a gem sockets into it (stats fold while worn), a hero card joins the
   collection, an EXP tome levels a targeted hero;
2. the battle line-up: two heroes at two fight positions, their config
   stats x level folded into the owner's EQUIP_AWARD stat group by the
   per-tick recompute;
3. SLG city: buy a building from the shop (level gate + Gold/Diamond
   cost), upgrade it on a timer, queue production, collect accrued
   resources;
4. social persistence: mail and guild state written through a KV agent
   survive a simulated process restart WITHOUT a world checkpoint.

Reference parity: NFCItemModule + the consume family, NFCHeroModule,
NFCSLGBuildingModule/NFCSLGShopModule, NFDataAgent_NosqlPlugin.

Run:  python examples/tutorial7_gameplay.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

from noahgameframe_tpu.game import (
    EShopType,
    GameWorld,
    ItemSubType,
    ItemType,
    PropertyGroup,
    SLGBuildingState,
    WorldConfig,
)
from noahgameframe_tpu.persist import MemoryKV, SocialDataAgent


def build_world() -> GameWorld:
    # dt=1.0 so one tick == one second of SLG-timer time
    w = GameWorld(WorldConfig(combat=False, movement=False, regen=False,
                              npc_capacity=64, player_capacity=8,
                              dt=1.0)).start()
    w.scene.create_scene(1)
    e = w.kernel.elements
    # item catalogue (Item.xlsx rows)
    e.add_element("Item", "blade", {"ItemType": int(ItemType.EQUIP),
                                    "ATK_VALUE": 9})
    e.add_element("Item", "ruby", {"ItemType": int(ItemType.GEM),
                                   "ATK_VALUE": 3})
    e.add_element("Item", "hero_mage", {"ItemType": int(ItemType.CARD),
                                        "ATK_VALUE": 4,
                                        "Skill1": "fireball_1"})
    e.add_element("Item", "tome", {"ItemType": int(ItemType.ITEM),
                                   "ItemSubType": int(ItemSubType.EXP),
                                   "AwardValue": 250})
    e.add_element("Skill", "fireball_1", {"AfterUpID": "fireball_2"})
    e.add_element("Skill", "fireball_2", {})
    # SLG catalogue
    e.add_element("Building", "farm", {"Type": 3, "ItemID": "bread",
                                       "ProduceTime": 2})
    e.add_element("Item", "bread", {"ItemType": int(ItemType.ITEM)})
    e.add_element("Shop", "shop_farm", {"Type": int(EShopType.BUILDING),
                                        "Level": 1, "Gold": 50,
                                        "ItemID": "farm"})
    return w


def main() -> None:
    kv = MemoryKV()
    w = build_world()
    SocialDataAgent(kv).bind(w.kernel, mail=w.mail, rank=w.rank,
                             guilds=w.guilds)
    k = w.kernel
    p = k.create_object("Player", {"Name": "Ada", "Account": "ada"},
                        scene=1, group=0)
    k.set_property(p, "Level", 3)
    k.set_property(p, "Gold", 500)
    k.set_property(p, "Diamond", 10)

    # 1 — items and gems
    w.pack.create_item(p, "blade", 1)
    assert w.items.use_item(p, "blade")  # EQUIP family -> BagEquipList
    equip_row = next(iter(w.pack.equips(p)))
    w.pack.create_item(p, "ruby", 1)
    assert w.items.use_item(p, "ruby", target=equip_row)  # socket the gem
    w.equip.wear(p, equip_row)
    atk = w.properties.get_group_value(p, "ATK_VALUE", PropertyGroup.EQUIP)
    print(f"worn blade + ruby -> EQUIP ATK {atk}")  # 9 + 3

    # 2 — heroes
    w.pack.create_item(p, "hero_mage", 1)
    assert w.items.use_item(p, "hero_mage")  # CARD family -> collection
    row = w.heroes.hero_row_of(p, "hero_mage")
    w.pack.create_item(p, "tome", 1)
    assert w.items.use_item(p, "tome", target=row)  # 250 exp -> level 2
    assert w.heroes.hero_skill_up(p, row, 1)  # fireball_1 -> fireball_2
    w.heroes.set_fight_hero(p, row, pos=0)
    award = w.properties.get_group_value(p, "ATK_VALUE",
                                         PropertyGroup.EQUIP_AWARD)
    print(f"fight hero level {w.heroes.hero_level(p, row)} -> "
          f"EQUIP_AWARD ATK {award}")  # 4 x 2

    # 3 — SLG city
    assert w.slg_shop.buy(p, "shop_farm", x=3, y=4)
    brow = next(iter(w.slg_building.buildings(p)))
    b = w.slg_building
    b.upgrade_s = 3
    assert b.upgrade(p, brow)
    for _ in range(4):
        w.tick()  # dt=1.0: each tick is one SLG second
    print(f"farm upgraded to level {b.building_level(p, brow)}, "
          f"state {SLGBuildingState(b.building_state(p, brow)).name}")
    assert b.produce(p, brow, "bread", 2)
    for _ in range(5):
        w.tick()
    print(f"bread produced: {w.pack.item_count(p, 'bread')}")

    # 4 — social persistence across a "process restart"
    w.mail.send("ada", "system", "Welcome!", gold=25)
    w.guilds.create_guild(p, "Pioneers")
    w2 = build_world()
    SocialDataAgent(kv).bind(w2.kernel, mail=w2.mail, rank=w2.rank,
                             guilds=w2.guilds)
    p2 = w2.kernel.create_object("Player", {"Name": "Ada",
                                            "Account": "ada"},
                                 scene=1, group=0)
    box = w2.mail.mailbox("ada")
    guild = w2.guilds.find_by_name("Pioneers")
    print(f"after restart: {len(box)} mail, guild "
          f"{guild.name!r} relinked={p2 in guild.members}")
    print("tutorial 7 done")


if __name__ == "__main__":
    main()
