"""Plugin template — the NFMidWare-style extension point.

The reference ships 11 nearly-identical middleware plugin skeletons
(`NFMidWare/`, SURVEY §2.9); this file is the equivalent template for
this framework.  Copy it, rename the module, and either:

- register it programmatically:   pm.register_plugin(create_plugin(pm))
- or list it in a Plugin.xml manifest and call pm.load_manifest(path):
      <XML><Plugin Name="my_game.my_plugin"/></XML>

A module can hook the world three ways, shown below:
1. host lifecycle + per-frame `execute()` (control plane),
2. kernel events/property subscriptions (reactive),
3. a device phase fused into the jitted tick (data plane).
"""

from __future__ import annotations

import jax.numpy as jnp

from noahgameframe_tpu.core.store import WorldState, with_class
from noahgameframe_tpu.kernel import Module, Plugin, PluginManager


class MyGameplayModule(Module):
    name = "MyGameplayModule"

    def __init__(self, drain_per_tick: int = 1) -> None:
        super().__init__()
        self.drain_per_tick = drain_per_tick
        # (3) a device phase: runs inside the compiled tick, vectorized
        # over every entity.  Order picks its slot in the phase chain
        # (movement=30..50, combat=40, buffs=55, stat recompute=60).
        self.add_phase("mp_drain", self._drain_phase, order=58)

    # -- (1) host lifecycle ------------------------------------------------
    def init(self) -> None:
        # declare timers/schemas here; cross-module lookups via
        # self.kernel or a PluginManager.find_module(...) in after_init
        pass

    def after_init(self) -> None:
        # (2) reactive hooks: class events + property subscriptions
        self.kernel.register_property_event(
            "Player", "MP", self._on_mp_changed
        )

    def execute(self) -> None:
        # per-frame host work (network, persistence drains) — keep light
        pass

    # -- handlers ----------------------------------------------------------
    def _on_mp_changed(self, cname: str, pname: str, rows) -> None:
        # rows: numpy indices of entities whose MP changed this frame
        pass

    # -- the device phase --------------------------------------------------
    def _drain_phase(self, state: WorldState, ctx) -> WorldState:
        """Example: every entity loses `drain_per_tick` MP per tick,
        floored at 0 — one fused vector op for the whole class."""
        cname = "Player"
        if cname not in ctx.store.class_index:
            return state
        spec = ctx.store.spec(cname)
        if not spec.has_property("MP"):
            return state
        cs = state.classes[cname]
        col = spec.slot("MP").col
        mp = cs.i32[:, col]
        new_mp = jnp.maximum(mp - self.drain_per_tick, 0)
        # only touch live rows; dead rows keep their values
        new_mp = jnp.where(cs.alive, new_mp, mp)
        return with_class(state, cname,
                          cs.replace(i32=cs.i32.at[:, col].set(new_mp)))


def create_plugin(pm: PluginManager) -> Plugin:
    """Entry point the manifest loader calls (DllStartPlugin parity)."""
    return Plugin("MyGameplayPlugin", [MyGameplayModule()])
