"""Tutorial 3 — heartbeats, events, property callbacks on a live world.

Mirrors the reference's Tutorial3 (`Tutorial/Tutorial3/HelloWorld3Module
.cpp:36-104`): create a Player, register a heartbeat and an event, wire a
property callback, and watch them fire as the world ticks.  Here the
heartbeat is a vectorized timer column and the tick is one jitted step —
but the observable behavior matches.

Run:  python examples/tutorial3_heartbeat_events.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

jax.config.update("jax_platforms", "cpu")

from noahgameframe_tpu.game import GameWorld, WorldConfig

EVENT_LEVEL_REWARD = 1001


def main() -> None:
    world = GameWorld(WorldConfig(combat=False, movement=False, regen=True,
                                  npc_capacity=16, player_capacity=4,
                                  regen_period_s=2 / 30)).start()
    world.scene.create_scene(1)
    k = world.kernel

    player = k.create_object("Player", {"Name": "Hero"}, scene=1, group=0)
    world.properties.set_group_value(player, "MAXHP", 1, 100)
    world.properties.set_group_value(player, "HPREGEN", 1, 5)
    world.properties.recompute_now(player)
    k.set_property(player, "HP", 50)
    world.regen.arm_all("Player")

    # property callback: fires for host writes AND device-tick changes
    k.register_property_event(
        "Player", "HP",
        lambda cname, pname, rows: print(f"  HP changed (rows {rows})"))

    # integer-ID event pub/sub (reference NFCEventModule DoEvent)
    k.events.subscribe(
        EVENT_LEVEL_REWARD,
        lambda guid, eid, args: print(f"  event {eid} for {guid}: {args}"))

    print("ticking; HP regens on the 2-tick heartbeat:")
    for i in range(6):
        world.tick()
        print(f"frame {k.tick_count}: HP={int(k.get_property(player, 'HP'))}")

    print("firing a host event:")
    k.events.do_event(player, EVENT_LEVEL_REWARD, {"gold": 25})
    print("tutorial3 done")


if __name__ == "__main__":
    main()
