"""DrillRunner: drive a campaign against a LocalCluster, invariants on.

One :meth:`step_once` is one drill tick: fire every campaign step due at
this tick, pump the whole cluster once, run the caller's extra pump
(client sockets, surge traffic), then sample every invariant.  The
runner is the only component that reads the wall clock, and only as
``monotonic()`` pump pacing — campaign *scheduling* is tick-indexed by
construction (lint-enforced in the schedule/invariant modules).

Telemetry (on the master's registry, so ``/metrics`` and ``/json`` see
it cluster-wide):

- ``nf_drill_ticks_total`` — drill pump passes driven
- ``nf_drill_actions_total{action}`` — campaign steps fired
- ``nf_drill_invariant_checks_total{invariant}`` — samples taken
- ``nf_drill_invariant_violations_total{invariant}`` — breaches found

The master's ``/json`` additionally carries a live ``drill`` block
(campaign name/seed, clock, fired/remaining steps, per-invariant breach
counts) via :meth:`LocalCluster.attach_drill`.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

from .invariants import DrillContext, Invariant, default_invariants
from .report import DrillReport, Violation
from .schedule import Campaign, Step


class DrillRunner:
    def __init__(self, cluster, campaign: Campaign,
                 invariants: Optional[List[Invariant]] = None,
                 registry=None, max_violations: int = 256) -> None:
        self.cluster = cluster
        self.campaign = campaign
        self.invariants = (invariants if invariants is not None
                           else default_invariants())
        self.tick = 0
        self._steps: List[Step] = campaign.steps
        self._next_step = 0
        self.actions_fired: List[Dict[str, object]] = []
        self.violations: List[Violation] = []
        #: breaches past this cap are counted but not stored verbatim —
        #: a broken invariant at pump rate would otherwise OOM the run
        self.max_violations = int(max_violations)
        self.checks: Dict[str, int] = {}
        self._violation_tally: Dict[str, int] = {}
        reg = (registry if registry is not None
               else cluster.master.telemetry.registry)
        self._c_ticks = reg.counter(
            "nf_drill_ticks_total", "drill pump passes driven")
        self._c_actions = reg.counter(
            "nf_drill_actions_total", "campaign steps fired", ("action",))
        self._c_checks = reg.counter(
            "nf_drill_invariant_checks_total",
            "invariant samples taken", ("invariant",))
        self._c_violations = reg.counter(
            "nf_drill_invariant_violations_total",
            "invariant breaches observed", ("invariant",))
        attach = getattr(cluster, "attach_drill", None)
        if attach is not None:
            attach(self)

    # ------------------------------------------------------------ steps
    def step_once(self, extra: Optional[Callable[[], None]] = None) -> None:
        """One drill tick: due actions → cluster pump → extra pump →
        invariant sample."""
        while (self._next_step < len(self._steps)
               and self._steps[self._next_step].at_tick <= self.tick):
            self._fire(self._steps[self._next_step])
            self._next_step += 1
        self.cluster.execute()
        if extra is not None:
            extra()
        self._sample(_time.monotonic())
        self.tick += 1
        self._c_ticks.inc()

    def pump(self, rounds: int = 50,
             extra: Optional[Callable[[], None]] = None,
             sleep: float = 0.002) -> None:
        for _ in range(int(rounds)):
            self.step_once(extra)
            _time.sleep(sleep)

    def pump_until(self, cond: Callable[[], bool],
                   extra: Optional[Callable[[], None]] = None,
                   timeout: float = 10.0, sleep: float = 0.002) -> bool:
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            self.step_once(extra)
            if cond():
                return True
            _time.sleep(sleep)
        return False

    @property
    def steps_remaining(self) -> int:
        return len(self._steps) - self._next_step

    def run(self, settle_ticks: int = 50,
            extra: Optional[Callable[[], None]] = None,
            sleep: float = 0.002) -> DrillReport:
        """Drive the whole campaign: pump through the horizon, then
        ``settle_ticks`` more so recovery (and its invariants) are
        observed, then hand back the report."""
        self.pump(self.campaign.horizon + 1 + int(settle_ticks),
                  extra=extra, sleep=sleep)
        return self.report()

    # ---------------------------------------------------------- actions
    def _fire(self, step: Step) -> None:
        kw = step.kwargs
        cluster = self.cluster
        if step.action == "kill_role":
            cluster.kill_role(kw["role"], hard=bool(kw.get("hard", True)))
        elif step.action == "revive_role":
            world = kw.get("world")
            factory = kw.get("world_factory")
            if world is None and factory is not None:
                world = factory()
            cluster.revive_role(kw["name"], world=world,
                                resume=bool(kw.get("resume", True)))
        elif step.action == "heal":
            if cluster.chaos is not None:
                cluster.chaos.heal(kw.get("pattern"))
        elif step.action == "store_faults":
            if cluster.chaos is not None:
                cluster.chaos.set_store_faults(kw["pattern"], kw["faults"])
        elif step.action == "link_faults":
            if cluster.chaos is not None:
                cluster.chaos.set_link_faults(kw["pattern"], kw["faults"])
        elif step.action == "checkpoint":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.checkpoint_now()
        elif step.action == "grow_mesh":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.grow_mesh(int(kw["n"]))
        elif step.action == "drain_device":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.drain_device(int(kw["device"]))
        elif step.action == "create_room":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.create_room(seed=kw.get("seed"),
                             room_id=kw.get("room_id"),
                             control=bool(kw.get("control", False)))
        elif step.action == "destroy_room":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.destroy_room(int(kw["room_id"]))
        elif step.action == "rehome_room":
            role = next(r for r in cluster.roles
                        if r.config.name == kw["role"])
            role.rehome_room(int(kw["room_id"]))
        elif step.action == "call":
            kw["fn"](self)
        # "note" is a pure marker — the fired log below is its effect
        self.actions_fired.append({
            "tick": int(self.tick),
            **step.describe(),
        })
        self._c_actions.inc(action=step.action)

    # ------------------------------------------------------- invariants
    def _sample(self, now: float) -> None:
        ctx = DrillContext(cluster=self.cluster, tick=self.tick, now=now)
        for inv in self.invariants:
            self.checks[inv.name] = self.checks.get(inv.name, 0) + 1
            self._c_checks.inc(invariant=inv.name)
            for detail in inv.check(ctx):
                self._violation_tally[inv.name] = (
                    self._violation_tally.get(inv.name, 0) + 1)
                self._c_violations.inc(invariant=inv.name)
                if len(self.violations) < self.max_violations:
                    self.violations.append(
                        Violation(inv.name, self.tick, detail))

    # ------------------------------------------------------------ status
    def status(self) -> Dict[str, object]:
        """Live drill block for the master's ``/json``."""
        nxt = (self._steps[self._next_step].describe()
               if self._next_step < len(self._steps) else None)
        return {
            "campaign": self.campaign.name,
            "seed": int(self.campaign.seed),
            "tick": int(self.tick),
            "horizon": int(self.campaign.horizon),
            "actions_fired": len(self.actions_fired),
            "steps_remaining": self.steps_remaining,
            "next_step": nxt,
            "invariant_violations": dict(self._violation_tally),
        }

    def report(self) -> DrillReport:
        return DrillReport(
            campaign=self.campaign.describe(),
            ticks=int(self.tick),
            actions_fired=list(self.actions_fired),
            violations=list(self.violations),
            checks=dict(self.checks),
        )
