"""Game-day drill engine (ISSUE 11): composed fault campaigns with
continuously-checked cluster invariants.

Every reliability mechanism in the repo is individually proven — chaos
injection, write-behind WAL, supervised session failover, bit-identical
journal replay — but production clusters fail *compositionally*: a game
dies during a store outage during a session surge.  This package turns
that composition into a first-class, repeatable artifact:

- :mod:`drill.schedule` — a seeded, **tick-indexed** campaign: a
  declarative list of ``(at_tick, action)`` steps over a LocalCluster
  (kill/revive roles, arm/heal chaos faults, checkpoints, arbitrary
  callables).  No wall-clock scheduling — the campaign clock is the
  drill pump count, so two runs fire the same actions at the same
  points in the event stream.
- :mod:`drill.invariants` — a library of cluster invariants sampled
  every pump: no session silently dropped, lease transitions legal,
  WAL watermarks monotone per store key, failover lag bounded, parked
  replay in order, telemetry counter bank conserved.
- :mod:`drill.runner` — drives the cluster pump, fires due campaign
  steps, samples every invariant each tick, and exports ``nf_drill_*``
  counters + a ``drill`` block on the master's ``/json``.
- :mod:`drill.report` — the run distilled to a JSON artifact
  (``bench_runs/r07_gameday.json`` for the flagship campaign).

The flagship game-day (``scripts/gameday_smoke.py``) kills a game
DURING a hard store outage DURING a session surge, heals, and proves
failover + WAL recovery + journal replay converge bit-identically to a
fault-free control with zero dropped sessions.
"""

from .invariants import (
    BoundedFailoverLag,
    ConsistentCounters,
    DrillContext,
    Invariant,
    LegalLeaseTransitions,
    MonotoneWatermarks,
    NoSilentDrop,
    OrderedReplay,
    RoomIsolation,
    StableUnderReshard,
    default_invariants,
)
from .report import DrillReport, Violation
from .runner import DrillRunner
from .schedule import Campaign, Step, merged

__all__ = [
    "BoundedFailoverLag",
    "Campaign",
    "ConsistentCounters",
    "DrillContext",
    "DrillReport",
    "DrillRunner",
    "Invariant",
    "LegalLeaseTransitions",
    "MonotoneWatermarks",
    "NoSilentDrop",
    "OrderedReplay",
    "RoomIsolation",
    "StableUnderReshard",
    "Step",
    "Violation",
    "default_invariants",
    "merged",
]
