"""Cluster invariants sampled every drill pump.

Each checker is a small stateful object: :meth:`Invariant.check` gets a
:class:`DrillContext` (cluster + drill tick + pump timestamp) and
returns a list of human-readable violation details — empty when the
invariant holds.  Checkers keep their own baselines (previous lease map,
previous watermarks, unbound-session streaks) so a single runner
instance observes *transitions*, not just states.

The library (ISSUE 11 tentpole):

- :class:`NoSilentDrop` — every session that loses its game binding
  hears about it (a REHOMING/BUSY/DROPPED notice); dropped parked
  frames are never silent.
- :class:`LegalLeaseTransitions` — master lease strings only move along
  UP→SUSPECT→DOWN (plus recovery back to UP); no teleporting.
- :class:`MonotoneWatermarks` — WAL flush watermarks never move
  backwards per store key, across kills, revives, and outages.
- :class:`BoundedFailoverLag` — the oldest pending re-home never
  outlives ``NF_FAILOVER_DEADLINE_S`` (+ slack for the pump quantum).
- :class:`OrderedReplay` — parked-frame replay preserves per-session
  arrival order (fed by :class:`net.failover.ParkingBuffer`'s seq
  audit).
- :class:`ConsistentCounters` — the failover/parking telemetry bank is
  conserved: ``initiated == completed + deadline_exceeded + pending``
  and ``parked == replayed + dropped + still-parked``.  (ISSUE 11
  phrases the first identity with ``busy``, but ``nf_failover_busy_
  total`` counts placement *rounds*, not sessions — the conserved
  session-count identity uses the pending gauge; busy is separately
  required to be monotone.)

Later issues extend the library in place:

- :class:`StableUnderReshard` — the elastic mesh never drops a row and
  stays digest-identical to a static-mesh control (ISSUE 17).
- :class:`RoomIsolation` — in the many-worlds room engine, a room's
  digest moves only in lockstep with its own isolated control world;
  faults in room j never perturb room i (ISSUE 19).

Checkers read cluster state defensively (``getattr`` with fallbacks) so
violation tests can feed them minimal forged stand-ins.

This module is tick-indexed like the schedules: it must not reference
the ``time`` module (nf-lint ``drill-clockless`` rule, docs/LINT.md).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from ..net.defines import SwitchNoticeCode


@dataclasses.dataclass(frozen=True)
class DrillContext:
    """What a checker sees each sample: the cluster under drill, the
    drill tick, and the pump pass's monotonic timestamp (taken once by
    the runner so every checker in a sample shares one clock read)."""

    cluster: object
    tick: int
    now: float


class Invariant:
    """Base checker; subclasses set ``name`` and implement ``check``."""

    name = "invariant"

    def check(self, ctx: DrillContext) -> List[str]:
        raise NotImplementedError


class NoSilentDrop(Invariant):
    """No session is ever silently dropped.

    Two clauses, both over the proxy edge:

    1. If any parked frames were dropped for a *live* client (overflow
       or deadline — disconnect drops have no receiver to notify), at
       least one DROPPED notice must have been pushed.
    2. A client whose bound game has vanished from the proxy's routed
       set for ``grace_samples`` consecutive samples must have received
       at least one switch notice (REHOMING/BUSY/DROPPED).  The grace
       covers the push-ordering window between the world's game-list
       update and the notice fan-out.
    """

    name = "no_silent_drop"

    def __init__(self, grace_samples: int = 25) -> None:
        self.grace_samples = max(1, int(grace_samples))
        self._streak: Dict[object, int] = {}

    def check(self, ctx: DrillContext) -> List[str]:
        proxy = ctx.cluster.proxy
        out: List[str] = []
        parking = proxy.parking
        loud_drops = (int(parking.dropped_overflow)
                      + int(parking.dropped_deadline))
        notices = getattr(proxy, "notice_counts", {})
        if loud_drops and not notices.get(int(SwitchNoticeCode.DROPPED), 0):
            out.append(f"{loud_drops} parked frames dropped with zero "
                       "DROPPED notices sent")
        live = set(getattr(proxy.games, "servers", {}))
        per_conn = getattr(proxy, "conn_notices", {})
        for conn_id, info in dict(proxy._conn_info).items():
            gid = info.get("game_id")
            if gid is None or int(gid) in live:
                self._streak.pop(conn_id, None)
                continue
            streak = self._streak.get(conn_id, 0) + 1
            self._streak[conn_id] = streak
            if streak >= self.grace_samples and not per_conn.get(conn_id):
                out.append(
                    f"conn {conn_id} unbound from dead game {gid} for "
                    f"{streak} samples with no switch notice"
                )
        return out


class LegalLeaseTransitions(Invariant):
    """Master lease strings move only along the legal machine:
    UP→SUSPECT, SUSPECT→DOWN, and recovery SUSPECT→UP / DOWN→UP.

    UP→DOWN is tolerated only when a recent inter-sample gap exceeds
    the SUSPECT window itself (the pump, not the state machine, stalled
    through the intermediate state).  The *two* most recent gaps are
    considered: the master sweeps at the top of a pump pass and we
    sample at the bottom, so a stall late in pass N (inside our
    N-1→N gap) surfaces as a lease jump at sweep N+1 — one sample
    after the gap that explains it."""

    name = "legal_lease_transitions"
    LEGAL = {("UP", "SUSPECT"), ("SUSPECT", "DOWN"),
             ("SUSPECT", "UP"), ("DOWN", "UP")}

    def __init__(self) -> None:
        self._prev: Dict[Tuple[int, int], str] = {}
        self._prev_now: Optional[float] = None
        self._prev_gap = 0.0

    def check(self, ctx: DrillContext) -> List[str]:
        master = ctx.cluster.master
        out: List[str] = []
        suspect_window = max(
            0.0,
            float(getattr(master, "lease_down_seconds", 0.0))
            - float(getattr(master, "lease_suspect_seconds", 0.0)),
        )
        gap = (ctx.now - self._prev_now
               if self._prev_now is not None else 0.0)
        coarse = suspect_window > 0.0 and max(gap, self._prev_gap) > suspect_window
        for stype, by_id in master.registry.items():
            for sid, reg in by_id.items():
                key = (int(stype), int(sid))
                cur = str(reg.lease)
                prev = self._prev.get(key)
                self._prev[key] = cur
                if prev is None or prev == cur:
                    continue
                if (prev, cur) in self.LEGAL:
                    continue
                if (prev, cur) == ("UP", "DOWN") and coarse:
                    continue  # sampler skipped SUSPECT, machine did not
                out.append(f"server type={stype} id={sid} lease jumped "
                           f"{prev}->{cur}")
        self._prev_gap = gap
        self._prev_now = ctx.now
        return out


class MonotoneWatermarks(Invariant):
    """WAL flush watermarks never move backwards per store key.

    Default probe: every live game role's write-behind pipeline
    (``wal:<name>`` → its WAL's ``(flushed_seq, flushed_tick)``).  An
    optional ``store_probe`` adds store-side keys (e.g. the
    ``__wb__:<name>`` watermark blobs in the shared KV) so the check
    spans the full staging→flush path.

    Keys are allowed to *disappear* (a killed role) — the baseline is
    kept, so a revived pipeline that restarts below its old watermark
    is caught the moment it reports again."""

    name = "monotone_watermarks"

    def __init__(self, store_probe: Optional[
            Callable[[], Dict[str, Tuple[int, int]]]] = None) -> None:
        self.store_probe = store_probe
        self._prev: Dict[str, Tuple[int, int]] = {}

    def _marks(self, ctx: DrillContext) -> Dict[str, Tuple[int, int]]:
        marks: Dict[str, Tuple[int, int]] = {}
        for game in list(getattr(ctx.cluster, "games", ())):
            pipeline = getattr(game, "persist", None)
            if pipeline is None:
                continue
            marks[f"wal:{pipeline.name}"] = (
                int(pipeline.wal.flushed_seq),
                int(pipeline.wal.flushed_tick),
            )
        if self.store_probe is not None:
            for key, mark in self.store_probe().items():
                marks[str(key)] = (int(mark[0]), int(mark[1]))
        return marks

    def check(self, ctx: DrillContext) -> List[str]:
        out: List[str] = []
        for key, (seq, tick) in self._marks(ctx).items():
            pseq, ptick = self._prev.get(key, (-1, -1))
            if seq < pseq or (seq == pseq and tick < ptick):
                out.append(f"watermark {key} moved backwards: "
                           f"{pseq}:{ptick} -> {seq}:{tick}")
            else:
                self._prev[key] = (seq, tick)
        return out


class BoundedFailoverLag(Invariant):
    """The oldest pending re-home never outlives the failover deadline
    (+ slack for the pump quantum: the driver expires at deadline on its
    next pump, so lag can legitimately overshoot by one pass)."""

    name = "bounded_failover_lag"

    def __init__(self, slack_s: float = 1.0) -> None:
        self.slack_s = float(slack_s)

    def check(self, ctx: DrillContext) -> List[str]:
        driver = getattr(ctx.cluster.world, "failover", None)
        if driver is None:
            return []
        lag = float(driver.lag(ctx.now))
        bound = float(driver.deadline_s) + self.slack_s
        if lag > bound:
            return [f"failover lag {lag:.3f}s exceeds deadline "
                    f"{driver.deadline_s:.3f}s + {self.slack_s:.3f}s slack"]
        return []


class OrderedReplay(Invariant):
    """Parked-frame replay preserves per-session arrival order.

    The :class:`net.failover.ParkingBuffer` stamps every parked frame
    with a global sequence number and audits replay order itself
    (``order_violations``); this checker surfaces any *new* breach at
    the tick it happened."""

    name = "ordered_replay"

    def __init__(self) -> None:
        self._reported = 0

    def check(self, ctx: DrillContext) -> List[str]:
        total = int(ctx.cluster.proxy.parking.order_violations)
        if total > self._reported:
            fresh = total - self._reported
            self._reported = total
            return [f"{fresh} parked frame(s) replayed out of per-session "
                    "arrival order"]
        return []


class ConsistentCounters(Invariant):
    """The failover/parking telemetry bank stays conserved:

    - sessions: ``nf_failover_initiated_total == completed +
      deadline_exceeded + pending`` (every initiated re-home is exactly
      one of finished, abandoned, or still in flight);
    - frames: ``parked_total == replayed_total + dropped_total +
      depth()`` on the parking buffer;
    - ``nf_failover_busy_total`` (placement rounds) is monotone."""

    name = "consistent_counters"

    def __init__(self) -> None:
        self._prev_busy = 0.0

    def check(self, ctx: DrillContext) -> List[str]:
        out: List[str] = []
        world = ctx.cluster.world
        driver = getattr(world, "failover", None)
        if driver is not None:
            reg = world.telemetry.registry
            initiated = reg.value("nf_failover_initiated_total")
            completed = reg.value("nf_failover_completed_total")
            deadline = reg.value("nf_failover_deadline_exceeded_total")
            pending = float(driver.pending_count())
            if initiated != completed + deadline + pending:
                out.append(
                    "failover bank not conserved: initiated="
                    f"{initiated:g} != completed={completed:g} + "
                    f"deadline={deadline:g} + pending={pending:g}"
                )
            busy = reg.value("nf_failover_busy_total")
            if busy < self._prev_busy:
                out.append(f"nf_failover_busy_total went backwards: "
                           f"{self._prev_busy:g} -> {busy:g}")
            else:
                self._prev_busy = busy
        parking = ctx.cluster.proxy.parking
        still = int(parking.depth())
        if int(parking.parked_total) != (int(parking.replayed_total)
                                         + int(parking.dropped_total)
                                         + still):
            out.append(
                "parking bank not conserved: parked="
                f"{parking.parked_total} != replayed="
                f"{parking.replayed_total} + dropped="
                f"{parking.dropped_total} + still_parked={still}"
            )
        return out


class StableUnderReshard(Invariant):
    """The world is bit-stable through mesh topology changes (ISSUE 17).

    Four clauses, sampled over every game role exposing an ``elastic``
    driver (read defensively — non-elastic games are skipped):

    1. **zero dropped rows** — the migrate protocol's drop counter and
       the reshard ledger must both stay at 0, ever;
    2. **population conserved** — after every completed grow/drain the
       migrating class's live count equals the op's baseline (budget
       overflow strands rows, it never destroys them);
    3. **bounded exodus lag** — a drain's pre-copy empties the evicted
       device's row range within ``exodus_tick_bound`` ticks;
    4. **digest parity** — when a :class:`~..parallel.elastic.
       DigestControl` is given, the live world's placement-invariant
       ``canonical_digest`` equals the single-shard fault-free control
       advanced to the same tick — the mesh may have grown, drained and
       rebalanced in between, the bytes may not differ.
    """

    name = "stable_under_reshard"

    def __init__(self, control=None, digest_every: int = 1) -> None:
        self.control = control
        self.digest_every = max(1, int(digest_every))
        self._digest_checks = 0
        self._last_digest_tick = -1

    def check(self, ctx: DrillContext) -> List[str]:
        out: List[str] = []
        for game in list(getattr(ctx.cluster, "games", ())):
            el = getattr(game, "elastic", None)
            if el is None:
                continue
            st = el.status()
            name = getattr(getattr(game, "config", None), "name", "game")
            if int(st.get("dropped_rows", 0)):
                out.append(f"{name}: reshard dropped "
                           f"{st['dropped_rows']} row(s)")
            inflight = st.get("inflight")
            if inflight is None and int(st.get("resharded_total", 0)):
                pop, base = int(st.get("pop", 0)), int(
                    st.get("pop_baseline", 0))
                if pop != base:
                    out.append(f"{name}: population not conserved across "
                               f"reshard: {base} -> {pop}")
            bound = int(st.get("exodus_tick_bound", 0))
            lag = int(st.get("exodus_ticks", 0))
            if inflight == "drain" and bound and lag > bound:
                out.append(f"{name}: exodus lag {lag} ticks exceeds "
                           f"bound {bound}")
            if self.control is not None:
                tick = int(getattr(getattr(game, "kernel", None),
                                   "tick_count", 0))
                if (tick > self._last_digest_tick
                        and tick >= self.control.tick_count
                        and tick % self.digest_every == 0):
                    self._last_digest_tick = tick
                    self._digest_checks += 1
                    live = el.digest()
                    want = self.control.advance_to(tick)
                    if live is not None and live != want:
                        out.append(
                            f"{name}: canonical digest diverged from "
                            f"static-mesh control at tick {tick}: "
                            f"{live:#x} != {want:#x}")
        return out


class RoomIsolation(Invariant):
    """No cross-room reads in the many-worlds engine (ISSUE 19).

    For every game role hosting a :class:`~..parallel.rooms.
    RoomDirectory` (read defensively — room-less games are skipped),
    every room with an attached lockstep CONTROL world must digest
    bit-identically to it.  Faults injected into room j — kills, store
    outages, churn, even hostile writes — may change room j, but a
    watched room i's digest can only move in lockstep with its own
    isolated control; any divergence is a cross-room read/write.

    Digesting is a host-side fold over an extracted room, so
    ``sample_every`` bounds the cost: rooms are checked on drill ticks
    where ``tick % sample_every == 0`` and only when the batch actually
    advanced since the last check."""

    name = "room_isolation"

    def __init__(self, sample_every: int = 1) -> None:
        self.sample_every = max(1, int(sample_every))
        self._last_batch_tick: Dict[str, int] = {}

    def check(self, ctx: DrillContext) -> List[str]:
        out: List[str] = []
        if ctx.tick % self.sample_every:
            return out
        for game in list(getattr(ctx.cluster, "games", ())):
            rooms = getattr(game, "rooms", None)
            if rooms is None or not getattr(rooms, "controls", None):
                continue
            name = getattr(getattr(game, "config", None), "name", "game")
            batch_tick = int(getattr(getattr(rooms, "batch", None),
                                     "tick_count", 0))
            if self._last_batch_tick.get(name) == batch_tick:
                continue  # no frames since last sample; digests can't move
            self._last_batch_tick[name] = batch_tick
            for room_id in sorted(rooms.controls):
                if room_id not in getattr(rooms, "rooms", {}):
                    continue  # control outlived the room (destroy raced)
                live = int(rooms.digest(room_id))
                want = int(rooms.control_digest(room_id))
                if live != want:
                    out.append(
                        f"{name}: room {room_id} diverged from its "
                        f"isolated control at batch tick {batch_tick}: "
                        f"{live:#x} != {want:#x} — cross-room leak")
        return out


def default_invariants(
    store_probe: Optional[Callable[[], Dict[str, Tuple[int, int]]]] = None,
    lag_slack_s: float = 1.0,
    grace_samples: int = 25,
) -> List[Invariant]:
    """The full shipped library, fresh state, ready for one runner."""
    return [
        NoSilentDrop(grace_samples=grace_samples),
        LegalLeaseTransitions(),
        MonotoneWatermarks(store_probe=store_probe),
        BoundedFailoverLag(slack_s=lag_slack_s),
        OrderedReplay(),
        ConsistentCounters(),
    ]
