"""Seeded, tick-indexed fault-campaign schedules.

A campaign is a declarative list of ``(at_tick, action)`` steps.  The
clock is the **drill tick** — one tick per :meth:`DrillRunner.step_once`
pump pass — never wall time: the same campaign over the same cluster
fires the same actions at the same points in the event stream, which is
what makes a game-day drill a regression test instead of an anecdote.
(The nf-lint ``drill-clockless`` rule enforces this structurally: this
module must not reference the ``time`` module at all.)

Built-in actions (resolved by the runner against its cluster):

=================  ====================================================
``kill_role``      ``role=<config name>, hard=True`` → ``cluster.kill_role``
``revive_role``    ``name=<config name>, resume=True, world_factory=fn``
``heal``           ``pattern=None`` → ``cluster.chaos.heal(pattern)``
``store_faults``   ``pattern=, faults=StoreFaults(...)`` → live re-arm
``link_faults``    ``pattern=, faults=LinkFaults(...)`` → live re-arm
``checkpoint``     ``role=<config name>`` → ``role.checkpoint_now()``
``grow_mesh``      ``role=<config name>, n=<devices>`` → ``role.grow_mesh``
``drain_device``   ``role=<config name>, device=<index>`` →
                   ``role.drain_device``
``create_room``    ``role=<config name>, seed=, room_id=, control=`` →
                   ``role.create_room`` (many-worlds engine)
``destroy_room``   ``role=<config name>, room_id=`` → ``role.destroy_room``
``rehome_room``    ``role=<config name>, room_id=`` → ``role.rehome_room``
``call``           ``fn=<callable(runner)>`` — surge traffic, asserts, …
``note``           no-op marker; lands in the report's action log
=================  ====================================================

Steps at the same tick fire in insertion order.  ``kwargs`` may hold
live objects (fault dataclasses, world factories); :meth:`Campaign.
describe` renders them safely for the report/``/json``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Tuple

#: action names the runner knows how to fire (anything else must be a
#: ``call`` step); kept here so schedules can be validated at build time
BUILTIN_ACTIONS = (
    "kill_role",
    "revive_role",
    "heal",
    "store_faults",
    "link_faults",
    "checkpoint",
    "grow_mesh",
    "drain_device",
    "create_room",
    "destroy_room",
    "rehome_room",
    "call",
    "note",
)


@dataclasses.dataclass(frozen=True)
class Step:
    """One scheduled action: fire ``action(**kwargs)`` when the drill
    clock reaches ``at_tick`` (fires before that tick's pump pass)."""

    at_tick: int
    action: str
    kwargs: Dict[str, object] = dataclasses.field(default_factory=dict)
    label: str = ""

    def describe(self) -> Dict[str, object]:
        """JSON-safe rendering (kwargs may hold callables/dataclasses)."""

        def safe(v: object) -> object:
            if isinstance(v, (int, float, str, bool)) or v is None:
                return v
            if dataclasses.is_dataclass(v) and not isinstance(v, type):
                return dataclasses.asdict(v)
            if callable(v):
                return f"<callable {getattr(v, '__name__', repr(v))}>"
            return repr(v)

        return {
            "at_tick": int(self.at_tick),
            "action": self.action,
            "label": self.label,
            "kwargs": {k: safe(v) for k, v in self.kwargs.items()},
        }


class Campaign:
    """An ordered, seeded schedule of :class:`Step`\\ s.

    The seed does not drive the schedule itself (that is fully explicit)
    — it is the campaign's *identity* seed, recorded in the report and
    conventionally shared with the cluster's :class:`FaultPlan` so one
    number reproduces the whole run."""

    def __init__(self, name: str, seed: int = 0,
                 steps: Iterable[Step] = ()) -> None:
        self.name = str(name)
        self.seed = int(seed)
        self._steps: List[Step] = list(steps)
        for s in self._steps:
            self._validate(s)

    @staticmethod
    def _validate(step: Step) -> None:
        if step.at_tick < 0:
            raise ValueError(f"step {step.label or step.action}: "
                             f"at_tick must be >= 0, got {step.at_tick}")
        if step.action not in BUILTIN_ACTIONS:
            raise ValueError(
                f"unknown action {step.action!r}; use one of "
                f"{BUILTIN_ACTIONS} (arbitrary work goes through 'call')"
            )

    # ------------------------------------------------------------ build
    def add(self, at_tick: int, action: str, label: str = "",
            **kwargs: object) -> "Campaign":
        """Builder-style append; returns self for chaining."""
        step = Step(int(at_tick), action, dict(kwargs), label)
        self._validate(step)
        self._steps.append(step)
        return self

    # ------------------------------------------------------------ query
    @property
    def steps(self) -> List[Step]:
        """Steps in firing order: by tick, insertion order within a
        tick (Python's sort is stable)."""
        return sorted(self._steps, key=lambda s: s.at_tick)

    @property
    def horizon(self) -> int:
        """The last scheduled tick (0 for an empty campaign)."""
        return max((s.at_tick for s in self._steps), default=0)

    def __len__(self) -> int:
        return len(self._steps)

    def describe(self) -> Dict[str, object]:
        return {
            "name": self.name,
            "seed": self.seed,
            "horizon": self.horizon,
            "steps": [s.describe() for s in self.steps],
        }


def merged(name: str, seed: int,
           *parts: Tuple[int, Campaign]) -> Campaign:
    """Compose campaigns: each ``(offset, campaign)`` part's steps are
    shifted by ``offset`` ticks into one schedule — e.g. a store-outage
    campaign overlaid on a kill/revive campaign."""
    out = Campaign(name, seed)
    for offset, part in parts:
        for s in part.steps:
            out.add(s.at_tick + int(offset), s.action,
                    label=s.label or f"{part.name}:{s.action}", **s.kwargs)
    return out
