"""Drill run distilled to a JSON-safe artifact.

A report pins everything needed to (a) fail CI when a campaign regresses
and (b) re-derive the run offline: the campaign description (seed +
tick-indexed steps), every action actually fired (with the drill tick it
fired at), every invariant violation (invariant name, tick, detail), and
the per-invariant check/violation tallies.  The flagship game-day writes
this as ``bench_runs/r07_gameday.json`` next to its digest-pinned
journal, so performance and correctness regress together.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant breach observed at one drill tick."""

    invariant: str
    tick: int
    detail: str

    def to_dict(self) -> Dict[str, object]:
        return {"invariant": self.invariant, "tick": int(self.tick),
                "detail": self.detail}


@dataclasses.dataclass
class DrillReport:
    campaign: Dict[str, object]          # Campaign.describe()
    ticks: int = 0
    actions_fired: List[Dict[str, object]] = dataclasses.field(
        default_factory=list)
    violations: List[Violation] = dataclasses.field(default_factory=list)
    checks: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: free-form extras the driving script pins alongside the drill
    #: (bench numbers, journal digests, convergence verdicts)
    extra: Dict[str, object] = dataclasses.field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.violations

    def violations_by_invariant(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.invariant] = out.get(v.invariant, 0) + 1
        return out

    def to_dict(self) -> Dict[str, object]:
        return {
            "campaign": self.campaign,
            "ticks": int(self.ticks),
            "clean": self.clean,
            "actions_fired": list(self.actions_fired),
            "invariant_checks": dict(self.checks),
            "invariant_violations": self.violations_by_invariant(),
            "violations": [v.to_dict() for v in self.violations],
            **({"extra": self.extra} if self.extra else {}),
        }

    def write(self, path) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
