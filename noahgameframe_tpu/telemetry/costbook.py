"""CostBook: the device cost observatory for every jit entry point.

PR 7's StageClock answers "where did the frame's *time* go"; the
CostBook answers "what did the compiled program *cost*" — and keeps the
two joinable.  Every jit entry point (kernel tick, fused run window,
serve prepare/scan/query, interest step/query, the spatial slab, the
profile scripts' pass list) routes through :meth:`CostBook.wrap`, which
replaces the bare ``jax.jit(fn)`` dispatch with an AOT-compiled cache
keyed by the call's abstract signature.  Per entry it records:

- **lowering + compile wall time** (``jit.lower()`` and
  ``lowered.compile()`` timed separately);
- **compiled cost**: ``cost_analysis()`` FLOPs / bytes-accessed and
  ``memory_analysis()`` argument/output/temp/alias bytes;
- **donation accounting**: which argnums donate and how many bytes the
  donated buffers alias back into the output;
- **every retrace, with cause attribution**: the new signature is
  diffed against the previous one leaf by leaf, so the event says
  *which* arg's shape/dtype/weak-type (or declared-static value)
  changed — surfaced as ``nf_recompiles_total{entry,cause}``.

Retraces are either bugs or sanctioned **generation bumps** (bucket
auto-resize doubling a cell table, ``Kernel.invalidate()``'s
``_trace_gen``).  Sanctioned sites call :meth:`generation_bump`; the
recompile-free soak gate (tests/test_costbook.py) marks the book after
warmup and asserts every later compile is covered by a bump —
``unexplained_since()`` is that query.

The book also owns the **HBM census**: :meth:`hbm_sample` reads
``device.memory_stats()`` live/peak/limit bytes per device (the real
allocator's numbers), falling back to summing ``jax.live_arrays()`` on
backends that expose no stats (CPU) with a host-tracked peak — replacing
the probe-once MemoryCensus guess with a periodic gauge
(``nf_hbm_*``, sampled every ``HBM_SAMPLE_FRAMES`` served frames and at
every scrape).

Finally :func:`roofline_fold` joins CostBook FLOPs/bytes with
StageClock device seconds (``NF_STAGE_TIMING=1``) into achieved-vs-peak
fractions per stage — the measured roofline
(``scripts/roofline_report.py``, ``docs/ROOFLINE.md``).

Everything here is host-side bookkeeping around the dispatch; nothing
reaches the trace, so observability on vs off cannot perturb the
simulation (same contract as the frame observatory).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import jax
from jax import tree_util

__all__ = [
    "CostBook", "CostEntry", "roofline_fold", "PEAKS",
    "HBM_SAMPLE_FRAMES",
]

#: served-frame cadence of the periodic HBM census (GameRole.execute)
HBM_SAMPLE_FRAMES = 64

#: retrace events kept in the book's ring (the web monitor's feed)
_EVENT_RING = 128

#: compile records kept per book (the soak gate reads these; a healthy
#: run compiles a few dozen programs, so the cap is a runaway backstop)
_COMPILE_LOG_CAP = 4096

#: peak FLOPs/s and HBM bytes/s per platform for the roofline fold.
#: CPU has no honest single number (it depends on the host SKU), so the
#: entry is a deliberately round placeholder marked *provisional* — the
#: schema and the achieved numerators are platform-agnostic; only the
#: denominators (and so the fractions) firm up on real hardware.
PEAKS: Dict[str, Dict[str, Any]] = {
    "cpu": {"flops_per_s": 5.0e10, "bytes_per_s": 2.0e10,
            "source": "provisional-nominal-cpu"},
    "tpu": {"flops_per_s": 1.97e14, "bytes_per_s": 1.23e12,
            "source": "tpu-v5e-spec-bf16"},
    "gpu": {"flops_per_s": 9.89e13, "bytes_per_s": 2.04e12,
            "source": "a100-spec-bf16"},
}


def _leaf_sharding(x):
    """Hashable input-sharding component of a leaf's signature.

    AOT-compiled executables are pinned to their argument shardings: the
    same (shape, dtype) arriving replicated vs NamedSharding'd over a
    mesh (e.g. kernel state after the first sharded-tick/migration
    round lands it on the mesh) needs a DIFFERENT executable, and
    handing it the cached one is a pxla ValueError, not a retrace."""
    s = getattr(x, "sharding", None)
    if s is None:
        return None
    try:
        hash(s)
        return s
    except TypeError:  # pragma: no cover - exotic sharding types
        return str(s)


def _leaf_sig(x) -> Tuple:
    """Abstract signature of one pytree leaf — cheap on the hot path.

    Python scalars collapse to their type (jit retraces on a *type*
    change, not a value change); arrays to (shape, dtype, weak_type,
    sharding)."""
    if x is None or isinstance(x, (bool, int, float, complex, str, bytes)):
        return ("py", type(x).__name__)
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)),
                str(_leaf_sharding(x)))
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype), False, str(None))
    return ("py", type(x).__name__)


def _leaf_key(x):
    """Hot-path cache key for one leaf.  jax arrays key on their aval
    object (hashable, equal iff shape/dtype/weak-type equal) plus their
    committed sharding, so the per-call cost is two attribute reads
    instead of the shape/dtype stringification `_leaf_sig` does;
    everything else falls back to the descriptive sig.  Equal keys imply
    equal `_leaf_sig`s, so the compile ledger and cause attribution are
    unchanged — only the dict-lookup key is cheaper."""
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (aval, _leaf_sharding(x))
    return _leaf_sig(x)


def _leaf_bytes(x) -> int:
    n = getattr(x, "nbytes", None)
    return int(n) if n is not None else 0


class CostEntry:
    """One named jit entry point's ledger."""

    def __init__(self, name: str, stage: Optional[str] = None) -> None:
        self.name = name
        self.stage = stage
        self.calls = 0
        self.compiles = 0
        self.lower_s_total = 0.0
        self.compile_s_total = 0.0
        self.causes: Dict[str, int] = {}
        self.last: Dict[str, Any] = {}
        self._last_sig = None   # (treedef, leaf sigs, static reprs)
        self._last_paths: Optional[List[str]] = None

    @property
    def recompiles(self) -> int:
        return max(0, self.compiles - 1)

    def attribute(self, sig, args) -> str:
        """Why did this signature miss the cache?  Diffs against the
        PREVIOUS signature leaf by leaf; paths are computed lazily (only
        when a compile actually happens)."""
        prev = self._last_sig
        if prev is None:
            return "first"
        if prev[2] != sig[2]:
            for i, (a, b) in enumerate(zip(prev[2], sig[2])):
                if a != b:
                    return f"static:arg{i}"
            return "static:arity"
        if prev[0] != sig[0]:
            return "tree-structure"
        paths = self._last_paths or [f"leaf{i}"
                                     for i in range(len(sig[1]))]
        for p, a, b in zip(paths, prev[1], sig[1]):
            if a == b:
                continue
            if a[0] == "py" or b[0] == "py":
                return f"pytype:{p}"
            if a[0] != b[0]:
                return f"shape:{p}"
            if a[1] != b[1]:
                return f"dtype:{p}"
            if a[2] != b[2]:
                return f"weak-type:{p}"
            return f"sharding:{p}"
        # identical signature: a fresh dispatcher re-wrapped the entry —
        # the retrace is about traced CONSTANTS (invalidate/set_phases
        # close over new tables), not about the arguments
        return "rewrap"

    def note_compile(self, sig, args, dyn_args) -> None:
        self._last_sig = sig
        flat, _ = tree_util.tree_flatten_with_path(dyn_args)
        self._last_paths = [tree_util.keystr(p) for p, _ in flat]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "stage": self.stage,
            "calls": self.calls,
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "lower_ms_total": round(self.lower_s_total * 1e3, 3),
            "compile_ms_total": round(self.compile_s_total * 1e3, 3),
            "causes": dict(self.causes),
            "last": dict(self.last),
        }


class CostBook:
    """Registry of :class:`CostEntry` ledgers + HBM census + the
    sanctioned-retrace generation counter."""

    def __init__(self) -> None:
        self.entries: Dict[str, CostEntry] = {}
        self.generation = 0
        self.gen_events: List[Dict[str, Any]] = []
        self.compile_log: List[Dict[str, Any]] = []
        self.events: List[Dict[str, Any]] = []  # retrace ring
        self._seq = 0
        self.hbm: Dict[str, Any] = {}
        self._hbm_samples = 0
        self._fallback_peak = 0

    # --------------------------------------------------------- entries
    def entry(self, name: str, stage: Optional[str] = None) -> CostEntry:
        e = self.entries.get(name)
        if e is None:
            e = self.entries[name] = CostEntry(name, stage=stage)
        elif stage is not None and e.stage is None:
            e.stage = stage
        return e

    def wrap(self, name: str, fn: Callable, *,
             static_argnums: Tuple[int, ...] = (),
             donate_argnums: Tuple[int, ...] = (),
             stage: Optional[str] = None,
             jit_kwargs: Optional[Dict[str, Any]] = None) -> Callable:
        """``jax.jit(fn, ...)`` with the ledger attached.

        Returns a dispatcher with identical call semantics (donation
        included) that keeps its own signature→executable cache: every
        miss is lowered + compiled AOT under a timer, its
        cost/memory analysis recorded, and its cause attributed.  The
        nf-lint callgraph treats ``*.wrap("name", fn)`` as a jit root,
        so trace-safety coverage survives the indirection."""
        if isinstance(static_argnums, int):
            static_argnums = (static_argnums,)
        if isinstance(donate_argnums, int):
            donate_argnums = (donate_argnums,)
        static_set = frozenset(static_argnums)
        entry = self.entry(name, stage=stage)
        jkw = dict(jit_kwargs or {})
        jfn = jax.jit(fn, static_argnums=static_argnums,
                      donate_argnums=donate_argnums, **jkw)
        cache: Dict[Any, Any] = {}
        book = self

        tree_flatten = tree_util.tree_flatten
        leaf_key = _leaf_key
        cache_get = cache.get

        def dispatch(*args):
            if static_set:
                dyn = tuple(a for i, a in enumerate(args)
                            if i not in static_set)
                statics = tuple(repr(args[i]) for i in sorted(static_set)
                                if i < len(args))
            else:
                dyn = args
                statics = ()
            leaves, treedef = tree_flatten(dyn)
            key = (treedef, tuple(map(leaf_key, leaves)), statics)
            compiled = cache_get(key)
            if compiled is None:
                sig = (treedef, tuple(_leaf_sig(x) for x in leaves),
                       statics)
                compiled = book._compile(entry, jfn, args, dyn, sig,
                                         donate_argnums)
                cache[key] = compiled
            entry.calls += 1
            return compiled(*dyn)

        dispatch.costbook_entry = entry
        return dispatch

    def _compile(self, entry: CostEntry, jfn, args, dyn_args, sig,
                 donate_argnums) -> Callable:
        cause = entry.attribute(sig, args)
        t0 = time.perf_counter()
        lowered = jfn.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        entry.note_compile(sig, args, dyn_args)
        lower_s, compile_s = t1 - t0, t2 - t1
        entry.compiles += 1
        entry.lower_s_total += lower_s
        entry.compile_s_total += compile_s
        if cause != "first":
            entry.causes[cause] = entry.causes.get(cause, 0) + 1
        rec: Dict[str, Any] = {
            "entry": entry.name,
            "cause": cause,
            "generation": self.generation,
            "seq": self._seq,
            "lower_ms": round(lower_s * 1e3, 3),
            "compile_ms": round(compile_s * 1e3, 3),
            "donated_argnums": list(donate_argnums),
            "donated_bytes": sum(
                _leaf_bytes(leaf)
                for i in donate_argnums if i < len(args)
                for leaf in tree_util.tree_leaves(args[i])
            ),
        }
        self._seq += 1
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            rec["flops"] = float(ca.get("flops", 0.0))
            rec["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
        except Exception:  # backends without HLO cost analysis
            rec["flops"] = 0.0
            rec["bytes_accessed"] = 0.0
        try:
            ma = compiled.memory_analysis()
        except Exception:
            ma = None
        if ma is not None:
            for key, attr in (
                ("argument_bytes", "argument_size_in_bytes"),
                ("output_bytes", "output_size_in_bytes"),
                ("temp_bytes", "temp_size_in_bytes"),
                ("alias_bytes", "alias_size_in_bytes"),
                ("code_bytes", "generated_code_size_in_bytes"),
            ):
                v = getattr(ma, attr, None)
                if v is not None:
                    rec[key] = int(v)
        entry.last = rec
        if len(self.compile_log) < _COMPILE_LOG_CAP:
            self.compile_log.append(rec)
        if cause != "first":
            self.events.append(rec)
            del self.events[:-_EVENT_RING]
        return compiled

    # ------------------------------------------------ sanctioned bumps
    def generation_bump(self, cause: str) -> int:
        """A legitimate retrace is coming (bucket auto-resize, kernel
        invalidate).  Compiles after this carry the new generation and
        the soak gate's allowlist covers them."""
        self.generation += 1
        self.gen_events.append({"generation": self.generation,
                                "cause": str(cause), "seq": self._seq})
        return self.generation

    def mark(self) -> Dict[str, int]:
        """Snapshot for the recompile-free gate: compare with
        :meth:`unexplained_since` after the churn window."""
        return {"seq": self._seq, "generation": self.generation}

    def compiles_since(self, mark: Dict[str, int]) -> List[Dict[str, Any]]:
        return [r for r in self.compile_log if r["seq"] >= mark["seq"]]

    def unexplained_since(self, mark: Dict[str, int]) -> List[Dict[str, Any]]:
        """Compiles after `mark` NOT covered by a generation bump —
        the live complement of nf-lint's static recompile-hazard rule."""
        return [r for r in self.compiles_since(mark)
                if r["generation"] <= mark["generation"]]

    # ------------------------------------------------------ HBM census
    def hbm_sample(self) -> Dict[str, Any]:
        """One census pass: per-device allocator stats when the backend
        exposes them, live-array fallback (host-tracked peak) otherwise."""
        per_dev: List[Dict[str, Any]] = []
        live = peak = limit = 0
        source = None
        try:
            devices = list(jax.local_devices())
        except Exception:
            devices = []
        for d in devices:
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            source = "memory_stats"
            d_live = int(ms.get("bytes_in_use", 0))
            d_peak = int(ms.get("peak_bytes_in_use", d_live))
            d_limit = int(ms.get("bytes_limit", 0))
            live += d_live
            peak += d_peak
            limit += d_limit
            per_dev.append({
                "device": f"{d.platform}:{d.id}", "live_bytes": d_live,
                "peak_bytes": d_peak, "limit_bytes": d_limit,
            })
        if source is None:
            source = "live_arrays"
            live = sum(_leaf_bytes(a) for a in jax.live_arrays())
            self._fallback_peak = max(self._fallback_peak, live)
            peak = self._fallback_peak
            limit = 0
        self._hbm_samples += 1
        self.hbm = {
            "live_bytes": live, "peak_bytes": peak, "limit_bytes": limit,
            "source": source, "samples": self._hbm_samples,
            "per_device": per_dev,
        }
        return self.hbm

    # -------------------------------------------------------- exposure
    @property
    def total_compiles(self) -> int:
        return sum(e.compiles for e in self.entries.values())

    @property
    def total_recompiles(self) -> int:
        return sum(e.recompiles for e in self.entries.values())

    @property
    def compile_s_total(self) -> float:
        return sum(e.lower_s_total + e.compile_s_total
                   for e in self.entries.values())

    def snapshot(self) -> Dict[str, Any]:
        """The ``/costbook`` JSON document (docs/OBSERVABILITY.md has
        the schema)."""
        return {
            "generation": self.generation,
            "gen_events": list(self.gen_events[-_EVENT_RING:]),
            "compiles": self.total_compiles,
            "recompiles": self.total_recompiles,
            "compile_ms": round(self.compile_s_total * 1e3, 3),
            "hbm": dict(self.hbm),
            "entries": {n: e.to_dict()
                        for n, e in sorted(self.entries.items())},
            "events": list(self.events),
        }

    def summary(self) -> Dict[str, Any]:
        """Compact blob for the heartbeat ext map (master aggregation):
        per-entry compiles/recompiles/flops/bytes plus the HBM totals."""
        return {
            "compiles": self.total_compiles,
            "recompiles": self.total_recompiles,
            "compile_ms": round(self.compile_s_total * 1e3, 1),
            "generation": self.generation,
            "hbm_live": int(self.hbm.get("live_bytes", 0)),
            "hbm_peak": int(self.hbm.get("peak_bytes", 0)),
            "hbm_source": self.hbm.get("source", ""),
            "entries": {
                n: {"c": e.compiles, "r": e.recompiles,
                    "f": e.last.get("flops", 0.0),
                    "b": e.last.get("bytes_accessed", 0.0)}
                for n, e in sorted(self.entries.items())
            },
        }

    # ------------------------------------------- registry sample feeds
    def recompile_samples(self) -> Iterable[Tuple[dict, float]]:
        for name, e in sorted(self.entries.items()):
            for cause, n in sorted(e.causes.items()):
                yield ({"entry": name, "cause": cause}, float(n))

    def compile_samples(self, which: int) -> Iterable[Tuple[dict, float]]:
        """which: 0=compiles, 1=compile seconds (lower+compile)."""
        for name, e in sorted(self.entries.items()):
            v = (float(e.compiles) if which == 0
                 else e.lower_s_total + e.compile_s_total)
            yield ({"entry": name}, v)

    def cost_samples(self, key: str) -> Iterable[Tuple[dict, float]]:
        """Latest compiled cost per entry (flops / bytes_accessed /
        argument_bytes / output_bytes / temp_bytes / donated_bytes)."""
        for name, e in sorted(self.entries.items()):
            if key in e.last:
                yield ({"entry": name}, float(e.last[key]))


def roofline_fold(book: CostBook, pipeline_stats: Dict[str, Any],
                  platform: Optional[str] = None) -> Dict[str, Any]:
    """Join CostBook FLOPs/bytes with StageClock device seconds into
    achieved-vs-peak fractions per stage.

    ``pipeline_stats`` is ``GameRole.pipeline_stats()`` (frames + per-
    stage mean/p50/p95 ms).  Per-frame cost of a stage is the sum over
    that stage's entries of (per-dispatch cost x dispatches) / frames;
    honest device seconds require the run to have had
    ``NF_STAGE_TIMING=1`` (otherwise the tick stage times only the
    async dispatch and the fractions are upper bounds)."""
    if platform is None:
        try:
            platform = jax.default_backend()
        except Exception:
            platform = "cpu"
    peaks = PEAKS.get(platform, PEAKS["cpu"])
    frames = max(1, int(pipeline_stats.get("frames", 0)))
    stage_ms = pipeline_stats.get("stages", {})
    per_stage: Dict[str, Dict[str, Any]] = {}
    for name, e in sorted(book.entries.items()):
        if e.stage is None or not e.last:
            continue
        s = per_stage.setdefault(e.stage, {
            "entries": [], "flops_per_frame": 0.0,
            "bytes_per_frame": 0.0,
        })
        s["entries"].append(name)
        s["flops_per_frame"] += e.last.get("flops", 0.0) * e.calls / frames
        s["bytes_per_frame"] += (
            e.last.get("bytes_accessed", 0.0) * e.calls / frames)
    for stage, s in per_stage.items():
        ms = stage_ms.get(stage, {})
        dev_s = float(ms.get("mean_ms", 0.0)) / 1e3
        s["device_s_per_frame"] = dev_s
        if dev_s > 0:
            s["achieved_flops_per_s"] = s["flops_per_frame"] / dev_s
            s["achieved_bytes_per_s"] = s["bytes_per_frame"] / dev_s
            s["frac_of_peak_flops"] = (
                s["achieved_flops_per_s"] / peaks["flops_per_s"])
            s["frac_of_peak_bytes"] = (
                s["achieved_bytes_per_s"] / peaks["bytes_per_s"])
        else:
            s["achieved_flops_per_s"] = 0.0
            s["achieved_bytes_per_s"] = 0.0
            s["frac_of_peak_flops"] = 0.0
            s["frac_of_peak_bytes"] = 0.0
    return {
        "platform": platform,
        "provisional": str(peaks.get("source", "")).startswith(
            "provisional"),
        "peaks": dict(peaks),
        "frames": frames,
        "stages": per_stage,
    }
