"""Prometheus-style metrics registry with text-format exposition.

Only what the stack needs is implemented (the same economy as
net/http.py): counters, gauges, histograms, callback-backed collectors,
and the text exposition format v0.0.4 — enough for a Prometheus scrape
of ``/metrics`` or the web monitor's regex parser.  No third-party
client library: the container must not grow dependencies, and the whole
surface is ~200 lines.

Design points:

- Metrics are cheap to update on the hot path (dict bump / deque
  append); all formatting cost is paid at scrape time.
- :class:`Histogram` owns BOTH the cumulative-bucket exposition and the
  exact percentile math over a bounded sample window — the single
  source of truth for every p50/p95/p99 in the repo (bench JSON, role
  reports, /metrics can never disagree).
- :class:`CallbackMetric` samples an external source lazily at scrape
  time (kernel counter bank totals, net counter dicts, memory census) —
  zero per-tick cost for anything nobody is scraping.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Callable, Deque, Dict, Iterable, Optional, Sequence, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# frame/tick latency buckets in seconds: sub-ms host pumps up to
# multi-second 1M-entity compiles land in a real bucket
DEFAULT_TIME_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

LabelValues = Tuple[str, ...]
Sample = Tuple[str, Dict[str, str], float]  # (name suffix, labels, value)


def escape_label_value(v: str) -> str:
    """Escape a label value per the exposition format: backslash,
    double-quote and newline."""
    return (
        str(v)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _escape_help(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def format_sample(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        body = ",".join(
            f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{body}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


class Metric:
    """Base: a named family yielding (suffix, labels, value) samples."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)

    def _key(self, labels: Dict[str, str]) -> LabelValues:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != "
                f"declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)

    def samples(self) -> Iterable[Sample]:  # pragma: no cover - overridden
        return ()

    def expose(self) -> str:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self.kind}",
        ]
        for suffix, labels, value in self.samples():
            lines.append(format_sample(self.name + suffix, labels, value))
        return "\n".join(lines)


class Counter(Metric):
    """Monotonically increasing value, optionally per label set."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelValues, float] = {}
        # hot-path metrics are bumped from the tick thread AND the
        # write-behind flusher thread: the read-modify-write below must
        # not lose increments (ISSUE 7 satellite)
        self._mu = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counter decrease ({amount})")
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(self._key(labels), 0.0)

    def samples(self) -> Iterable[Sample]:
        if not self._values and not self.labelnames:
            yield ("", {}, 0.0)
            return
        for key in sorted(self._values):
            yield ("", dict(zip(self.labelnames, key)), self._values[key])


class Gauge(Metric):
    """Value that can go up and down; optionally backed by a callable."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        super().__init__(name, help, labelnames)
        self._values: Dict[LabelValues, float] = {}
        self._fn: Optional[Callable[[], float]] = None
        self._mu = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._mu:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Label-less gauge evaluated at scrape time."""
        self._fn = fn

    def value(self, **labels: str) -> float:
        if self._fn is not None and not labels:
            return float(self._fn())
        return self._values.get(self._key(labels), 0.0)

    def samples(self) -> Iterable[Sample]:
        if self._fn is not None:
            try:
                yield ("", {}, float(self._fn()))
            except Exception:  # noqa: BLE001 — a dead probe must not kill scrape
                yield ("", {}, float("nan"))
            return
        if not self._values and not self.labelnames:
            yield ("", {}, 0.0)
            return
        for key in sorted(self._values):
            yield ("", dict(zip(self.labelnames, key)), self._values[key])


class Histogram(Metric):
    """Cumulative-bucket histogram + exact percentiles over a window.

    The buckets serve Prometheus (quantile estimation server-side); the
    bounded deque window serves in-process consumers (role reports,
    bench JSON) that want exact percentiles without a scrape loop.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS_S,
                 window: int = 512) -> None:
        super().__init__(name, help, ())
        b = sorted(float(x) for x in buckets)
        if not b or math.isinf(b[-1]):
            raise ValueError("buckets must be finite and non-empty")
        self.buckets = tuple(b)
        self._counts = [0] * (len(b) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: Deque[float] = collections.deque(maxlen=window)
        # observe() runs a multi-field read-modify-write from both the
        # tick thread and the write-behind flusher; an unlocked race
        # drops counts and skews _sum (ISSUE 7 satellite)
        self._mu = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._mu:
            self._sum += v
            self._count += 1
            self._window.append(v)
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    # -- exact window math (the one percentile implementation) -----------
    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def window_values(self) -> list:
        with self._mu:
            return list(self._window)

    def window_mean(self) -> float:
        with self._mu:
            if not self._window:
                return 0.0
            return sum(self._window) / len(self._window)

    def percentile(self, p: float) -> float:
        """Exact p-th percentile (linear interpolation) over the sample
        window; 0.0 when empty."""
        with self._mu:
            vals = sorted(self._window)
        if not vals:
            return 0.0
        if len(vals) == 1:
            return vals[0]
        rank = (p / 100.0) * (len(vals) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(vals) - 1)
        frac = rank - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def samples(self) -> Iterable[Sample]:
        with self._mu:  # consistent snapshot: sum/count/buckets agree
            counts = list(self._counts)
            total, n = self._sum, self._count
        cum = 0
        for ub, c in zip(self.buckets, counts):
            cum += c
            yield ("_bucket", {"le": _fmt_value(ub)}, float(cum))
        cum += counts[-1]
        yield ("_bucket", {"le": "+Inf"}, float(cum))
        yield ("_sum", {}, total)
        yield ("_count", {}, float(n))


class CallbackMetric(Metric):
    """Samples an external source at scrape time.

    ``fn`` returns either a plain number (label-less) or an iterable of
    ``(labels_dict, value)`` pairs.  Used for sources that already keep
    their own counters (kernel counter bank, net opcode dicts, census).
    """

    def __init__(self, name: str, fn: Callable[[], object],
                 kind: str = "gauge", help: str = "") -> None:
        super().__init__(name, help, ())
        self.kind = kind
        self._fn = fn

    def samples(self) -> Iterable[Sample]:
        try:
            out = self._fn()
        except Exception:  # noqa: BLE001 — a dead source must not kill scrape
            return
        if isinstance(out, (int, float)):
            yield ("", {}, float(out))
            return
        for labels, value in out:
            yield ("", dict(labels), float(value))


class MetricsRegistry:
    """Named metric collection with Prometheus text exposition."""

    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- factories
    def register(self, metric: Metric) -> Metric:
        with self._lock:
            cur = self._metrics.get(metric.name)
            if cur is metric:
                return metric
            if cur is not None:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def _get_or_make(self, cls, name: str, **kwargs) -> Metric:
        with self._lock:
            cur = self._metrics.get(name)
            if cur is not None:
                if not isinstance(cur, cls):
                    raise ValueError(
                        f"metric {name!r} exists with kind {cur.kind!r}"
                    )
                return cur
            m = cls(name, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help=help, labelnames=labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help=help, labelnames=labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS_S,
                  window: int = 512) -> Histogram:
        return self._get_or_make(
            Histogram, name, help=help, buckets=buckets, window=window
        )

    def register_callback(self, name: str, fn: Callable[[], object],
                          kind: str = "gauge", help: str = "") -> CallbackMetric:
        m = CallbackMetric(name, fn, kind=kind, help=help)
        self.register(m)
        return m

    # ---------------------------------------------------------- queries
    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def value(self, name: str, **labels: str) -> float:
        """Read one sample back out (tests, bench JSON).  For callback
        metrics the labels must match a yielded sample exactly."""
        m = self._metrics.get(name)
        if m is None:
            raise KeyError(name)
        if isinstance(m, (Counter, Gauge)):
            return m.value(**labels)
        want = {k: str(v) for k, v in labels.items()}
        for suffix, lbls, value in m.samples():
            if suffix == "" and lbls == want:
                return value
        raise KeyError(f"{name}{labels}")

    # ------------------------------------------------------- exposition
    def exposition(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return "\n".join(m.expose() for m in metrics) + "\n"

    def handler(self, _path: str = "", _params: Optional[dict] = None):
        """An HttpServer route handler serving this registry."""
        return (200, CONTENT_TYPE, self.exposition().encode("utf-8"))
