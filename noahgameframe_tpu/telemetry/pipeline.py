"""Frame-pipeline attribution: stage clock, wire trace context, clock sync.

ISSUE 7 (frame observatory).  Three cooperating pieces:

- :class:`StageClock` — exclusive-time stage accounting for the served
  frame path (tick → diff harvest → interest query → encode → send).
  Nested stages subtract child time from the parent so the per-frame
  waterfall *sums* to the frame wall time (an explicit ``other`` bucket
  absorbs unattributed time).  Per-stage label-less histograms land in
  the role's :class:`~noahgameframe_tpu.telemetry.registry.MetricsRegistry`.

- Trace context codec — a fixed-size little-endian header that rides
  sampled served frames as the ``msg_data`` of a ``FRAME_TRACE``
  MsgBase envelope.  The game stamps ``t_encode_ns``, the proxy stamps
  ``proxy_in_ns``/``proxy_out_ns`` in :meth:`_transpond`'s dispatch
  seam, the client stamps ``client_recv_ns`` and echoes the header back
  as ``FRAME_TRACE_ACK``.  All stamps are ``time.perf_counter_ns()``
  reads — monotonic, per-process clocks; cross-process deltas are only
  meaningful after :class:`ClockSync` alignment, while same-clock
  deltas (game RTT, proxy relay) are exact.

- :class:`ClockSync` — NTP-style min-delay filter over heartbeat
  echoes: each report carries the sender's monotonic stamp, the master
  records ``recv - sent`` and keeps a sliding minimum as the offset
  estimate (bias = one-way network delay of the luckiest sample).

Nothing here may feed the journal, the state digest, or any compiled
function — the nf-lint ``wall-clock`` rule scans this file and the
wire path for wall-clock leaks (docs/LINT.md), and ``tests/test_pipeline.py`` proves a
journaled run replays bit-identically with tracing on.
"""

from __future__ import annotations

import os
import struct
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "StageClock",
    "TraceContext",
    "TraceError",
    "TRACE_VERSION",
    "TRACE_SIZE",
    "encode_trace",
    "decode_trace",
    "trace_sample_n",
    "stage_timing_enabled",
    "ClockSync",
    "merge_chrome_traces",
]


# --------------------------------------------------------------------------
# env knobs
# --------------------------------------------------------------------------

def trace_sample_n(default: int = 64) -> int:
    """``NF_TRACE_SAMPLE``: trace 1-in-N sessions (0 disables).

    Defaults to 64 — cheap enough to stay on (one ~60-byte sidecar per
    sampled session per flush), so production captures always carry
    end-to-end latency without a redeploy.
    """
    try:
        return max(0, int(os.environ.get("NF_TRACE_SAMPLE", default)))
    except ValueError:
        return default


def stage_timing_enabled() -> bool:
    """``NF_STAGE_TIMING=1``: honest per-stage device timing.

    Inserts a ``block_until_ready`` after the compiled dispatch so the
    ``kernel.dispatch`` span measures real device time instead of async
    dispatch latency.  Never on by default — it serializes the device
    queue and de-fuses the production overlap.
    """
    return os.environ.get("NF_STAGE_TIMING", "0") == "1"


# --------------------------------------------------------------------------
# stage clock
# --------------------------------------------------------------------------

class _StageCtx:
    """Context manager for one stage interval (re-entrant per frame).

    Exclusive-time accounting: on exit the *full* interval is charged to
    the parent's child-counter while only ``interval - child_time`` is
    charged to this stage, so nesting ``send`` inside ``encode`` never
    double-counts.
    """

    __slots__ = ("_clock", "_name", "_t0", "_child_ns")

    def __init__(self, clock: "StageClock", name: str):
        self._clock = clock
        self._name = name
        self._t0 = 0
        self._child_ns = 0

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        self._child_ns = 0
        self._clock._stack.append(self)
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter_ns() - self._t0
        clock = self._clock
        clock._stack.pop()
        clock._acc[self._name] = (
            clock._acc.get(self._name, 0) + dur - self._child_ns
        )
        if clock._stack:
            clock._stack[-1]._child_ns += dur
        return False


class StageClock:
    """Per-frame exclusive stage timing for the served pipeline.

    Usage (one frame)::

        sc.frame_begin(tick)
        with sc.stage("tick"): ...
        with sc.stage("encode"):
            with sc.stage("send"): ...   # excluded from "encode"
        sc.frame_end()

    ``frame_end`` freezes the waterfall into :attr:`last` (stage → ns,
    plus ``other`` = wall - attributed so the dict sums to
    :attr:`last_wall_ns` exactly) and feeds per-stage histograms.
    """

    STAGES: Tuple[str, ...] = ("tick", "migrate", "harvest", "interest",
                               "encode", "assemble", "send", "reshard",
                               "other")

    def __init__(self, registry=None, window: int = 512):
        self._acc: Dict[str, int] = {}
        self._stack: List[_StageCtx] = []
        # per-frame histogram divisors (stage -> int), cleared by
        # frame_begin: a K-tick train charges K frames of device work
        # to ONE "tick" stage span, so the banked histogram sample is
        # divided by K to stay per-tick comparable across NF_TICK_TRAIN
        # settings.  ONLY the histogram observation scales — the
        # waterfall (`last`, `other`, wall) stays exact.
        self._scale: Dict[str, int] = {}
        self._frame_t0 = 0
        self.last: Dict[str, int] = {}
        self.last_tick = -1
        self.last_wall_ns = 0
        self.frames = 0
        self._hists: Dict[str, object] = {}
        if registry is not None:
            for s in self.STAGES:
                self._hists[s] = registry.histogram(
                    f"nf_stage_{s}_seconds",
                    f"exclusive time of served-frame stage '{s}'",
                    window=window,
                )

    def stage(self, name: str) -> _StageCtx:
        return _StageCtx(self, name)

    def add_ns(self, name: str, ns: int) -> None:
        """Charge ``ns`` to ``name`` outside a context manager (and to the
        innermost open stage's child-counter, preserving exclusivity)."""
        self._acc[name] = self._acc.get(name, 0) + ns
        if self._stack:
            self._stack[-1]._child_ns += ns

    def set_scale(self, name: str, k: int) -> None:
        """Amortize this frame's ``name`` stage over ``k`` logical ticks
        when banking its histogram (``nf_stage_<name>_seconds`` stays a
        PER-TICK distribution under K-tick trains).  Resets each frame."""
        self._scale[name] = max(1, int(k))

    def frame_begin(self, tick: int) -> None:
        self._acc = {}
        self._stack = []
        self._scale = {}
        self.last_tick = int(tick)
        self._frame_t0 = time.perf_counter_ns()

    def frame_end(self) -> Dict[str, int]:
        wall = time.perf_counter_ns() - self._frame_t0
        acc = self._acc
        attributed = sum(acc.values())
        acc["other"] = max(0, wall - attributed)
        self.last = dict(acc)
        self.last_wall_ns = wall
        self.frames += 1
        for name, ns in acc.items():
            h = self._hists.get(name)
            if h is not None:
                h.observe(ns / 1e9 / self._scale.get(name, 1))
        return self.last

    def stats(self) -> Dict[str, Dict[str, float]]:
        """Per-stage p50/p95/mean in ms from the histogram windows."""
        out: Dict[str, Dict[str, float]] = {}
        for name, h in self._hists.items():
            if getattr(h, "count", 0) <= 0:
                continue
            out[name] = {
                "p50_ms": round(h.percentile(50.0) * 1e3, 4),
                "p95_ms": round(h.percentile(95.0) * 1e3, 4),
                "mean_ms": round(h.sum / max(1, h.count) * 1e3, 4),
            }
        return out


# --------------------------------------------------------------------------
# trace context codec
# --------------------------------------------------------------------------

TRACE_VERSION = 1

# version u8 | flags u8 | reserved u16 | game_id u32 | seq u32 |
# tick u64 | t_encode u64 | proxy_in u64 | proxy_out u64 | client_recv u64
_TRACE_STRUCT = struct.Struct("<BBHIIQQQQQ")
TRACE_SIZE = _TRACE_STRUCT.size  # 52 bytes


class TraceError(ValueError):
    """Malformed trace header (torn, oversize, or unknown version)."""


@dataclass
class TraceContext:
    tick: int
    game_id: int
    seq: int
    t_encode_ns: int
    proxy_in_ns: int = 0
    proxy_out_ns: int = 0
    client_recv_ns: int = 0
    flags: int = 0


_U64 = (1 << 64) - 1
_U32 = (1 << 32) - 1


def encode_trace(ctx: TraceContext) -> bytes:
    return _TRACE_STRUCT.pack(
        TRACE_VERSION, ctx.flags & 0xFF, 0,
        ctx.game_id & _U32, ctx.seq & _U32,
        ctx.tick & _U64, ctx.t_encode_ns & _U64,
        ctx.proxy_in_ns & _U64, ctx.proxy_out_ns & _U64,
        ctx.client_recv_ns & _U64,
    )


def decode_trace(buf: bytes) -> TraceContext:
    if len(buf) != TRACE_SIZE:
        raise TraceError(
            f"trace header is {len(buf)} bytes, want {TRACE_SIZE}")
    (version, flags, _reserved, game_id, seq, tick,
     t_encode, proxy_in, proxy_out, client_recv) = _TRACE_STRUCT.unpack(buf)
    if version != TRACE_VERSION:
        raise TraceError(f"unknown trace version {version}")
    return TraceContext(tick=tick, game_id=game_id, seq=seq,
                        t_encode_ns=t_encode, proxy_in_ns=proxy_in,
                        proxy_out_ns=proxy_out, client_recv_ns=client_recv,
                        flags=flags)


# --------------------------------------------------------------------------
# clock sync (master side)
# --------------------------------------------------------------------------

class ClockSync:
    """Per-source monotonic clock-offset estimation from one-way stamps.

    Every heartbeat report carries the sender's ``perf_counter_ns`` in
    its ext map; :meth:`update` records ``recv_ns - sent_ns`` =
    ``offset + network_delay``.  The sliding *minimum* over a window is
    the NTP-style estimate: delay is non-negative, so the min converges
    on ``offset + min_delay`` — biased high by the best-case one-way
    delay, which on a LAN is microseconds against millisecond frames.
    """

    def __init__(self, window: int = 64):
        self._window = max(1, int(window))
        self._samples: Dict[str, Deque[int]] = {}

    def update(self, key: str, sent_ns: int, recv_ns: int) -> None:
        d = self._samples.get(key)
        if d is None:
            d = self._samples[key] = deque(maxlen=self._window)
        d.append(int(recv_ns) - int(sent_ns))

    def offset_ns(self, key: str) -> Optional[int]:
        d = self._samples.get(key)
        return min(d) if d else None

    def offsets(self) -> Dict[str, int]:
        return {k: min(d) for k, d in sorted(self._samples.items()) if d}


# --------------------------------------------------------------------------
# multi-process chrome-trace merge
# --------------------------------------------------------------------------

def merge_chrome_traces(docs: Sequence[dict],
                        offsets_us: Optional[Sequence[float]] = None) -> dict:
    """Merge per-process chrome-trace docs into one Perfetto timeline.

    Each doc should already carry a distinct ``pid`` (see
    ``SpanTracer.chrome_trace(pid=...)``); ``offsets_us[i]`` shifts doc
    *i*'s timestamps onto the reference clock (use ``ClockSync`` offsets
    divided by 1e3).  Metadata events (``ph == "M"``) pass through
    unshifted — they carry no timestamp semantics.
    """
    merged: List[dict] = []
    for i, doc in enumerate(docs):
        shift = float(offsets_us[i]) if offsets_us else 0.0
        for ev in doc.get("traceEvents", []):
            if shift and ev.get("ph") != "M":
                ev = dict(ev)
                ev["ts"] = ev.get("ts", 0.0) + shift
            merged.append(ev)
    return {"traceEvents": merged, "displayTimeUnit": "ms"}
