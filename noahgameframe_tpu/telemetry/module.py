"""TelemetryModule: one registry per role/world, every source wired in.

Sources absorbed (all sampled lazily at scrape time — a role that nobody
scrapes pays nothing per frame):

- frame latency: :class:`~noahgameframe_tpu.utils.metrics.TickMetrics`
  observing into a registry-owned histogram (``nf_frame_seconds``), plus
  precomputed quantile gauges (``nf_frame_latency_ms``) so dashboards
  don't need server-side histogram math;
- the kernel's ON-DEVICE counter bank (``nf_tick_counters_total`` /
  ``nf_tick_counters``): events fired, diff cells, deaths, combat hits,
  AOI/stencil overflow drops — accumulated inside the jitted tick and
  decoded from the summary vector the host already fetches (zero extra
  device syncs; kernel/kernel.py);
- per-opcode net counters (``nf_net_msgs_total`` / ``nf_net_bytes_total``
  with direction/link/opcode labels) from every NetServerModule /
  NetClientModule pool the role owns;
- the memory census (``nf_census`` per kind, ``nf_device_bytes``) and
  per-class live-entity gauges.

``mount(http)`` exposes the registry at ``/metrics`` on any
net/http.py HttpServer; ServerRole.serve_metrics() spins up a dedicated
one for roles without a status server.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Tuple

from ..kernel.module import Module
from .costbook import CostBook
from .registry import MetricsRegistry, CONTENT_TYPE  # noqa: F401
from .tracing import SpanTracer


class TelemetryModule(Module):
    name = "TelemetryModule"

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 window: int = 512) -> None:
        super().__init__()
        self.registry = registry if registry is not None else MetricsRegistry()
        self.tracer = SpanTracer(enabled=False)
        # import here: utils.metrics imports telemetry.registry
        from ..utils.metrics import MemoryCensus, TickMetrics

        self.tick = TickMetrics(
            window=window,
            histogram=self.registry.histogram(
                "nf_frame_seconds", "main-loop frame latency (seconds)",
                window=window,
            ),
        )
        self.census = MemoryCensus()
        # the device cost observatory: replaced by the kernel's book in
        # attach_kernel so one ledger covers kernel + serve-edge entries;
        # roles without a kernel keep this (empty) one so /costbook is
        # uniform across all five roles
        self.costbook = CostBook()
        self._net_sources: Dict[str, object] = {}
        self._pool_sources: Dict[str, object] = {}  # link -> NetClientModule
        self._chaos_sources: list = []  # (link prefix, ChaosDirector)
        self._kernel_attached = False
        self._role_attached = False
        self.registry.register_callback(
            "nf_frame_latency_ms", self._frame_quantiles, kind="gauge",
            help="frame latency quantiles in ms (exact, window-based)",
        )
        self.registry.register_callback(
            "nf_net_msgs_total", lambda: self._net_samples(0),
            kind="counter", help="messages per link/direction/opcode",
        )
        self.registry.register_callback(
            "nf_net_bytes_total", lambda: self._net_samples(1),
            kind="counter", help="payload bytes per link/direction/opcode",
        )
        self.registry.register_callback(
            "nf_relay_msgs_total", lambda: self._relay_samples(0),
            kind="counter", help="proxy-forwarded messages per link/opcode",
        )
        self.registry.register_callback(
            "nf_relay_seconds_total", lambda: self._relay_samples(1),
            kind="counter",
            help="cumulative proxy forward latency per link/opcode",
        )
        self.registry.register_callback(
            "nf_reconnects_total", self._pool_samples, kind="counter",
            help="re-dial attempts after a link failure, per pool/server",
        )
        self.registry.register_callback(
            "nf_chaos_faults_total", self._chaos_samples, kind="counter",
            help="injected faults per link and kind (net/chaos.py)",
        )
        # cost observatory (telemetry/costbook.py): lambdas read
        # self.costbook dynamically so attach_kernel's adoption of the
        # kernel's book retargets every series
        self.registry.register_callback(
            "nf_recompiles_total",
            lambda: self.costbook.recompile_samples(), kind="counter",
            help="jit retraces per entry with cause attribution",
        )
        self.registry.register_callback(
            "nf_compiles_total",
            lambda: self.costbook.compile_samples(0), kind="counter",
            help="XLA compiles per jit entry (first trace included)",
        )
        self.registry.register_callback(
            "nf_compile_seconds_total",
            lambda: self.costbook.compile_samples(1), kind="counter",
            help="cumulative lowering+compile wall seconds per entry",
        )
        self.registry.register_callback(
            "nf_entry_flops",
            lambda: self.costbook.cost_samples("flops"), kind="gauge",
            help="cost_analysis FLOPs of each entry's latest executable",
        )
        self.registry.register_callback(
            "nf_entry_bytes_accessed",
            lambda: self.costbook.cost_samples("bytes_accessed"),
            kind="gauge",
            help="cost_analysis bytes accessed per entry (latest)",
        )
        self.registry.register_callback(
            "nf_entry_temp_bytes",
            lambda: self.costbook.cost_samples("temp_bytes"), kind="gauge",
            help="memory_analysis temp buffer bytes per entry (latest)",
        )
        self.registry.register_callback(
            "nf_hbm_bytes_in_use", self._hbm_samples_live, kind="gauge",
            help="device allocator live bytes (memory_stats; "
                 "live-array fallback on backends without stats)",
        )
        self.registry.register_callback(
            "nf_hbm_peak_bytes", lambda: self._hbm_samples_cached(
                "peak_bytes"), kind="gauge",
            help="device allocator peak bytes since process start",
        )
        self.registry.register_callback(
            "nf_hbm_bytes_limit", lambda: self._hbm_samples_cached(
                "limit_bytes"), kind="gauge",
            help="device allocator capacity (0 when unknown)",
        )
        self.registry.register_callback(
            "nf_pallas_fallback_total", self._pallas_fallback_samples,
            kind="counter",
            help="NF_PALLAS=2 fused-engine downgrades to the split-table "
                 "path (VMEM budget), counted per retrace",
        )

    def _pallas_fallback_samples(self) -> Iterable[Tuple[dict, float]]:
        # lazy import: the scrape must not drag the Pallas module (and
        # through it jax.experimental) into processes that never combat
        from ..ops.stencil_pallas import fused_fallback_total

        yield ({}, float(fused_fallback_total()))

    # ------------------------------------------------------------ sources
    def _hbm_samples_live(self) -> Iterable[Tuple[dict, float]]:
        """Scrape-time census pass (the periodic frame-loop sampling in
        GameRole covers unscraped stretches); the peak/limit gauges read
        the refreshed cache so one scrape is one census."""
        hbm = self.costbook.hbm_sample()
        yield ({}, float(hbm["live_bytes"]))
        for d in hbm["per_device"]:
            yield ({"device": d["device"]}, float(d["live_bytes"]))

    def _hbm_samples_cached(self, key: str) -> Iterable[Tuple[dict, float]]:
        hbm = self.costbook.hbm or self.costbook.hbm_sample()
        yield ({}, float(hbm.get(key, 0)))
        for d in hbm.get("per_device", ()):
            yield ({"device": d["device"]}, float(d.get(key, 0)))

    def _frame_quantiles(self) -> Iterable[Tuple[dict, float]]:
        h = self.tick.hist
        for q in (50, 95, 99):
            yield ({"quantile": f"p{q}"}, h.percentile(q) * 1e3)

    def add_net_source(self, link: str, counters) -> None:
        """Register a NetCounters (net/module.py) under a link label."""
        self._net_sources[str(link)] = counters

    def add_pool_source(self, link: str, pool) -> None:
        """Register a NetClientModule whose ``retries_total`` feeds
        ``nf_reconnects_total`` under a link label."""
        self._pool_sources[str(link)] = pool

    def _pool_samples(self) -> Iterable[Tuple[dict, float]]:
        for link, pool in sorted(self._pool_sources.items()):
            for sid in sorted(pool.retries_total):
                yield (
                    {"link": link, "server_id": str(sid)},
                    pool.retries_total[sid],
                )

    def add_chaos_source(self, director, prefix: str = "") -> None:
        """Register a ChaosDirector (net/chaos.py); only links starting
        with `prefix` are exposed (one role sees its own links)."""
        self._chaos_sources.append((str(prefix), director))

    def _chaos_samples(self) -> Iterable[Tuple[dict, float]]:
        for prefix, director in self._chaos_sources:
            for link in sorted(director.counts):
                if prefix and not link.startswith(prefix):
                    continue
                for kind, v in sorted(director.counts[link].items()):
                    yield ({"link": link, "kind": kind}, v)

    def _net_samples(self, which: int) -> Iterable[Tuple[dict, float]]:
        for link, c in sorted(self._net_sources.items()):
            for direction, d in (
                ("in", (c.in_msgs, c.in_bytes)[which]),
                ("out", (c.out_msgs, c.out_bytes)[which]),
            ):
                for opcode in sorted(d):
                    yield (
                        {"link": link, "direction": direction,
                         "opcode": str(opcode)},
                        d[opcode],
                    )

    def _relay_samples(self, which: int) -> Iterable[Tuple[dict, float]]:
        """which: 0 = relayed message count, 1 = forward latency seconds.
        Sourced from NetCounters.count_relay (net/module.py) — only the
        proxy feeds these, so most roles yield nothing."""
        for link, c in sorted(self._net_sources.items()):
            msgs = getattr(c, "relay_msgs", None)
            if not msgs:
                continue
            for opcode in sorted(msgs):
                v = (msgs[opcode] if which == 0
                     else c.relay_ns.get(opcode, 0) / 1e9)
                yield ({"link": link, "opcode": str(opcode)}, v)

    def attach_role(self, role) -> None:
        """Wire a ServerRole: identity gauge + its net counter sources.
        (Frame timing attaches by the role adopting ``self.tick``.)"""
        if self._role_attached:
            return
        self._role_attached = True
        info = self.registry.gauge(
            "nf_role_info", "role identity (value is always 1)",
            ("role", "server_id"),
        )
        info.set(1, role=type(role).__name__,
                 server_id=str(role.config.server_id))
        self.add_net_source("server", role.server.counters)

    def attach_kernel(self, kernel) -> None:
        """Wire a Kernel: counter bank, tick count, entities, census."""
        if self._kernel_attached or kernel is None:
            return
        self._kernel_attached = True
        self.census.kernel = kernel
        kernel.tracer = self.tracer
        # one CostBook per world: the kernel built its own at
        # construction (bare-kernel benches record into it before any
        # telemetry exists); adopt it so role-level entries (serve,
        # interest) and kernel entries share a ledger
        kbook = getattr(kernel, "costbook", None)
        if kbook is not None:
            self.costbook = kbook
        else:
            kernel.costbook = self.costbook
        reg = self.registry
        reg.register_callback(
            "nf_ticks_total", lambda: kernel.tick_count, kind="counter",
            help="world ticks advanced (tick + run_device)",
        )
        reg.register_callback(
            "nf_tick_counters_total",
            lambda: (
                ({"counter": k}, v)
                for k, v in sorted(kernel.counter_totals.items())
            ),
            kind="counter",
            help="on-device counter bank, cumulative over observed ticks",
        )
        reg.register_callback(
            "nf_tick_counters",
            lambda: (
                ({"counter": k}, v)
                for k, v in sorted(kernel.last_counters.items())
            ),
            kind="gauge",
            help="on-device counter bank, last observed tick",
        )
        reg.register_callback(
            "nf_entities_live",
            lambda: (
                ({"class": c}, kernel.store.live_count(c))
                for c in kernel.store.class_order
            )
            if kernel.store is not None
            else (),
            kind="gauge", help="live entity rows per class",
        )
        reg.register_callback(
            "nf_census",
            lambda: (
                ({"kind": k}, v) for k, v in sorted(self.census.census().items())
            ),
            kind="gauge", help="memory census: live objects per kind",
        )
        reg.register_callback(
            "nf_device_bytes", self.census.device_bytes, kind="gauge",
            help="bytes held by live device arrays (best effort)",
        )
        # Verlet neighbor-cache effectiveness (ops/verlet.py): caches ride
        # WorldState.aux under "verlet/<grid>"; sampled lazily at scrape
        # time (np.asarray of three i32 scalars per grid) so the knob
        # costs nothing when nobody scrapes
        reg.register_callback(
            "nf_grid_rebuilds_total", lambda: self._verlet_samples(0),
            kind="counter",
            help="cell-table sort+build executions per Verlet-cached grid",
        )
        reg.register_callback(
            "nf_grid_rebuild_interval_ticks",
            lambda: self._verlet_samples(3), kind="gauge",
            help="mean ticks between rebuilds (builds+reuses per build)",
        )
        reg.register_callback(
            "nf_grid_staleness_ticks", lambda: self._verlet_samples(2),
            kind="gauge",
            help="ticks since each Verlet grid's last rebuild (cache age)",
        )

    def _verlet_samples(self, which: int) -> Iterable[Tuple[dict, float]]:
        """which: 0=rebuilds, 1=reuses, 2=age, 3=mean rebuild interval."""
        import numpy as np

        kernel = self.census.kernel
        state = getattr(kernel, "state", None)
        for key, cache in sorted((getattr(state, "aux", None) or {}).items()):
            if not key.startswith("verlet/"):
                continue
            grid = key[len("verlet/"):]
            if which == 3:
                reb = float(np.asarray(cache.rebuilds))
                reu = float(np.asarray(cache.reuses))
                yield ({"grid": grid}, (reb + reu) / max(reb, 1.0))
            else:
                v = (cache.rebuilds, cache.reuses, cache.age)[which]
                yield ({"grid": grid}, float(np.asarray(v)))

    # ------------------------------------------------- module lifecycle
    def after_init(self) -> None:
        # when registered in a world's PluginManager the kernel is bound
        # by now (pm runs after_init post kernel.build)
        self.attach_kernel(self.kernel)
        if self.census.log_module is None and self.kernel is not None:
            # discover a LogModule sibling for census probe failures
            pass

    # ------------------------------------------------------------ expose
    def costbook_handler(self, _path=None, _params=None):
        """HTTP handler for ``/costbook``: the book's full snapshot with
        a fresh HBM census folded in."""
        self.costbook.hbm_sample()
        body = json.dumps(self.costbook.snapshot()).encode()
        return 200, "application/json", body

    def mount(self, http) -> None:
        """Route /metrics and /costbook on an existing HttpServer."""
        http.route("/metrics", self.registry.handler)
        http.route("/costbook", self.costbook_handler)

    def exposition(self) -> str:
        return self.registry.exposition()
