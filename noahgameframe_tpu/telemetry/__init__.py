"""Telemetry: metrics registry, span tracing, and role wiring.

The reference's observability is a web monitor plus a memory census with
performance tracking disabled in every shipped conf (SURVEY §5).  This
package is its replacement for the TPU port, in three layers:

- :mod:`registry` — a Prometheus-style counter/gauge/histogram registry
  with text exposition, mounted at ``/metrics`` on any role's
  :class:`~noahgameframe_tpu.net.http.HttpServer`.
- :mod:`tracing` — a host-side ring-buffer span tracer with Chrome
  trace-event JSON export (open in Perfetto), complementing the
  ``jax.named_scope`` stage annotations inside the compiled tick
  (visible in XProf device timelines).
- :mod:`module` — :class:`TelemetryModule`, the one wiring point: it
  binds the kernel's on-device counter bank, the frame-latency
  histogram, the memory census, and per-opcode net counters into one
  registry per role/world.
"""

from .registry import (
    CallbackMetric,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
)
from .costbook import CostBook, roofline_fold
from .tracing import SpanTracer
from .module import TelemetryModule

__all__ = [
    "CallbackMetric",
    "CostBook",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "SpanTracer",
    "TelemetryModule",
    "escape_label_value",
    "roofline_fold",
]
