"""Host-side span tracing: ring buffer + Chrome trace-event export.

Two complementary timelines answer "where does a tick go":

- DEVICE stages: the kernel wraps every phase of the compiled tick in
  ``jax.named_scope``, so an XProf capture (``jax.profiler``) shows
  per-stage device time under those names.  Nothing to do here — the
  scopes ride the HLO metadata.
- HOST framing: this tracer records wall-clock spans (dispatch, summary
  fetch, post-tick fan-out, sync flush, net pump) into a fixed-size
  ring buffer and exports them as Chrome trace-event JSON —
  ``chrome://tracing`` / https://ui.perfetto.dev load the file directly.

The tracer is DISABLED by default: ``span()`` then returns a shared
no-op context manager, so instrumented hot paths pay one attribute read
and a truthiness check per span.  scripts/export_trace.py shows the
intended capture workflow.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

_NULL_CTX = contextlib.nullcontext()


class _Span:
    """Re-entrant-safe timed block writing one complete ("X") event."""

    __slots__ = ("tracer", "name", "args", "t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.t0 = 0

    def __enter__(self) -> "_Span":
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter_ns()
        self.tracer._record(self.name, self.t0, t1 - self.t0, self.args)


class SpanTracer:
    """Fixed-capacity ring buffer of (name, ts, dur) spans."""

    def __init__(self, capacity: int = 65536, enabled: bool = False) -> None:
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._events: List[tuple] = []  # (name, ts_ns, dur_ns, tid, args)
        self._head = 0  # next write slot once the ring is full
        self._lock = threading.Lock()
        self._epoch_ns = time.perf_counter_ns()

    # ------------------------------------------------------------ record
    def span(self, name: str, **args):
        """Context manager timing a block; no-op when disabled."""
        if not self.enabled:
            return _NULL_CTX
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event."""
        if not self.enabled:
            return
        self._record(name, time.perf_counter_ns(), -1, args or None)

    def _record(self, name: str, t0_ns: int, dur_ns: int,
                args: Optional[dict]) -> None:
        ev = (name, t0_ns, dur_ns, threading.get_ident(), args)
        with self._lock:
            if len(self._events) < self.capacity:
                self._events.append(ev)
            else:
                self._events[self._head] = ev
                self._head = (self._head + 1) % self.capacity

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._head = 0
            self._epoch_ns = time.perf_counter_ns()

    def __len__(self) -> int:
        return len(self._events)

    @property
    def epoch_ns(self) -> int:
        """perf_counter_ns at construction/clear — ts=0 in the export.
        To merge same-clock tracers, pass
        ``offset_us=(tracer.epoch_ns - ref_epoch_ns) / 1e3``."""
        return self._epoch_ns

    # ------------------------------------------------------------ export
    def events(self) -> List[tuple]:
        """Chronological (name, ts_ns, dur_ns, tid, args) tuples."""
        with self._lock:
            ring = self._events[self._head:] + self._events[:self._head]
        return ring

    def chrome_trace(self, process_name: str = "noahgameframe_tpu",
                     pid: Optional[int] = None,
                     offset_us: float = 0.0) -> dict:
        """Chrome trace-event JSON object (Perfetto/about:tracing).

        ``pid`` overrides the OS pid so several tracers captured in one
        process (LocalCluster roles) still render as distinct Perfetto
        process tracks; ``offset_us`` shifts all timestamps onto a
        reference clock (feed it a ClockSync offset / 1e3) so a
        multi-process merge lines up — see
        :func:`noahgameframe_tpu.telemetry.pipeline.merge_chrome_traces`.
        """
        pid = os.getpid() if pid is None else int(pid)
        tid_map: Dict[int, int] = {}
        trace_events: List[dict] = [
            {
                "ph": "M", "pid": pid, "tid": 0,
                "name": "process_name",
                "args": {"name": process_name},
            }
        ]
        for name, ts_ns, dur_ns, tid, args in self.events():
            small_tid = tid_map.setdefault(tid, len(tid_map) + 1)
            ev = {
                "name": name,
                "pid": pid,
                "tid": small_tid,
                # trace-event timestamps are microseconds
                "ts": (ts_ns - self._epoch_ns) / 1000.0 + offset_us,
            }
            if dur_ns < 0:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = dur_ns / 1000.0
            if args:
                ev["args"] = {k: _jsonable(v) for k, v in args.items()}
            trace_events.append(ev)
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export(self, path: str, process_name: str = "noahgameframe_tpu") -> int:
        """Write the Chrome trace JSON; returns the span count."""
        doc = self.chrome_trace(process_name)
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(doc["traceEvents"]) - 1  # minus the metadata event


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


@contextlib.contextmanager
def device_annotation(name: str):
    """jax.profiler.TraceAnnotation when available (shows the host block
    on the XProf timeline next to the device stream), else a no-op —
    keeps call sites importable without jax."""
    try:
        import jax

        with jax.profiler.TraceAnnotation(name):
            yield
    except Exception:  # noqa: BLE001 — profiler backends vary by platform
        yield
