"""Log module: role-aware structured game logging with rollover.

Reference: NFLogPlugin wraps easylogging++ — per-server conf files, a
level enum (`NLL_DEBUG_NORMAL…NLL_FATAL_NORMAL`), the game-specific API
surface `LogElement/LogProperty/LogRecord/LogObject/LogNormal`
(`NFCLogModule.h:34-49`) and a 200 MB rollout handler
(`NFCLogModule.cpp:33-50`).  Implemented over stdlib logging with size
rollover; the game-specific calls format GUID/property/record context
the same way so grep-driven ops workflows carry over.
"""

from __future__ import annotations

import enum
import logging
import logging.handlers
import sys
from pathlib import Path
from typing import Optional

from ..core.datatypes import Guid
from ..kernel.module import Module

ROLLOVER_BYTES = 200 * 1024 * 1024  # the reference's 200 MB rollout


class LogLevel(enum.IntEnum):
    """NF_LOG_LEVEL (NFILogModule.h)."""

    DEBUG = logging.DEBUG
    INFO = logging.INFO
    WARNING = logging.WARNING
    ERROR = logging.ERROR
    FATAL = logging.CRITICAL


class LogModule(Module):
    name = "LogModule"

    def __init__(
        self,
        app_name: str = "server",
        app_id: int = 0,
        log_dir: Optional[Path] = None,
        level: LogLevel = LogLevel.INFO,
        to_stderr: bool = False,
        rollover_bytes: int = ROLLOVER_BYTES,
        backups: int = 5,
    ) -> None:
        super().__init__()
        self.app_name = app_name
        self.app_id = app_id
        self._logger = logging.getLogger(f"nf.{app_name}.{app_id}")
        self._logger.setLevel(int(level))
        self._logger.propagate = False
        # getLogger returns a shared instance: drop handlers left by a
        # previous LogModule with the same identity (restart paths) so
        # lines aren't duplicated into leaked file handles
        for h in list(self._logger.handlers):
            h.close()
            self._logger.removeHandler(h)
        fmt = logging.Formatter(
            "%(asctime)s [%(levelname)s] " + f"{app_name}:{app_id} "
            + "%(message)s"
        )
        if log_dir is not None:
            log_dir = Path(log_dir)
            log_dir.mkdir(parents=True, exist_ok=True)
            h = logging.handlers.RotatingFileHandler(
                log_dir / f"{app_name}_{app_id}.log",
                maxBytes=rollover_bytes,
                backupCount=backups,
            )
            h.setFormatter(fmt)
            self._logger.addHandler(h)
        if to_stderr or log_dir is None:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(fmt)
            self._logger.addHandler(h)

    # -- plain levels ----------------------------------------------------
    def log(self, level: int, msg: str, *args) -> None:
        # level is a LogLevel (IntEnum) — declared int: a host scalar,
        # never a traced value
        self._logger.log(int(level), msg, *args)

    def debug(self, msg: str, *args) -> None:
        self.log(LogLevel.DEBUG, msg, *args)

    def info(self, msg: str, *args) -> None:
        self.log(LogLevel.INFO, msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.log(LogLevel.WARNING, msg, *args)

    def error(self, msg: str, *args) -> None:
        self.log(LogLevel.ERROR, msg, *args)

    def fatal(self, msg: str, *args) -> None:
        self.log(LogLevel.FATAL, msg, *args)

    # -- game-shaped API (reference NFCLogModule.h:34-49) ----------------
    def log_normal(self, level: LogLevel, guid: Guid, msg: str,
                   detail: str = "") -> None:
        self.log(level, "[%s] %s %s", guid, msg, detail)

    def log_element(self, level: LogLevel, guid: Guid, element_id: str,
                    desc: str = "") -> None:
        self.log(level, "[%s] element=%s %s", guid, element_id, desc)

    def log_property(self, level: LogLevel, guid: Guid, prop_name: str,
                     desc: str = "") -> None:
        self.log(level, "[%s] property=%s %s", guid, prop_name, desc)

    def log_record(self, level: LogLevel, guid: Guid, record_name: str,
                   desc: str = "") -> None:
        self.log(level, "[%s] record=%s %s", guid, record_name, desc)

    def log_object(self, level: LogLevel, guid: Guid) -> None:
        """Dump one object's full state (reference LogObject / kernel
        LogSelfInfo, `NFCKernelModule.h:137-139`)."""
        k = self.kernel
        if k is None or guid not in k.store.guid_map:
            self.log(level, "[%s] <no such object>", guid)
            return
        cname, _ = k.store.row_of(guid)
        spec = k.store.spec(cname)
        parts = []
        for pname in spec.prop_order:
            try:
                parts.append(f"{pname}={k.get_property(guid, pname)!r}")
            except Exception:
                parts.append(f"{pname}=<err>")
        self.log(level, "[%s] class=%s %s", guid, cname, " ".join(parts))

    def shut(self) -> None:
        for h in list(self._logger.handlers):
            h.close()
            self._logger.removeHandler(h)
