"""Platform forcing for driver/test entry points.

This container's sitecustomize registers a tunnelled-TPU ("axon") PJRT
backend at interpreter startup and force-updates jax's config to
``jax_platforms="axon,cpu"`` — overriding any JAX_PLATFORMS env var.  So
forcing the CPU platform needs BOTH the env vars (for child processes /
pre-import) and a post-import ``jax.config.update`` (for this process).
The axon client init can hang indefinitely when the tunnel is
unreachable, which is why every CPU-only entry point must call this
before its first backend touch (round-1 driver failure mode).
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int | None = None):
    """Force jax onto the CPU platform, optionally with `n_devices`
    virtual devices.  Safe to call whether or not jax was already
    imported; if backends were already initialised they are cleared.
    Returns the jax module."""
    if n_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass
    return jax
