"""Platform forcing for driver/test entry points.

This container's sitecustomize registers a tunnelled-TPU ("axon") PJRT
backend at interpreter startup and force-updates jax's config to
``jax_platforms="axon,cpu"`` — overriding any JAX_PLATFORMS env var.  So
forcing the CPU platform needs BOTH the env vars (for child processes /
pre-import) and a post-import ``jax.config.update`` (for this process).
The axon client init can hang indefinitely when the tunnel is
unreachable, which is why every CPU-only entry point must call this
before its first backend touch (round-1 driver failure mode).
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int | None = None):
    """Force jax onto the CPU platform, optionally with `n_devices`
    virtual devices.  Safe to call whether or not jax was already
    imported; if backends were already initialised they are cleared.
    Returns the jax module."""
    if n_devices is not None:
        flag = f"--xla_force_host_platform_device_count={n_devices}"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" in flags:
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = (flags + " " + flag).strip()
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
    except Exception:
        pass
    return jax


def init_compile_cache(path: str | None = None) -> str | None:
    """Enable jax's persistent compilation cache so re-runs skip XLA
    compile entirely (the sharded tick at 512k x 8 virtual devices costs
    ~50 s to compile; a 4M ladder re-run should pay it once).  Path from
    the arg, else $NF_COMPILE_CACHE, else disabled.  Returns the path in
    effect (None = disabled)."""
    path = path or os.environ.get("NF_COMPILE_CACHE")
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path
