"""Host-side device reads with shape bucketing.

jnp fancy-indexing with a host-varying index length retraces and
recompiles per distinct length: a per-frame diff flush, whose
changed-row count differs almost every frame, turns into an XLA compile
per frame (measured: ~1000 compiles over 33 served frames at 50k
entities — compile time dwarfed the actual work).  `gather_rows` pads
the index to the next power of two so every (array shape, bucket) pair
compiles ONCE and the jit cache serves all later frames; the padding
rows (index 0, always valid) are sliced off after the fetch.

This is the serving-edge counterpart of the reference reading object
state synchronously off its in-process maps (NFCGameServerNet_Server's
OnPropertyEnter path) — here the state lives on device, so every read
must be a compiled gather with a cache-stable shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


from ..core.datatypes import next_pow2  # noqa: F401  (re-export)


@jax.jit
def _take0(arr, idx):
    return jnp.take(arr, idx, axis=0, mode="clip")


@jax.jit
def _take0_cols(arr, idx, cols):
    # XLA fuses the row gather with the column selection — no [N, ...]
    # column-slice intermediate ever materializes
    return jnp.take(arr, idx, axis=0, mode="clip")[:, cols]


def gather_rows(arr, rows: np.ndarray, cols=None) -> np.ndarray:
    """arr[rows] (optionally [:, cols]) fetched to host, with power-of-2
    index padding so the compiled gather is reused across frames.  `arr`
    is any device array with the row axis leading; `rows` a host int
    array; `cols` an optional column index (int or small sequence) fused
    into the same compiled call."""
    n = int(rows.size)
    if n == 0:
        shape = (0,) + tuple(arr.shape[1:])
        if cols is not None:
            c = np.atleast_1d(np.asarray(cols))
            shape = (0, c.size) + tuple(arr.shape[2:])
        return np.empty(shape, dtype=np.dtype(arr.dtype))
    m = next_pow2(n)
    idx = np.zeros(m, np.int32)
    idx[:n] = rows
    if cols is None:
        return np.asarray(_take0(arr, jnp.asarray(idx)))[:n]
    c = jnp.atleast_1d(jnp.asarray(cols, jnp.int32))
    return np.asarray(_take0_cols(arr, jnp.asarray(idx), c))[:n]
