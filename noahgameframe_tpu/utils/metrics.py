"""Per-tick metrics + profiler hooks — the observability the reference
lacks (SURVEY §5: easylogging's PERFORMANCE_TRACKING is disabled in every
conf; the TPU build replaces it with real timing + JAX profiler traces).
"""

from __future__ import annotations

import collections
import contextlib
import json
import time
from typing import Deque, Dict, Optional

import numpy as np

from ..kernel.module import Module


class TickMetrics(Module):
    """Rolling window of frame timings; p50/p95/p99, entities/sec, and a
    JSON-line emitter for dashboards (the master /json analogue)."""

    name = "TickMetrics"

    def __init__(self, window: int = 512) -> None:
        super().__init__()
        self.window = window
        self._durations: Deque[float] = collections.deque(maxlen=window)
        self._t0: Optional[float] = None
        self.frames = 0

    # call around the tick (world/role loops use the context wrapper)
    def frame_start(self) -> None:
        self._t0 = time.perf_counter()

    def frame_end(self) -> None:
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.frames += 1
        self._durations.append(dt)

    @contextlib.contextmanager
    def frame(self):
        self.frame_start()
        try:
            yield
        finally:
            self.frame_end()

    # -- aggregates ------------------------------------------------------
    def percentiles(self) -> Dict[str, float]:
        if not self._durations:
            return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                    "mean_ms": 0.0}
        a = np.asarray(self._durations) * 1e3
        return {
            "p50_ms": float(np.percentile(a, 50)),
            "p95_ms": float(np.percentile(a, 95)),
            "p99_ms": float(np.percentile(a, 99)),
            "mean_ms": float(a.mean()),
        }

    def live_entities(self) -> int:
        if self.kernel is None:
            return 0
        return sum(
            self.kernel.store.live_count(c)
            for c in self.kernel.store.class_order
        )

    def entities_per_second(self) -> float:
        if not self._durations:
            return 0.0
        mean_s = float(np.mean(self._durations))
        return self.live_entities() / mean_s if mean_s > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        out = dict(self.percentiles())
        out["frames"] = self.frames
        live = self.live_entities()
        mean_s = (float(np.mean(self._durations))
                  if self._durations else 0.0)
        out["entities_per_s"] = live / mean_s if mean_s > 0 else 0.0
        out["live"] = live
        return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot())


class MemoryCensus(Module):
    """Live-object census per kind — the reference's NFMemoryCounter
    (global class-name -> live-instance-count map inherited by core
    types, NFMemoryCounter.cpp:13-27) rebuilt for the SoA world: entity
    rows per class from the store allocators, plus host-side registries
    (actor mailboxes, per-object components, net sessions) registered as
    probes.  XLA owns device memory, so device bytes are reported from
    live device buffers when available."""

    name = "MemoryCensus"

    def __init__(self) -> None:
        super().__init__()
        self._probes: Dict[str, object] = {}

    def register_probe(self, kind: str, fn) -> None:
        """fn() -> int live count for a host-side object kind."""
        self._probes[kind] = fn

    def census(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        if self.kernel is not None and self.kernel.store is not None:
            for c in self.kernel.store.class_order:
                out[f"entity:{c}"] = self.kernel.store.live_count(c)
        for kind, fn in self._probes.items():
            try:
                out[kind] = int(fn())
            except Exception:  # noqa: BLE001 — census must never throw
                out[kind] = -1
        return out

    def device_bytes(self) -> int:
        """Bytes held by this process's live device arrays (best effort)."""
        try:
            import jax

            return sum(
                buf.nbytes
                for buf in jax.live_arrays()
                if hasattr(buf, "nbytes")
            )
        except Exception:  # noqa: BLE001
            return -1

    def json_line(self) -> str:
        out = dict(self.census())
        out["device_bytes"] = self.device_bytes()
        return json.dumps(out)


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """JAX profiler capture around a block — open the result with
    TensorBoard/XProf to see the compiled tick's device timeline."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
