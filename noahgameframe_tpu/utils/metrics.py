"""Per-tick metrics + profiler hooks — the observability the reference
lacks (SURVEY §5: easylogging's PERFORMANCE_TRACKING is disabled in every
conf; the TPU build replaces it with real timing + JAX profiler traces).

The timing window and ALL percentile math live in
:class:`~noahgameframe_tpu.telemetry.registry.Histogram` — TickMetrics
is a thin frame-timing facade over one histogram instance, so the role
report, the bench JSON and a ``/metrics`` scrape read the same numbers
from the same samples.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Dict, Optional

from ..kernel.module import Module
from ..telemetry.registry import Histogram


class TickMetrics(Module):
    """Rolling window of frame timings; p50/p95/p99, entities/sec, and a
    JSON-line emitter for dashboards (the master /json analogue)."""

    name = "TickMetrics"

    def __init__(self, window: int = 512,
                 histogram: Optional[Histogram] = None) -> None:
        super().__init__()
        self.window = window
        # the histogram owns the sample window AND the percentile math;
        # pass a registry-owned instance to surface frames on /metrics
        self.hist = histogram if histogram is not None else Histogram(
            "nf_frame_seconds", "main-loop frame latency", window=window
        )
        self._t0: Optional[float] = None
        self.frames = 0

    @property
    def _durations(self):
        """The raw window in seconds (compat view; the histogram owns it)."""
        return self.hist.window_values()

    # call around the tick (world/role loops use the context wrapper)
    def frame_start(self) -> None:
        self._t0 = time.perf_counter()

    def frame_end(self) -> None:
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.frames += 1
        self.hist.observe(dt)

    @contextlib.contextmanager
    def frame(self):
        self.frame_start()
        try:
            yield
        finally:
            self.frame_end()

    # -- aggregates ------------------------------------------------------
    def _mean_s(self) -> float:
        """One mean, one place: every consumer below routes through it."""
        return self.hist.window_mean()

    def percentiles(self) -> Dict[str, float]:
        if not self.hist.count:
            return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                    "mean_ms": 0.0}
        return {
            "p50_ms": self.hist.percentile(50) * 1e3,
            "p95_ms": self.hist.percentile(95) * 1e3,
            "p99_ms": self.hist.percentile(99) * 1e3,
            "mean_ms": self._mean_s() * 1e3,
        }

    def live_entities(self) -> int:
        if self.kernel is None:
            return 0
        return sum(
            self.kernel.store.live_count(c)
            for c in self.kernel.store.class_order
        )

    def entities_per_second(self) -> float:
        mean_s = self._mean_s()
        return self.live_entities() / mean_s if mean_s > 0 else 0.0

    def snapshot(self) -> Dict[str, float]:
        out = dict(self.percentiles())
        out["frames"] = self.frames
        out["entities_per_s"] = self.entities_per_second()
        out["live"] = self.live_entities()
        return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot())


class MemoryCensus(Module):
    """Live-object census per kind — the reference's NFMemoryCounter
    (global class-name -> live-instance-count map inherited by core
    types, NFMemoryCounter.cpp:13-27) rebuilt for the SoA world: entity
    rows per class from the store allocators, plus host-side registries
    (actor mailboxes, per-object components, net sessions) registered as
    probes.  XLA owns device memory, so device bytes are reported from
    live device buffers when available."""

    name = "MemoryCensus"

    def __init__(self, log_module=None) -> None:
        super().__init__()
        self._probes: Dict[str, object] = {}
        # a probe that throws reports -1 but must not stay silent: each
        # failing kind is logged ONCE (LogModule when attached, stdlib
        # logger otherwise) so dead probes are discoverable in ops logs
        self.log_module = log_module
        self._failed_probes: set = set()

    def register_probe(self, kind: str, fn) -> None:
        """fn() -> int live count for a host-side object kind."""
        self._probes[kind] = fn
        self._failed_probes.discard(kind)

    def _log_probe_failure(self, kind: str, exc: Exception) -> None:
        if kind in self._failed_probes:
            return
        self._failed_probes.add(kind)
        msg = "memory census probe %r failed (reporting -1): %s: %s"
        args = (kind, type(exc).__name__, exc)
        if self.log_module is not None:
            self.log_module.warning(msg, *args)
        else:
            logging.getLogger("nf.metrics").warning(msg, *args)

    def census(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        if self.kernel is not None and self.kernel.store is not None:
            for c in self.kernel.store.class_order:
                out[f"entity:{c}"] = self.kernel.store.live_count(c)
        for kind, fn in self._probes.items():
            try:
                out[kind] = int(fn())
            except Exception as e:  # noqa: BLE001 — census must never throw
                self._log_probe_failure(kind, e)
                out[kind] = -1
        return out

    def device_bytes(self) -> int:
        """Bytes held by this process's live device arrays (best effort)."""
        try:
            import jax

            return sum(
                buf.nbytes
                for buf in jax.live_arrays()
                if hasattr(buf, "nbytes")
            )
        except Exception:  # noqa: BLE001
            return -1

    def json_line(self) -> str:
        out = dict(self.census())
        out["device_bytes"] = self.device_bytes()
        return json.dumps(out)


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """JAX profiler capture around a block — open the result with
    TensorBoard/XProf to see the compiled tick's device timeline (the
    per-stage ``jax.named_scope`` names from Kernel._trace_step)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
