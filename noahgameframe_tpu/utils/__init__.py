"""Infra utilities: logging, metrics, profiler hooks."""

from .log import LogLevel, LogModule  # noqa: F401
from .metrics import TickMetrics, profiler_trace  # noqa: F401
