"""Divergence bisection between two digest streams + state field diff.

Two runs that should agree (CPU vs TPU, 1-shard vs 8-shard mesh, Verlet
skin=0 vs skin=2, live vs replay) each leave a per-tick digest stream.
State divergence is persistent under the tick — once the worlds differ,
their digests keep differing (a uint32 collision every tick thereafter
is astronomically unlikely) — so the first divergent tick is a monotone
boundary and binary search finds it in O(log n) digest compares instead
of a linear scan.  With the tick in hand, replay both runs up to it and
diff the flattened WorldState field by field.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

import numpy as np

from ..persist.checkpoint import _flatten_state


def bisect_divergence(a: Mapping[int, int],
                      b: Mapping[int, int]) -> Optional[int]:
    """First tick where the two digest streams disagree, or None.

    Binary search over the common tick range, relying on divergence
    persistence (see module docstring).  The found boundary is verified
    forward at geometrically spaced probes — a divergence that HEALS
    after the boundary breaks the persistence assumption and raises
    ValueError instead of returning a wrong answer.  A purely transient
    blip whose streams re-agree at the tail is invisible here by
    construction (the search never looks at it): use
    :func:`first_divergence_linear` for streams where healing is
    possible."""
    common = sorted(set(a) & set(b))
    if not common:
        return None
    if a[common[0]] != b[common[0]]:
        return common[0]
    if a[common[-1]] == b[common[-1]]:
        return None
    lo, hi = 0, len(common) - 1  # invariant: equal at lo, diverged at hi
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if a[common[mid]] == b[common[mid]]:
            lo = mid
        else:
            hi = mid
    step = 1
    while hi + step < len(common):  # forward persistence probes
        t = common[hi + step]
        if a[t] == b[t]:
            raise ValueError(
                f"digest streams re-agree at tick {t} after diverging at "
                f"{common[hi]} — divergence is not persistent, fall back "
                f"to a linear scan"
            )
        step *= 2
    return common[hi]


def first_divergence_linear(a: Mapping[int, int],
                            b: Mapping[int, int]) -> Optional[int]:
    """Exact linear scan — for streams where divergence might heal
    (e.g. a perturbed value that a later phase clamps back)."""
    for t in sorted(set(a) & set(b)):
        if a[t] != b[t]:
            return t
    return None


def field_diff(state_a, state_b, max_per_key: int = 8) -> List[dict]:
    """Field-level WorldState diff: every flattened bank (see
    persist.checkpoint._flatten_state) where the two states disagree,
    with the first `max_per_key` differing cells spelled out."""
    fa, fb = _flatten_state(state_a), _flatten_state(state_b)
    out: List[dict] = []
    for key in fa:
        va = fa[key]
        vb = fb.get(key)
        if vb is None or va.shape != vb.shape:
            out.append({"key": key, "error": "shape/layout mismatch",
                        "a_shape": list(va.shape),
                        "b_shape": list(vb.shape) if vb is not None else None})
            continue
        neq = np.atleast_1d(va != vb)
        if not neq.any():
            continue
        idx = np.argwhere(neq)
        cells = []
        flat_a, flat_b = np.atleast_1d(va), np.atleast_1d(vb)
        for i in idx[:max_per_key]:
            t = tuple(int(x) for x in i)
            cells.append({"index": t,
                          "a": flat_a[t].item(),
                          "b": flat_b[t].item()})
        out.append({"key": key, "count": int(idx.shape[0]), "cells": cells})
    return out


def dump_divergence(
    journal_a,
    journal_b,
    world_factory=None,
    checkpoint_a=None,
    checkpoint_b=None,
    max_per_key: int = 8,
) -> dict:
    """End-to-end bisect: locate the first divergent tick between two
    journaled runs, replay each side up to it, and return the field
    diff.  Both replays run on THIS host's backend — the point is to
    materialize the states the digests fingerprinted."""
    from .journal import read_ticks
    from .replayer import make_offline_role, replay_journal

    da, db = read_ticks(journal_a), read_ticks(journal_b)
    tick = bisect_divergence(da, db)
    if tick is None:
        return {"tick": None, "diff": []}
    states = []
    for jdir, ckpt in ((journal_a, checkpoint_a), (journal_b, checkpoint_b)):
        role = make_offline_role(
            world_factory() if world_factory is not None else None
        )
        try:
            replay_journal(jdir, checkpoint=ckpt, role=role, upto=tick)
            states.append(role.kernel.state)
        finally:
            role.shut()
    return {"tick": tick, "diff": field_diff(*states, max_per_key=max_per_key)}


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m noahgameframe_tpu.replay.bisect A_JOURNAL B_JOURNAL``
    — digest-only bisection (no state materialization)."""
    import argparse

    from .journal import read_ticks

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("journal_a")
    ap.add_argument("journal_b")
    args = ap.parse_args(argv)
    da, db = read_ticks(args.journal_a), read_ticks(args.journal_b)
    overlap = len(set(da) & set(db))
    tick = bisect_divergence(da, db)
    if tick is None:
        print(f"no divergence across {overlap} common ticks")
        return 0
    print(f"first divergent tick: {tick} "
          f"(a={da[tick]:#010x} b={db[tick]:#010x})")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
