"""Append-only, segmented, CRC-framed tick journal.

Framing follows the wire discipline of :mod:`net.framing` — a fixed
header carrying an id and an explicit length, decoded incrementally with
hard bounds — plus a CRC32 per record, because unlike a TCP stream a
file on disk CAN be torn or bit-flipped and the reader must fail closed
(`test_wire_fuzz.py` covers the stream case; `tests/test_replay.py`
fuzzes this one).

Layout of a journal directory::

    journal.json            run metadata (world seed, dt, writer info)
    seg-00000001.nfj        segment: 8-byte magic, then records
    seg-00000002.nfj        ...rotated by size at record boundaries

Record frame (header ``>HII`` = 10 bytes, big-endian like the wire)::

    +---------+-----------+-----------+----------------+
    | type u16| length u32| crc32 u32 | body (length)  |
    +---------+-----------+-----------+----------------+

Record types:

- ``REC_META``  — JSON; one per segment head (self-describing segments)
- ``REC_EVENT`` — one dispatched net event (``>Bqii`` source/conn/kind/
  msg_id + raw body bytes), in exact dispatch order
- ``REC_TICK``  — ``>qI`` kernel tick count + uint32 on-device state
  digest, written after every completed tick
- ``REC_NOTE``  — JSON epoch markers (chaos seed + link budgets, config
  changes, resumes)
- ``REC_CKPT``  — ``>q`` tick at which an atomic checkpoint landed; the
  writer fsyncs here so the ``(checkpoint, journal-suffix)`` pair on
  disk is always mutually recoverable

The writer rotates segments by size and fsyncs the old segment before
opening the next, so only the very tail of the newest segment is ever
at risk from a crash — exactly the suffix the checkpoint protocol
already bounds.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from pathlib import Path
from typing import Dict, Iterator, Optional, Tuple

SEGMENT_MAGIC = b"NFJSEG1\n"
SEGMENT_GLOB = "seg-*.nfj"
HEADER = struct.Struct(">HII")  # (rec_type, body_len, crc32)
EVENT_HEAD = struct.Struct(">Bqii")  # (source, conn_id, kind, msg_id)
TICK_BODY = struct.Struct(">qI")  # (tick, digest)
CKPT_BODY = struct.Struct(">q")  # (tick,)

REC_META = 1
REC_EVENT = 2
REC_TICK = 3
REC_NOTE = 4
REC_CKPT = 5
_KNOWN_RECS = (REC_META, REC_EVENT, REC_TICK, REC_NOTE, REC_CKPT)

# which endpoint dispatched a journaled event
SRC_SERVER = 0  # the role's listening NetServerModule (client/proxy side)
SRC_WORLD = 1  # the world-link NetClientModule (world commands, switches)

# same ceiling as net.framing.MAX_FRAME_SIZE: a length field pointing
# past it is corruption, not a big record
MAX_RECORD_SIZE = 64 * 1024 * 1024


class JournalError(Exception):
    """Raised on any malformed journal byte — torn tail, bad magic, CRC
    mismatch, impossible length, unknown record type.  Replay must never
    silently skip input."""


def _segment_name(index: int) -> str:
    return f"seg-{index:08d}.nfj"


def _segment_index(path: Path) -> int:
    return int(path.stem.split("-", 1)[1])


class JournalWriter:
    """Appender for one recording run.  Single-owner, pump-thread only
    (the roles are single-threaded); durability points are explicit via
    :meth:`sync`, which :meth:`GameRole.checkpoint_now` calls."""

    def __init__(self, path, meta: Optional[dict] = None,
                 segment_bytes: int = 1 << 20) -> None:
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = max(4096, int(segment_bytes))
        existing = sorted(self.path.glob(SEGMENT_GLOB))
        self._seg_index = (_segment_index(existing[-1]) if existing else 0)
        self._file = None
        self._seg_size = 0
        # telemetry feed (nf_journal_*_total): monotonic over the writer
        self.bytes_total = 0
        self.segments_total = 0
        self.ticks_total = 0
        self.last_tick = -1
        self.meta = dict(meta or {})
        (self.path / "journal.json").write_text(
            json.dumps({"version": 1, "meta": self.meta})
        )
        self._open_segment()

    # ------------------------------------------------------------ segments
    def _open_segment(self) -> None:
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
        self._seg_index += 1
        self._file = open(self.path / _segment_name(self._seg_index), "wb")
        self._file.write(SEGMENT_MAGIC)
        self._seg_size = len(SEGMENT_MAGIC)
        self.bytes_total += len(SEGMENT_MAGIC)
        self.segments_total += 1
        self._append(REC_META, json.dumps(
            {"segment": self._seg_index, "after_tick": self.last_tick}
        ).encode())
        # push the header past the userspace buffer right away: open()
        # already created the file, so without this a concurrent reader
        # (live digest checks, the failover smoke) sees an EMPTY segment
        # and calls it corrupt — fail-closed readers need the magic on
        # disk the moment the segment is observable
        self._file.flush()

    def _append(self, rec_type: int, body: bytes) -> None:
        if self._file is None:
            raise JournalError("journal writer is closed")
        if len(body) > MAX_RECORD_SIZE:
            raise JournalError(
                f"record body {len(body)} exceeds {MAX_RECORD_SIZE}"
            )
        frame = HEADER.pack(rec_type, len(body), zlib.crc32(body)) + body
        self._file.write(frame)
        self._seg_size += len(frame)
        self.bytes_total += len(frame)

    # ------------------------------------------------------------- records
    def event(self, source: int, kind: int, conn_id: int, msg_id: int,
              body: bytes) -> None:
        """One dispatched net event, in dispatch order (the host→device
        boundary: every world mutation between two ticks comes from
        these)."""
        self._append(
            REC_EVENT,
            EVENT_HEAD.pack(int(source), int(conn_id), int(kind),
                            int(msg_id)) + bytes(body),
        )

    def tick_mark(self, tick: int, digest: int) -> None:
        """Close the tick window: everything journaled since the last
        mark fed THIS tick, whose post-state hashes to `digest`.
        Rotation happens here — between ticks — so one tick's input
        window never straddles a segment boundary mid-event."""
        self._append(REC_TICK, TICK_BODY.pack(int(tick),
                                              int(digest) & 0xFFFFFFFF))
        self.ticks_total += 1
        self.last_tick = int(tick)
        if self._seg_size >= self.segment_bytes:
            self._open_segment()

    def note(self, info: dict) -> None:
        """Epoch marker (chaos seed + budgets, config flips, resume)."""
        self._append(REC_NOTE, json.dumps(info, default=str).encode())

    def checkpoint_mark(self, tick: int) -> None:
        """Record that an atomic checkpoint landed after `tick`, then
        make everything up to here durable — the journal suffix past the
        newest mark is exactly what replay needs on top of that
        checkpoint."""
        self._append(REC_CKPT, CKPT_BODY.pack(int(tick)))
        self.sync()

    def sync(self) -> None:
        if self._file is not None:
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        if self._file is not None:
            self.sync()
            self._file.close()
            self._file = None


class JournalReader:
    """Strict, ordered reader over every segment of a journal directory.
    Any framing violation raises :class:`JournalError` with the segment
    and byte offset — fail closed, never guess."""

    def __init__(self, path) -> None:
        self.path = Path(path)
        if not self.path.is_dir():
            raise JournalError(f"no journal directory at {self.path}")
        self.segments = sorted(self.path.glob(SEGMENT_GLOB),
                               key=_segment_index)
        if not self.segments:
            raise JournalError(f"no segments in {self.path}")
        meta_path = self.path / "journal.json"
        self.meta: dict = {}
        if meta_path.exists():
            try:
                self.meta = json.loads(meta_path.read_text()).get("meta", {})
            except ValueError as e:
                raise JournalError(f"corrupt journal.json: {e}") from e

    def __iter__(self) -> Iterator[Tuple[int, bytes]]:
        for seg in self.segments:
            yield from self._iter_segment(seg)

    def _iter_segment(self, seg: Path) -> Iterator[Tuple[int, bytes]]:
        data = seg.read_bytes()
        if data[: len(SEGMENT_MAGIC)] != SEGMENT_MAGIC:
            raise JournalError(f"{seg.name}: bad segment magic")
        off = len(SEGMENT_MAGIC)
        while off < len(data):
            if off + HEADER.size > len(data):
                raise JournalError(
                    f"{seg.name}@{off}: torn record header "
                    f"({len(data) - off} of {HEADER.size} bytes)"
                )
            rec_type, length, crc = HEADER.unpack_from(data, off)
            if rec_type not in _KNOWN_RECS:
                raise JournalError(
                    f"{seg.name}@{off}: unknown record type {rec_type}"
                )
            if length > MAX_RECORD_SIZE:
                raise JournalError(
                    f"{seg.name}@{off}: record length {length} exceeds "
                    f"{MAX_RECORD_SIZE}"
                )
            off += HEADER.size
            if off + length > len(data):
                raise JournalError(
                    f"{seg.name}@{off}: torn record body "
                    f"({len(data) - off} of {length} bytes)"
                )
            body = data[off: off + length]
            if zlib.crc32(body) != crc:
                raise JournalError(f"{seg.name}@{off}: CRC mismatch")
            off += length
            yield rec_type, body


# --------------------------------------------------------------- decoding
def decode_event(body: bytes) -> Tuple[int, int, int, int, bytes]:
    """-> (source, conn_id, kind, msg_id, payload)."""
    if len(body) < EVENT_HEAD.size:
        raise JournalError(f"event record too short ({len(body)} bytes)")
    source, conn_id, kind, msg_id = EVENT_HEAD.unpack_from(body)
    return source, conn_id, kind, msg_id, body[EVENT_HEAD.size:]


def decode_tick(body: bytes) -> Tuple[int, int]:
    """-> (tick, digest)."""
    if len(body) != TICK_BODY.size:
        raise JournalError(f"tick record wrong size ({len(body)} bytes)")
    return TICK_BODY.unpack(body)


def decode_ckpt(body: bytes) -> int:
    if len(body) != CKPT_BODY.size:
        raise JournalError(f"ckpt record wrong size ({len(body)} bytes)")
    return CKPT_BODY.unpack(body)[0]


def decode_json(body: bytes) -> dict:
    try:
        return json.loads(body.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise JournalError(f"corrupt JSON record: {e}") from e


def read_ticks(path) -> Dict[int, int]:
    """The digest stream: tick -> uint32 digest, every tick on record.
    This is all bisect needs from a run."""
    out: Dict[int, int] = {}
    for rec_type, body in JournalReader(path):
        if rec_type == REC_TICK:
            tick, digest = decode_tick(body)
            out[tick] = digest
    return out
