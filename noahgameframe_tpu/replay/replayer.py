"""Offline re-execution of a journaled GameRole.

The recorded role's device state evolved from exactly two inputs: the
net events its dispatchers delivered between ticks, and the jitted tick
itself (whose RNG is folded from state, not wall clock).  So replay is:
load the checkpoint, then for each journaled tick window feed the
recorded events through the role's REAL dispatch tables (same handlers,
same fault isolation) and run the REAL compiled tick — no network, no
timers, no proxy.  After every tick the on-device digest (kernel counter
bank, "state_digest") must equal the journaled one bit for bit; any
mismatch is a divergence, counted on ``nf_replay_divergences_total``.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from ..game.world import GameWorld
from ..net.defines import ServerType
from ..net.roles.base import RoleConfig
from ..net.roles.game import GameRole
from ..net.transport import NetEvent
from .journal import (
    JournalReader,
    JournalError,
    REC_CKPT,
    REC_EVENT,
    REC_META,
    REC_NOTE,
    REC_TICK,
    SRC_SERVER,
    decode_event,
    decode_tick,
)


@dataclasses.dataclass
class ReplayReport:
    """Outcome of one replay pass."""

    start_tick: int
    ticks_replayed: int = 0
    events_fed: int = 0
    # tick -> uint32 digest: what replay computed vs what was journaled
    digests: Dict[int, int] = dataclasses.field(default_factory=dict)
    expected: Dict[int, int] = dataclasses.field(default_factory=dict)
    divergences: List[Tuple[int, int, int]] = dataclasses.field(
        default_factory=list
    )  # (tick, expected, got)
    notes: List[dict] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.ticks_replayed > 0 and not self.divergences

    @property
    def first_divergence(self) -> Optional[int]:
        return self.divergences[0][0] if self.divergences else None

    def summary(self) -> str:
        if self.ok:
            return (f"REPLAY OK: {self.ticks_replayed} ticks from "
                    f"{self.start_tick}, {self.events_fed} events, "
                    f"all digests bit-identical")
        if not self.ticks_replayed:
            return f"REPLAY EMPTY: no journaled ticks past {self.start_tick}"
        t, want, got = self.divergences[0]
        return (f"REPLAY DIVERGED at tick {t}: journal {want:#010x} vs "
                f"replay {got:#010x} ({len(self.divergences)} of "
                f"{self.ticks_replayed} ticks differ)")


def make_offline_role(world: Optional[GameWorld] = None, server_id: int = 6,
                      name: str = "Replay", backend: str = "auto") -> GameRole:
    """A GameRole with no upstreams and swallowed sends — the handler
    tables and tick loop are real, the network is inert.  Build it with
    the SAME world recipe (and kwargs that shape handlers) as the
    recorded role, or the handlers won't compute the same mutations."""
    role = GameRole(
        RoleConfig(server_id, int(ServerType.GAME), name, "127.0.0.1", 0,
                   targets=[]),
        backend=backend,
        world=world,
    )
    # replies/broadcasts target connections that only existed in the
    # recorded run; swallow them (the recorded role's sends are outputs,
    # not inputs — they cannot affect device state)
    role.server.send_raw = lambda _conn, _msg, _body: True
    return role


def _drive_tick(role: GameRole) -> None:
    """GameRole.execute()'s exact tick block, minus the wall-clock gate
    (chaos_smoke drives its control world identically)."""
    pm = role.game_world.pm
    for m in pm.modules.values():
        if m is not role.kernel:
            m.execute()
    role.kernel.execute()
    role.kernel.tick()
    pm.frame += 1
    # no clients: drop the sync accumulators a live role would flush
    role._changed.clear()
    role._rec_changed.clear()
    role._interest_dirty.clear()


def replay_journal(
    journal_dir,
    world_factory: Optional[Callable[[], GameWorld]] = None,
    checkpoint=None,
    role: Optional[GameRole] = None,
    upto: Optional[int] = None,
    perturb: Optional[Callable[[GameRole, int], None]] = None,
) -> ReplayReport:
    """Replay `journal_dir` and verify every per-tick digest.

    - `role` or `world_factory` provides the substrate (same recipe as
      the recorded role); with neither, the stock GameRole world is
      built — right only for roles started with the stock world.
    - `checkpoint` (a persist.checkpoint directory) positions the world;
      journaled ticks at or before its tick are skipped, the rest must
      be contiguous from it.
    - `upto` stops after that tick (bisect replays a prefix).
    - `perturb(role, tick)` runs before each tick — divergence-injection
      hook for tests and for what-if debugging.

    Returns a :class:`ReplayReport`; divergences also increment the
    role's ``nf_replay_divergences_total``.
    """
    reader = JournalReader(journal_dir)
    if role is None:
        role = make_offline_role(
            world_factory() if world_factory is not None else None
        )
    k = role.kernel
    # the recorded role pinned its guid allocator at journal setup (the
    # seed is in the meta); pin ours to the same point so every replayed
    # create mints the exact recorded guid — handlers keyed by
    # wire-carried guids (the switch ack's destroy) depend on it
    guid_seed = reader.meta.get("guid_seed")
    if guid_seed is not None:
        k.store.guids.pin(int(guid_seed))
    k.enable_digest()
    if checkpoint is not None and (Path(checkpoint) / "meta.json").exists():
        role.game_world.load(checkpoint)
    report = ReplayReport(start_tick=k.tick_count)
    div_counter = role.telemetry.registry.counter(
        "nf_replay_divergences_total",
        "replayed ticks whose state digest differed from the journal",
    )
    pending: List[Tuple[int, int, int, int, bytes]] = []
    for rec_type, body in reader:
        if rec_type == REC_EVENT:
            pending.append(decode_event(body))
        elif rec_type == REC_TICK:
            tick, want = decode_tick(body)
            if tick <= report.start_tick:
                # this window's effects are already inside the checkpoint
                pending.clear()
                continue
            if upto is not None and tick > upto:
                break
            if tick != k.tick_count + 1:
                raise JournalError(
                    f"journal tick {tick} is not contiguous with world "
                    f"tick {k.tick_count} — wrong checkpoint for this "
                    f"journal suffix?"
                )
            for source, conn_id, kind, msg_id, payload in pending:
                dispatch = (role.server.dispatch if source == SRC_SERVER
                            else role.world_link.dispatch)
                dispatch.feed([NetEvent(kind, conn_id, msg_id, payload)])
                report.events_fed += 1
            pending.clear()
            if perturb is not None:
                perturb(role, tick)
            _drive_tick(role)
            got = k.last_counters.get("state_digest", 0) & 0xFFFFFFFF
            report.digests[tick] = got
            report.expected[tick] = want
            report.ticks_replayed += 1
            if got != want:
                report.divergences.append((tick, want, got))
                div_counter.inc()
        elif rec_type == REC_NOTE:
            from .journal import decode_json

            report.notes.append(decode_json(body))
        elif rec_type in (REC_META, REC_CKPT):
            continue
    return report
