"""Deterministic flight recorder: per-tick input journal + replay.

The world tick is a pure function of its inputs (SURVEY §3.3: injected
commands + config in, diffs out), and PR 2/3 proved the property end to
end — checkpoints restore bit-identical worlds, 120-tick soaks stay
bit-identical.  This package turns that from a test-only property into
an operational one:

- :mod:`journal` — append-only, segmented, CRC-framed log of everything
  that crosses the host→device boundary in a live GameRole (dispatched
  net events, tick markers with on-device state digests, checkpoint
  marks, chaos/config notes);
- :mod:`replayer` — rebuild a GameRole offline from a
  ``(checkpoint, journal-suffix)`` pair by re-feeding the journaled
  events through the real handlers and the real jitted tick, asserting
  every per-tick digest;
- :mod:`bisect` — binary-search the first divergent tick between two
  runs via their digest streams, then dump a field-level WorldState
  diff at that tick.
"""

from .journal import (  # noqa: F401
    JournalError,
    JournalReader,
    JournalWriter,
    REC_CKPT,
    REC_EVENT,
    REC_META,
    REC_NOTE,
    REC_TICK,
    SRC_SERVER,
    SRC_WORLD,
    read_ticks,
)
from .replayer import ReplayReport, make_offline_role, replay_journal  # noqa: F401
from .bisect import bisect_divergence, field_diff  # noqa: F401
