"""Supervised session failover (ISSUE 10): a game-role CRASH becomes a
bounded latency blip instead of a session loss.

The reference treats re-homing a live player between game servers as a
first-class supervised flow (`NFCWorldNet_ServerModule.cpp:600-830`) but
only when a game ASKS; a crashed game orphans its sessions.  Here the
world drives the same `SWITCH_SERVER_DATA` / `REQ_SWITCH_SERVER` /
`ACK_SWITCH_SERVER` protocol on the dead game's behalf:

1. Every game reports each session's bind metadata to the world
   (SESSION_BIND_NOTIFY sidecar to ACK_ONLINE_NOTIFY): account/name,
   proxy-side client ident, scene/group, and the persist key the
   player's durable blob lives under.
2. When the lease sweep (or socket loss) marks a game CRASH, the
   :class:`FailoverDriver` reconstructs each bound player's blob from
   the newest durable (checkpoint, WAL suffix) pair — the PR 6 recovery
   path, read-side and read-only via
   :func:`persist.writebehind.read_peer_wal` — falling back to the
   store itself, and stages it to the least-loaded survivor exactly as
   the dead game would have (DATA then REQ on the same conn, so they
   cannot reorder).
3. The target admits the blob through the existing switch-in path and
   acks; the driver intercepts the ack (the origin it names is dead)
   and marks the session re-homed.  A target without capacity answers
   ACK_SWITCH_REFUSED and the driver retries elsewhere with backoff,
   giving up only at ``NF_FAILOVER_DEADLINE_S``.
4. Meanwhile the proxy **parks** (bounded, deadline-capped —
   :class:`ParkingBuffer`) client frames headed for the dead binding
   and replays them in order once the target's re-point lands, so
   in-flight sessions see a stall, not a drop.

Thread contract: everything here runs on the owning role's pump thread.
No sleeps, no blocking I/O on the parking path — enforced structurally
by the nf-lint ``pump-surface`` rule (docs/LINT.md).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import time as _time
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

from .defines import MsgID, ServerState
from .wire import (
    AckSwitchServer,
    Ident,
    ReqSwitchServer,
    SwitchRefused,
    SwitchServerData,
    ident_key as _ident_key,
    wrap,
)

#: SwitchRefused.result codes (TPU-native; 0 is never sent)
REFUSE_BUSY = 1      # target at Player capacity — try another survivor
REFUSE_BAD_BLOB = 2  # staged blob failed to apply (torn in transit)

#: knob defaults (env-overridable; constructor args win over env)
DEADLINE_S_DEFAULT = 10.0
PARK_MAX_FRAMES_DEFAULT = 256


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def ext_map(report) -> Dict[str, str]:
    """A ServerInfoReport's ext key/value list as a str→str map (the
    wire carries bytes); tolerant of missing/empty ext."""
    ext = getattr(report, "server_info_list_ext", None)
    if ext is None or not ext.key:
        return {}

    def s(v):
        return (v.decode("utf-8", "replace")
                if isinstance(v, (bytes, bytearray)) else str(v))

    return {s(k): s(v) for k, v in zip(ext.key, ext.value)}


@dataclasses.dataclass
class SessionInfo:
    """One live session's re-home metadata, as reported by the owning
    game via SESSION_BIND_NOTIFY.  ``selfid``/``client_id`` are
    (svrid, index) ident keys."""

    selfid: Tuple[int, int]
    account: str
    name: str
    client_id: Tuple[int, int]
    scene_id: int
    group_id: int
    save_key: str
    game_id: int


class ParkingBuffer:
    """Bounded, deadline-capped hold queue for client frames whose bound
    game died mid-flight (proxy-owned; keyed by client conn id).

    Two drop disciplines, both counted under
    ``nf_failover_dropped_total``:

    - **overflow** (oldest-drop): a session may park at most
      ``NF_PARK_MAX_FRAMES`` frames; beyond that the oldest go first —
      the newest input is the one the player still cares about.
    - **deadline**: frames parked longer than ``NF_FAILOVER_DEADLINE_S``
      are dropped wholesale — at that point the failover itself has
      given up and replaying stale input would be worse than losing it.

    Replay preserves arrival order per session and stops (leaving the
    remainder parked) the moment a send fails, so a flapping new binding
    cannot reorder or lose the tail.
    """

    def __init__(self, max_frames: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 registry=None) -> None:
        self.max_frames = (max_frames if max_frames is not None
                           else _env_int("NF_PARK_MAX_FRAMES",
                                         PARK_MAX_FRAMES_DEFAULT))
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("NF_FAILOVER_DEADLINE_S",
                                           DEADLINE_S_DEFAULT))
        self._q: Dict[object, Deque[Tuple[float, int, bytes, int]]] = {}
        self.parked_total = 0
        self.replayed_total = 0
        self.dropped_overflow = 0
        self.dropped_deadline = 0
        self.dropped_disconnect = 0
        # per-frame arrival stamp + per-key replay audit (ISSUE 11): the
        # drill's ordered-replay invariant reads `order_violations` every
        # pump, so an ordering bug is caught the tick it happens instead
        # of (maybe) surfacing as a scrambled chat log much later
        self._seq = 0
        self._last_replayed: Dict[object, int] = {}
        self.order_violations = 0
        self._c_parked = self._c_replayed = self._c_dropped = None
        if registry is not None:
            self._c_parked = registry.counter(
                "nf_failover_parked_frames_total",
                "client frames parked while their session re-homed",
            )
            self._c_replayed = registry.counter(
                "nf_failover_replayed_total",
                "parked frames replayed in order to the new binding",
            )
            self._c_dropped = registry.counter(
                "nf_failover_dropped_total",
                "parked frames dropped instead of replayed", ("reason",),
            )

    @property
    def dropped_total(self) -> int:
        return (self.dropped_overflow + self.dropped_deadline
                + self.dropped_disconnect)

    def depth(self, key=None) -> int:
        if key is not None:
            return len(self._q.get(key, ()))
        return sum(len(q) for q in self._q.values())

    def keys(self) -> List[object]:
        return list(self._q)

    def _drop(self, n: int, reason: str) -> None:
        if not n:
            return
        setattr(self, f"dropped_{reason}",
                getattr(self, f"dropped_{reason}") + n)
        if self._c_dropped is not None:
            self._c_dropped.inc(n, reason=reason)

    def park(self, key, msg_id: int, body: bytes, now: float) -> int:
        """Hold one frame for `key`; returns how many OLDEST frames were
        dropped to stay under ``max_frames``."""
        q = self._q.setdefault(key, collections.deque())
        self._seq += 1
        q.append((float(now), int(msg_id), bytes(body), self._seq))
        self.parked_total += 1
        if self._c_parked is not None:
            self._c_parked.inc()
        dropped = 0
        while len(q) > self.max_frames:
            q.popleft()
            dropped += 1
        self._drop(dropped, "overflow")
        return dropped

    def expire(self, now: float) -> int:
        """Drop every frame parked past the deadline; returns the count."""
        dropped = 0
        for key in list(self._q):
            q = self._q[key]
            while q and now - q[0][0] >= self.deadline_s:
                q.popleft()
                dropped += 1
            if not q:
                del self._q[key]
        self._drop(dropped, "deadline")
        return dropped

    def replay(self, key,
               send: Callable[[int, bytes], bool]) -> Tuple[int, bool]:
        """Replay `key`'s parked frames in arrival order through `send`;
        stops at the first failed send (remainder stays parked).
        Returns ``(replayed, drained)``."""
        q = self._q.get(key)
        if not q:
            self._q.pop(key, None)
            return 0, True
        n = 0
        while q:
            _t, msg_id, body, seq = q[0]
            if not send(msg_id, body):
                break
            q.popleft()
            n += 1
            # arrival-order audit: every replayed frame must carry a
            # strictly increasing stamp per session
            if seq <= self._last_replayed.get(key, -1):
                self.order_violations += 1
            else:
                self._last_replayed[key] = seq
        self.replayed_total += n
        if n and self._c_replayed is not None:
            self._c_replayed.inc(n)
        if q:
            return n, False
        self._q.pop(key, None)
        return n, True

    def discard(self, key) -> int:
        """The session itself is gone (client disconnected): drop its
        parked frames; returns the count."""
        q = self._q.pop(key, None)
        self._last_replayed.pop(key, None)
        n = len(q) if q else 0
        self._drop(n, "disconnect")
        return n


@dataclasses.dataclass
class _Pending:
    info: SessionInfo
    blob: bytes
    basis: str              # "wal" | "store" | "none"
    started: float
    next_try: float
    target: int = 0
    attempts: int = 0
    tried: Set[int] = dataclasses.field(default_factory=set)


class FailoverDriver:
    """World-owned re-home driver: turns `_mark_dead` orphans into
    staged switches on surviving games (module docstring has the full
    protocol walk)."""

    def __init__(self, world, recover_store=None,
                 deadline_s: Optional[float] = None,
                 retry_s: float = 0.5) -> None:
        self.world = world
        self.recover_store = recover_store
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _env_float("NF_FAILOVER_DEADLINE_S",
                                           DEADLINE_S_DEFAULT))
        self.retry_s = float(retry_s)
        self._pending: Dict[Tuple[int, int], _Pending] = {}
        self.completed: List[dict] = []  # bounded audit of finished re-homes
        self.last_basis: Dict[str, object] = {}
        reg = world.telemetry.registry
        self._c_initiated = reg.counter(
            "nf_failover_initiated_total",
            "sessions whose re-home the world started after a game CRASH",
        )
        self._c_completed = reg.counter(
            "nf_failover_completed_total",
            "re-homed sessions acked by the adopting game",
        )
        self._c_deadline = reg.counter(
            "nf_failover_deadline_exceeded_total",
            "re-homes abandoned at NF_FAILOVER_DEADLINE_S",
        )
        self._c_busy = reg.counter(
            "nf_failover_busy_total",
            "placement rounds where no survivor had capacity",
        )
        reg.gauge(
            "nf_failover_pending", "sessions currently awaiting re-home",
        ).set_function(lambda: float(len(self._pending)))
        reg.gauge(
            "nf_failover_lag_seconds",
            "age of the oldest pending re-home",
        ).set_function(lambda: self.lag(_time.monotonic()))

    # ------------------------------------------------------------ state
    def pending_count(self) -> int:
        return len(self._pending)

    def lag(self, now: float) -> float:
        if not self._pending:
            return 0.0
        return max(now - p.started for p in self._pending.values())

    # ------------------------------------------------------- death entry
    def game_died(self, dead_sid: int, sessions: List[SessionInfo],
                  wal_dir: Optional[str], ckpt_dir: Optional[str],
                  now: float) -> None:
        """Begin re-homing every session bound to `dead_sid`.  Blob
        basis, newest-durable first: the dead game's WAL suffix (writes
        staged but not yet flushed), then the store itself (the flushed
        watermark), then empty (the adopting game's data agent loads
        from the store on create — covers sessions that never saved)."""
        wal_pending: Dict[str, Optional[bytes]] = {}
        wal_meta: Dict[str, object] = {}
        if wal_dir:
            from ..persist.writebehind import WALError, read_peer_wal
            try:
                view = read_peer_wal(wal_dir)
                wal_pending = view.pending
                wal_meta = {
                    "wal_pending_keys": len(view.pending),
                    "wal_flushed_seq": view.flushed_seq,
                    "wal_max_tick": view.max_tick,
                    "wal_torn_tail_skipped": view.torn_tail_skipped,
                }
            except WALError as e:
                wal_meta = {"wal_error": str(e)}
        ckpt_meta = None
        if ckpt_dir:
            from ..persist.checkpoint import peek_checkpoint
            ckpt_meta = peek_checkpoint(ckpt_dir)
        self.last_basis = {
            "game_id": int(dead_sid),
            "sessions": len(sessions),
            "ckpt": ckpt_meta,
            **wal_meta,
        }
        for info in sessions:
            blob: Optional[bytes] = None
            basis = "none"
            if info.save_key and info.save_key in wal_pending:
                staged = wal_pending[info.save_key]
                if staged is not None:  # a tombstone means deleted: no blob
                    blob, basis = staged, "wal"
            if blob is None and info.save_key and self.recover_store is not None:
                stored = self.recover_store.get(info.save_key)
                if stored is not None:
                    blob, basis = stored, "store"
            p = _Pending(info=info, blob=blob or b"", basis=basis,
                         started=now, next_try=now)
            p.tried.add(int(dead_sid))
            self._pending[info.selfid] = p
            self._c_initiated.inc()
            self._stage(p, now)

    # -------------------------------------------------------- placement
    def _pick_target(self, tried: Set[int]) -> Optional[int]:
        """Least-loaded live game with free Player capacity (the same
        discipline as the world's proxy pick)."""
        best = None
        for sid, d in self.world.games.items():
            r = d.report
            if sid in tried or int(r.server_state) == int(ServerState.CRASH):
                continue
            cur = int(r.server_cur_count)
            cap = int(r.server_max_online)
            if cap > 0 and cur >= cap:
                continue
            if best is None or cur < int(self.world.games[best].report.server_cur_count):
                best = sid
        return best

    def _stage(self, p: _Pending, now: float) -> None:
        target = self._pick_target(p.tried)
        if target is None:
            # no survivor can take this session right now: clear the
            # per-attempt exclusions (capacity frees up as players leave)
            # and come back next round — the proxy's BUSY notice keeps
            # the client informed meanwhile
            p.tried = {int(p.info.game_id)}
            p.next_try = now + self.retry_s
            self._c_busy.inc()
            return
        d = self.world.games.get(target)
        if d is None:
            return
        info = p.info
        selfid = Ident(svrid=info.selfid[0], index=info.selfid[1])
        client = Ident(svrid=info.client_id[0], index=info.client_id[1])
        # frame the recovered blob with the shared row-blob CRC
        # (persist/rowblob.py) so the target distinguishes "torn in
        # transit" from "was empty"; an empty basis stays empty
        from ..persist.rowblob import frame_blob

        data = SwitchServerData(
            selfid=selfid,
            account=info.account.encode(),
            name=info.name.encode(),
            blob=frame_blob(p.blob) if p.blob else p.blob,
            target_serverid=int(target),
        )
        req = ReqSwitchServer(
            selfid=selfid,
            self_serverid=int(info.game_id),
            target_serverid=int(target),
            gate_serverid=0,
            scene_id=int(info.scene_id),
            client_id=client,
            group_id=int(info.group_id),
        )
        # DATA then REQ on the same conn — same no-reorder guarantee the
        # origin game relies on when it stages a voluntary switch
        self.world.server.send_raw(
            d.conn_id, int(MsgID.SWITCH_SERVER_DATA), wrap(data)
        )
        self.world.server.send_raw(
            d.conn_id, int(MsgID.REQ_SWITCH_SERVER), wrap(req)
        )
        p.target = int(target)
        p.attempts += 1
        p.next_try = now + self.retry_s * p.attempts

    # ------------------------------------------------------- ack intake
    def on_ack(self, ack: AckSwitchServer) -> bool:
        """Consume an ACK_SWITCH_SERVER naming a dead origin we staged
        for; returns False when the ack belongs to a normal voluntary
        switch (the caller relays it to the living origin)."""
        key = _ident_key(ack.selfid)
        p = self._pending.get(key)
        if p is None:
            return False
        del self._pending[key]
        self._c_completed.inc()
        self.completed.append({
            "selfid": key,
            "from": int(p.info.game_id),
            "to": int(ack.target_serverid),
            "basis": p.basis,
            "attempts": p.attempts,
        })
        del self.completed[:-512]
        return True

    def on_refused(self, msg: SwitchRefused) -> bool:
        """A staged target refused (capacity / torn blob): exclude it
        and retry the next survivor immediately."""
        key = _ident_key(msg.selfid)
        p = self._pending.get(key)
        if p is None:
            return False
        p.tried.add(int(msg.target_serverid))
        p.next_try = _time.monotonic()
        return True

    # ------------------------------------------------------------- pump
    def execute(self, now: float) -> None:
        if not self._pending:
            return
        expired = [k for k, p in self._pending.items()
                   if now - p.started >= self.deadline_s]
        for k in expired:
            del self._pending[k]
            self._c_deadline.inc()
        for p in list(self._pending.values()):
            if now >= p.next_try:
                self._stage(p, now)
