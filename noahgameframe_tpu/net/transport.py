"""Non-blocking TCP transport with the NF pump model.

The reference pumps libevent once per main-loop tick
(`NFCNet.cpp:165-180`: ``event_base_loop(EVLOOP_ONCE|EVLOOP_NONBLOCK)``).
Here the same contract is ``poll()``: call it each tick, it performs all
ready I/O and returns the framed events since the last call.  No
threads touch game state — identical to the reference's single-threaded
discipline (SURVEY §5 race-avoidance-by-structure).

Two interchangeable backends implement this contract:

- this module: pure-Python ``selectors`` (always available; tests, CI);
- :mod:`noahgameframe_tpu.net.native`: the C++ epoll runtime in
  ``native/nfnet.cc`` (production path), same event tuples.

Use :func:`create_server` / :func:`create_client` to pick a backend.
"""

from __future__ import annotations

import dataclasses
import errno
import selectors
import socket
from typing import Dict, List, Optional, Tuple

from .framing import FrameDecoder, ProtocolError, pack_frame

# event kinds
EV_CONNECTED = 1
EV_DISCONNECTED = 2
EV_MSG = 3


@dataclasses.dataclass
class NetEvent:
    kind: int
    conn_id: int
    msg_id: int = 0
    body: bytes = b""


class _Conn:
    __slots__ = ("sock", "decoder", "outbuf", "connecting")

    def __init__(self, sock: socket.socket, connecting: bool = False) -> None:
        self.sock = sock
        self.decoder = FrameDecoder()
        self.outbuf = bytearray()
        self.connecting = connecting


class _Endpoint:
    """Shared server/client machinery: registered socket set + pump."""

    def __init__(self) -> None:
        self._sel = selectors.DefaultSelector()
        self._conns: Dict[int, _Conn] = {}
        self._events: List[NetEvent] = []
        self._next_id = 1

    # ------------------------------------------------------------- io
    def _register(self, sock: socket.socket, connecting: bool = False) -> int:
        cid = self._next_id
        self._next_id += 1
        conn = _Conn(sock, connecting)
        self._conns[cid] = conn
        mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if connecting else 0)
        self._sel.register(sock, mask, cid)
        return cid

    def _close(self, cid: int, notify: bool = True) -> None:
        conn = self._conns.pop(cid, None)
        if conn is None:
            return
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        if notify:
            self._events.append(NetEvent(EV_DISCONNECTED, cid))

    def send(self, conn_id: int, msg_id: int, body: bytes) -> bool:
        conn = self._conns.get(conn_id)
        if conn is None:
            return False
        conn.outbuf.extend(pack_frame(msg_id, body))
        self._want_write(conn_id, True)
        return True

    def _want_write(self, cid: int, on: bool) -> None:
        conn = self._conns.get(cid)
        if conn is None or conn.connecting:
            return
        mask = selectors.EVENT_READ | (selectors.EVENT_WRITE if on else 0)
        try:
            self._sel.modify(conn.sock, mask, cid)
        except (KeyError, ValueError):
            pass

    def _pump_conn(self, cid: int, mask: int) -> None:
        conn = self._conns.get(cid)
        if conn is None:
            return
        if conn.connecting and mask & selectors.EVENT_WRITE:
            err = conn.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                self._close(cid)
                return
            conn.connecting = False
            self._events.append(NetEvent(EV_CONNECTED, cid))
            self._want_write(cid, bool(conn.outbuf))
        if mask & selectors.EVENT_READ:
            try:
                data = conn.sock.recv(256 * 1024)
            except BlockingIOError:
                data = None
            except OSError:
                self._close(cid)
                return
            if data == b"":
                self._close(cid)
                return
            if data:
                try:
                    frames = conn.decoder.feed(data)
                except ProtocolError:
                    self._close(cid)
                    return
                for msg_id, body in frames:
                    self._events.append(NetEvent(EV_MSG, cid, msg_id, body))
        if mask & selectors.EVENT_WRITE and not conn.connecting and conn.outbuf:
            try:
                n = conn.sock.send(conn.outbuf)
            except BlockingIOError:
                n = 0
            except OSError:
                self._close(cid)
                return
            if n:
                del conn.outbuf[:n]
            if not conn.outbuf:
                self._want_write(cid, False)

    def _pump(self) -> None:
        while True:
            ready = self._sel.select(timeout=0)
            if not ready:
                return
            for key, mask in ready:
                self._on_ready(key, mask)
            # one pass is enough per tick; loop only drains accept bursts
            return

    def _on_ready(self, key: selectors.SelectorKey, mask: int) -> None:
        self._pump_conn(key.data, mask)

    def poll(self) -> List[NetEvent]:
        """One main-loop tick: perform ready I/O, return framed events."""
        self._pump()
        out = self._events
        self._events = []
        return out

    def close(self) -> None:
        for cid in list(self._conns):
            self._close(cid, notify=False)
        self._sel.close()

    @property
    def num_connections(self) -> int:
        return len(self._conns)


class PyNetServer(_Endpoint):
    """Listening endpoint; `conn_id`s identify accepted peers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        super().__init__()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self._sel.register(self._listener, selectors.EVENT_READ, 0)  # 0 = listener

    def _on_ready(self, key: selectors.SelectorKey, mask: int) -> None:
        if key.data == 0:
            while True:
                try:
                    sock, _ = self._listener.accept()
                except (BlockingIOError, OSError):
                    break
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                cid = self._register(sock)
                self._events.append(NetEvent(EV_CONNECTED, cid))
        else:
            self._pump_conn(key.data, mask)

    def close_conn(self, conn_id: int) -> None:
        self._close(conn_id)

    def close(self) -> None:
        super().close()
        try:
            self._listener.close()
        except OSError:
            pass


class PyNetClient(_Endpoint):
    """Single outbound connection (one per pooled link)."""

    def __init__(self, host: str, port: int) -> None:
        super().__init__()
        self.host, self.port = host, port
        self._cid: Optional[int] = None
        self.connected = False

    def connect(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        rc = sock.connect_ex((self.host, self.port))
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            sock.close()
            self._events.append(NetEvent(EV_DISCONNECTED, 0))
            return
        self._cid = self._register(sock, connecting=True)

    def poll(self) -> List[NetEvent]:
        evs = super().poll()
        for ev in evs:
            if ev.kind == EV_CONNECTED:
                self.connected = True
            elif ev.kind == EV_DISCONNECTED and ev.conn_id == self._cid:
                self.connected = False
                self._cid = None
        return evs

    def send_msg(self, msg_id: int, body: bytes) -> bool:
        if self._cid is None:
            return False
        return self.send(self._cid, msg_id, body)

    def disconnect(self) -> None:
        if self._cid is not None:
            self._close(self._cid)
            self.connected = False
            self._cid = None


def create_server(host: str = "127.0.0.1", port: int = 0, backend: str = "auto"):
    """backend: 'py', 'native', or 'auto' (native if the C++ lib builds)."""
    if backend in ("native", "auto"):
        try:
            from .native import NativeNetServer

            return NativeNetServer(host, port)
        except Exception:
            if backend == "native":
                raise
    return PyNetServer(host, port)


def create_client(host: str, port: int, backend: str = "auto"):
    if backend in ("native", "auto"):
        try:
            from .native import NativeNetClient

            return NativeNetClient(host, port)
        except Exception:
            if backend == "native":
                raise
    return PyNetClient(host, port)
