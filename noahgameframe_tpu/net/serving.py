"""SessionTable: SoA session store + host assembly for the batched serve
edge (NF_SERVE_BATCH=1, net/roles/game.py).

The legacy serve path keeps per-session Python state — a `Session`
dataclass per client plus an `_interest_seen` dict of numpy tuples — and
walks them one by one every flush.  The batched engine replaces that
with one Struct-of-Arrays table:

- host columns: ``conn_id`` (int64), ``avatar_row`` (int32, the Player
  row whose position anchors the view) and ``valid`` (bool) per session
  SLOT.  Slots are stable across frames (freed on session release,
  recycled LIFO), so the device seen-state never needs reindexing when
  an unrelated session joins or leaves.
- device columns: one :class:`~noahgameframe_tpu.ops.serving.SeenTable`
  per synced class — the per-session seen-version vectors ([S, M] rows/
  gen/qver) that ops/serving.interest_delta diffs against.

The table is the vmap axis of the serve kernel: every dispatch covers
all slots (or fixed-size chunks of them, NF_SERVE_CHUNK), valid or not;
invalid slots compute an empty visible set and send nothing.  Capacity
grows by powers of two so the per-(class, capacity) jit cache stays
small, exactly like the legacy `_interest_jit` policy.

`segments` is the zero-sync frame assembler: given the fetched dense
``[S, M]`` buffers it byte-slices ONE flat payload per field into
per-session packets — no per-session numpy ops, no per-session device
round trips (the tentpole's "batched frame assembly").
"""

from __future__ import annotations

from typing import Dict, Hashable, List, Optional, Tuple

import numpy as np

from ..core.datatypes import next_pow2
from ..ops.serving import SeenTable, init_seen


class SessionTable:
    """SoA mirror of the serve-side session set; the session axis of the
    batched interest kernel."""

    def __init__(self, lo: int = 8):
        self._lo = int(lo)
        self.capacity = 0
        self.slot_of: Dict[Hashable, int] = {}
        self._key_of: List[Optional[Hashable]] = []
        self._free: List[int] = []
        # slots whose seen-state may be non-empty from a past occupant;
        # wiped lazily on realloc (fresh-grown slots are born empty, so
        # a mass join costs zero device scatters)
        self._stale: set = set()
        self.conn_id = np.zeros(0, np.int64)
        self.avatar_row = np.zeros(0, np.int32)
        self.valid = np.zeros(0, bool)
        # room this session is routed to under the many-worlds engine
        # (parallel/rooms.py); -1 = the host's single world.  Routing is
        # a host column only — the serve edge filters lanes per room, it
        # never crosses the device room axis.
        self.room = np.full(0, -1, np.int32)
        # per-class device seen-state, lazily sized [capacity, M]
        self.seen: Dict[str, SeenTable] = {}
        self._seen_m: Dict[str, int] = {}

    # ------------------------------------------------------------- slots
    def _grow(self, need: int) -> None:
        new_cap = next_pow2(max(need, 1), lo=self._lo)
        if new_cap <= self.capacity:
            return
        import jax.numpy as jnp

        pad = new_cap - self.capacity
        self.conn_id = np.concatenate([self.conn_id, np.zeros(pad, np.int64)])
        self.avatar_row = np.concatenate(
            [self.avatar_row, np.zeros(pad, np.int32)]
        )
        self.valid = np.concatenate([self.valid, np.zeros(pad, bool)])
        self.room = np.concatenate([self.room, np.full(pad, -1, np.int32)])
        self._key_of.extend([None] * pad)
        self._free.extend(range(new_cap - 1, self.capacity - 1, -1))
        for cname, tbl in list(self.seen.items()):
            ext = init_seen(pad, self._seen_m[cname])
            self.seen[cname] = SeenTable(
                rows=jnp.concatenate([tbl.rows, ext.rows]),
                gen=jnp.concatenate([tbl.gen, ext.gen]),
                qver=jnp.concatenate([tbl.qver, ext.qver]),
            )
        self.capacity = new_cap

    def ensure(self, key: Hashable, conn_id: int, avatar_row: int) -> int:
        """Slot for `key`, allocating (and wiping any previous occupant's
        seen-state) on first sight.  Updates the host columns in place."""
        slot = self.slot_of.get(key)
        if slot is None:
            if not self._free:
                self._grow(self.capacity + 1)
            slot = self._free.pop()
            self.slot_of[key] = slot
            self._key_of[slot] = key
            if slot in self._stale:
                self._stale.discard(slot)
                self._wipe_seen(slot)
            # a recycled slot must not inherit the previous occupant's
            # room routing (same lazy-wipe discipline as seen-state,
            # except the column is host-side so the wipe is free)
            self.room[slot] = -1
        self.conn_id[slot] = conn_id
        self.avatar_row[slot] = avatar_row
        self.valid[slot] = True
        return slot

    def release(self, key: Hashable) -> None:
        """Free a session's slot (session closed / switched away).  The
        seen-state is wiped on the NEXT alloc, not here — releases come
        in bursts (proxy link death) and the wipe is a device scatter."""
        slot = self.slot_of.pop(key, None)
        if slot is None:
            return
        self._key_of[slot] = None
        self.valid[slot] = False
        self._stale.add(slot)
        self._free.append(slot)

    def invalidate(self, key: Hashable) -> None:
        """Mark a still-allocated session as not currently observing
        (avatar despawned); its slot and seen reset stay pending."""
        slot = self.slot_of.get(key)
        if slot is not None:
            self.valid[slot] = False

    # ------------------------------------------------------------- rooms
    def bind_room(self, key: Hashable, room_id: int) -> None:
        """Route a session to a room of the many-worlds engine; -1
        returns it to the host's single world."""
        self.room[self.slot_of[key]] = int(room_id)

    def room_of(self, key: Hashable) -> int:
        slot = self.slot_of.get(key)
        return -1 if slot is None else int(self.room[slot])

    def sessions_in_room(self, room_id: int) -> List[Hashable]:
        """Keys of every live session routed to `room_id` — the set a
        room destroy/re-home must release or reset."""
        rid = int(room_id)
        return [self._key_of[s] for s in np.flatnonzero(
            (self.room == rid) & self.valid)
            if self._key_of[s] is not None]

    def reset_view(self, key: Hashable) -> None:
        """Wipe the session's device seen-state NOW (the batched half of
        game.py reset_view: avatar despawn/switch/destroy must resend
        the world on the next sight, legacy `_interest_seen = {}`)."""
        slot = self.slot_of.get(key)
        if slot is not None:
            self._stale.discard(slot)
            self._wipe_seen(slot)
            self.valid[slot] = False

    def _wipe_seen(self, slot: int) -> None:
        # rows-only wipe: both match passes in interest_delta test row
        # equality first, and SENTINEL never equals a real row — stale
        # gen/qver behind a SENTINEL row can never resurrect a match
        from ..ops.serving import SENTINEL

        for cname, tbl in list(self.seen.items()):
            self.seen[cname] = tbl._replace(
                rows=tbl.rows.at[slot].set(SENTINEL)
            )

    # ------------------------------------------------------- device state
    def seen_for(self, cname: str, m: int) -> SeenTable:
        """[capacity, m] seen-state for a class, created empty on first
        use.  `m` is static per class (9 * stencil bucket, possibly
        capped by NF_SERVE_SLOTS) — a changed m means a changed kernel
        geometry, so the table resets (full resend, same as a fresh
        compile of the legacy path after capacity growth)."""
        tbl = self.seen.get(cname)
        if tbl is None or self._seen_m.get(cname) != m or (
            tbl.rows.shape[0] != self.capacity
        ):
            tbl = init_seen(self.capacity, m)
            self.seen[cname] = tbl
            self._seen_m[cname] = m
        return tbl

    def store_seen(self, cname: str, tbl: SeenTable) -> None:
        self.seen[cname] = tbl


def sessions_seeing_rows(
    table: SessionTable, cname: str, rows
) -> List[Hashable]:
    """Session keys whose device seen-state for ``cname`` references any
    of ``rows`` — the exact force-``reset_view`` set after an elastic
    reshard moved those entity rows (parallel/elastic.py): a seen-row
    now describing a different entity would silently diff against the
    wrong baseline, while every other session's mirror is still valid
    and must NOT pay a full resend."""
    from ..ops.serving import SENTINEL

    tbl = table.seen.get(cname)
    moved = np.asarray(rows)
    # empty seen slots are SENTINEL-padded — a SENTINEL in `rows` would
    # otherwise mark every session as affected
    moved = moved[moved != SENTINEL]
    if tbl is None or moved.size == 0:
        return []
    hit = np.isin(np.asarray(tbl.rows), moved).any(axis=1)
    return [
        key for key, slot in table.slot_of.items()
        if slot < hit.shape[0] and bool(hit[slot])
    ]


def segments(
    counts: np.ndarray, item_bytes: int, payload: bytes
) -> Tuple[np.ndarray, bytes]:
    """(byte offsets [S+1], payload) for per-slot packet slicing: slot
    s's bytes are ``payload[offs[s]:offs[s + 1]]``.  The payload is ONE
    tobytes() of the flat (already session-major) value array — the
    whole frame's wire bytes materialize with a single copy and each
    packet is a cheap bytes slice."""
    offs = np.zeros(len(counts) + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    offs *= item_bytes
    return offs, payload
