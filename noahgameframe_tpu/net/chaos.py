"""Deterministic fault injection at the transport seam.

`FaultyTransport` wraps any transport from :mod:`net.transport` (client
or server side — it proxies both `send_msg` and `send(conn_id, ...)`)
and injects message-level faults on the way out and on the way in:
drops, duplicates, delays (measured in poll ticks), payload truncation/
corruption, connection refusal, and directional partitions.  All
decisions come from one per-link `random.Random` seeded from
``(plan.seed, link name)``, so the same plan over the same message
sequence yields a byte-identical fault sequence — chaos tests are
reproducible, not merely "usually pass".

Design notes:

- Faults apply to *message bodies*, never to frame headers: the wrapper
  sits above the framing layer, so a corrupted body exercises handler
  fault isolation (`_Dispatch._safe`) while the stream stays parseable.
  Frame-level garbage is a different failure class, covered directly by
  the `FrameDecoder` fuzz in ``tests/test_wire_fuzz.py``.
- The wrapper's clock is its poll count (one tick per ``poll()`` call),
  not wall time: partition windows and delay durations are scheduleable
  in tests without sleeping.
- Partitions drop established-link messages only; EV_CONNECTED /
  EV_DISCONNECTED pass through (a real partition stalls traffic, it
  does not synthesize socket closes).  Use ``refuse`` to fault the
  connect path itself.
- Per-link counts, the fault log, AND the rng live *outside* the
  wrapper (see :class:`ChaosDirector`): every re-dial builds a fresh
  transport + wrapper, and both the failure budget and the random
  sequence must survive that — a restarted rng would re-roll the same
  outcome on every connect attempt (``refuse`` would be all-or-nothing
  per link instead of a probability).
"""

from __future__ import annotations

import dataclasses
import random
import time as _time
import zlib
from typing import Dict, List, Optional, Tuple

from .transport import EV_CONNECTED, EV_DISCONNECTED, EV_MSG, NetEvent


class StoreFaultError(ConnectionError):
    """Injected store failure (the write-behind flusher retries these
    exactly like a real connection error)."""


@dataclasses.dataclass
class LinkFaults:
    """Per-link fault probabilities (each applied per message per
    direction) + scheduled partitions."""

    drop: float = 0.0       # message silently discarded
    dup: float = 0.0        # message delivered twice
    delay: float = 0.0      # message held for `delay_polls` ticks
    delay_polls: int = 3
    truncate: float = 0.0   # body cut at a random offset
    corrupt: float = 0.0    # one body byte flipped
    refuse: float = 0.0     # EV_CONNECTED turned into a disconnect
    # refuse connects until the link's refuse count reaches this floor —
    # a *deterministic* retry exercise (the budget lives in the shared
    # counts, so it survives re-dials and then the link heals for good)
    refuse_first: int = 0
    # (start_tick, end_tick, direction) windows; direction is one of
    # "in", "out", "both".  Ticks are poll counts on this link.
    partitions: Tuple[Tuple[int, int, str], ...] = ()

    def any(self) -> bool:
        return bool(self.drop or self.dup or self.delay or self.truncate
                    or self.corrupt or self.refuse or self.refuse_first
                    or self.partitions)


@dataclasses.dataclass
class StoreFaults:
    """Per-store-link fault schedule for the persistence flush path.

    The clock here is the link's *operation count* (one tick per store
    call), mirroring how transport faults use poll counts: schedules
    stay deterministic without wall time.  Probabilistic faults draw
    from the same shared per-link rng the director owns, so budgets and
    sequences survive pipeline rebuilds on revive exactly like
    transport wrappers survive re-dials."""

    fail: float = 0.0        # store call raises StoreFaultError
    # refuse the first N calls outright — the deterministic retry
    # exercise (budget lives in the shared counts, then heals for good)
    fail_first: int = 0
    latency: float = 0.0     # store call sleeps `latency_s` first
    latency_s: float = 0.05  # flusher-thread sleep; never the tick path
    # [start_op, end_op) windows where the store is down hard
    down: Tuple[Tuple[int, int], ...] = ()

    def any(self) -> bool:
        return bool(self.fail or self.fail_first or self.latency
                    or self.down)


@dataclasses.dataclass
class FaultPlan:
    """A seeded schedule of per-link faults.

    ``links`` maps a *pattern* to its faults; a pattern matches any link
    whose name contains it (link names look like ``game6.world->7``), so
    one entry can target a pool ("proxy5.games") or a single peer.
    First matching pattern (insertion order) wins; unmatched links get
    ``default``."""

    seed: int = 0
    links: Dict[str, LinkFaults] = dataclasses.field(default_factory=dict)
    default: LinkFaults = dataclasses.field(default_factory=LinkFaults)
    # store links (names look like "game6.store") follow the same
    # pattern-match discipline as message links
    stores: Dict[str, StoreFaults] = dataclasses.field(default_factory=dict)
    store_default: StoreFaults = dataclasses.field(
        default_factory=StoreFaults)

    def for_link(self, link: str) -> LinkFaults:
        for pattern, faults in self.links.items():
            if pattern in link:
                return faults
        return self.default

    def for_store(self, link: str) -> StoreFaults:
        for pattern, faults in self.stores.items():
            if pattern in link:
                return faults
        return self.store_default


class FaultyTransport:
    """Transport wrapper applying a `FaultPlan` to one link.

    Everything not intercepted (connect/close/connected/port/…)
    delegates to the wrapped transport, so the wrapper drops into
    `NetClientModule`/`NetServerModule` unchanged.
    """

    def __init__(self, inner, link: str, plan: FaultPlan,
                 counts: Optional[Dict[str, int]] = None,
                 log: Optional[list] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.inner = inner
        self.link = str(link)
        self.faults = plan.for_link(self.link)
        # the rng may be shared across re-dials (ChaosDirector passes a
        # per-link one): a fresh wrapper restarting the sequence would
        # re-roll the SAME outcome on every connect attempt — refuse=0.25
        # becomes either never or a permanent livelock
        self.rng = rng if rng is not None else random.Random(
            (int(plan.seed) * 1000003) ^ zlib.crc32(self.link.encode())
        )
        self.counts = counts if counts is not None else {}
        self.log = log
        self.tick = 0
        self._delayed_out: List[Tuple[int, object]] = []  # (due, thunk)
        self._delayed_in: List[Tuple[int, NetEvent]] = []

    def __getattr__(self, name):
        return getattr(self.inner, name)

    # ------------------------------------------------------- bookkeeping
    def _count(self, kind: str, msg_id: int = 0) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1
        if self.log is not None:
            self.log.append((self.tick, self.link, kind, int(msg_id)))

    def _partitioned(self, direction: str) -> bool:
        for start, end, d in self.faults.partitions:
            if start <= self.tick < end and d in (direction, "both"):
                return True
        return False

    def _mangle(self, body: bytes, msg_id: int) -> bytes:
        f, r = self.faults, self.rng
        if body and f.truncate and r.random() < f.truncate:
            self._count("truncate", msg_id)
            body = body[: r.randrange(len(body))]
        if body and f.corrupt and r.random() < f.corrupt:
            self._count("corrupt", msg_id)
            i = r.randrange(len(body))
            body = body[:i] + bytes([body[i] ^ (1 + r.randrange(255))]) + body[i + 1:]
        return body

    # ------------------------------------------------------- send path
    def send_msg(self, msg_id: int, body: bytes) -> bool:
        return self._send_out(
            lambda b: self.inner.send_msg(msg_id, b), msg_id, body
        )

    def send(self, conn_id: int, msg_id: int, body: bytes) -> bool:
        return self._send_out(
            lambda b: self.inner.send(conn_id, msg_id, b), msg_id, body
        )

    def _send_out(self, deliver, msg_id: int, body: bytes) -> bool:
        f, r = self.faults, self.rng
        if self._partitioned("out"):
            self._count("partition_out", msg_id)
            return True  # swallowed; the sender sees a healthy link
        if f.drop and r.random() < f.drop:
            self._count("drop_out", msg_id)
            return True
        body = self._mangle(body, msg_id)
        copies = 1
        if f.dup and r.random() < f.dup:
            self._count("dup_out", msg_id)
            copies = 2
        if f.delay and r.random() < f.delay:
            self._count("delay_out", msg_id)
            due = self.tick + max(1, int(f.delay_polls))
            for _ in range(copies):
                self._delayed_out.append((due, lambda b=body: deliver(b)))
            return True
        ok = True
        for _ in range(copies):
            ok = deliver(body) and ok
        return ok

    # ------------------------------------------------------- poll path
    def poll(self) -> List[NetEvent]:
        self.tick += 1
        # release due delayed traffic first: a delayed message must not
        # overtake one delayed earlier (list order is arrival order)
        still = []
        for due, thunk in self._delayed_out:
            if due <= self.tick:
                thunk()
            else:
                still.append((due, thunk))
        self._delayed_out = still
        ready = [ev for due, ev in self._delayed_in if due <= self.tick]
        self._delayed_in = [
            (due, ev) for due, ev in self._delayed_in if due > self.tick
        ]
        out: List[NetEvent] = list(ready)
        f, r = self.faults, self.rng
        for ev in self.inner.poll():
            if ev.kind == EV_CONNECTED and (
                (f.refuse_first
                 and self.counts.get("refuse", 0) < int(f.refuse_first))
                or (f.refuse and r.random() < f.refuse)
            ):
                # connection refused: tear the link down instead of
                # admitting it — exercises the RetryPolicy path
                self._count("refuse")
                self.inner.disconnect()
                out.append(NetEvent(EV_DISCONNECTED, ev.conn_id))
                continue
            if ev.kind != EV_MSG:
                out.append(ev)
                continue
            if self._partitioned("in"):
                self._count("partition_in", ev.msg_id)
                continue
            if f.drop and r.random() < f.drop:
                self._count("drop_in", ev.msg_id)
                continue
            body = self._mangle(ev.body, ev.msg_id)
            ev = NetEvent(EV_MSG, ev.conn_id, ev.msg_id, body)
            copies = 1
            if f.dup and r.random() < f.dup:
                self._count("dup_in", ev.msg_id)
                copies = 2
            if f.delay and r.random() < f.delay:
                self._count("delay_in", ev.msg_id)
                due = self.tick + max(1, int(f.delay_polls))
                for _ in range(copies):
                    self._delayed_in.append((due, ev))
                continue
            for _ in range(copies):
                out.append(ev)
        return out


class FaultyStore:
    """Write-behind store backend wrapper applying :class:`StoreFaults`
    to one store link.

    Sits where the flusher thread talks to the store (the
    ``StoreBackend`` seam in :mod:`persist.writebehind`): ``write`` /
    ``delete`` pass through the fault schedule; everything else
    delegates.  Injected latency sleeps on the *flusher* thread — the
    whole point of write-behind is that this never reaches the tick,
    and the persist smoke asserts exactly that.
    """

    def __init__(self, inner, link: str, plan: FaultPlan,
                 counts: Optional[Dict[str, int]] = None,
                 log: Optional[list] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.inner = inner
        self.link = str(link)
        self.faults = plan.for_store(self.link)
        self.rng = rng if rng is not None else random.Random(
            (int(plan.seed) * 1000003) ^ zlib.crc32(self.link.encode())
        )
        self.counts = counts if counts is not None else {}
        self.log = log

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def _count(self, kind: str) -> None:
        self.counts[kind] = self.counts.get(kind, 0) + 1
        if self.log is not None:
            self.log.append((self.counts.get("store_op", 0), self.link,
                             kind, 0))

    def _down(self, op: int) -> bool:
        return any(a <= op < b for a, b in self.faults.down)

    def _guard(self) -> None:
        f, r = self.faults, self.rng
        op = self.counts.get("store_op", 0)
        self.counts["store_op"] = op + 1
        if self._down(op):
            self._count("store_down")
            raise StoreFaultError(f"{self.link}: store down (op {op})")
        if f.fail_first and self.counts.get("store_fail", 0) < int(
                f.fail_first):
            self._count("store_fail")
            raise StoreFaultError(f"{self.link}: refused (first-N budget)")
        if f.fail and r.random() < f.fail:
            self._count("store_fail")
            raise StoreFaultError(f"{self.link}: injected write failure")
        if f.latency and r.random() < f.latency:
            self._count("store_latency")
            _time.sleep(max(0.0, float(f.latency_s)))

    def write(self, key: str, blob: bytes) -> None:
        self._guard()
        return self.inner.write(key, blob)

    def delete(self, key: str) -> None:
        self._guard()
        return self.inner.delete(key)

    def ping(self) -> bool:
        if self._down(self.counts.get("store_op", 0)):
            return False
        return self.inner.ping()


class ChaosDirector:
    """One per cluster: wraps transports and owns the per-link fault
    counts + logs so they survive transport rebuilds (every reconnect
    dial creates a fresh client)."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self.counts: Dict[str, Dict[str, int]] = {}
        self.logs: Dict[str, list] = {}
        self.rngs: Dict[str, random.Random] = {}
        # every live wrapper, so heal() can flip faults off mid-run
        # (a re-dial's fresh wrapper re-reads the — by then healed — plan)
        self._live: List[Tuple[str, object]] = []

    def wrap(self, transport, link: str) -> FaultyTransport:
        link = str(link)
        w = FaultyTransport(
            transport, link, self.plan,
            counts=self.counts.setdefault(link, {}),
            log=self.logs.setdefault(link, []),
            rng=self.rngs.setdefault(link, random.Random(
                (int(self.plan.seed) * 1000003) ^ zlib.crc32(link.encode())
            )),
        )
        self._live.append((link, w))
        return w

    def wrap_store(self, backend, link: str) -> FaultyStore:
        """Wrap a write-behind store backend the same way `wrap` wraps
        a transport: counts/log/rng live here, so a revived game role's
        rebuilt pipeline continues the SAME fault schedule (op counts
        and first-N budgets do not reset)."""
        link = str(link)
        # re-wrap guard: revive_role re-runs the chaos hookup, and a
        # drill may apply_chaos more than once — if the backend is
        # already this link's wrapper, wrap its INNER store instead of
        # nesting (a nested pair would double-advance the shared op
        # clock per call and replay consumed down-windows against the
        # second count)
        while isinstance(backend, FaultyStore) and backend.link == link:
            backend = backend.inner
        w = FaultyStore(
            backend, link, self.plan,
            counts=self.counts.setdefault(link, {}),
            log=self.logs.setdefault(link, []),
            rng=self.rngs.setdefault(link, random.Random(
                (int(self.plan.seed) * 1000003) ^ zlib.crc32(link.encode())
            )),
        )
        self._live.append((link, w))
        return w

    def heal(self, pattern: Optional[str] = None) -> int:
        """Turn faults OFF for every link whose name contains `pattern`
        (all links when None), effective immediately on live wrappers
        and on any future re-dial/re-wrap.  Returns how many distinct
        links went from faulted to clean — so the call is idempotent:
        a second identical heal returns 0 and changes nothing.

        This is the failover-drill shape (ISSUE 10): inject faults
        through the kill window, then heal and assert the cluster
        actually converges — a plan that stays hostile forever can mask
        a recovery path that never finishes.  Counts/logs/rngs are
        kept; only the schedules reset.  That makes heal safe
        mid-campaign (ISSUE 11): a `revive_role` re-wrap that races the
        heal reads the already-healed plan, and because the op clocks
        and first-N budgets live in the shared counts, re-arming faults
        later (:meth:`set_store_faults`) cannot resurrect a consumed
        fault window."""
        if pattern is None:
            self.plan.links.clear()
            self.plan.default = LinkFaults()
            self.plan.stores.clear()
            self.plan.store_default = StoreFaults()
        else:
            self.plan.links = {p: f for p, f in self.plan.links.items()
                               if p not in pattern and pattern not in p}
            self.plan.stores = {p: f for p, f in self.plan.stores.items()
                                if p not in pattern and pattern not in p}
        healed = set()
        for link, w in self._live:
            if pattern is not None and pattern not in link:
                continue
            if w.faults.any():
                healed.add(link)
            w.faults = (StoreFaults() if isinstance(w, FaultyStore)
                        else LinkFaults())
        return len(healed)

    # -------------------------------------------------- live re-arming
    def set_link_faults(self, pattern: str, faults: LinkFaults) -> int:
        """Arm (or re-arm) transport faults mid-campaign: the plan entry
        is upserted (future re-dials see it) AND every live transport
        wrapper whose link contains `pattern` switches to `faults`
        immediately.  Returns how many live links were re-armed.

        Budgets stay consumed: refuse_first counts etc. live in the
        shared per-link counts, so re-arming an already-exhausted
        schedule does not restart it."""
        pattern = str(pattern)
        self.plan.links[pattern] = faults
        touched = set()
        for link, w in self._live:
            if isinstance(w, FaultyTransport) and pattern in link:
                w.faults = faults
                touched.add(link)
        return len(touched)

    def set_store_faults(self, pattern: str, faults: StoreFaults) -> int:
        """Store-side twin of :meth:`set_link_faults` — the campaign
        primitive behind scheduled store outages.  Op clocks and
        first-N budgets live in the shared counts, so a re-armed
        schedule continues from the link's current op count; a consumed
        ``fail_first`` budget or passed ``down`` window stays consumed."""
        pattern = str(pattern)
        self.plan.stores[pattern] = faults
        touched = set()
        for link, w in self._live:
            if isinstance(w, FaultyStore) and pattern in link:
                w.faults = faults
                touched.add(link)
        return len(touched)

    def total(self, kind: Optional[str] = None) -> int:
        return sum(
            v
            for per_link in self.counts.values()
            for k, v in per_link.items()
            if kind is None or k == kind
        )

    def store_phase(self) -> Dict[str, dict]:
        """Per-store-link fault *phase* (ISSUE 11 satellite): where each
        store link's op clock sits relative to its schedule — ops seen,
        remaining first-N fail budget, the active/upcoming down windows
        — so drills and operators can assert the current fault phase
        from master `/json` instead of inferring it from side effects.

        The effective schedule is read from the newest live wrapper
        (live re-arming via :meth:`set_store_faults` lands there first)
        and falls back to the plan for links awaiting a re-wrap."""
        current: Dict[str, StoreFaults] = {}
        for link, w in self._live:
            if isinstance(w, FaultyStore):
                current[link] = w.faults  # latest wrapper wins
        links = set(current) | {
            link for link, c in self.counts.items() if "store_op" in c
        }
        phases: Dict[str, dict] = {}
        for link in sorted(links):
            c = self.counts.get(link, {})
            op = int(c.get("store_op", 0))
            f = current.get(link)
            if f is None:
                f = self.plan.for_store(link)
            active = None
            remaining = 0
            for a, b in f.down:
                if a <= op < b:
                    active = [int(a), int(b)]
                    remaining = int(b) - op
                    break
            phases[link] = {
                "ops_seen": op,
                "fails_injected": int(c.get("store_fail", 0)),
                "downs_hit": int(c.get("store_down", 0)),
                "latencies_injected": int(c.get("store_latency", 0)),
                "fail_first_remaining": (
                    max(0, int(f.fail_first) - int(c.get("store_fail", 0)))
                    if f.fail_first else 0
                ),
                "fail_p": float(f.fail),
                "latency_p": float(f.latency),
                "latency_s": float(f.latency_s),
                "down_active": active,
                "down_remaining_ops": remaining,
                "down_upcoming": [[int(a), int(b)] for a, b in f.down
                                  if op < a],
            }
        return phases

    def status(self) -> dict:
        """The plan spelled out for operators: seed + per-link fault
        budgets (the FaultPlan patterns) + live injected counts + the
        store links' op-clock phase.  The master mounts this on /json
        and game roles journal it, so any chaos run can be re-derived
        exactly for replay."""
        return {
            "seed": int(self.plan.seed),
            "links": {
                pattern: dataclasses.asdict(faults)
                for pattern, faults in self.plan.links.items()
            },
            "default": dataclasses.asdict(self.plan.default),
            "stores": {
                pattern: dataclasses.asdict(faults)
                for pattern, faults in self.plan.stores.items()
            },
            "store_default": dataclasses.asdict(self.plan.store_default),
            "store_phase": self.store_phase(),
            "counts": {link: dict(c) for link, c in self.counts.items()},
        }
