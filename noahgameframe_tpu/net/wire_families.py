"""The remaining reference proto families as wire.py message classes.

net/wire.py carries the core protocol (NFMsgBase / NFMsgPreGame /
NFMsgShare subset the five roles speak).  This module completes the wire
surface with the other reference families so clients and middleware can
exchange every message the reference defines
(/root/reference/NFComm/NFMessageDefine/):

- NFMsgMysql.proto  — async-MySQL actor request/server-info packs
  (shipped to NFCMysqlComponent workers, NFCAsyMysqlModule.cpp:558-599).
- NFMsgURl.proto    — async HTTP-request pack.
- NFSLGDefine.proto — SLG building/army messages + their enum spaces.
- NFFleetingDefine.proto — client-side FX/animation event tracks
  (package NFFS; nested event messages are flattened to module level
  under their proto nested names, e.g. BulletEvents.Bullet -> Bullet).

Every class here is cross-validated byte-for-byte against
protoc-generated code in tests/test_wire_protoc.py, exactly like the
core set.  Field names keep the reference's spelling where it is legal
Python, so generated docs line up with the .proto sources.
"""

from __future__ import annotations

import enum

from .wire import Ident, Message, R

# ---------------------------------------------------------------- NFMsgMysql


class PackMysqlParam(Message):
    FIELDS = [
        (1, "strRecordName", "bytes", b""),
        (2, "strKey", "bytes", b""),
        (3, "fieldVecList", R("bytes"), None),
        (4, "valueVecList", R("bytes"), None),
        (5, "bExit", "int64", 0),
        (6, "nreqid", "int64", 0),
        (7, "nRet", "int64", 0),
        (8, "eType", "int64", 0),
    ]


class PackMysqlServerInfo(Message):
    FIELDS = [
        (1, "nRconnectTime", "int64", 0),
        (2, "nRconneCount", "int64", 0),
        (3, "nPort", "int64", 0),
        (4, "strDBName", "bytes", b""),
        (5, "strDnsIp", "bytes", b""),
        (6, "strDBUser", "bytes", b""),
        (7, "strDBPwd", "bytes", b""),
        (8, "nServerID", "int64", 0),
    ]


# ----------------------------------------------------------------- NFMsgURl


class PackSURLParam(Message):
    FIELDS = [
        (1, "strUrl", "bytes", b""),
        (2, "strGetParams", "bytes", b""),
        (3, "strBodyData", "bytes", b""),
        (4, "strCookies", "bytes", b""),
        (5, "fTimeOutSec", "double", 0.0),
        (6, "strRsp", "bytes", b""),
        (7, "nRet", "int64", 0),
        (8, "nReqID", "int64", 0),
    ]


# -------------------------------------------------------------- NFSLGDefine


# single-source enums: the gameplay layer owns them; the wire layer
# re-exports so both sides can never diverge on values that ride the wire
from ..game.defines import SLGBuildingState, SLGBuildingType  # noqa: F401,E402


class SLGFuncType(enum.IntEnum):
    INFO = 0
    BOOST = 1
    LVLUP = 2
    CREATE_SOLDER = 3
    CREATE_SPEEL = 4
    RESEARCH = 5
    COLLECT_GOLD = 6
    COLLECT_STONE = 7
    COLLECT_STEEL = 8
    COLLECT_DIAMOND = 9
    SELL = 10
    REPAIR = 11
    CANCEL = 12
    FINISH = 13


class ReqAckBuyObjectFormShop(Message):
    FIELDS = [
        (1, "config_id", "string", b""),
        (2, "x", "float", 0.0),
        (3, "y", "float", 0.0),
        (4, "z", "float", 0.0),
        (5, "Shop_id", "string", b""),
    ]


class ReqAckMoveBuildObject(Message):
    FIELDS = [
        (1, "row", "int32", None),
        (2, "object_guid", Ident, None),
        (3, "x", "float", 0.0),
        (4, "y", "float", 0.0),
        (5, "z", "float", 0.0),
    ]


class ReqUpBuildLv(Message):
    FIELDS = [
        (1, "row", "int32", None),
        (2, "object_guid", Ident, None),
    ]


class ReqCreateItem(Message):
    FIELDS = [
        (1, "row", "int32", None),
        (2, "object_guid", Ident, None),
        (3, "config_id", "string", b""),
        (4, "count", "int32", 0),
    ]


class ReqBuildOperate(Message):
    FIELDS = [
        (1, "row", "int32", None),
        (2, "object_guid", Ident, None),
        (3, "functype", "enum", 0),
    ]


# --------------------------------------------------------- NFFleetingDefine
# Client FX/animation event tracks (package NFFS).  The proto nests the
# per-event messages; here each nested message is a module-level class
# under its nested name.


class FSVector3(Message):
    FIELDS = [
        (1, "x", "float", 0.0),
        (2, "y", "float", 0.0),
        (3, "z", "float", 0.0),
    ]


class Suwayyah(Message):
    FIELDS = [
        (1, "EventType", "enum", 0),
        (2, "EventTime", "float", 0.0),
        (3, "EndTime", "float", 0.0),
        (4, "DamageRang", "float", 0.0),
        (5, "BackHeroDis", "float", 0.0),
        (6, "BackNpcDis", "float", 0.0),
        (7, "BeAttackParticle", "string", b""),
        (8, "MethodCall", "string", b""),
        (9, "MethodParam", "string", b""),
        (10, "TargetMethodCall", "string", b""),
        (11, "TargetMethodParam", "string", b""),
    ]


class SuwayyahEvents(Message):
    FIELDS = [(1, "xSuwayyahList", R(Suwayyah), None)]


class TacheBomp(Message):
    FIELDS = [
        (1, "BompTime", "float", 0.0),
        (2, "BompRang", "float", 0.0),
        (3, "BompPrefabPath", "string", b""),
        (4, "BeAttackParticle", "string", b""),
        (5, "BackNpcDis", "float", 0.0),
        (6, "BackHeroDis", "float", 0.0),
        (7, "MethodCall", "string", b""),
        (8, "MethodParam", "string", b""),
        (9, "TargetMethodCall", "string", b""),
        (10, "TargetMethodParam", "string", b""),
    ]


class Bullet(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "Speed", "float", 0.0),
        (4, "MaxDis", "float", 0.0),
        (5, "BulletRang", "float", 0.0),
        (6, "BulletBackType", "enum", 0),
        (7, "BackHeroDis", "float", 0.0),
        (8, "BackNpcDis", "float", 0.0),
        (9, "TacheDetroy", "int32", 0),
        (10, "BeAttackParticle", "string", b""),
        (11, "FireTacheName", "string", b""),
        (12, "FireTacheOffest", FSVector3, None),
        (13, "BulletPrefabPath", "string", b""),
        (14, "MethodCall", "string", b""),
        (15, "MethodParam", "string", b""),
        (16, "TargetMethodCall", "string", b""),
        (17, "TargetMethodParam", "string", b""),
        (18, "Bomp", R(TacheBomp), None),
    ]


class BulletEvents(Message):
    FIELDS = [(1, "xBulletList", R(Bullet), None)]


class Move(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "MoveDis", "float", 0.0),
        (4, "MoveTime", "float", 0.0),
        (5, "MethodCall", "string", b""),
        (6, "MethodParam", "string", b""),
    ]


class AnimatorMoves(Message):
    FIELDS = [(1, "xMoveList", R(Move), None)]


class Camera(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "AmountParam", FSVector3, None),
        (4, "ShakeTime", "float", 0.0),
        (5, "MethodCall", "string", b""),
        (6, "MethodParam", "string", b""),
    ]


class CameraControlEvents(Message):
    FIELDS = [(1, "xCameraList", R(Camera), None)]


class Particle(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (3, "Rotation", "enum", 0),
        (4, "ParticlePath", "string", b""),
        (5, "TargetTacheName", "string", b""),
        (6, "TargetTacheOffest", FSVector3, None),
        (7, "CastToSurface", "int32", 0),
        (8, "BindTarget", "int32", 0),
        (9, "DestroyTime", "float", 0.0),
        (10, "MethodCall", "string", b""),
        (11, "MethodParam", "string", b""),
    ]


class ParticleEvents(Message):
    FIELDS = [(1, "xParticleList", R(Particle), None)]


class Enable(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "TargetName", "string", b""),
        (4, "MethodCall", "string", b""),
        (5, "MethodParam", "string", b""),
    ]


class EnableEvents(Message):
    FIELDS = [(1, "xEnableList", R(Enable), None)]


class Trail(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "TargetName", "string", b""),
        (4, "MethodCall", "string", b""),
        (5, "MethodParam", "string", b""),
    ]


class TrailEvents(Message):
    FIELDS = [(1, "xTrailList", R(Trail), None)]


class Audio(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "AudioName", "string", b""),
        (4, "MethodCall", "string", b""),
        (5, "MethodParam", "string", b""),
    ]


class AudioEvents(Message):
    FIELDS = [(1, "xAudioList", R(Audio), None)]


class Speed(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "SpeedValue", "float", 0.0),
    ]


class GlobalSpeeds(Message):
    FIELDS = [(1, "xSpeedList", R(Speed), None)]


class Fly(Message):
    FIELDS = [
        (1, "EventTime", "float", 0.0),
        (2, "EventType", "enum", 0),
        (3, "MoveDis", "float", 0.0),
        (4, "MoveTime", "float", 0.0),
        (5, "MoveTopDis", "float", 0.0),
        (6, "MethodCall", "string", b""),
        (7, "MethodParam", "string", b""),
    ]


class AnimatorFlys(Message):
    FIELDS = [(1, "xFlyList", R(Fly), None)]
