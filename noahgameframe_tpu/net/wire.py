"""Minimal proto2 wire-format codec + the NF message set.

The reference speaks protobuf (`NFComm/NFMessageDefine/*.proto`) inside
its 6-byte frames; to stay byte-compatible with existing Unity/Cocos
clients without depending on protoc-generated code, this module
implements the protobuf wire format directly (varint / fixed32 /
length-delimited) and declares the handful of messages the framework
needs (`NFMsgBase.proto`, `NFMsgPreGame.proto`, `NFMsgShare.proto`).

Messages are declared with a tiny DSL:

    class Ident(Message):
        FIELDS = [(1, "svrid", "int64", 0), (2, "index", "int64", 0)]

Encoding skips fields equal to ``None``; decoding tolerates unknown
fields (skips them by wire type), matching protobuf semantics.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple, Type

# ---------------------------------------------------------------- varint


def _enc_varint(out: bytearray, v: int) -> None:
    if v < 0:
        v &= (1 << 64) - 1  # proto2 negative ints are 10-byte varints
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _dec_varint(buf: bytes, off: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _signed32(v: int) -> int:
    v &= (1 << 32) - 1
    return v - (1 << 32) if v >= (1 << 31) else v


_F32 = struct.Struct("<f")
_F64 = struct.Struct("<d")

# wire types
_WT_VARINT, _WT_64BIT, _WT_LEN, _WT_32BIT = 0, 1, 2, 5

_WIRE_TYPE = {
    "int32": _WT_VARINT,
    "int64": _WT_VARINT,
    "uint64": _WT_VARINT,
    "bool": _WT_VARINT,
    "enum": _WT_VARINT,
    "float": _WT_32BIT,
    "double": _WT_64BIT,
    "bytes": _WT_LEN,
    "string": _WT_LEN,
}


class Message:
    """Base class: subclasses declare FIELDS = [(tag, name, type, default)].

    type is one of the scalar names above, a Message subclass (embedded
    message), or ("repeated", inner) for repeated fields.
    """

    FIELDS: List[Tuple[int, str, Any, Any]] = []

    # populated lazily per-class
    _by_tag: Optional[Dict[int, Tuple[str, Any, bool]]] = None

    def __init__(self, **kw: Any) -> None:
        for _, name, ftype, default in self.FIELDS:
            if isinstance(ftype, tuple):  # repeated
                setattr(self, name, list(kw.get(name) or []))
            else:
                setattr(self, name, kw.get(name, default))
        bad = set(kw) - {f[1] for f in self.FIELDS}
        if bad:
            raise TypeError(f"{type(self).__name__}: unknown fields {bad}")

    # -------------------------------------------------------- encoding
    def encode(self) -> bytes:
        out = bytearray()
        for tag, name, ftype, _ in self.FIELDS:
            val = getattr(self, name)
            if isinstance(ftype, tuple):
                inner = ftype[1]
                for item in val:
                    _enc_field(out, tag, inner, item)
            elif val is not None:
                _enc_field(out, tag, ftype, val)
        return bytes(out)

    # -------------------------------------------------------- decoding
    @classmethod
    def decode(cls, data: bytes) -> "Message":
        if cls._by_tag is None or "_by_tag" not in cls.__dict__:
            cls._by_tag = {
                tag: (name, ftype, isinstance(ftype, tuple))
                for tag, name, ftype, _ in cls.FIELDS
            }
        msg = cls()
        off = 0
        n = len(data)
        while off < n:
            key, off = _dec_varint(data, off)
            tag, wt = key >> 3, key & 7
            spec = cls._by_tag.get(tag)
            if spec is None:
                off = _skip(data, off, wt)
                continue
            name, ftype, repeated = spec
            inner = ftype[1] if repeated else ftype
            val, off = _dec_field(data, off, wt, inner)
            if repeated:
                getattr(msg, name).append(val)
            else:
                setattr(msg, name, val)
        return msg

    # ------------------------------------------------------ niceties
    def __repr__(self) -> str:
        parts = ", ".join(
            f"{name}={getattr(self, name)!r}"
            for _, name, _, _ in self.FIELDS
            if getattr(self, name) not in (None, [])
        )
        return f"{type(self).__name__}({parts})"

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and all(
            getattr(self, f[1]) == getattr(other, f[1]) for f in self.FIELDS
        )


def _enc_field(out: bytearray, tag: int, ftype: Any, val: Any) -> None:
    if isinstance(ftype, type) and issubclass(ftype, Message):
        _enc_varint(out, tag << 3 | _WT_LEN)
        body = val.encode()
        _enc_varint(out, len(body))
        out.extend(body)
        return
    wt = _WIRE_TYPE[ftype]
    _enc_varint(out, tag << 3 | wt)
    if wt == _WT_VARINT:
        _enc_varint(out, int(val))
    elif wt == _WT_32BIT:
        out.extend(_F32.pack(val))
    elif wt == _WT_64BIT:
        out.extend(_F64.pack(val))
    else:
        if isinstance(val, str):
            val = val.encode("utf-8")
        _enc_varint(out, len(val))
        out.extend(val)


def _dec_field(buf: bytes, off: int, wt: int, ftype: Any) -> Tuple[Any, int]:
    if isinstance(ftype, type) and issubclass(ftype, Message):
        ln, off = _dec_varint(buf, off)
        return ftype.decode(buf[off : off + ln]), off + ln
    if wt == _WT_VARINT:
        v, off = _dec_varint(buf, off)
        if ftype in ("int32", "enum"):
            # protoc treats enum exactly like int32: negative values ride
            # as 10-byte two's-complement varints and truncate back
            v = _signed32(v)
        elif ftype == "int64":
            v = _signed64(v)
        elif ftype == "bool":
            v = bool(v)
        return v, off
    if wt == _WT_32BIT:
        return _F32.unpack_from(buf, off)[0], off + 4
    if wt == _WT_64BIT:
        return _F64.unpack_from(buf, off)[0], off + 8
    ln, off = _dec_varint(buf, off)
    return bytes(buf[off : off + ln]), off + ln


def _skip(buf: bytes, off: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, off = _dec_varint(buf, off)
        return off
    if wt == _WT_64BIT:
        return off + 8
    if wt == _WT_32BIT:
        return off + 4
    if wt == _WT_LEN:
        ln, off = _dec_varint(buf, off)
        return off + ln
    raise ValueError(f"unsupported wire type {wt}")


def R(inner: Any) -> Tuple[str, Any]:
    """repeated-field marker."""
    return ("repeated", inner)


# =====================================================================
# NFMsgBase.proto equivalents (field tags byte-compatible)
# =====================================================================


class Ident(Message):
    """128-bit GUID on the wire (`NFMsgBase.proto` Ident{svrid,index})."""

    FIELDS = [(1, "svrid", "int64", 0), (2, "index", "int64", 0)]


def ident_key(i: Optional["Ident"]) -> Tuple[int, int]:
    """Hashable identity of a wire Ident (routing-table key)."""
    return (i.svrid, i.index) if i is not None else (0, 0)


class Vector2(Message):
    FIELDS = [(1, "x", "float", 0.0), (2, "y", "float", 0.0)]


class Vector3(Message):
    FIELDS = [(1, "x", "float", 0.0), (2, "y", "float", 0.0), (3, "z", "float", 0.0)]


class MsgBase(Message):
    """The routing envelope every framed payload is wrapped in
    (`NFMsgBase.proto:281-287`)."""

    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "msg_data", "bytes", b""),
        (3, "player_client_list", R(Ident), None),
        (4, "hash_ident", Ident, None),
    ]


def scan_envelope_targets(body: bytes) -> List[Tuple[int, int]]:
    """Routing keys of a :class:`MsgBase` envelope, without decoding it.

    The proxy's scatter path (`ProxyRole._transpond`) needs only the
    client list to fan a frame out — not the (possibly megabyte)
    ``msg_data`` payload and not per-client ``Ident`` objects.  This
    walks the top-level fields once: ``msg_data`` is skipped in O(1)
    after its length varint, and each embedded Ident is decoded straight
    into its :func:`ident_key` tuple.  Returns the
    ``player_client_list`` keys, falling back to ``player_id`` when the
    list is absent (the same semantics ``_transpond`` applies to the
    decoded envelope).  Torn framing raises ``ValueError``/``IndexError``
    — callers fall back to the tolerant full decode.
    """
    targets: List[Tuple[int, int]] = []
    player: Optional[Tuple[int, int]] = None
    off, n = 0, len(body)
    while off < n:
        key, off = _dec_varint(body, off)
        tag, wt = key >> 3, key & 7
        if wt == _WT_LEN and tag in (1, 3):
            ln, off = _dec_varint(body, off)
            end = off + ln
            svrid = index = 0
            while off < end:
                ik, off = _dec_varint(body, off)
                itag, iwt = ik >> 3, ik & 7
                if iwt == _WT_VARINT and itag in (1, 2):
                    v, off = _dec_varint(body, off)
                    if itag == 1:
                        svrid = _signed64(v)
                    else:
                        index = _signed64(v)
                else:
                    off = _skip(body, off, iwt)
            off = end
            if tag == 3:
                targets.append((svrid, index))
            else:
                player = (svrid, index)
        else:
            off = _skip(body, off, wt)
    if targets:
        return targets
    return [player] if player is not None else []


class Position(Message):
    FIELDS = [(1, "x", "float", 0.0), (2, "y", "float", 0.0), (3, "z", "float", 0.0)]


# ---- property / record sync ----------------------------------------


class PropertyInt(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", "int64", 0)]


class PropertyFloat(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", "float", 0.0)]


class PropertyString(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", "bytes", b"")]


class PropertyObject(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", Ident, None)]


class PropertyVector2(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", Vector2, None)]


class PropertyVector3(Message):
    FIELDS = [(1, "property_name", "bytes", b""), (2, "data", Vector3, None)]


class ObjectPropertyList(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_int_list", R(PropertyInt), None),
        (3, "property_float_list", R(PropertyFloat), None),
        (4, "property_string_list", R(PropertyString), None),
        (5, "property_object_list", R(PropertyObject), None),
        (6, "property_vector2_list", R(PropertyVector2), None),
        (7, "property_vector3_list", R(PropertyVector3), None),
    ]


class ObjectPropertyInt(Message):
    FIELDS = [(1, "player_id", Ident, None), (2, "property_list", R(PropertyInt), None)]


class ObjectPropertyFloat(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_list", R(PropertyFloat), None),
    ]


class ObjectPropertyString(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_list", R(PropertyString), None),
    ]


class ObjectPropertyObject(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_list", R(PropertyObject), None),
    ]


class ObjectPropertyVector2(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_list", R(PropertyVector2), None),
    ]


class ObjectPropertyVector3(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "property_list", R(PropertyVector3), None),
    ]


class RecordInt(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", "int64", 0)]


class RecordFloat(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", "float", 0.0)]


class RecordString(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", "bytes", b"")]


class RecordObject(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", Ident, None)]


class RecordVector2(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", Vector2, None)]


class RecordVector3(Message):
    FIELDS = [(1, "row", "int32", 0), (2, "col", "int32", 0), (3, "data", Vector3, None)]


class RecordAddRowStruct(Message):
    FIELDS = [
        (1, "row", "int32", 0),
        (2, "record_int_list", R(RecordInt), None),
        (3, "record_float_list", R(RecordFloat), None),
        (4, "record_string_list", R(RecordString), None),
        (5, "record_object_list", R(RecordObject), None),
        (6, "record_vector2_list", R(RecordVector2), None),
        (7, "record_vector3_list", R(RecordVector3), None),
    ]


class ObjectRecordBase(Message):
    FIELDS = [
        (1, "record_name", "bytes", b""),
        (2, "row_struct", R(RecordAddRowStruct), None),
    ]


class ObjectRecordList(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_list", R(ObjectRecordBase), None),
    ]


# ---- per-change record sync (reference NFMsgBase.proto:183-251; the
# messages NFCGameServerNet_ServerModule::OnRecordEvent emits per op) ----


class ObjectRecordInt(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordInt), None),
    ]


class ObjectRecordFloat(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordFloat), None),
    ]


class ObjectRecordString(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordString), None),
    ]


class ObjectRecordObject(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordObject), None),
    ]


class ObjectRecordVector2(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordVector2), None),
    ]


class ObjectRecordVector3(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "property_list", R(RecordVector3), None),
    ]


class ObjectRecordSwap(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "origin_record_name", "bytes", b""),
        (3, "target_record_name", "bytes", None),
        (4, "row_origin", "int32", 0),
        (5, "row_target", "int32", 0),
    ]


class ObjectRecordAddRow(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "row_data", R(RecordAddRowStruct), None),
    ]


class ObjectRecordRemove(Message):
    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "record_name", "bytes", b""),
        (3, "remove_row", R("int32"), None),
    ]


# =====================================================================
# NFMsgPreGame.proto equivalents — cluster control plane
# =====================================================================


class ServerInfoExt(Message):
    FIELDS = [(1, "key", R("bytes"), None), (2, "value", R("bytes"), None)]


class ServerInfoReport(Message):
    """10-second server heartbeat to Master (`NFMsgPreGame.proto:24-36`)."""

    FIELDS = [
        (1, "server_id", "int32", 0),
        (2, "server_name", "bytes", b""),
        (3, "server_ip", "bytes", b""),
        (4, "server_port", "int32", 0),
        (5, "server_max_online", "int32", 0),
        (6, "server_cur_count", "int32", 0),
        (7, "server_state", "enum", 1),
        (8, "server_type", "int32", 0),
        (9, "server_info_list_ext", ServerInfoExt, None),
    ]


class ServerInfoReportList(Message):
    FIELDS = [(1, "server_list", R(ServerInfoReport), None)]


class AckEventResult(Message):
    FIELDS = [
        (1, "event_code", "enum", 0),
        (2, "event_object", Ident, None),
        (3, "event_client", Ident, None),
    ]


class ReqAccountLogin(Message):
    FIELDS = [
        (2, "account", "bytes", b""),
        (3, "password", "bytes", b""),
        (4, "security_code", "bytes", b""),
        (5, "sign_buff", "bytes", b""),
        (6, "client_version", "int32", 0),
        (7, "login_mode", "int32", 0),
        (8, "client_ip", "int32", 0),
        (9, "client_mac", "int64", 0),
        (10, "device_info", "bytes", b""),
        (11, "extra_info", "bytes", b""),
        (12, "platform_type", "int32", None),
    ]


class ServerInfo(Message):
    FIELDS = [
        (1, "server_id", "int32", 0),
        (2, "name", "bytes", b""),
        (3, "wait_count", "int32", 0),
        (4, "status", "enum", 1),
    ]


class ReqServerList(Message):
    FIELDS = [(1, "type", "enum", 0)]


class AckServerList(Message):
    FIELDS = [(1, "type", "enum", 0), (2, "info", R(ServerInfo), None)]


class ReqConnectWorld(Message):
    FIELDS = [
        (1, "world_id", "int32", 0),
        (2, "account", "bytes", b""),
        (3, "sender", Ident, None),
        (4, "login_id", "int32", 0),
    ]


class AckConnectWorldResult(Message):
    FIELDS = [
        (1, "world_id", "int32", 0),
        (2, "sender", Ident, None),
        (3, "login_id", "int32", 0),
        (4, "account", "bytes", b""),
        (5, "world_ip", "bytes", b""),
        (6, "world_port", "int32", 0),
        (7, "world_key", "bytes", b""),
    ]


class ReqSelectServer(Message):
    FIELDS = [(1, "world_id", "int32", 0)]


class ReqRoleList(Message):
    FIELDS = [(1, "game_id", "int32", 0), (2, "account", "bytes", b"")]


class RoleLiteInfo(Message):
    FIELDS = [
        (1, "id", Ident, None),
        (2, "career", "int32", 0),
        (3, "sex", "int32", 0),
        (4, "race", "int32", 0),
        (5, "noob_name", "bytes", b""),
        (6, "game_id", "int32", 0),
        (7, "role_level", "int32", 0),
        (8, "delete_time", "int32", 0),
        (9, "reg_time", "int32", 0),
        (10, "last_offline_time", "int32", 0),
        (11, "last_offline_ip", "int32", 0),
        (12, "view_record", "bytes", b""),
    ]


class AckRoleLiteInfoList(Message):
    FIELDS = [(1, "char_data", R(RoleLiteInfo), None)]


class ReqCreateRole(Message):
    FIELDS = [
        (1, "account", "bytes", b""),
        (2, "career", "int32", 0),
        (3, "sex", "int32", 0),
        (4, "race", "int32", 0),
        (5, "noob_name", "bytes", b""),
        (6, "game_id", "int32", 0),
    ]


class ReqDeleteRole(Message):
    FIELDS = [
        (1, "account", "bytes", b""),
        (2, "name", "bytes", b""),
        (3, "game_id", "int32", 0),
    ]


class ServerHeartBeat(Message):
    FIELDS = [(1, "count", "int32", 0)]


class BatchPropertySync(Message):
    """TPU-native columnar sync (msg id ACK_BATCH_PROPERTY, outside the
    reference message space): every changed entity's value for ONE
    (class, property), packed as little-endian arrays — the wire mirror
    of the SoA store.  `ptype` is the DataType enum; `data` holds
    int32[n] / float32[n] / float32[n*3] depending on ptype; guids ride
    as i64 pairs.  Encoding stays valid proto2 (bytes fields), so
    unaware reference clients skip it cleanly by field type."""

    FIELDS = [
        (1, "class_name", "bytes", b""),
        (2, "property_name", "bytes", b""),
        (3, "ptype", "int32", 0),
        (4, "count", "int32", 0),
        (5, "svrid", "bytes", b""),  # i64le[n]
        (6, "index", "bytes", b""),  # i64le[n]
        (7, "data", "bytes", b""),
    ]


class InterestPosSync(Message):
    """TPU-native per-session position stream (msg id ACK_INTEREST_POS):
    ONLY the entities inside this client's interest radius, positions
    quantized to u16 over the scene extent (`scale` = extent / 65535 —
    multiply back on the client).  Replaces group-wide Position fan-out
    when the game role runs with an interest radius; guids ride as i64
    pairs like BatchPropertySync.  qpos holds u16le[n*3].

    The stream is a per-session DELTA (only entities this session hasn't
    seen at this quantized position), so leave-view must be explicit:
    `gone_svrid`/`gone_index` list the entities that dropped out of this
    observer's radius (or died) since the last message — the client
    despawns them (the reference's OnObjectListLeave)."""

    FIELDS = [
        (1, "scale", "float", 0.0),
        (2, "count", "int32", 0),
        (3, "svrid", "bytes", b""),  # i64le[n]
        (4, "index", "bytes", b""),  # i64le[n]
        (5, "qpos", "bytes", b""),  # u16le[n*3]
        (6, "gone_svrid", "bytes", b""),  # i64le[m]
        (7, "gone_index", "bytes", b""),  # i64le[m]
    ]


class ReqSwitchServer(Message):
    """Cross-game-server player switch request
    (`NFMsgShare.proto:527-536`, EGMI_REQSWICHSERVER) — game A asks game
    B (via World) to take over a player."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "self_serverid", "int64", 0),
        (3, "target_serverid", "int64", 0),
        (4, "gate_serverid", "int64", 0),
        (5, "scene_id", "int64", 0),
        (6, "client_id", Ident, None),
        (7, "group_id", "int64", 0),
    ]


class AckSwitchServer(Message):
    """Switch completed on the target (`NFMsgShare.proto:539-545`,
    EGMI_ACKSWICHSERVER) — game A destroys its copy on receipt."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "self_serverid", "int64", 0),
        (3, "target_serverid", "int64", 0),
        (4, "gate_serverid", "int64", 0),
    ]


class SwitchServerData(Message):
    """TPU-native companion to ReqSwitchServer (msg id
    SWITCH_SERVER_DATA): the player's serialized save-flag state
    (persist.codec snapshot blob) plus the identity keys, so the target
    game re-homes the player without a shared database — the reference
    relies on both games loading the same DB row."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "account", "bytes", b""),
        (3, "name", "bytes", b""),
        (4, "blob", "bytes", b""),
        (5, "target_serverid", "int64", 0),
    ]


class ReqSetFightHero(Message):
    """Pick the battle line-up hero (`NFMsgShare.proto:481-486`,
    EGEC_REQ_SET_FIGHT_HERO).  Heroes are row-identified here, so the
    hero's PlayerHero record row rides `heroid.index` (svrid 0)."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "heroid", Ident, None),
        (3, "fight_pos", "int32", 0),
    ]


class RoleOnlineNotify(Message):
    """Game → World: a player came online (player guid rides the MsgBase
    envelope; `NFMsgPreGame.proto` RoleOnlineNotify)."""

    FIELDS = [(1, "guild", Ident, None)]


class RoleOfflineNotify(Message):
    FIELDS = [(1, "guild", Ident, None)]


class SwitchNotice(Message):
    """Proxy → client (msg id ACK_SWITCH_NOTICE): the bound game died.
    TPU-native — the reference lets orphaned clients time out; we tell
    them what is happening (re-home in flight / retry later / parked
    frames dropped).  Codes in :class:`net.defines.SwitchNoticeCode`."""

    FIELDS = [
        (1, "code", "int32", 0),
        (2, "target_serverid", "int64", 0),
        (3, "retry_after_ms", "int64", 0),
    ]


class SessionBindNotify(Message):
    """Game → world (msg id SESSION_BIND_NOTIFY): sidecar to
    ACK_ONLINE_NOTIFY carrying the session metadata the world's failover
    driver needs to re-home this player if the owning game dies without
    ever being asked — account/name (the durable save identity),
    client ident (the proxy-side session key), scene/group, and the
    exact persist key the player's blob lives under."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "account", "bytes", b""),
        (3, "name", "bytes", b""),
        (4, "client_id", Ident, None),
        (5, "scene_id", "int64", 0),
        (6, "group_id", "int64", 0),
        (7, "save_key", "bytes", b""),
        (8, "game_id", "int64", 0),
    ]


class SwitchRefused(Message):
    """Target game → world (msg id ACK_SWITCH_REFUSED): a staged
    switch-in could not be admitted (capacity, torn blob).  The
    reference's AckSwitchServer has no failure leg — extending it would
    break protoc byte-compat — so refusal rides its own message and the
    failover driver retries a different survivor."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "self_serverid", "int64", 0),
        (3, "target_serverid", "int64", 0),
        (4, "result", "int32", 0),
    ]


# =====================================================================
# NFMsgShare.proto equivalents — in-game
# =====================================================================


class ReqEnterGameServer(Message):
    FIELDS = [
        (1, "id", Ident, None),
        (2, "account", "bytes", b""),
        (3, "game_id", "int32", 0),
        (4, "name", "bytes", b""),
    ]


class PlayerEntryInfo(Message):
    FIELDS = [
        (1, "object_guid", Ident, None),
        (2, "x", "float", 0.0),
        (3, "y", "float", 0.0),
        (4, "z", "float", 0.0),
        (5, "career_type", "int32", 0),
        (6, "player_state", "int32", 0),
        (7, "config_id", "bytes", b""),
        (8, "scene_id", "int32", 0),
        (9, "class_id", "bytes", b""),
    ]


class AckPlayerEntryList(Message):
    FIELDS = [(1, "object_list", R(PlayerEntryInfo), None)]


class AckPlayerLeaveList(Message):
    FIELDS = [(1, "object_list", R(Ident), None)]


class ReqAckPlayerMove(Message):
    FIELDS = [
        (1, "mover", Ident, None),
        (2, "move_type", "int32", 0),
        (3, "target_pos", R(Position), None),
        (4, "source_pos", R(Position), None),
    ]


class ChatContainer(Message):
    FIELDS = [(2, "container_type", "int32", 0), (3, "data_info", "bytes", b"")]


class ReqAckPlayerChat(Message):
    FIELDS = [
        (1, "chat_id", Ident, None),
        (2, "chat_type", "enum", 0),
        (3, "chat_info", "bytes", b""),
        (4, "chat_name", "bytes", b""),
        (5, "target_id", Ident, None),
        (6, "container_data", R(ChatContainer), None),
    ]


class EffectData(Message):
    FIELDS = [
        (1, "effect_ident", Ident, None),
        (2, "effect_value", "int32", 0),
        (3, "effect_rlt", "enum", 0),
    ]


class ReqAckUseSkill(Message):
    FIELDS = [
        (1, "user", Ident, None),
        (2, "skill_id", "bytes", b""),
        (3, "now_pos", Position, None),
        (4, "tar_pos", Position, None),
        (5, "use_index", "int32", 0),
        (6, "effect_data", R(EffectData), None),
    ]


class ReqAckSwapScene(Message):
    FIELDS = [
        (1, "transfer_type", "enum", 0),
        (2, "scene_id", "int32", 0),
        (3, "line_id", "int32", 0),
        (4, "x", "float", None),
        (5, "y", "float", None),
        (6, "z", "float", None),
    ]


class ItemStruct(Message):
    """`NFMsgShare.proto:155-159` — config id + count."""

    FIELDS = [
        (1, "item_id", "string", b""),
        (2, "item_count", "int32", 0),
    ]


class ReqAckUseItem(Message):
    """Use-item request/ack (`NFMsgShare.proto:128-135`,
    EGMI_REQ_ITEM_OBJECT).  Items are ConfigID-keyed stackables here, so
    `item.item_id` names what to use; family-specific targets (hero row,
    equip row) ride `targetid.index` with `targetid.svrid == 1` (the
    game role's ROW_TARGET_SVRID tag — row 0 is a valid record row, and
    a required-field protoc client sends a ZEROED ident when it has no
    target, so the index alone cannot discriminate)."""

    FIELDS = [
        (1, "user", Ident, None),
        (2, "item_guid", Ident, None),
        (3, "effect_data", R(EffectData), None),
        (4, "item", ItemStruct, None),
        (5, "targetid", Ident, None),
    ]


class ReqWearEquip(Message):
    """`NFMsgShare.proto:489-495`, EGEC_WEAR_EQUIP — the BagEquipList
    row rides `equipid.index` (row-identified equips)."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "equipid", Ident, None),
        (3, "target_id", Ident, None),
    ]


class TakeOffEquip(Message):
    """`NFMsgShare.proto:498-503`, EGEC_TAKEOFF_EQUIP."""

    FIELDS = [
        (1, "selfid", Ident, None),
        (2, "equipid", Ident, None),
        (3, "target_id", Ident, None),
    ]


class ReqAcceptTask(Message):
    """`NFMsgShare.proto:183-186`, EGMI_REQ_ACCEPT_TASK."""

    FIELDS = [(1, "task_id", "bytes", b"")]


class ReqCompeleteTask(Message):
    """`NFMsgShare.proto:188-191` (reference's own spelling),
    EGMI_REQ_COMPELETE_TASK — claim the award of a DONE task."""

    FIELDS = [(1, "task_id", "bytes", b"")]


class TeammemberInfo(Message):
    """`NFMsgShare.proto:555-562`."""

    FIELDS = [
        (1, "player_id", Ident, None),
        (2, "name", "string", b""),
        (3, "nLevel", "int32", 0),
        (4, "job", "int32", 0),
        (5, "HeadIcon", "string", b""),
    ]


class TeamInfo(Message):
    """`NFMsgShare.proto:548-553`."""

    FIELDS = [
        (1, "team_id", Ident, None),
        (2, "captain_id", Ident, None),
        (3, "teammemberInfo", R(TeammemberInfo), None),
    ]


class ReqAckCreateTeam(Message):
    """`NFMsgShare.proto:566-570`, EGMI_REQ/ACK_CREATE_TEAM."""

    FIELDS = [
        (1, "team_id", Ident, None),
        (2, "xTeamInfo", TeamInfo, None),
    ]


class ReqAckJoinTeam(Message):
    FIELDS = [
        (1, "team_id", Ident, None),
        (2, "xTeamInfo", TeamInfo, None),
    ]


class ReqAckLeaveTeam(Message):
    FIELDS = [
        (1, "team_id", Ident, None),
        (2, "xTeamInfo", TeamInfo, None),
    ]


class ReqAckOprTeamMember(Message):
    """`NFMsgShare.proto:591-612`, EGMI_REQ/ACK_OPRMEMBER_TEAM —
    captain member operations (KICK etc.)."""

    FIELDS = [
        (1, "team_id", Ident, None),
        (2, "member_id", Ident, None),
        (3, "type", "enum", 0),
        (4, "xTeamInfo", TeamInfo, None),
    ]


class ReqAckCreateGuild(Message):
    """`NFMsgShare.proto:235-239`, EGMI_REQ/ACK_CREATE_GUILD."""

    FIELDS = [
        (1, "guild_id", Ident, None),
        (2, "guild_name", "string", b""),
    ]


class ReqAckJoinGuild(Message):
    FIELDS = [
        (1, "guild_id", Ident, None),
        (2, "guild_name", "string", b""),
    ]


class ReqAckLeaveGuild(Message):
    FIELDS = [
        (1, "guild_id", Ident, None),
        (2, "guild_name", "string", b""),
    ]


class ReqSearchGuild(Message):
    """`NFMsgShare.proto:241-244`, EGMI_REQ_SEARCH_GUILD."""

    FIELDS = [(1, "guild_name", "string", b"")]


class ReqCommand(Message):
    """GM command (`NFMsgBase.proto:296-312`, EGMI_REQ_CMD_NORMAL):
    EGCT_MODIY_PROPERTY / MODIY_ITEM / CREATE_OBJECT / ADD_ROLE_EXP."""

    FIELDS = [
        (1, "control_id", Ident, None),
        (2, "command_id", "enum", 0),
        (3, "command_str_value", "bytes", None),
        (4, "command_value_int", "int64", None),
        (5, "command_value_float", "double", None),
        (6, "command_value_str", "bytes", None),
        (7, "command_value_object", Ident, None),
        (8, "row", "int32", None),
    ]


class PVPRoomInfo(Message):
    """`NFMsgShare.proto:772-784`."""

    FIELDS = [
        (1, "nCellStatus", "int32", 0),
        (2, "RoomID", Ident, None),
        (3, "nPVPMode", "int32", 0),
        (4, "nPVPGrade", "int32", 0),
        (5, "MaxPalyer", "int32", 0),
        (6, "xRedPlayer", R(Ident), None),
        (7, "xBluePlayer", R(Ident), None),
        (8, "serverid", "int64", None),
        (9, "SceneID", "int64", None),
        (10, "groupID", "int64", None),
    ]


class ReqPVPApplyMatch(Message):
    """`NFMsgShare.proto:787-801`, EGMI_REQ_PVPAPPLYMACTCH."""

    FIELDS = [
        (1, "self_id", Ident, None),
        (2, "nPVPMode", "int32", 0),
        (3, "score", "int64", None),
        (4, "ApplyType", "int32", 0),
        (5, "team_id", Ident, None),
    ]


class AckPVPApplyMatch(Message):
    """`NFMsgShare.proto:803-810`."""

    FIELDS = [
        (1, "self_id", Ident, None),
        (2, "xRoomInfo", PVPRoomInfo, None),
        (3, "ApplyType", "int32", 0),
        (4, "nResult", "int32", 0),
    ]


class ReqCreatePVPEctype(Message):
    """`NFMsgShare.proto:812-817`, EGMI_REQ_CREATEPVPECTYPE."""

    FIELDS = [
        (1, "self_id", Ident, None),
        (2, "xRoomInfo", PVPRoomInfo, None),
    ]


class AckCreatePVPEctype(Message):
    """`NFMsgShare.proto:819-825`."""

    FIELDS = [
        (1, "self_id", Ident, None),
        (2, "xRoomInfo", PVPRoomInfo, None),
        (3, "ApplyType", "int32", 0),
    ]


class SearchGuildObject(Message):
    """Nested result row of AckSearchGuild (`NFMsgShare.proto:247-257`)."""

    FIELDS = [
        (1, "guild_ID", Ident, None),
        (2, "guild_name", "string", b""),
        (3, "guild_icon", "string", b""),
        (4, "guild_member_count", "int32", 0),
        (5, "guild_member_max_count", "int32", 0),
        (6, "guild_honor", "int32", 0),
        (7, "guild_rank", "int32", 0),
    ]


class AckSearchGuild(Message):
    FIELDS = [(1, "guild_list", R(SearchGuildObject), None)]


def wrap(msg: Message, player_id: Optional[Ident] = None, clients=None,
         hash_ident: Optional[Ident] = None) -> bytes:
    """Encode a payload inside the MsgBase envelope (SendMsgPB path,
    `NFINetModule.h:316-471`)."""
    return MsgBase(
        player_id=player_id or Ident(),
        msg_data=msg.encode(),
        player_client_list=clients or [],
        hash_ident=hash_ident,
    ).encode()


def unwrap(data: bytes, payload_cls: Optional[Type[Message]] = None):
    """Decode a MsgBase envelope; optionally decode its payload too
    (ReceivePB path, `NFINetModule.h:263-300`)."""
    base = MsgBase.decode(data)
    if payload_cls is None:
        return base, None
    return base, payload_cls.decode(base.msg_data)
