"""Network stack: the distributed communication edge of the framework.

TPU-native rethink of the reference's libevent/protobuf stack
(SURVEY §2.4): device-side state exchange rides XLA collectives
(:mod:`noahgameframe_tpu.parallel`); this package is the *host edge* —
byte-compatible NF framing + MsgBase envelope for clients and the
five-role control plane, with a native C++ epoll runtime
(``native/nfnet.cc``) and a pure-Python fallback.
"""

from . import defines, framing, wire
from .defines import MsgID, ServerState, ServerType
from .framing import FrameDecoder, ProtocolError, pack_frame, unpack_head
from .module import NetClientModule, NetServerModule
from .transport import (
    EV_CONNECTED,
    EV_DISCONNECTED,
    EV_MSG,
    NetEvent,
    PyNetClient,
    PyNetServer,
    create_client,
    create_server,
)
from .wire import Ident, Message, MsgBase, unwrap, wrap

__all__ = [
    "defines",
    "framing",
    "wire",
    "MsgID",
    "ServerState",
    "ServerType",
    "FrameDecoder",
    "ProtocolError",
    "pack_frame",
    "unpack_head",
    "NetClientModule",
    "NetServerModule",
    "EV_CONNECTED",
    "EV_DISCONNECTED",
    "EV_MSG",
    "NetEvent",
    "PyNetClient",
    "PyNetServer",
    "create_client",
    "create_server",
    "Ident",
    "Message",
    "MsgBase",
    "unwrap",
    "wrap",
]
