"""RetryPolicy: capped exponential backoff with deterministic jitter.

The reference reconnects on a fixed 10 s timer
(`NFINetClientModule.hpp:312-370`, `RECONNECT_SECONDS`).  A fixed timer
is both too slow for a healthy peer that bounced (always waits the full
period) and too aggressive for a dead one (every client in the cluster
re-dials in lockstep, a thundering herd on recovery).  The policy keeps
the old constant as the *base* delay — existing configs read unchanged —
and grows it exponentially per consecutive failure up to a cap, with a
deterministic per-(key, attempt) jitter so concurrent dialers de-sync
without making tests flaky: the same seed/key/attempt always yields the
same delay.
"""

from __future__ import annotations

import zlib

from .defines import RECONNECT_CAP_SECONDS, RECONNECT_SECONDS


class RetryPolicy:
    """``delay(attempt)`` = min(cap, base * factor^(attempt-1)) ± jitter.

    `attempt` counts consecutive failures (1 = first retry).  Jitter is
    a multiplicative ±`jitter` fraction derived from crc32(seed, key,
    attempt) — reproducible, no shared RNG state, distinct per link.
    """

    def __init__(self, base: float = RECONNECT_SECONDS,
                 cap: float = RECONNECT_CAP_SECONDS,
                 factor: float = 2.0, jitter: float = 0.25,
                 seed: int = 0) -> None:
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def delay(self, attempt: int, key: object = 0) -> float:
        d = min(self.cap, self.base * self.factor ** max(0, int(attempt) - 1))
        if self.jitter:
            h = zlib.crc32(f"{self.seed}:{key}:{int(attempt)}".encode())
            u = h / 0xFFFFFFFF  # uniform [0, 1], deterministic
            d *= 1.0 + self.jitter * (2.0 * u - 1.0)
        return min(d, self.cap)
