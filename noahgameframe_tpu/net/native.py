"""ctypes binding for the native C++ epoll transport (native/nfnet.cc).

Builds ``libnfnet.so`` on demand with g++ (the image has no pybind11;
the flat C API + ctypes keeps the binding dependency-free).  The
classes expose the exact poll/send contract of the pure-Python backend
in :mod:`noahgameframe_tpu.net.transport`, so the two are drop-in
interchangeable via ``create_server/create_client``.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

from .transport import EV_CONNECTED, EV_DISCONNECTED, NetEvent

# repo checkout layout by default; installed environments point
# NF_NATIVE_DIR at a checkout of native/ (or anywhere holding
# nfnet.cc/Makefile) — create_server/create_client fall back to the
# pure-Python transport when neither exists
import os as _os

_NATIVE_DIR = Path(
    _os.environ.get("NF_NATIVE_DIR")
    or Path(__file__).resolve().parents[2] / "native"
)
_LIB_PATH = _NATIVE_DIR / "build" / "libnfnet.so"
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists():
            subprocess.run(
                ["make", "-s", "-C", str(_NATIVE_DIR)],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.nfnet_server_create.restype = ctypes.c_void_p
        lib.nfnet_server_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.nfnet_client_create.restype = ctypes.c_void_p
        lib.nfnet_client_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.nfnet_client_connect.restype = ctypes.c_int
        lib.nfnet_client_connect.argtypes = [ctypes.c_void_p]
        lib.nfnet_server_port.restype = ctypes.c_int
        lib.nfnet_server_port.argtypes = [ctypes.c_void_p]
        lib.nfnet_num_conns.restype = ctypes.c_int
        lib.nfnet_num_conns.argtypes = [ctypes.c_void_p]
        lib.nfnet_poll.restype = ctypes.c_int
        lib.nfnet_poll.argtypes = [ctypes.c_void_p]
        for fn in ("nfnet_event_kind", "nfnet_event_conn", "nfnet_event_msgid"):
            f = getattr(lib, fn)
            f.restype = ctypes.c_int
            f.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.nfnet_event_body.restype = ctypes.POINTER(ctypes.c_char)
        lib.nfnet_event_body.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.nfnet_send.restype = ctypes.c_int
        lib.nfnet_send.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.nfnet_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.nfnet_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class _NativeEndpoint:
    def __init__(self, handle: int) -> None:
        self._lib = _load()
        self._h = handle
        if not self._h:
            raise OSError("nfnet endpoint creation failed")

    def poll(self) -> List[NetEvent]:
        lib, h = self._lib, self._h
        n = lib.nfnet_poll(h)
        out: List[NetEvent] = []
        ln = ctypes.c_uint32()
        for i in range(n):
            kind = lib.nfnet_event_kind(h, i)
            cid = lib.nfnet_event_conn(h, i)
            if kind == 3:
                ptr = lib.nfnet_event_body(h, i, ctypes.byref(ln))
                body = ctypes.string_at(ptr, ln.value)
                out.append(NetEvent(kind, cid, lib.nfnet_event_msgid(h, i), body))
            else:
                out.append(NetEvent(kind, cid))
        return out

    def send(self, conn_id: int, msg_id: int, body: bytes) -> bool:
        return bool(self._lib.nfnet_send(self._h, conn_id, msg_id, body, len(body)))

    @property
    def num_connections(self) -> int:
        return self._lib.nfnet_num_conns(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.nfnet_destroy(self._h)
            self._h = 0

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class NativeNetServer(_NativeEndpoint):
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        lib = _load()
        super().__init__(lib.nfnet_server_create(host.encode(), port))
        self.port = lib.nfnet_server_port(self._h)

    def close_conn(self, conn_id: int) -> None:
        self._lib.nfnet_close_conn(self._h, conn_id)


class NativeNetClient(_NativeEndpoint):
    def __init__(self, host: str, port: int) -> None:
        lib = _load()
        super().__init__(lib.nfnet_client_create(host.encode(), port))
        self.host, self.port = host, port
        self._cid: Optional[int] = None
        self.connected = False

    def connect(self) -> None:
        cid = self._lib.nfnet_client_connect(self._h)
        self._cid = cid if cid > 0 else None
        if cid <= 0:
            # surface as a disconnect on next poll, matching the py backend
            self.connected = False

    def poll(self) -> List[NetEvent]:
        evs = super().poll()
        for ev in evs:
            if ev.kind == EV_CONNECTED:
                self.connected = True
            elif ev.kind == EV_DISCONNECTED and ev.conn_id == self._cid:
                self.connected = False
                self._cid = None
        return evs

    def send_msg(self, msg_id: int, body: bytes) -> bool:
        if self._cid is None:
            return False
        return self.send(self._cid, msg_id, body)

    def disconnect(self) -> None:
        if self._cid is not None:
            self._lib.nfnet_close_conn(self._h, self._cid)
            self._cid = None
            self.connected = False
