"""Minimal non-blocking HTTP server, pumped from the main loop.

Reference equivalent: NFCHttpNet — an evhttp server the Master role uses
to expose cluster status (`NFComm/NFNet/NFCHttpNet.{h,cpp}`, pumped from
`Execute` `:38-45`).  Like everything else in the stack it is poll-driven:
``execute()`` accepts, reads, dispatches and writes without blocking, so
it composes with the 1 ms main loop.

Only what the monitor needs is implemented: GET routing by path with
string/bytes/JSON responses.  Handlers run synchronously on the main
thread (the reference dispatches on its event loop the same way).
"""

from __future__ import annotations

import json
import selectors
import socket
from typing import Callable, Dict, Optional, Tuple, Union

Response = Union[str, bytes, dict, list, Tuple[int, str, bytes]]
Handler = Callable[[str, Dict[str, str]], Response]

_MAX_HEADER = 64 * 1024


class _HttpConn:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.inbuf = b""
        self.outbuf = b""
        self.done_reading = False


class HttpServer:
    """GET-only HTTP endpoint (the Master monitor API)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._sel = selectors.DefaultSelector()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self._sel.register(self._listener, selectors.EVENT_READ, None)
        self._conns: Dict[socket.socket, _HttpConn] = {}
        self._routes: Dict[str, Handler] = {}
        self._fallback: Optional[Handler] = None

    # ------------------------------------------------------------ routes
    def route(self, path: str, fn: Handler) -> None:
        self._routes[path] = fn

    def route_default(self, fn: Handler) -> None:
        self._fallback = fn

    # ------------------------------------------------------------ pump
    def execute(self) -> None:
        for key, mask in self._sel.select(timeout=0):
            if key.data is None:
                self._accept()
            else:
                self._pump(key.data, mask)

    def _accept(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            conn = _HttpConn(sock)
            self._conns[sock] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _pump(self, conn: _HttpConn, mask: int) -> None:
        if mask & selectors.EVENT_READ and not conn.done_reading:
            try:
                chunk = conn.sock.recv(8192)
            except (BlockingIOError, InterruptedError):
                chunk = None
            except OSError:
                self._drop(conn)
                return
            if chunk == b"":
                self._drop(conn)
                return
            if chunk:
                conn.inbuf += chunk
                if len(conn.inbuf) > _MAX_HEADER:
                    self._drop(conn)
                    return
                if b"\r\n\r\n" in conn.inbuf:
                    conn.done_reading = True
                    conn.outbuf = self._respond(conn.inbuf)
                    self._sel.modify(conn.sock, selectors.EVENT_WRITE, conn)
        if mask & selectors.EVENT_WRITE and conn.outbuf:
            try:
                n = conn.sock.send(conn.outbuf)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop(conn)
                return
            conn.outbuf = conn.outbuf[n:]
            if not conn.outbuf:
                self._drop(conn)  # HTTP/1.0 close-after-response

    def _drop(self, conn: _HttpConn) -> None:
        self._sel.unregister(conn.sock)
        self._conns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------ dispatch
    def _respond(self, raw: bytes) -> bytes:
        try:
            request_line = raw.split(b"\r\n", 1)[0].decode("latin-1")
            method, target, _ = request_line.split(" ", 2)
        except ValueError:
            return _http(400, "text/plain", b"bad request")
        if method != "GET":
            return _http(405, "text/plain", b"method not allowed")
        path, _, query = target.partition("?")
        params: Dict[str, str] = {}
        for part in query.split("&"):
            if "=" in part:
                k, _, v = part.partition("=")
                params[k] = v
        fn = self._routes.get(path) or self._fallback
        if fn is None:
            return _http(404, "text/plain", b"not found")
        try:
            result = fn(path, params)
        except Exception as e:  # handler bug must not kill the server
            return _http(500, "text/plain", f"error: {e}".encode())
        if isinstance(result, tuple):
            status, ctype, body = result
            return _http(status, ctype, body)
        if isinstance(result, (dict, list)):
            return _http(200, "application/json", json.dumps(result).encode())
        if isinstance(result, str):
            result = result.encode("utf-8")
        return _http(200, "text/html", result)

    def close(self) -> None:
        for conn in list(self._conns.values()):
            self._drop(conn)
        self._sel.unregister(self._listener)
        self._listener.close()
        self._sel.close()


_STATUS = {200: "OK", 400: "Bad Request", 404: "Not Found",
           405: "Method Not Allowed", 500: "Internal Server Error"}


def _http(status: int, ctype: str, body: bytes) -> bytes:
    head = (
        f"HTTP/1.0 {status} {_STATUS.get(status, 'Unknown')}\r\n"
        f"Content-Type: {ctype}\r\n"
        f"Content-Length: {len(body)}\r\n"
        # a read-only status API; allow the web-monitor page to poll it
        # when opened from disk or another host
        "Access-Control-Allow-Origin: *\r\n"
        "Connection: close\r\n\r\n"
    )
    return head.encode("latin-1") + body
