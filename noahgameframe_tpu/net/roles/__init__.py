"""The five server roles (SURVEY §2.8) + the localhost cluster harness."""

from .base import RoleConfig, ServerRole, load_server_xml  # noqa: F401
from .cluster import LocalCluster  # noqa: F401
from .game import GameRole  # noqa: F401
from .login import LoginRole  # noqa: F401
from .master import MasterRole  # noqa: F401
from .proxy import ProxyRole  # noqa: F401
from .world import WorldRole  # noqa: F401
