"""LocalCluster: the five-role topology assembled in one process.

The reference's de-facto integration test is "start all five servers on
localhost and watch the master dashboard go green"
(`_Out/Tester/rund_*.sh`, SURVEY §4).  LocalCluster is that bring-up as a
library call: every role on 127.0.0.1 ephemeral ports, all pumped from one
loop — which also makes it the single-process simulation mode for tests
and bots.  For a real multi-process deployment run one role per process
via ``scripts/run_role.py`` with a shared Server.xml.
"""

from __future__ import annotations

import time as _time
from typing import Callable, List, Optional

from ...game.world import GameWorld
from ..defines import ServerType
from .base import RoleConfig
from .game import GameRole
from .login import LoginRole
from .master import MasterRole
from .proxy import ProxyRole
from .world import WorldRole


class LocalCluster:
    """Master + Login + World + Proxy + Game on localhost, one pump."""

    def __init__(
        self,
        backend: str = "auto",
        http_port: Optional[int] = None,
        game_world: Optional[GameWorld] = None,
        n_games: int = 1,
        keepalive_seconds: float = 0.2,
    ) -> None:
        host = "127.0.0.1"
        self.master = MasterRole(
            RoleConfig(1, int(ServerType.MASTER), "Master1", host, 0),
            backend=backend,
            http_port=http_port,
        )
        master_t = [self.master.config]
        self.world = WorldRole(
            RoleConfig(7, int(ServerType.WORLD), "World1", host, 0,
                       targets=master_t),
            backend=backend,
        )
        world_t = [self.world.config]
        self.login = LoginRole(
            RoleConfig(4, int(ServerType.LOGIN), "Login1", host, 0,
                       targets=master_t),
            backend=backend,
        )
        self.proxy = ProxyRole(
            RoleConfig(5, int(ServerType.PROXY), "Proxy1", host, 0,
                       targets=world_t),
            backend=backend,
        )
        self.games: List[GameRole] = []
        for i in range(n_games):
            self.games.append(
                GameRole(
                    RoleConfig(6 + i * 10, int(ServerType.GAME),
                               f"Game{i + 1}", host, 0, targets=world_t),
                    backend=backend,
                    world=game_world if i == 0 else None,
                )
            )
        self.game = self.games[0]
        self.roles = [self.master, self.world, self.login, self.proxy, *self.games]
        # speed up the registration/report cadence for in-process runs
        for role in self.roles:
            for pool in role.clients.values():
                pool.keepalive_seconds = keepalive_seconds

    # ------------------------------------------------------------- pump
    def execute(self) -> None:
        for role in self.roles:
            role.execute()

    def pump(self, extra: Callable[[], None] = None, rounds: int = 50,
             sleep: float = 0.002) -> None:
        """Drive everything for `rounds` iterations (plus an optional
        client-side pump)."""
        for _ in range(rounds):
            self.execute()
            if extra is not None:
                extra()
            _time.sleep(sleep)

    def pump_until(self, cond: Callable[[], bool],
                   extra: Callable[[], None] = None,
                   timeout: float = 10.0, sleep: float = 0.002) -> bool:
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            self.execute()
            if extra is not None:
                extra()
            if cond():
                return True
            _time.sleep(sleep)
        return False

    def wired(self) -> bool:
        """True when the full topology is registered: world+login at
        master, proxy+game at world, proxy has a live game link."""
        reg = self.master.registry
        return (
            bool(reg.get(int(ServerType.WORLD)))
            and bool(reg.get(int(ServerType.LOGIN)))
            and len(self.world.proxies) > 0
            and len(self.world.games) >= len(self.games)
            and len(self.proxy.games.connected_servers()) >= len(self.games)
        )

    def start(self, timeout: float = 15.0) -> "LocalCluster":
        if not self.pump_until(self.wired, timeout=timeout):
            raise RuntimeError("cluster failed to wire up")
        return self

    def shut(self) -> None:
        for role in self.roles:
            role.shut()
