"""LocalCluster: the five-role topology assembled in one process.

The reference's de-facto integration test is "start all five servers on
localhost and watch the master dashboard go green"
(`_Out/Tester/rund_*.sh`, SURVEY §4).  LocalCluster is that bring-up as a
library call: every role on 127.0.0.1 ephemeral ports, all pumped from one
loop — which also makes it the single-process simulation mode for tests
and bots.  For a real multi-process deployment run one role per process
via ``scripts/run_role.py`` with a shared Server.xml.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Dict, List, Optional

from ...game.world import GameWorld
from ..chaos import ChaosDirector, FaultPlan
from ..defines import ServerType
from ..retry import RetryPolicy
from .base import RoleConfig
from .game import GameRole
from .login import LoginRole
from .master import MasterRole
from .proxy import ProxyRole
from .world import WorldRole


class LocalCluster:
    """Master + Login + World + Proxy + Game on localhost, one pump."""

    def __init__(
        self,
        backend: str = "auto",
        http_port: Optional[int] = None,
        game_world: Optional[GameWorld] = None,
        n_games: int = 1,
        keepalive_seconds: float = 0.2,
        lease_suspect_seconds: Optional[float] = None,
        lease_down_seconds: Optional[float] = None,
        game_kwargs: Optional[dict] = None,
        game_kwargs_by_name: Optional[Dict[str, dict]] = None,
        world_kwargs: Optional[dict] = None,
    ) -> None:
        host = "127.0.0.1"
        self._backend = backend
        self._host = host
        self.keepalive_seconds = keepalive_seconds
        # extra GameRole kwargs (checkpoint_dir, checkpoint_seconds, …)
        # remembered so revive_role() rebuilds an identical role
        self._game_kwargs = dict(game_kwargs or {})
        # per-role overrides keyed by config name ("Game1", "Game2"):
        # failover drills need each game on its OWN wal/checkpoint dirs —
        # a shared dict would have every game scribbling over one WAL
        self._game_kwargs_by_name = {
            k: dict(v) for k, v in (game_kwargs_by_name or {}).items()
        }
        # killed-role configs by config name, revivable later
        self._killed: Dict[str, RoleConfig] = {}
        self.chaos: Optional[ChaosDirector] = None
        master_kw = {}
        world_kw = {}
        if lease_suspect_seconds is not None:
            master_kw["lease_suspect_seconds"] = lease_suspect_seconds
        if lease_down_seconds is not None:
            master_kw["lease_down_seconds"] = lease_down_seconds
            world_kw["lease_down_seconds"] = lease_down_seconds
        # caller-supplied WorldRole kwargs (recover_store for the
        # failover driver's store fallback, failover=False to opt out…)
        world_kw.update(world_kwargs or {})
        self.master = MasterRole(
            RoleConfig(1, int(ServerType.MASTER), "Master1", host, 0),
            backend=backend,
            http_port=http_port,
            **master_kw,
        )
        master_t = [self.master.config]
        self.world = WorldRole(
            RoleConfig(7, int(ServerType.WORLD), "World1", host, 0,
                       targets=master_t),
            backend=backend,
            **world_kw,
        )
        world_t = [self.world.config]
        self._world_t = world_t
        self.login = LoginRole(
            RoleConfig(4, int(ServerType.LOGIN), "Login1", host, 0,
                       targets=master_t),
            backend=backend,
        )
        self.proxy = ProxyRole(
            RoleConfig(5, int(ServerType.PROXY), "Proxy1", host, 0,
                       targets=world_t),
            backend=backend,
        )
        self.games: List[GameRole] = []
        for i in range(n_games):
            name = f"Game{i + 1}"
            kw = self._merged_game_kwargs(name)
            # per-game worlds: game_kwargs_by_name may carry a "world"
            # for ANY game (a game-day survivor needs capacity for the
            # whole surge); the legacy game_world argument still wins
            # for Game1
            world = kw.pop("world", None)
            if i == 0 and game_world is not None:
                world = game_world
            self.games.append(
                GameRole(
                    RoleConfig(6 + i * 10, int(ServerType.GAME),
                               name, host, 0, targets=world_t),
                    backend=backend,
                    world=world,
                    **kw,
                )
            )
        self.game = self.games[0]
        self.roles = [self.master, self.world, self.login, self.proxy, *self.games]
        # speed up the registration/report cadence for in-process runs
        for role in self.roles:
            self._speed_role(role)

    def _merged_game_kwargs(self, name: str) -> dict:
        kw = dict(self._game_kwargs)
        kw.update(self._game_kwargs_by_name.get(name, {}))
        return kw

    def _speed_role(self, role) -> None:
        """Scale every outbound pool's cadence to the cluster keepalive:
        reports at `keepalive_seconds`, re-dials backing off from it (the
        library defaults are sized for real deployments — a test cluster
        on a 10 s reconnect timer would make every fault take minutes)."""
        ka = self.keepalive_seconds
        for pool in role.clients.values():
            pool.keepalive_seconds = ka
            pool.retry = RetryPolicy(base=ka, cap=max(1.0, 5 * ka))
            pool.reconnect_seconds = max(1.0, 5 * ka)  # CONNECTING timeout

    # ------------------------------------------------------------- pump
    def execute(self) -> None:
        for role in self.roles:
            role.execute()

    def pump(self, extra: Callable[[], None] = None, rounds: int = 50,
             sleep: float = 0.002) -> None:
        """Drive everything for `rounds` iterations (plus an optional
        client-side pump)."""
        for _ in range(rounds):
            self.execute()
            if extra is not None:
                extra()
            _time.sleep(sleep)

    def pump_until(self, cond: Callable[[], bool],
                   extra: Callable[[], None] = None,
                   timeout: float = 10.0, sleep: float = 0.002) -> bool:
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            self.execute()
            if extra is not None:
                extra()
            if cond():
                return True
            _time.sleep(sleep)
        return False

    def role_by_name(self, name: str):
        """Live role by config name ("Game1", "Proxy1", …); raises
        StopIteration-free KeyError semantics via ValueError."""
        for r in self.roles:
            if r.config.name == name:
                return r
        raise ValueError(f"no live role named {name!r}")

    def wired(self) -> bool:
        """True when the full topology is registered: world+login at
        master, proxy+game at world, proxy has a live game link."""
        reg = self.master.registry
        return (
            bool(reg.get(int(ServerType.WORLD)))
            and bool(reg.get(int(ServerType.LOGIN)))
            and len(self.world.proxies) > 0
            and len(self.world.games) >= len(self.games)
            and len(self.proxy.games.connected_servers()) >= len(self.games)
        )

    def start(self, timeout: float = 15.0) -> "LocalCluster":
        if not self.pump_until(self.wired, timeout=timeout):
            raise RuntimeError("cluster failed to wire up")
        return self

    def shut(self) -> None:
        for role in self.roles:
            role.shut()

    # ----------------------------------------------------------- chaos
    @staticmethod
    def _role_name(role) -> str:
        return (type(role).__name__.replace("Role", "").lower()
                + str(role.config.server_id))

    def apply_chaos(self, plan: FaultPlan) -> ChaosDirector:
        """Interpose a :class:`FaultyTransport` on every outbound link of
        every role (link names like ``proxy5.games->6``; FaultPlan
        patterns substring-match them).  Faults survive re-dials: the
        director owns the per-link counters and each fresh transport the
        pool creates is wrapped again."""
        self.chaos = ChaosDirector(plan)
        # surface the plan: /json shows seed + per-link budgets so any
        # chaos run is re-derivable for offline replay
        self.master.chaos_status = self.chaos.status
        for role in self.roles:
            self._chaos_role(role)
        return self.chaos

    def _chaos_role(self, role) -> None:
        if self.chaos is None:
            return
        rname = self._role_name(role)
        director = self.chaos

        def make_wrapper(key: str):
            def wrap(client, sd):
                return director.wrap(
                    client, f"{rname}.{key}->{sd.server_id}"
                )
            return wrap

        for key, pool in role.clients.items():
            pool.transport_wrapper = make_wrapper(key)
            # wrap links that are already live (apply_chaos after start)
            for sd in pool.servers.values():
                if sd.client is not None:
                    sd.client = director.wrap(
                        sd.client, f"{rname}.{key}->{sd.server_id}"
                    )
        # store links: the write-behind flusher's backend gets the same
        # treatment as a transport — the director owns the op counts and
        # rng, so a revived role's rebuilt pipeline CONTINUES the fault
        # schedule instead of restarting it
        pipeline = getattr(role, "persist", None)
        if pipeline is not None:
            pipeline.backend = director.wrap_store(
                pipeline.backend, f"{rname}.store"
            )
        role.telemetry.add_chaos_source(director, prefix=f"{rname}.")
        # flight recorder: a recording game role journals the fault-plan
        # seed + link budgets as an epoch note (RNG seeds of everything
        # that can reorder its inputs belong in the journal)
        note = getattr(role, "journal_note", None)
        if note is not None:
            plan = director.plan
            note(
                kind="chaos",
                seed=int(plan.seed),
                links={p: dataclasses.asdict(f)
                       for p, f in plan.links.items()},
                stores={p: dataclasses.asdict(f)
                        for p, f in plan.stores.items()},
            )

    # ----------------------------------------------------------- drills
    def attach_drill(self, runner) -> None:
        """Surface a DrillRunner on the master's /json (``drill`` block:
        campaign clock, fired/remaining steps, invariant breaches) —
        the drill-side twin of what apply_chaos does for the fault
        plan.  Called by :class:`drill.runner.DrillRunner` itself."""
        self.master.drill_status = runner.status
        # a recording game role journals the campaign identity, so a
        # drilled run's journal pins the schedule that shaped it
        for role in self.roles:
            note = getattr(role, "journal_note", None)
            if note is not None:
                note(kind="drill", campaign=runner.campaign.name,
                     seed=int(runner.campaign.seed),
                     steps=len(runner.campaign.steps))

    # ----------------------------------------------------- kill / revive
    def kill_role(self, role, hard: bool = False) -> RoleConfig:
        """Kill one role: sockets dropped, removed from the pump.
        Accepts the role object or its config name.  Returns the config
        (revive_role uses the remembered name).

        ``hard=True`` uses the role's crash path (:meth:`GameRole.kill`)
        — no session saves, no persist drain, the WAL keeps whatever
        reached it.  That is the failover-drill semantics: the world
        must recover from durable state alone.  Default stays the
        graceful :meth:`shut`."""
        if isinstance(role, str):
            role = next(r for r in self.roles if r.config.name == role)
        if hard and hasattr(role, "kill"):
            role.kill()
        else:
            role.shut()
        self.roles.remove(role)
        if role in self.games:
            self.games.remove(role)
        if self.game is role:
            self.game = self.games[0] if self.games else None
        self._killed[role.config.name] = role.config
        return role.config

    def revive_role(self, name: str, world: Optional[GameWorld] = None,
                    resume: bool = True) -> GameRole:
        """Bring a killed game role back on a fresh ephemeral port,
        resuming from its checkpoint by default.  Re-registration with
        World (and Master, via the relay) rides the normal on-connect
        path; the proxy learns the new endpoint from World's next game
        list push."""
        cfg = self._killed.pop(name)
        if cfg.server_type != int(ServerType.GAME):
            raise NotImplementedError(
                f"revive_role supports game roles only, not {name}"
            )
        kwargs = self._merged_game_kwargs(cfg.name)
        kwargs["resume"] = resume
        # an explicit world (fresh substrate for the checkpoint load)
        # wins over a per-game world remembered in the kwargs map
        kw_world = kwargs.pop("world", None)
        if world is None:
            world = kw_world
        role = GameRole(
            RoleConfig(cfg.server_id, cfg.server_type, cfg.name,
                       self._host, 0, targets=self._world_t),
            backend=self._backend,
            world=world,
            **kwargs,
        )
        self._speed_role(role)
        self._chaos_role(role)
        self.games.append(role)
        if self.game is None:
            self.game = role
        self.roles.append(role)
        return role
