"""Login role: account auth, world list, select-world handshake.

Reference: NFLoginLogicPlugin / NFLoginNet_ServerPlugin /
NFLoginNet_ClientPlugin — client-facing auth (`OnLoginProcess`
`NFCLoginNet_ServerModule.cpp:128-167`, permissive by default), world-list
view fed by Master, and the select-world relay toward Master
(`OnSelectWorldProcess` `:169-196`).  The auth decision is a pluggable
callback so deployments can attach a real account backend.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..defines import EventCode, MsgID, ServerType
from ..transport import EV_DISCONNECTED
from ..wire import (
    AckConnectWorldResult,
    AckEventResult,
    AckServerList,
    Ident,
    ReqAccountLogin,
    ReqConnectWorld,
    ServerInfo,
    ServerInfoReport,
    unwrap,
    wrap,
)
from .base import RoleConfig, ServerRole, decode_reports

# (account, password) -> EventCode
AuthFn = Callable[[str, str], int]


def permissive_auth(_account: str, _password: str) -> int:
    """The reference default: any non-empty account logs in."""
    return int(EventCode.ACCOUNT_SUCCESS) if _account else int(
        EventCode.ACCOUNTPWD_INVALID
    )


class LoginRole(ServerRole):
    server_type = int(ServerType.LOGIN)

    def __init__(self, config: RoleConfig, backend: str = "auto",
                 auth: AuthFn = permissive_auth) -> None:
        self.auth = auth
        self.worlds: List[ServerInfoReport] = []
        # account -> client conn awaiting a world ack
        self._account_conn: Dict[str, int] = {}
        super().__init__(config, backend=backend)
        self.master = self.add_upstream(
            "master",
            [t for t in config.targets if t.server_type == int(ServerType.MASTER)],
            register_msg=MsgID.LTM_LOGIN_REGISTERED,
            refresh_msg=MsgID.LTM_LOGIN_REFRESH,
        )
        self.master.on(MsgID.STS_NET_INFO, self._on_world_list)
        self.master.on(MsgID.ACK_CONNECT_WORLD, self._on_ack_connect_world)

    def _install(self) -> None:
        s = self.server
        s.on(MsgID.REQ_LOGIN, self._on_login)
        s.on(MsgID.REQ_WORLD_LIST, self._on_world_list_req)
        s.on(MsgID.REQ_CONNECT_WORLD, self._on_connect_world)
        s.on_socket_event(self._on_socket)

    def _on_socket(self, conn_id: int, kind: int) -> None:
        if kind == EV_DISCONNECTED:
            self._account_conn = {
                a: c for a, c in self._account_conn.items() if c != conn_id
            }

    # ------------------------------------------------------ client side
    def _on_login(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        _, req = unwrap(body, ReqAccountLogin)
        account = req.account.decode("utf-8", "replace")
        code = self.auth(account, req.password.decode("utf-8", "replace"))
        tags = self.server.conn_tags.setdefault(conn_id, {})
        if code == int(EventCode.ACCOUNT_SUCCESS):
            tags["account"] = account
        ack = AckEventResult(event_code=code, event_object=Ident())
        self.server.send_pb(conn_id, int(MsgID.ACK_LOGIN), ack)

    def _on_world_list_req(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        infos = [
            ServerInfo(
                server_id=r.server_id,
                name=r.server_name,
                wait_count=r.server_cur_count,
                status=int(r.server_state),
            )
            for r in self.worlds
        ]
        ack = AckServerList(type=int(ServerType.WORLD), info=infos)
        self.server.send_pb(conn_id, int(MsgID.ACK_WORLD_LIST), ack)

    def _on_connect_world(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """Client picked a world → ask Master, remember who asked
        (routing key = account, `NFCLoginNet_ServerModule.cpp:169-196`)."""
        tags = self.server.conn_tags.get(conn_id, {})
        account = tags.get("account")
        if not account:
            return  # not authed; the reference silently drops too
        _, req = unwrap(body, ReqConnectWorld)
        self._account_conn[account] = conn_id
        fwd = ReqConnectWorld(
            world_id=req.world_id,
            account=account.encode(),
            sender=Ident(svrid=self.config.server_id, index=conn_id),
            login_id=self.config.server_id,
        )
        self.master.send_to_all(int(MsgID.REQ_CONNECT_WORLD), wrap(fwd))

    # ------------------------------------------------------ master side
    def _on_world_list(self, _sid: int, _msg_id: int, body: bytes) -> None:
        self.worlds = decode_reports(body)

    def _on_ack_connect_world(self, _sid: int, _msg_id: int, body: bytes) -> None:
        _, ack = unwrap(body, AckConnectWorldResult)
        account = ack.account.decode("utf-8", "replace")
        conn_id = self._account_conn.pop(account, None)
        if conn_id is not None:
            self.server.send_pb(conn_id, int(MsgID.ACK_CONNECT_WORLD), ack)
