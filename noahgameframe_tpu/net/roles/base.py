"""Server-role scaffolding: config, registration, report plumbing.

The reference deploys five process roles (Master/Login/World/Proxy/Game),
each a `NFPluginLoader` instance whose net plugins read `Server.xml`
(`_Out/NFDataCfg/Ini/NPC/Server.xml:3-8` — attributes ID/Type/IP/Port/
MaxOnline/CpuCount/Name) and then keep the cluster wired by three
mechanisms (SURVEY §3.5):

- register on connect: client module sends `*_REGISTERED` with a
  ServerInfoReportList describing itself;
- refresh every 10 s: `*_REFRESH` + `STS_SERVER_REPORT` keepalives
  (`NFINetClientModule.hpp:395-405`);
- upstream fan-in: World relays game/proxy reports to Master
  (`NFCWorldNet_ServerModule.cpp:36`), Master aggregates + serves JSON.

`ServerRole` is the shared shell: one listening `NetServerModule`,
any number of upstream `NetClientModule`s, a pump, and report helpers.
Roles are pump-driven and single-threaded like the reference main loop.
"""

from __future__ import annotations

import dataclasses
import time as _time
import xml.etree.ElementTree as ET
from pathlib import Path
from typing import Dict, List, Optional

from ..defines import MsgID, ServerState, ServerType
from ..module import NetClientModule, NetServerModule
from ..wire import (
    Ident,
    MsgBase,
    ServerInfoExt,
    ServerInfoReport,
    ServerInfoReportList,
    unwrap,
    wrap,
)


@dataclasses.dataclass
class RoleConfig:
    """One server instance's identity + endpoint (Server.xml row)."""

    server_id: int
    server_type: int
    name: str = ""
    ip: str = "127.0.0.1"
    port: int = 0
    max_online: int = 5000
    cpu_count: int = 1
    # upstream endpoints this role dials out to (master for login/world,
    # world for proxy/game); filled from the cluster's Server.xml
    targets: List["RoleConfig"] = dataclasses.field(default_factory=list)


def load_server_xml(path: Path) -> List[RoleConfig]:
    """Parse a reference-format Server.xml: <XML><Server ID=.. Type=..
    IP=.. Port=.. MaxOnline=.. CpuCount=.. Name=../>...</XML>.

    Type may be a ServerType name ("GAME") or its integer value."""
    root = ET.parse(str(path)).getroot()
    out: List[RoleConfig] = []
    for node in root.findall("Server"):
        t = node.get("Type", "0")
        try:
            server_type = int(t)
        except ValueError:
            server_type = int(ServerType[t.upper()])
        out.append(
            RoleConfig(
                server_id=int(node.get("ID", "0")),
                server_type=server_type,
                name=node.get("Name", ""),
                ip=node.get("IP", "127.0.0.1"),
                port=int(node.get("Port", "0")),
                max_online=int(node.get("MaxOnline", "5000")),
                cpu_count=int(node.get("CpuCount", "1")),
            )
        )
    return out


class ServerRole:
    """Base for the five roles: listening endpoint + upstream links."""

    server_type: int = int(ServerType.NONE)

    def __init__(self, config: RoleConfig, backend: str = "auto") -> None:
        self.config = config
        self.server = NetServerModule(config.ip, config.port, backend=backend)
        config.port = self.server.port  # resolve ephemeral port
        self.backend = backend
        self.clients: Dict[str, NetClientModule] = {}
        self.state = int(ServerState.NORMAL)
        # telemetry: one registry per role.  A role that owns a world
        # (GameRole sets self.game_world before super().__init__) adopts
        # the world's TelemetryModule so /metrics includes the kernel's
        # counter bank alongside role/net metrics — ONE registry, never
        # two disagreeing ones.
        from ...telemetry import TelemetryModule

        gw = getattr(self, "game_world", None)
        tel = getattr(gw, "telemetry", None)
        self.telemetry: TelemetryModule = (
            tel if tel is not None else TelemetryModule()
        )
        # frame-latency window; run_role's loop (and any operator pump)
        # wraps role.execute in metrics.frame() — percentiles ride the
        # 10 s report's ext map up to the master dashboard AND the
        # nf_frame_seconds histogram on /metrics (same samples)
        self.metrics = self.telemetry.tick
        self.telemetry.attach_role(self)
        self.telemetry.attach_kernel(getattr(self, "kernel", None))
        self._metrics_http = None
        self._install()

    # hook for subclasses to register handlers
    def _install(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    # ---------------------------------------------------------- helpers
    def add_upstream(self, key: str, targets: List[RoleConfig],
                     register_msg: Optional[int] = None,
                     refresh_msg: Optional[int] = None) -> NetClientModule:
        """Create a client pool dialing `targets`; auto-send registration
        on connect and refresh on the 10 s keepalive."""
        pool = NetClientModule(backend=self.backend)
        for t in targets:
            pool.add_server(t.server_id, t.server_type, t.ip, t.port, t.name)
        if register_msg is not None:
            pool.on_connected(
                lambda sid: pool.send_by_server_id(
                    sid, int(register_msg), wrap(self.report_list())
                )
            )
        if refresh_msg is not None:
            pool.on_keepalive(
                lambda: pool.send_to_all(int(refresh_msg), wrap(self.report_list()))
            )
        self.clients[key] = pool
        self.telemetry.add_net_source(key, pool.counters)
        self.telemetry.add_pool_source(key, pool)
        return pool

    def serve_metrics(self, port: int = 0,
                      host: Optional[str] = None):
        """Expose /metrics on a dedicated HttpServer (for roles without a
        status server; Master mounts onto its existing /json server
        instead).  Pumped from execute(); returns the server (inspect
        ``.port`` when asking for an ephemeral one)."""
        if self._metrics_http is None:
            from ..http import HttpServer

            self._metrics_http = HttpServer(
                host if host is not None else self.config.ip, port
            )
            self.telemetry.mount(self._metrics_http)
        return self._metrics_http

    def cur_count(self) -> int:
        """Load metric reported upstream; roles override (players online,
        connections, …)."""
        return self.server.num_connections

    def report(self) -> ServerInfoReport:
        c = self.config
        r = ServerInfoReport(
            server_id=c.server_id,
            server_name=c.name.encode() if isinstance(c.name, str) else c.name,
            server_ip=c.ip.encode(),
            server_port=c.port,
            server_max_online=c.max_online,
            server_cur_count=self.cur_count(),
            server_state=self.state,
            server_type=self.server_type,
        )
        ext = ServerInfoExt()
        # clock-sync echo (ISSUE 7): the sender's monotonic stamp lets
        # the master estimate per-role clock offsets NTP-style (sliding
        # min of recv - sent over the heartbeat stream)
        ext.key.append(b"mono_ns")
        ext.value.append(str(_time.perf_counter_ns()).encode())
        if self.metrics.frames:
            p = self.metrics.percentiles()
            for k in ("p50_ms", "p95_ms", "p99_ms"):
                ext.key.append(f"frame_{k}".encode())
                ext.value.append(f"{p[k]:.3f}".encode())
        r.server_info_list_ext = ext
        return r

    def report_list(self) -> ServerInfoReportList:
        return ServerInfoReportList(server_list=[self.report()])

    def ident(self) -> Ident:
        return Ident(svrid=self.config.server_id, index=0)

    # ---------------------------------------------------------- pump
    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        self.server.execute()
        for pool in self.clients.values():
            pool.execute(now)
        if self._metrics_http is not None:
            self._metrics_http.execute()

    def run(self, seconds: float, sleep: float = 0.001) -> None:
        end = _time.monotonic() + seconds
        while _time.monotonic() < end:
            self.execute()
            _time.sleep(sleep)

    def shut(self) -> None:
        self.server.shut()
        for pool in self.clients.values():
            pool.shut()
        if self._metrics_http is not None:
            self._metrics_http.close()
            self._metrics_http = None


def decode_reports(body: bytes) -> List[ServerInfoReport]:
    """Unwrap a MsgBase-enveloped ServerInfoReportList."""
    _, payload = unwrap(body, ServerInfoReportList)
    return list(payload.server_list)


def report_to_dict(r: ServerInfoReport) -> dict:
    d = {
        "server_id": r.server_id,
        "name": _s(r.server_name),
        "ip": _s(r.server_ip),
        "port": r.server_port,
        "max_online": r.server_max_online,
        "cur_count": r.server_cur_count,
        "state": int(r.server_state),
        "type": int(r.server_type),
    }
    ext = r.server_info_list_ext
    if ext is not None and ext.key:
        d["ext"] = {_s(k): _s(v) for k, v in zip(ext.key, ext.value)}
    return d


def _s(v) -> str:
    return v.decode("utf-8", "replace") if isinstance(v, (bytes, bytearray)) else str(v)
