"""Master role: cluster registry, rendezvous relay, status JSON + HTTP.

Reference: NFMasterServerPlugin / NFMasterNet_ServerPlugin /
NFMasterNet_HttpServerPlugin — handlers for world/login register+refresh
and server reports upsert per-type `ServerData` maps
(`NFCMasterNet_ServerModule.cpp:239-249,441-494`); the select-world
handshake is relayed Login→Master→World→Master→Login (`:187-203`);
`GetServersStatus` renders whole-cluster JSON served over evhttp
(`:496-640`, `NFCMasterNet_HttpJsonModule.cpp:22-82`).
"""

from __future__ import annotations

import dataclasses
import html
import json as _json
import time as _time
from typing import Dict, Optional

from ...telemetry.pipeline import ClockSync

from ..defines import (
    LEASE_DOWN_SECONDS,
    LEASE_SUSPECT_SECONDS,
    MsgID,
    ServerState,
    ServerType,
)
from ..http import HttpServer
from ..module import EV_DISCONNECTED
from ..transport import EV_CONNECTED
from ..wire import (
    AckConnectWorldResult,
    ReqConnectWorld,
    ServerInfoReport,
    ServerInfoReportList,
    unwrap,
    wrap,
)
from .base import RoleConfig, ServerRole, decode_reports, report_to_dict


# heartbeat-lease states: every refresh/report renews the lease; a
# server that stops reporting ages UP -> SUSPECT -> DOWN (the reference
# lists dead entries forever — NFCMasterNet_ServerModule never expires)
LEASE_UP, LEASE_SUSPECT, LEASE_DOWN = "UP", "SUSPECT", "DOWN"


@dataclasses.dataclass
class _Registered:
    report: ServerInfoReport
    conn_id: int = -1  # -1: known only via relayed report (no direct link)
    last_seen: float = 0.0
    lease: str = LEASE_UP


class MasterRole(ServerRole):
    """The cluster brain: every other role registers here (directly or via
    World relay) and the web monitor reads the aggregate."""

    server_type = int(ServerType.MASTER)

    def __init__(self, config: RoleConfig, backend: str = "auto",
                 http_port: Optional[int] = None,
                 lease_suspect_seconds: float = LEASE_SUSPECT_SECONDS,
                 lease_down_seconds: float = LEASE_DOWN_SECONDS) -> None:
        # per-type registries: type -> server_id -> _Registered
        self.registry: Dict[int, Dict[int, _Registered]] = {}
        self.http: Optional[HttpServer] = None
        # chaos visibility: when a ChaosDirector is active the harness
        # points this at director.status so /json shows the fault-plan
        # seed + per-link budgets (replay can re-derive the chaos run)
        self.chaos_status = None  # Optional[Callable[[], dict]]
        # drill visibility (ISSUE 11): when a DrillRunner is attached the
        # harness points this at runner.status so /json shows the live
        # campaign clock, fired/remaining steps, and invariant breaches
        self.drill_status = None  # Optional[Callable[[], dict]]
        self.lease_suspect_seconds = lease_suspect_seconds
        self.lease_down_seconds = lease_down_seconds
        # per-role monotonic clock offsets estimated from the mono_ns
        # stamp every heartbeat report carries (frame observatory):
        # offset ≈ sliding min of (master recv − sender stamp)
        self.clock = ClockSync()
        super().__init__(config, backend=backend)
        reg = self.telemetry.registry
        self._lease_expirations = reg.counter(
            "nf_lease_expirations_total",
            "leases aged past the DOWN threshold", ("role",),
        )
        self._lease_recoveries = reg.counter(
            "nf_lease_recoveries_total",
            "DOWN servers seen reporting again", ("role",),
        )
        if http_port is not None:
            self.http = HttpServer(config.ip, http_port)
            self.http.route("/json", lambda _p, _q: self.servers_status())
            self.http.route("/pipeline", lambda _p, _q: self.pipeline_status())
            self.http.route("/", self._index_page)
            # Prometheus exposition rides the same status server
            self.telemetry.mount(self.http)
            # the cluster-aggregated costbook view shadows the mount's
            # per-role snapshot on the master (registered after mount so
            # the later route wins): operators want the fleet rollup here
            self.http.route("/costbook", lambda _p, _q: self.costbook_status())

    def _install(self) -> None:
        s = self.server
        for msg in (MsgID.MTL_WORLD_REGISTERED, MsgID.MTL_WORLD_REFRESH):
            s.on(msg, self._on_register(ServerType.WORLD))
        s.on(MsgID.MTL_WORLD_UNREGISTERED, self._on_unregister)
        for msg in (MsgID.LTM_LOGIN_REGISTERED, MsgID.LTM_LOGIN_REFRESH):
            s.on(msg, self._on_register(ServerType.LOGIN))
        s.on(MsgID.LTM_LOGIN_UNREGISTERED, self._on_unregister)
        s.on(MsgID.STS_SERVER_REPORT, self._on_report)
        s.on(MsgID.REQ_CONNECT_WORLD, self._on_req_connect_world)
        s.on(MsgID.ACK_CONNECT_WORLD, self._on_ack_connect_world)
        s.on_socket_event(self._on_socket)

    # ------------------------------------------------------ registration
    def _on_register(self, expect_type: ServerType):
        def handler(conn_id: int, _msg_id: int, body: bytes) -> None:
            for r in decode_reports(body):
                self._upsert(r, conn_id)
                self.server.conn_tags.setdefault(conn_id, {})["server_id"] = r.server_id
            if expect_type == ServerType.WORLD:
                self._push_world_list()
            elif expect_type == ServerType.LOGIN:
                self._send_world_list(conn_id)
        return handler

    def _on_unregister(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        for r in decode_reports(body):
            self.registry.get(int(r.server_type), {}).pop(r.server_id, None)
        self._push_world_list()

    def _on_report(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """Game/proxy reports relayed up by World (`OnServerReport`)."""
        for r in decode_reports(body):
            self._upsert(r, -1)

    def _upsert(self, r: ServerInfoReport, conn_id: int) -> None:
        by_id = self.registry.setdefault(int(r.server_type), {})
        prev = by_id.get(r.server_id)
        recovered = prev is not None and prev.lease == LEASE_DOWN
        by_id[r.server_id] = _Registered(r, conn_id, _time.monotonic())
        # clock-sync echo: every report carries the sender's monotonic
        # stamp; min-filter (recv - sent) into the per-role offset
        sent = self._ext_of(r).get("mono_ns")
        if sent:
            try:
                self.clock.update(
                    f"{self._type_name(int(r.server_type))}{r.server_id}",
                    int(sent), _time.perf_counter_ns(),
                )
            except ValueError:
                pass  # garbled stamp: skip the sample
        if recovered:
            # a DOWN server reporting again has recovered (restart or
            # healed partition): count it and restore routing
            self._lease_recoveries.inc(role=self._type_name(int(r.server_type)))
            if int(r.server_type) == int(ServerType.WORLD):
                self._push_world_list()

    @staticmethod
    def _ext_of(r: ServerInfoReport) -> Dict[str, str]:
        """The report's ext map as str→str (wire carries bytes)."""
        ext = r.server_info_list_ext
        if ext is None or not ext.key:
            return {}
        def s(v):
            return (v.decode("utf-8", "replace")
                    if isinstance(v, (bytes, bytearray)) else str(v))
        return {s(k): s(v) for k, v in zip(ext.key, ext.value)}

    @staticmethod
    def _type_name(stype: int) -> str:
        try:
            return ServerType(stype).name.lower()
        except ValueError:
            return str(stype)

    def _sweep_leases(self, now: float) -> None:
        """Age every lease; flips feed the counters, DOWN marks the
        report CRASH and drops the server from routed lists (worlds
        vanish from the login list; world does the same for games)."""
        for stype, by_id in self.registry.items():
            for reg in by_id.values():
                age = now - reg.last_seen
                if age >= self.lease_down_seconds:
                    state = LEASE_DOWN
                elif age >= self.lease_suspect_seconds:
                    state = LEASE_SUSPECT
                else:
                    state = LEASE_UP
                if state == reg.lease:
                    continue
                reg.lease = state
                if state == LEASE_DOWN:
                    reg.report.server_state = int(ServerState.CRASH)
                    self._lease_expirations.inc(role=self._type_name(stype))
                    if stype == int(ServerType.WORLD):
                        self._push_world_list()

    def _on_socket(self, conn_id: int, kind: int) -> None:
        if kind != EV_DISCONNECTED:
            return
        # mark any server registered over this link as crashed
        # (the reference flips EServerState on link loss)
        for by_id in self.registry.values():
            for reg in by_id.values():
                if reg.conn_id == conn_id:
                    reg.report.server_state = int(ServerState.CRASH)
                    reg.conn_id = -1

    # ------------------------------------------------ world list to logins
    def _world_reports(self) -> ServerInfoReportList:
        worlds = self.registry.get(int(ServerType.WORLD), {})
        # DOWN worlds are evicted from the routed list (SUSPECT still
        # routes: one late heartbeat must not unseat a healthy server)
        return ServerInfoReportList(
            server_list=[
                reg.report for reg in worlds.values()
                if reg.lease != LEASE_DOWN
            ]
        )

    def _send_world_list(self, conn_id: int) -> None:
        self.server.send_raw(
            conn_id, int(MsgID.STS_NET_INFO), wrap(self._world_reports())
        )

    def _push_world_list(self) -> None:
        for conn_id, tags in self.server.conn_tags.items():
            sid = tags.get("server_id")
            if sid is None:
                continue
            logins = self.registry.get(int(ServerType.LOGIN), {})
            if sid in logins and logins[sid].conn_id == conn_id:
                self._send_world_list(conn_id)

    # ------------------------------------------------ select-world relay
    def _conn_of(self, server_type: ServerType, server_id: int) -> int:
        reg = self.registry.get(int(server_type), {}).get(server_id)
        return reg.conn_id if reg is not None else -1

    def _on_req_connect_world(self, conn_id: int, msg_id: int, body: bytes) -> None:
        """Login asks for a world slot → relay to that world
        (`OnSelectWorldProcess` `NFCMasterNet_ServerModule.cpp:187-203`)."""
        _, req = unwrap(body, ReqConnectWorld)
        target = self._conn_of(ServerType.WORLD, req.world_id)
        if target >= 0:
            self.server.send_raw(target, msg_id, body)

    def _on_ack_connect_world(self, conn_id: int, msg_id: int, body: bytes) -> None:
        """World answers with proxy endpoint + key → relay to the asking
        login (`OnSelectWorldResultsProcess`)."""
        _, ack = unwrap(body, AckConnectWorldResult)
        target = self._conn_of(ServerType.LOGIN, ack.login_id)
        if target >= 0:
            self.server.send_raw(target, msg_id, body)

    # ------------------------------------------------------ status JSON
    def servers_status(self) -> dict:
        """Whole-cluster aggregate (`GetServersStatus` JSON), one entry
        per server with its lease state and heartbeat age."""
        now = _time.monotonic()
        out: Dict[str, list] = {}
        for stype, by_id in sorted(self.registry.items()):
            key = self._type_name(stype)
            entries = []
            for _, reg in sorted(by_id.items()):
                d = report_to_dict(reg.report)
                d["lease"] = reg.lease
                d["last_seen_age_s"] = round(max(0.0, now - reg.last_seen), 3)
                entries.append(d)
            out[key] = entries
        status = {
            "master": report_to_dict(self.report()),
            "servers": out,
        }
        if self.chaos_status is not None:
            try:
                status["chaos"] = self.chaos_status()
            except Exception:  # noqa: BLE001 — a dead probe must not kill /json
                status["chaos"] = {"error": "chaos status unavailable"}
        if self.drill_status is not None:
            try:
                status["drill"] = self.drill_status()
            except Exception:  # noqa: BLE001 — a dead probe must not kill /json
                status["drill"] = {"error": "drill status unavailable"}
        # session-failover health (ISSUE 10): each world's heartbeat ext
        # carries pending re-homes + oldest-pending age; aggregate them
        # so operators see a stuck failover without scraping every world
        fo: Dict[str, dict] = {}
        for sid, reg in sorted(
            self.registry.get(int(ServerType.WORLD), {}).items()
        ):
            ext = self._ext_of(reg.report)
            if "failover_pending" not in ext:
                continue
            try:
                fo[str(sid)] = {
                    "pending": int(ext.get("failover_pending", "0")),
                    "lag_s": float(ext.get("failover_lag", "0")),
                }
            except ValueError:
                fo[str(sid)] = {"error": "unparseable failover ext"}
        if fo:
            status["failover"] = fo
        # compiled-cost health: each game's heartbeat ext carries a
        # compact CostBook summary; parse it into a structured block so
        # the dashboard shows recompiles/HBM without scraping every world
        cb = self._costbook_ext()
        if cb:
            status["costbook"] = cb
        # many-worlds occupancy: each game hosting a RoomDirectory ships
        # slot totals + per-room placement in its heartbeat ext; surface
        # them per game plus cluster-wide room totals
        rooms: Dict[str, dict] = {}
        for sid, reg in sorted(
            self.registry.get(int(ServerType.GAME), {}).items()
        ):
            blob = self._ext_of(reg.report).get("rooms")
            if not blob:
                continue
            try:
                rooms[str(sid)] = _json.loads(blob)
            except ValueError:
                rooms[str(sid)] = {"error": "unparseable rooms ext"}
        if rooms:
            status["rooms"] = {
                "games": rooms,
                "total_active": sum(
                    int(g.get("active", 0)) for g in rooms.values()
                    if isinstance(g.get("active", 0), int)),
                "total_slots_free": sum(
                    int(g.get("slots_free", 0)) for g in rooms.values()
                    if isinstance(g.get("slots_free", 0), int)),
            }
        return status

    def _costbook_ext(self) -> Dict[str, dict]:
        """Per-game CostBook summaries parsed from heartbeat ext blobs."""
        out: Dict[str, dict] = {}
        for sid, reg in sorted(
            self.registry.get(int(ServerType.GAME), {}).items()
        ):
            blob = self._ext_of(reg.report).get("costbook")
            if not blob:
                continue
            try:
                out[str(sid)] = _json.loads(blob)
            except ValueError:
                out[str(sid)] = {"error": "unparseable costbook ext"}
        return out

    def costbook_status(self) -> dict:
        """Cluster-wide compiled-cost view (/costbook): per-game CostBook
        summaries plus cluster totals — the aggregate sibling of the
        per-role /costbook snapshot served by TelemetryModule."""
        games = self._costbook_ext()
        totals = {"compiles": 0, "recompiles": 0, "compile_ms": 0.0,
                  "hbm_live_bytes": 0, "hbm_peak_bytes": 0}
        for g in games.values():
            if "error" in g:
                continue
            totals["compiles"] += int(g.get("compiles", 0))
            totals["recompiles"] += int(g.get("recompiles", 0))
            totals["compile_ms"] += float(g.get("compile_ms", 0.0))
            totals["hbm_live_bytes"] += int(g.get("hbm_live", 0) or 0)
            totals["hbm_peak_bytes"] += int(g.get("hbm_peak", 0) or 0)
        totals["compile_ms"] = round(totals["compile_ms"], 3)
        return {"totals": totals, "games": games}

    def pipeline_status(self) -> dict:
        """Frame-pipeline waterfall for the whole cluster (/pipeline):
        per-game stage timings + trace round trips and per-proxy relay
        percentiles, parsed from the heartbeat ext blobs, alongside the
        NTP-style per-role clock offsets for multi-process trace merges."""
        out: Dict[str, object] = {
            "clock_offsets_ns": self.clock.offsets(),
            "games": [],
            "proxies": [],
        }
        for stype, bucket in (
            (int(ServerType.GAME), "games"),
            (int(ServerType.PROXY), "proxies"),
        ):
            for sid, reg in sorted(self.registry.get(stype, {}).items()):
                ext = self._ext_of(reg.report)
                entry: Dict[str, object] = {
                    "server_id": sid,
                    "lease": reg.lease,
                }
                blob = ext.get("pipeline")
                if blob:
                    try:
                        entry["pipeline"] = _json.loads(blob)
                    except ValueError:
                        entry["pipeline"] = {"error": "unparseable blob"}
                for k in ("frame_p50_ms", "frame_p95_ms", "frame_p99_ms",
                          "relay_p50_ms", "relay_p95_ms", "traces_relayed"):
                    if k in ext:
                        entry[k] = ext[k]
                out[bucket].append(entry)  # type: ignore[union-attr]
        return out

    def _index_page(self, _path: str, _params: Dict[str, str]):
        """Dashboard at "/": serves the standalone monitor page
        (tools/web_monitor/index.html, the Tool/NF_Web_Monitor
        equivalent — a static page polling /json) and falls back to a
        server-rendered table when the file is missing."""
        from pathlib import Path

        page = (
            Path(__file__).resolve().parents[3]
            / "tools" / "web_monitor" / "index.html"
        )
        if page.is_file():
            return (200, "text/html", page.read_bytes())
        return self._fallback_page()

    def _fallback_page(self) -> str:
        """Server-rendered table (no-JS fallback)."""
        rows = []
        status = self.servers_status()
        for group, servers in status["servers"].items():
            for s in servers:
                try:
                    state = ServerState(s["state"]).name
                except ValueError:
                    state = str(s["state"])
                name = html.escape(str(s['name']))
                endpoint = html.escape(f"{s['ip']}:{s['port']}")
                lease = html.escape(str(s.get("lease", "?")))
                age = s.get("last_seen_age_s", 0.0)
                ext = s.get("ext", {})
                if "persist_lag_ticks" in ext:
                    persist = f"lag {html.escape(str(ext['persist_lag_ticks']))}"
                    if str(ext.get("persist_degraded", "0")) != "0":
                        persist += " <b>DEGRADED</b>"
                elif "failover_pending" in ext:
                    # world rows repurpose the column for failover health
                    persist = (
                        f"failover {html.escape(str(ext['failover_pending']))}"
                        f" pending, lag "
                        f"{html.escape(str(ext.get('failover_lag', '0')))}s"
                    )
                    if str(ext.get("failover_pending", "0")) != "0":
                        persist = f"<b>{persist}</b>"
                else:
                    persist = "&mdash;"
                rows.append(
                    f"<tr><td>{html.escape(group)}</td><td>{s['server_id']}</td>"
                    f"<td>{name}</td><td>{endpoint}</td>"
                    f"<td>{s['cur_count']}/{s['max_online']}</td>"
                    f"<td>{html.escape(str(state))}</td>"
                    f"<td>{lease} ({age:.1f}s)</td>"
                    f"<td>{persist}</td></tr>"
                )
        return (
            "<html><head><title>cluster status</title></head><body>"
            "<h2>Cluster status</h2>"
            "<table border=1 cellpadding=4><tr><th>role</th><th>id</th>"
            "<th>name</th><th>endpoint</th><th>load</th><th>state</th>"
            "<th>lease (heartbeat age)</th><th>persist</th></tr>"
            + "".join(rows)
            + "</table><p><a href='/json'>raw json</a></p></body></html>"
        )

    # ------------------------------------------------------------ pump
    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        super().execute(now)
        self._sweep_leases(now)
        if self.http is not None:
            self.http.execute()

    def shut(self) -> None:
        super().shut()
        if self.http is not None:
            self.http.close()
