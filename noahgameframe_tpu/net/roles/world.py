"""World role: hub for game/proxy registration + enter-world rendezvous.

Reference: NFWorldNet_ServerPlugin / NFWorldLogicPlugin — game and proxy
servers register and refresh here (callbacks
`NFCWorldNet_ServerModule.cpp:28-36`); on a select-world request the world
picks the least-loaded proxy, mints a connect key, pre-authorizes it at
that proxy, and answers Master with the proxy endpoint + key; server
reports from games/proxies are relayed up to Master (SURVEY §3.5).  It
also pushes the live game-server list down to proxies so the gateway can
keep its outbound pool current.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time as _time
from typing import Dict, List, Optional

from ..defines import LEASE_DOWN_SECONDS, MsgID, ServerState, ServerType
from ..failover import FailoverDriver, SessionInfo, ext_map
from ..transport import EV_DISCONNECTED
from ..wire import (
    AckConnectWorldResult,
    Ident,
    MsgBase,
    ReqConnectWorld,
    RoleOfflineNotify,
    ServerInfoExt,
    ServerInfoReport,
    ServerInfoReportList,
    SessionBindNotify,
    SwitchRefused,
    ident_key as _ident_key,
    unwrap,
    wrap,
)
from .base import RoleConfig, ServerRole, decode_reports

# game→world sync traffic the World relays to every OTHER game server so
# players on different game servers converge on each other's public state
# (reference NFCWorldNet_ServerModule.cpp:600-830 rebuilds and re-sends
# property/record packs world-side; here the already-encoded game message
# is transponded verbatim — the TPU game server already batched it)
CROSS_SYNC_MSGS = (
    MsgID.ACK_ONLINE_NOTIFY,
    MsgID.ACK_OFFLINE_NOTIFY,
    MsgID.ACK_PROPERTY_INT,
    MsgID.ACK_PROPERTY_FLOAT,
    MsgID.ACK_PROPERTY_STRING,
    MsgID.ACK_PROPERTY_OBJECT,
    MsgID.ACK_PROPERTY_VECTOR2,
    MsgID.ACK_PROPERTY_VECTOR3,
    MsgID.ACK_ADD_ROW,
    MsgID.ACK_REMOVE_ROW,
    MsgID.ACK_SWAP_ROW,
    MsgID.ACK_RECORD_INT,
    MsgID.ACK_RECORD_FLOAT,
    MsgID.ACK_RECORD_STRING,
    MsgID.ACK_RECORD_OBJECT,
    MsgID.ACK_RECORD_VECTOR3,
)


@dataclasses.dataclass
class _Downstream:
    report: ServerInfoReport
    conn_id: int
    last_seen: float = 0.0


class WorldRole(ServerRole):
    server_type = int(ServerType.WORLD)

    def __init__(self, config: RoleConfig, backend: str = "auto",
                 lease_down_seconds: float = LEASE_DOWN_SECONDS,
                 recover_store=None, failover: bool = True) -> None:
        self.games: Dict[int, _Downstream] = {}
        self.proxies: Dict[int, _Downstream] = {}
        # a downstream that stops reporting for this long is treated as
        # dead even if its socket looks alive (half-open link/partition)
        self.lease_down_seconds = lease_down_seconds
        # world roster: online player ident -> owning game server id
        # (fed by ACK_ONLINE/OFFLINE_NOTIFY; the reference's OnOnlineProcess)
        self.roster: Dict[tuple, int] = {}
        # session bind metadata per online player (SESSION_BIND_NOTIFY
        # sidecars) — everything the failover driver needs to re-home a
        # session when its game dies (ISSUE 10)
        self.sessions: Dict[tuple, SessionInfo] = {}
        super().__init__(config, backend=backend)
        self._lease_expirations = self.telemetry.registry.counter(
            "nf_lease_expirations_total",
            "downstream leases aged past the DOWN threshold", ("role",),
        )
        self.failover: Optional[FailoverDriver] = (
            FailoverDriver(self, recover_store=recover_store)
            if failover else None
        )
        self.master = self.add_upstream(
            "master",
            [t for t in config.targets if t.server_type == int(ServerType.MASTER)],
            register_msg=MsgID.MTL_WORLD_REGISTERED,
            refresh_msg=MsgID.MTL_WORLD_REFRESH,
        )
        self.master.on(MsgID.REQ_CONNECT_WORLD, self._on_req_connect_world)

    def _install(self) -> None:
        s = self.server
        for msg in (MsgID.GTW_GAME_REGISTERED, MsgID.GTW_GAME_REFRESH):
            s.on(msg, self._on_game_register)
        s.on(MsgID.GTW_GAME_UNREGISTERED, self._on_game_unregister)
        for msg in (MsgID.PTWG_PROXY_REGISTERED, MsgID.PTWG_PROXY_REFRESH):
            s.on(msg, self._on_proxy_register)
        s.on(MsgID.PTWG_PROXY_UNREGISTERED, self._on_proxy_unregister)
        s.on(MsgID.STS_SERVER_REPORT, self._on_server_report)
        for msg in CROSS_SYNC_MSGS:
            s.on(msg, self._on_cross_sync)
        # cross-game-server switch: targeted relays (the reference routes
        # these through the world's cluster link, NFCGSSwichServerModule)
        s.on(MsgID.REQ_SWITCH_SERVER, self._on_switch_relay)
        s.on(MsgID.SWITCH_SERVER_DATA, self._on_switch_relay)
        s.on(MsgID.ACK_SWITCH_SERVER, self._on_switch_relay)
        # session failover (ISSUE 10): bind metadata + refusal intake
        s.on(MsgID.SESSION_BIND_NOTIFY, self._on_session_bind)
        s.on(MsgID.ACK_SWITCH_REFUSED, self._on_switch_refused)
        s.on_socket_event(self._on_socket)

    def _on_switch_relay(self, conn_id: int, msg_id: int, body: bytes) -> None:
        """Route a switch message to the ONE game it names: REQ/DATA go
        to target_serverid, ACK returns to the originating game
        (self_serverid)."""
        from ..wire import AckSwitchServer, ReqSwitchServer, SwitchServerData

        cls = {
            int(MsgID.REQ_SWITCH_SERVER): ReqSwitchServer,
            int(MsgID.SWITCH_SERVER_DATA): SwitchServerData,
            int(MsgID.ACK_SWITCH_SERVER): AckSwitchServer,
        }[int(msg_id)]
        _, msg = unwrap(body, cls)
        if msg_id == int(MsgID.ACK_SWITCH_SERVER):
            # a failover-staged switch names a DEAD origin: the driver
            # (standing in for it) consumes the ack; anything else is a
            # voluntary switch and relays to the living origin below
            if self.failover is not None and self.failover.on_ack(msg):
                return
            sid = int(msg.self_serverid)
        else:
            sid = int(msg.target_serverid)
        d = self.games.get(sid)
        if d is not None:
            self.server.send_raw(d.conn_id, msg_id, body)

    def _on_session_bind(self, conn_id: int, _msg_id: int,
                         body: bytes) -> None:
        """Game-side sidecar to ACK_ONLINE_NOTIFY: remember everything
        needed to re-home this session if its game dies unasked."""
        _, b = unwrap(body, SessionBindNotify)
        if b.selfid is None:
            return
        client = (_ident_key(b.client_id) if b.client_id is not None
                  else (0, 0))
        info = SessionInfo(
            selfid=_ident_key(b.selfid),
            account=b.account.decode("utf-8", "replace"),
            name=b.name.decode("utf-8", "replace"),
            client_id=client,
            scene_id=int(b.scene_id),
            group_id=int(b.group_id),
            save_key=b.save_key.decode("utf-8", "replace"),
            game_id=int(b.game_id),
        )
        self.sessions[info.selfid] = info

    def _on_switch_refused(self, conn_id: int, _msg_id: int,
                           body: bytes) -> None:
        """A staged target could not admit the switch (capacity / torn
        blob): hand the refusal to the failover driver so it retries
        another survivor.  Voluntary switches have no refusal leg — the
        origin's staged blob simply ages out of its TTL sweep."""
        _, msg = unwrap(body, SwitchRefused)
        if self.failover is not None:
            self.failover.on_refused(msg)

    # ------------------------------------------- cross-game sync relay
    def _on_cross_sync(self, conn_id: int, msg_id: int, body: bytes) -> None:
        """Property/record sync relay game→world→other games
        (NFCWorldNet_ServerModule.cpp:600-830).  The envelope is relayed
        verbatim; the roster tracks online players per game."""
        if msg_id in (int(MsgID.ACK_ONLINE_NOTIFY), int(MsgID.ACK_OFFLINE_NOTIFY)):
            base = MsgBase.decode(body)
            sid = self.server.conn_tags.get(conn_id, {}).get("server_id")
            key = _ident_key(base.player_id)
            if msg_id == int(MsgID.ACK_ONLINE_NOTIFY) and sid is not None:
                self.roster[key] = sid
            else:
                self.roster.pop(key, None)
                self.sessions.pop(key, None)
        for d in self.games.values():
            if d.conn_id != conn_id:
                self.server.send_raw(d.conn_id, msg_id, body)

    # ---------------------------------------------------- registration
    def _on_game_register(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        for r in decode_reports(body):
            self.games[r.server_id] = _Downstream(r, conn_id, _time.monotonic())
            self.server.conn_tags.setdefault(conn_id, {})["server_id"] = r.server_id
            self._relay_report(r)
        self._push_game_list()

    def _on_game_unregister(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        for r in decode_reports(body):
            self.games.pop(r.server_id, None)
        self._push_game_list()

    def _on_proxy_register(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        for r in decode_reports(body):
            self.proxies[r.server_id] = _Downstream(r, conn_id, _time.monotonic())
            self.server.conn_tags.setdefault(conn_id, {})["server_id"] = r.server_id
            self._relay_report(r)
        # a (re)joined proxy needs the current game list immediately
        self._send_game_list(conn_id)

    def _on_proxy_unregister(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        for r in decode_reports(body):
            self.proxies.pop(r.server_id, None)

    def _on_server_report(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """Keepalive load reports from games/proxies; refresh + relay up
        (`NFCWorldNet_ServerModule.cpp:36` → Master upsert)."""
        now = _time.monotonic()
        for r in decode_reports(body):
            book = self.games if r.server_type == int(ServerType.GAME) else self.proxies
            cur = book.get(r.server_id)
            if cur is not None:
                cur.report = r
                cur.last_seen = now
            elif conn_id >= 0:
                # a live reporter we don't know: its registration was
                # lost (dropped under chaos) or its lease false-expired —
                # re-adopt; the keepalive doubles as registration
                book[r.server_id] = _Downstream(r, conn_id, now)
                self.server.conn_tags.setdefault(conn_id, {})["server_id"] = r.server_id
                if r.server_type == int(ServerType.GAME):
                    self._push_game_list()
            self._relay_report(r)

    def _relay_report(self, r: ServerInfoReport) -> None:
        self.master.send_to_all(
            int(MsgID.STS_SERVER_REPORT),
            wrap(ServerInfoReportList(server_list=[r])),
        )

    def _on_socket(self, conn_id: int, kind: int) -> None:
        if kind != EV_DISCONNECTED:
            return
        dead = [v for v in list(self.games.values()) + list(self.proxies.values())
                if v.conn_id == conn_id]
        self.games = {k: v for k, v in self.games.items() if v.conn_id != conn_id}
        self.proxies = {k: v for k, v in self.proxies.items() if v.conn_id != conn_id}
        if dead:
            self._mark_dead(dead)

    def _sweep_leases(self, now: float) -> None:
        """Expire downstreams whose reports stopped arriving: a link can
        stay ESTABLISHED while the peer is partitioned away or wedged.
        Evicted entries re-adopt on their next report (upsert above)."""
        dead = [v for v in list(self.games.values()) + list(self.proxies.values())
                if now - v.last_seen >= self.lease_down_seconds]
        if not dead:
            return
        gone = {id(v) for v in dead}
        self.games = {k: v for k, v in self.games.items() if id(v) not in gone}
        self.proxies = {k: v for k, v in self.proxies.items() if id(v) not in gone}
        for d in dead:
            role = (
                "game" if d.report.server_type == int(ServerType.GAME)
                else "proxy"
            )
            self._lease_expirations.inc(role=role)
            if d.conn_id >= 0:
                self.server.close_conn(d.conn_id)
        self._mark_dead(dead)

    def _mark_dead(self, dead: List[_Downstream]) -> None:
        """Shared death path (socket loss or lease expiry): tell Master
        (CRASH state) and re-push the game list so proxies stop routing
        to the corpse."""
        dead_ids = set()
        dead_games: Dict[int, _Downstream] = {}
        for d in dead:
            d.report.server_state = int(ServerState.CRASH)
            dead_ids.add(d.report.server_id)
            if d.report.server_type == int(ServerType.GAME):
                dead_games[d.report.server_id] = d
            self._relay_report(d.report)
        # synthesize offline notifies for the dead game's players so other
        # games' clients drop their (now frozen) remote mirrors
        orphans = [k for k, v in self.roster.items() if v in dead_ids]
        for svrid, index in orphans:
            del self.roster[(svrid, index)]
            body = wrap(RoleOfflineNotify(),
                        player_id=Ident(svrid=svrid, index=index))
            for d in self.games.values():
                self.server.send_raw(
                    d.conn_id, int(MsgID.ACK_OFFLINE_NOTIFY), body
                )
        # supervised failover (ISSUE 10): hand every session bound to a
        # dead game to the driver, with the durable-media locations the
        # corpse last advertised (WAL + checkpoint dirs ride its report
        # ext), so players re-home instead of silently stalling
        if self.failover is not None and dead_games:
            now = _time.monotonic()
            for sid, d in dead_games.items():
                infos = [v for v in self.sessions.values()
                         if v.game_id == sid]
                for v in infos:
                    self.sessions.pop(v.selfid, None)
                if infos:
                    ext = ext_map(d.report)
                    self.failover.game_died(
                        sid, infos, ext.get("wal_dir"),
                        ext.get("ckpt_dir"), now,
                    )
        self._push_game_list()

    # ------------------------------------------------------------ pump
    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        super().execute(now)
        self._sweep_leases(now)
        if self.failover is not None:
            self.failover.execute(now)

    def report(self):
        """Heartbeat report extended with failover health: pending
        re-homes + oldest lag ride the ext map so the master can show
        `failover_pending`/`failover_lag` on /json and the status page."""
        r = super().report()
        if self.failover is None:
            return r
        ext = r.server_info_list_ext
        if ext is None:
            ext = ServerInfoExt()
            r.server_info_list_ext = ext
        now = _time.monotonic()
        for k, v in (
            ("failover_pending", self.failover.pending_count()),
            ("failover_lag", round(self.failover.lag(now), 3)),
        ):
            ext.key.append(k.encode())
            ext.value.append(str(v).encode())
        return r

    # ---------------------------------------------- game list to proxies
    def _game_reports(self) -> ServerInfoReportList:
        return ServerInfoReportList(
            server_list=[d.report for d in self.games.values()]
        )

    def _send_game_list(self, conn_id: int) -> None:
        self.server.send_raw(
            conn_id, int(MsgID.STS_NET_INFO), wrap(self._game_reports())
        )

    def _push_game_list(self) -> None:
        for d in self.proxies.values():
            self._send_game_list(d.conn_id)

    # -------------------------------------------------- enter-world path
    def _pick_proxy(self) -> Optional[_Downstream]:
        """Least-loaded live proxy (`NFCWorldNet_ServerModule` picks by
        current count)."""
        best = None
        for d in self.proxies.values():
            if best is None or d.report.server_cur_count < best.report.server_cur_count:
                best = d
        return best

    def _mint_key(self, account: str) -> str:
        return hashlib.sha1(
            account.encode() + os.urandom(16)
        ).hexdigest()[:32]

    def _on_req_connect_world(self, _sid: int, _msg_id: int, body: bytes) -> None:
        _, req = unwrap(body, ReqConnectWorld)
        account = req.account.decode("utf-8", "replace")
        proxy = self._pick_proxy()
        if proxy is None:
            return
        key = self._mint_key(account)
        grant = AckConnectWorldResult(
            world_id=self.config.server_id,
            sender=req.sender,
            login_id=req.login_id,
            account=account.encode(),
            world_ip=proxy.report.server_ip,
            world_port=proxy.report.server_port,
            world_key=key.encode(),
        )
        # pre-authorize the key at the chosen proxy, then answer Master
        self.server.send_raw(
            proxy.conn_id, int(MsgID.ACK_CONNECT_KEY), wrap(grant)
        )
        self.master.send_to_all(int(MsgID.ACK_CONNECT_WORLD), wrap(grant))
