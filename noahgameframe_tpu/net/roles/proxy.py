"""Proxy (gateway) role: client TCP edge, auth by connect key, routing.

Reference: NFProxyServerNet_ServerPlugin / NFProxyServerNet_ClientPlugin —
clients attach here after the select-world handshake; `OnConnectKeyProcess`
verifies the world-minted key and binds the account to the connection
(`NFCProxyServerNet_ServerModule.cpp:130-163`); every further client
message is stamped with the verified client ident and routed client→game
by selected server id or consistent hash (`OnOtherMessage` `:83-128`);
game→client traffic is fanned out per the envelope's client list
(`Transpond` `:297-352`, which forwards the *inner* payload to each
client).  The proxy learns the live game-server set from World
(STS_NET_INFO) and keeps an outbound pool with the reconnect FSM.
"""

from __future__ import annotations

import hmac
import time as _time
from typing import Dict, Optional, Tuple

from ...telemetry.pipeline import TraceError, decode_trace, encode_trace
from ..defines import EventCode, MsgID, ServerState, ServerType, SwitchNoticeCode
from ..failover import ParkingBuffer
from ..module import NORMAL, NetClientModule
from ..transport import EV_DISCONNECTED, EV_MSG
from ..wire import (
    AckConnectWorldResult,
    AckEventResult,
    Ident,
    MsgBase,
    ReqAccountLogin,
    ReqSelectServer,
    SwitchNotice,
    ident_key as _ident_key,
    scan_envelope_targets,
    unwrap,
    wrap,
)
from .base import RoleConfig, ServerRole, decode_reports

_IdentKey = Tuple[int, int]  # (svrid, index)


class ProxyRole(ServerRole):
    server_type = int(ServerType.PROXY)

    KEY_TTL_S = 120.0  # a grant the client never redeems expires
    #: retry hint carried in BUSY/REHOMING notices — roughly one lease
    #: refresh, by which time the world's failover has usually re-staged
    RETRY_AFTER_MS = 500

    def __init__(self, config: RoleConfig, backend: str = "auto") -> None:
        # account -> (world-minted connect key, expiry monotonic time);
        # one-time use, TTL-bounded — a captured account+key pair can't
        # re-authenticate after the legitimate redeem
        self._keys: Dict[str, Tuple[str, float]] = {}
        # verified client ident -> conn_id (the Transpond routing table)
        self._client_conn: Dict[_IdentKey, int] = {}
        # conn_id -> binding info, survives until the disconnect handler has
        # told the game (conn_tags are cleared before our socket hook runs)
        self._conn_info: Dict[int, Dict[str, object]] = {}
        super().__init__(config, backend=backend)
        self.world = self.add_upstream(
            "world",
            [t for t in config.targets if t.server_type == int(ServerType.WORLD)],
            register_msg=MsgID.PTWG_PROXY_REGISTERED,
            refresh_msg=MsgID.PTWG_PROXY_REFRESH,
        )
        self.world.on(MsgID.ACK_CONNECT_KEY, self._on_key_granted)
        self.world.on(MsgID.STS_NET_INFO, self._on_game_list)
        # outbound pool to game servers (fed by World's game list)
        self.games = NetClientModule(backend=self.backend)
        self.clients["games"] = self.games
        self.telemetry.add_net_source("games", self.games.counters)
        self.telemetry.add_pool_source("games", self.games)
        # switch re-route before the catch-all: the target game tells us
        # its client moved; we re-point the binding, the client never
        # sees the control message (reference: gate handles
        # EGMI_REQSWICHSERVER from the game, NFCGSSwichServerModule)
        self.games.on(MsgID.REQ_SWITCH_SERVER, self._on_switch_route)
        # frame observatory (ISSUE 7): the dispatch tap stamps arrival
        # time for every game→proxy message, so _transpond can attribute
        # its relay latency, and FRAME_TRACE sidecars get proxy_in/out
        # stamps before fan-out (a dedicated handler keeps them off the
        # blind _transpond path)
        self.games.dispatch.tap = self._games_tap
        self.games.on(MsgID.FRAME_TRACE, self._on_frame_trace)
        self.games.on_any(self._transpond)
        self._relay_arrival_ns = 0
        self._relay_hist = self.telemetry.registry.histogram(
            "nf_proxy_relay_seconds",
            "game→client transpond relay latency (arrival to fan-out done)",
        )
        self.traces_relayed = 0
        # session failover (ISSUE 10): frames headed for a dead/absent
        # binding park here instead of dropping, and replay in order once
        # the world's driver re-homes the session and the target's
        # re-point lands (_on_switch_route)
        self.parking = ParkingBuffer(registry=self.telemetry.registry)
        # switch-notice accounting (ISSUE 11): the drill's no-silent-drop
        # invariant needs to prove every unbound session *heard* about it
        # — aggregate per code, and per client conn (cleared with the
        # conn) so a specific orphan can be checked, not just totals
        self.notice_counts: Dict[int, int] = {}
        self.conn_notices: Dict[int, Dict[int, int]] = {}
        self._c_notices = self.telemetry.registry.counter(
            "nf_switch_notices_total",
            "ACK_SWITCH_NOTICE control frames pushed to clients",
            ("code",),
        )

    def _install(self) -> None:
        s = self.server
        s.on(MsgID.REQ_CONNECT_KEY, self._on_connect_key)
        s.on(MsgID.REQ_SELECT_SERVER, self._on_select_server)
        s.on_any(self._on_client_message)
        s.on_socket_event(self._on_socket)

    def cur_count(self) -> int:
        return len(self._client_conn)

    # ------------------------------------------------------ world side
    def _on_key_granted(self, _sid: int, _msg_id: int, body: bytes) -> None:
        _, grant = unwrap(body, AckConnectWorldResult)
        now = _time.monotonic()
        # sweep never-redeemed expired grants so the map stays bounded
        self._keys = {a: kv for a, kv in self._keys.items() if kv[1] > now}
        self._keys[grant.account.decode("utf-8", "replace")] = (
            grant.world_key.decode("utf-8", "replace"),
            now + self.KEY_TTL_S,
        )

    def _on_game_list(self, _sid: int, _msg_id: int, body: bytes) -> None:
        """Reconcile the outbound pool against World's authoritative game
        list: add new, re-dial changed endpoints, prune vanished servers
        (a restarted game comes back on a new ephemeral port)."""
        before = set(self.games.servers)
        seen = set()
        for r in decode_reports(body):
            if int(r.server_state) == int(ServerState.CRASH):
                # lease-evicted / crashed upstream: leave it out of
                # `seen` so the prune below stops routing to it
                continue
            sid = r.server_id
            ip = r.server_ip.decode("utf-8", "replace")
            seen.add(sid)
            sd = self.games.servers.get(sid)
            if sd is not None and (sd.ip != ip or sd.port != r.server_port):
                self.games.remove_server(sid)
                sd = None
            if sd is None:
                self.games.add_server(
                    sid, int(r.server_type), ip, r.server_port,
                    r.server_name.decode("utf-8", "replace"),
                )
        for sid in list(self.games.servers):
            if sid not in seen:
                self.games.remove_server(sid)
        # satellite 2: a prune used to silently unbind every client on
        # the vanished game — their messages fell into the void with no
        # signal.  Tell them explicitly: failover is re-homing you, park
        # in the meantime, retry after a beat if nothing arrives.  Only
        # the transition fires (`before - seen`), so a game that stays
        # CRASH across refreshes does not re-notify every push.
        gone = {int(s) for s in before - seen}
        if gone:
            for conn_id, info in self._conn_info.items():
                gid = info.get("game_id")
                if gid is not None and int(gid) in gone:
                    self._notify_switch(
                        conn_id, SwitchNoticeCode.REHOMING, int(gid),
                        self.RETRY_AFTER_MS,
                    )

    def _notify_switch(self, conn_id: int, code: SwitchNoticeCode,
                       target_sid: int, retry_after_ms: int) -> None:
        """Push an ACK_SWITCH_NOTICE control frame to one client (the
        reference has no equivalent — orphaned clients just time out)."""
        notice = SwitchNotice(
            code=int(code),
            target_serverid=int(target_sid),
            retry_after_ms=int(retry_after_ms),
        )
        self.server.send_raw(
            conn_id, int(MsgID.ACK_SWITCH_NOTICE), wrap(notice)
        )
        self.notice_counts[int(code)] = (
            self.notice_counts.get(int(code), 0) + 1)
        per = self.conn_notices.setdefault(conn_id, {})
        per[int(code)] = per.get(int(code), 0) + 1
        try:
            label = SwitchNoticeCode(int(code)).name
        except ValueError:
            label = str(int(code))
        self._c_notices.inc(code=label)

    # ------------------------------------------------------ client side
    def _on_connect_key(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        _, req = unwrap(body, ReqAccountLogin)
        account = req.account.decode("utf-8", "replace")
        key = req.security_code.decode("utf-8", "replace")
        granted = self._keys.get(account)
        if granted is not None and _time.monotonic() >= granted[1]:
            del self._keys[account]  # expired, never redeemable
            granted = None
        ok = (
            bool(account)
            and granted is not None
            and hmac.compare_digest(granted[0], key)
        )
        if ok:
            del self._keys[account]  # one-time use
            ident = Ident(svrid=self.config.server_id, index=conn_id)
            tags = self.server.conn_tags.setdefault(conn_id, {})
            tags["account"] = account
            tags["ident"] = ident
            self._client_conn[_ident_key(ident)] = conn_id
            self._conn_info[conn_id] = {"ident": ident, "account": account}
            ack = AckEventResult(
                event_code=int(EventCode.VERIFY_KEY_SUCCESS), event_object=ident
            )
        else:
            ack = AckEventResult(event_code=int(EventCode.VERIFY_KEY_FAIL))
        self.server.send_pb(conn_id, int(MsgID.ACK_CONNECT_KEY), ack)
        if not ok:
            self.server.close_conn(conn_id)

    def _on_select_server(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """Bind this client to a specific game server
        (`OnReqServerListProcess`/select path)."""
        tags = self.server.conn_tags.get(conn_id, {})
        if "ident" not in tags:
            return
        _, req = unwrap(body, ReqSelectServer)
        sd = self.games.servers.get(req.world_id)
        if sd is not None and sd.state == NORMAL:
            tags["game_id"] = req.world_id
            info = self._conn_info.get(conn_id)
            if info is not None:
                info["game_id"] = req.world_id
            code = int(EventCode.SELECTSERVER_SUCCESS)
        else:
            code = int(EventCode.SELECTSERVER_FAIL)
        self.server.send_pb(
            conn_id,
            int(MsgID.ACK_SELECT_SERVER),
            AckEventResult(event_code=code),
        )

    def _on_client_message(self, conn_id: int, msg_id: int, body: bytes) -> None:
        """The routing hot path: stamp the verified ident, forward to the
        bound game server or hash-route by account."""
        tags = self.server.conn_tags.get(conn_id, {})
        ident = tags.get("ident")
        if ident is None:
            return  # unauthenticated: drop (reference closes after abuse)
        base = MsgBase.decode(body)
        base.player_id = ident  # server-authoritative identity stamp
        out = base.encode()
        game_id = tags.get("game_id")
        if game_id is not None:
            # order guard: while frames are parked for this session, new
            # arrivals must queue BEHIND them even if the (re-pointed)
            # binding is already sendable — a direct send here would
            # overtake the parked prefix
            if self.parking.depth(conn_id):
                dropped = self.parking.park(
                    conn_id, msg_id, out, _time.monotonic()
                )
                if dropped:
                    self._notify_switch(
                        conn_id, SwitchNoticeCode.DROPPED, int(game_id),
                        self.RETRY_AFTER_MS,
                    )
            elif not self.games.send_by_server_id(game_id, msg_id, out):
                # bound game is gone or not NORMAL: park instead of drop
                # — failover is (or will be) re-homing this session, and
                # _on_switch_route replays the queue in order
                dropped = self.parking.park(
                    conn_id, msg_id, out, _time.monotonic()
                )
                if dropped:
                    self._notify_switch(
                        conn_id, SwitchNoticeCode.DROPPED, int(game_id),
                        self.RETRY_AFTER_MS,
                    )
        else:
            self.games.send_by_suit(tags.get("account", ""), msg_id, out)

    def _on_socket(self, conn_id: int, kind: int) -> None:
        if kind != EV_DISCONNECTED:
            return
        self._client_conn = {
            k: c for k, c in self._client_conn.items() if c != conn_id
        }
        # anything still parked for a dead client socket has no receiver
        # for its replies either — drop it (counted reason="disconnect")
        self.parking.discard(conn_id)
        self.conn_notices.pop(conn_id, None)
        # tell the game its player is gone (the reference proxy fires
        # REQ_LEAVE_GAME upstream when a client socket dies)
        info = self._conn_info.pop(conn_id, None)
        if info is None:
            return
        base = MsgBase(player_id=info["ident"], msg_data=b"")
        game_id = info.get("game_id")
        if game_id is not None:
            self.games.send_by_server_id(
                int(game_id), int(MsgID.REQ_LEAVE_GAME), base.encode()
            )
        else:
            self.games.send_by_suit(
                str(info.get("account", "")), int(MsgID.REQ_LEAVE_GAME),
                base.encode(),
            )

    # ------------------------------------------------------ game → client
    def _on_switch_route(self, _sid: int, _msg_id: int, body: bytes) -> None:
        """Re-point a client's game binding after a cross-server switch:
        subsequent client messages route to the new game server."""
        from ..wire import ReqSwitchServer

        _, req = unwrap(body, ReqSwitchServer)
        if req.client_id is None:
            return
        conn_id = self._client_conn.get(_ident_key(req.client_id))
        if conn_id is None:
            return  # not our client (multi-proxy broadcast)
        tags = self.server.conn_tags.get(conn_id)
        if tags is not None:
            tags["game_id"] = int(req.target_serverid)
        # the disconnect path reads _conn_info, not conn_tags — both must
        # re-point or a later socket death sends REQ_LEAVE_GAME to the
        # OLD game and the new one keeps a ghost avatar forever
        info = self._conn_info.get(conn_id)
        if info is not None:
            info["game_id"] = int(req.target_serverid)
        # new binding is live: replay anything parked while the old one
        # was dead, in arrival order.  A failed send leaves the rest
        # parked; _parking_pump retries on the next execute pass.
        if self.parking.depth(conn_id):
            target = int(req.target_serverid)
            self.parking.replay(
                conn_id,
                lambda m, b: self.games.send_by_server_id(target, m, b),
            )

    def _games_tap(self, ev) -> None:
        """Dispatch-tap seam (net/module.py:_Dispatch.tap): stamp arrival
        time for the message about to be handled.  feed() is synchronous
        — tap fires, then the handler — so the stamp always belongs to
        the event the handler sees."""
        if ev.kind == EV_MSG:
            self._relay_arrival_ns = _time.perf_counter_ns()

    def _on_frame_trace(self, _sid: int, msg_id: int, body: bytes) -> None:
        """Stamp the sampled trace sidecar with proxy in/out times and fan
        it out exactly like _transpond would — re-encoded, since the
        header mutates in flight."""
        arrival = self._relay_arrival_ns
        base = MsgBase.decode(body)
        try:
            ctx = decode_trace(base.msg_data)
        except TraceError:
            return  # malformed sidecar: drop, never crash the edge
        targets = base.player_client_list or (
            [base.player_id] if base.player_id is not None else []
        )
        ctx.proxy_in_ns = arrival
        ctx.proxy_out_ns = _time.perf_counter_ns()
        base.msg_data = encode_trace(ctx)
        out = base.encode()
        for ident in targets:
            conn_id = self._client_conn.get(_ident_key(ident))
            if conn_id is not None:
                self.server.send_raw(conn_id, msg_id, out)
        self.traces_relayed += 1
        done = _time.perf_counter_ns()
        self.games.counters.count_relay(msg_id, done - arrival)
        self._relay_hist.observe((done - arrival) / 1e9)

    def _transpond(self, _sid: int, msg_id: int, body: bytes) -> None:
        """Deliver the enveloped message to each client in the envelope's
        client list (empty list → the envelope's player_id).  The whole
        MsgBase goes through unchanged, exactly like the reference's
        `SendMsgWithOutHead(nMsgID, msg, nLen)` — clients always unwrap.

        Pre-assembled frame scatter (ISSUE 13): the game already encoded
        the envelope once for ALL recipients, so the relay's only job is
        routing.  `scan_envelope_targets` walks the header fields without
        materializing msg_data (the frame payload — the big part) or
        per-client Ident objects; the SAME `body` buffer is handed to
        every connection.  Per-frame relay cost is O(clients) dict
        lookups, independent of payload size."""
        try:
            keys = scan_envelope_targets(body)
        except (ValueError, IndexError):
            # torn envelope: the tolerant object decode decides (and
            # keeps the drop semantics identical to the legacy path)
            base = MsgBase.decode(body)
            keys = [
                _ident_key(i)
                for i in (base.player_client_list
                          or ([base.player_id]
                              if base.player_id is not None else []))
            ]
        for key in keys:
            conn_id = self._client_conn.get(key)
            if conn_id is not None:
                self.server.send_raw(conn_id, msg_id, body)
        # per-opcode forward-latency attribution (ISSUE 7 satellite):
        # dispatch-tap arrival → fan-out complete, two clock reads
        done = _time.perf_counter_ns()
        self.games.counters.count_relay(msg_id, done - self._relay_arrival_ns)
        self._relay_hist.observe((done - self._relay_arrival_ns) / 1e9)

    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        super().execute(now)
        self._parking_pump(now)

    def _parking_pump(self, now: float) -> None:
        """Per-pump parking maintenance — strictly non-blocking (nf-lint
        `pump-surface` contract, docs/LINT.md): retry replay for
        sessions whose binding healed without a switch-route (e.g. the
        origin game revived on the same id), expire deadline-overdue
        frames, and tell affected clients what was lost."""
        if self.parking.depth() == 0:
            return
        for key in self.parking.keys():
            info = self._conn_info.get(key)
            if info is None:
                self.parking.discard(key)  # client already gone
                continue
            gid = info.get("game_id")
            if gid is None:
                continue
            sd = self.games.servers.get(int(gid))
            if sd is not None and sd.state == NORMAL:
                self.parking.replay(
                    key,
                    lambda m, b, g=int(gid):
                        self.games.send_by_server_id(g, m, b),
                )
        depths = {k: self.parking.depth(k) for k in self.parking.keys()}
        if self.parking.expire(now):
            for key, depth in depths.items():
                if self.parking.depth(key) < depth and isinstance(key, int):
                    info = self._conn_info.get(key)
                    gid = (info or {}).get("game_id") or 0
                    self._notify_switch(
                        key, SwitchNoticeCode.DROPPED, int(gid),
                        self.RETRY_AFTER_MS,
                    )

    def report(self):
        r = super().report()
        ext = r.server_info_list_ext
        h = self._relay_hist
        if h.count > 0:
            ext.key.append(b"relay_p50_ms")
            ext.value.append(
                f"{h.percentile(50.0) * 1e3:.4f}".encode())
            ext.key.append(b"relay_p95_ms")
            ext.value.append(
                f"{h.percentile(95.0) * 1e3:.4f}".encode())
        ext.key.append(b"traces_relayed")
        ext.value.append(str(self.traces_relayed).encode())
        ext.key.append(b"parked_frames")
        ext.value.append(str(self.parking.depth()).encode())
        return r
