"""Game role: the kernel-backed world server behind the proxy.

Reference: NFGameServerNet_ServerPlugin + NFGameServerNet_ClientPlugin —
accepts proxy connections and serves ~30 message handlers (enter/leave
game, role CRUD, swap scene, move, chat;
`NFCGameServerNet_ServerModule.cpp:31-73`), registers at World with 10 s
reports (`NFCGameServerToWorldModule.cpp:34-130`), and binds the
scene/AOI callbacks so property & record changes serialize into `NFMsg`
sync messages sent via the proxy with explicit client lists
(`OnPropertyEnter` `:271-400` and the §3.3 data-flow spine).

TPU inversion: instead of per-write callbacks, the role pulls each tick's
flag-masked diff masks off the device (already reduced by the jit'd step)
and fans the changed cells out as grouped property-sync messages to every
player in the broadcast set — one device fetch per bank per tick instead
of one callback per write.
"""

from __future__ import annotations

import dataclasses
import json as _json
import os
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...core.datatypes import Bank, DataType, Guid
from ...telemetry.pipeline import (
    StageClock,
    TraceContext,
    TraceError,
    decode_trace,
    encode_trace,
    stage_timing_enabled,
    trace_sample_n,
)
from ...core.store import RecordOp
from ...game.world import GameWorld, WorldConfig
from ...kernel.kernel import (
    ObjectEvent,
    REC_ADDED,
    REC_REMOVED,
    REC_UPDATED,
    TickOutputs,
)
from ...utils.hostio import gather_rows
from ...persist.codec import (
    record_row_struct,
    serialize_properties,
    serialize_records,
    snapshot_object,
)
from ..defines import TRACE_MSG_IDS, EventCode, MsgID, ServerState, ServerType
from ..transport import EV_DISCONNECTED
from ..wire import (
    AckEventResult,
    ServerInfoExt,
    AckPlayerEntryList,
    AckPlayerLeaveList,
    AckRoleLiteInfoList,
    Ident,
    Message,
    MsgBase,
    ObjectPropertyFloat,
    ObjectPropertyInt,
    ObjectPropertyList,
    ObjectPropertyObject,
    ObjectPropertyString,
    ObjectPropertyVector2,
    ObjectPropertyVector3,
    ObjectRecordAddRow,
    ObjectRecordBase,
    ObjectRecordFloat,
    ObjectRecordInt,
    ObjectRecordList,
    ObjectRecordObject,
    ObjectRecordRemove,
    ObjectRecordString,
    ObjectRecordVector3,
    PlayerEntryInfo,
    PropertyFloat,
    PropertyInt,
    PropertyObject,
    PropertyString,
    PropertyVector2,
    PropertyVector3,
    RecordAddRowStruct,
    RecordFloat,
    RecordInt,
    RecordObject,
    RecordString,
    RecordVector3,
    ReqAcceptTask,
    ReqAckCreateGuild,
    ReqAckCreateTeam,
    ReqAckJoinGuild,
    ReqAckJoinTeam,
    ReqAckLeaveGuild,
    ReqAckLeaveTeam,
    ReqAckOprTeamMember,
    ReqAckPlayerChat,
    ReqAckPlayerMove,
    ReqAckSwapScene,
    ReqAckUseItem,
    ReqAckUseSkill,
    ReqCompeleteTask,
    ReqCreateRole,
    ReqDeleteRole,
    ReqEnterGameServer,
    ReqRoleList,
    ReqSearchGuild,
    ReqSetFightHero,
    ReqSwitchServer,
    ReqWearEquip,
    AckSearchGuild,
    AckSwitchServer,
    RoleLiteInfo,
    SearchGuildObject,
    SwitchServerData,
    TakeOffEquip,
    TeamInfo,
    TeammemberInfo,
    Vector2,
    Vector3,
    ident_key as _ident_key,
    unwrap,
    wrap,
)
from .base import RoleConfig, ServerRole

_IdentKey = Tuple[int, int]

# row-identified wire targets (hero/equip record rows) ride Ident.index
# with THIS svrid tag — row 0 is valid, and protoc clients always send
# the required field (zeroed when untargeted), so a plain falsy test on
# the index cannot discriminate "no target" from "row 0"
ROW_TARGET_SVRID = 1


def guid_ident(g: Guid) -> Ident:
    """GUID ↔ wire Ident (`NFMsgBase.proto` Ident{svrid,index})."""
    return Ident(svrid=g.head, index=g.data)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass
class Session:
    ident: Ident
    conn_id: int  # proxy connection that owns this client
    account: str = ""
    guid: Optional[Guid] = None


class GameRole(ServerRole):
    server_type = int(ServerType.GAME)

    def __init__(
        self,
        config: RoleConfig,
        backend: str = "auto",
        world: Optional[GameWorld] = None,
        scene_id: int = 1,
        sync_classes: Sequence[str] = ("Player", "NPC"),
        skill_damage: int = 10,
        data_agent=None,
        role_store=None,
        autosave_seconds: float = 30.0,
        cross_server_sync: bool = True,
        batch_sync_min: int = 256,
        interest_radius: Optional[float] = None,
        serve_batch: Optional[bool] = None,
        serve_overlap: Optional[bool] = None,
        tick_train: Optional[int] = None,
        checkpoint_dir=None,
        checkpoint_seconds: float = 30.0,
        resume: bool = False,
        journal_dir=None,
        journal_segment_bytes: int = 1 << 20,
        persist_store=None,
        persist_wal_dir=None,
        persist_drain_timeout: float = 3.0,
    ) -> None:
        # (class, prop) diffs with >= batch_sync_min changed rows go out
        # as ONE columnar ACK_BATCH_PROPERTY message per (cell, conn)
        # instead of per-entity messages — the served-path fast lane
        self.batch_sync_min = batch_sync_min
        # with a radius, Position leaves on the per-session interest
        # stream instead (u16-quantized, delta-gated, device-filtered):
        # each client gets only entities within `interest_radius` of its
        # avatar — group-granular broadcast is full-world fan-out when a
        # group is busy (round-3: 24.5 MB/frame at 100k / 500 sessions)
        self.interest_radius = interest_radius
        # Verlet skin for the interest grids (NF_VERLET_SKIN knob,
        # ops/verlet.py): > 0 inflates the interest cell size to
        # radius + skin and amortizes the per-flush argsort across
        # flushes via a displacement-gated cache carried in
        # WorldState.aux ("verlet/interest/<class>")
        from ...ops.verlet import skin_from_env

        self._interest_skin = (
            float(skin_from_env()) if interest_radius is not None else 0.0
        )
        self._interest_jit: Dict[Tuple[str, int], object] = {}
        # classes with a create/destroy since the last interest flush
        # (visible sets can change without any Position diff)
        self._interest_dirty: set = set()
        self._last_obs_sig: Optional[tuple] = None
        # --- batched serving edge (ISSUE 13) -------------------------
        # NF_SERVE_BATCH=1 swaps the per-session Python serve loops for
        # one vmap-over-sessions device kernel (ops/serving.py) plus
        # SoA host assembly (net/serving.py).  NF_SERVE_OVERLAP=1
        # (implies batch) additionally double-buffers the serve
        # snapshot: the interest Position lane is computed against the
        # PRE-tick state and its assembly/encode/send overlaps the
        # device tick — clients see those diffs exactly one tick later
        # (bounded staleness <= 1 tick, journaled in the run meta).
        def _env_flag(name: str, explicit: Optional[bool]) -> bool:
            if explicit is not None:
                return bool(explicit)
            return os.environ.get(name, "0") == "1"

        self.serve_overlap = (
            _env_flag("NF_SERVE_OVERLAP", serve_overlap)
            and interest_radius is not None
        )
        self.serve_batch = self.serve_overlap or (
            _env_flag("NF_SERVE_BATCH", serve_batch)
            and interest_radius is not None
        )
        # --- K-tick trains (ISSUE 20) --------------------------------
        # NF_TICK_TRAIN=K (K >= 2) runs the device tick as one K-frame
        # lax.scan megadispatch per due frame: every host-consumed lane
        # comes back stacked [K, ...] (kernel.TRAIN_LANE_SPEC), fetched
        # once, and fanned out in tick order — journal digest marks,
        # death attribution and counters stay per-tick exact at 1/K the
        # dispatch+fetch cost.  Election: trains need K >= 2 and lose
        # to overlap mode (overlap serves each frame against the
        # pre-tick snapshot; inside a train there is no between-frame
        # host window), so NF_SERVE_OVERLAP=1 keeps K at 1.  The
        # resulting staleness contract (clients see a burst of K frames
        # per train, i.e. diffs up to K-1 ticks old) is journaled like
        # the overlap contract so replay honors the same engine.
        k_train = (int(tick_train) if tick_train is not None
                   else _env_int("NF_TICK_TRAIN", 0))
        self.tick_train = k_train if (k_train >= 2
                                      and not self.serve_overlap) else 0
        from ..serving import SessionTable

        self._session_table = SessionTable()
        self._serve_jit: Dict[tuple, object] = {}
        # per-class device position-version state (role-held, NOT kernel
        # aux: kernel.invalidate() drops aux on recompile, but versions
        # must survive recompiles or every client would get a full
        # resend) — cname -> (qver [C] i32, prev_q [C,3] i32)
        self._serve_qver: Dict[str, tuple] = {}
        # host-side guid mirrors as of the LAST serve run: gone lists
        # name entities whose rows may already be freed (guid zeroed in
        # the live arrays), so the wire payload gathers from these
        self._serve_prev_guids: Dict[str, tuple] = {}
        # overlap mode: deferred Position-lane inputs from last frame
        self._serve_pending: Dict[str, object] = {}
        self.game_world = world if world is not None else GameWorld(
            WorldConfig(combat=False, movement=False, regen=True)
        ).start()
        self.kernel = self.game_world.kernel
        if self.tick_train:
            self.kernel.configure_train(self.tick_train)
        self.scene = self.game_world.scene
        self.scene_id = scene_id
        self.sync_classes = tuple(sync_classes)
        self.skill_damage = skill_damage
        if scene_id not in self.scene.scenes:
            self.scene.create_scene(scene_id)
        info = self.scene.scenes[scene_id]
        if 1 not in info.groups:
            self.scene.request_group(scene_id)
        # sessions by client ident; reverse map guid -> ident key
        self.sessions: Dict[_IdentKey, Session] = {}
        self._guid_session: Dict[Guid, _IdentKey] = {}
        # account -> role rows; backed by role_store when one is attached
        self.roles: Dict[str, List[RoleLiteInfo]] = {}
        self.role_store = role_store
        self.data_agent = data_agent
        self._last_tick = 0.0
        self.autosave_seconds = autosave_seconds
        self._last_autosave = 0.0
        # crash recovery: periodic atomic whole-world checkpoints
        # (persist/checkpoint.py) + resume-on-boot; re-registration with
        # world/master happens through the normal on-connect path
        from pathlib import Path as _Path

        self.checkpoint_dir = _Path(checkpoint_dir) if checkpoint_dir else None
        self.checkpoint_seconds = checkpoint_seconds
        self._last_checkpoint = 0.0
        # many-worlds room directory (parallel/rooms.py), attached via
        # attach_rooms(); None = this role serves its single GameWorld
        self.rooms = None
        # flight recorder (replay/journal.py): when a journal dir is
        # given, every dispatched net event + a per-tick on-device state
        # digest is logged so the run can be re-executed offline.  The
        # digest must be baked into the compiled tick, so flip it on
        # BEFORE anything can trigger the first compile.
        self.journal = None
        self._journal_dir = _Path(journal_dir) if journal_dir else None
        self._journal_segment_bytes = int(journal_segment_bytes)
        if self._journal_dir is not None:
            self.kernel.enable_digest()
        super().__init__(config, backend=backend)
        reg = self.telemetry.registry
        self._ckpt_counter = reg.counter(
            "nf_checkpoints_total", "atomic world checkpoints written"
        )
        self._reshard_resets = reg.counter(
            "nf_reshard_view_resets_total",
            "session views force-reset because a reshard moved their rows"
        )
        self._recover_counter = reg.counter(
            "nf_recoveries_total", "world restores from checkpoint (resume)"
        )
        if resume and self.checkpoint_dir is not None:
            if (self.checkpoint_dir / "meta.json").exists():
                # restores device banks + host identity; a torn pair
                # raises (load_world's array_tick guard) rather than
                # resuming a corrupt world
                self.game_world.load(self.checkpoint_dir)
                self._recover_counter.inc()
            # no checkpoint yet -> cold start
        # world-tick latency, separate from the pump's frame histogram
        # (a pump frame with no tick due is ~free; mixing them would
        # drown the tick percentiles in poll noise)
        self._tick_hist = self.telemetry.registry.histogram(
            "nf_game_tick_seconds", "world tick latency (kernel + modules)"
        )
        self.world_link = self.add_upstream(
            "world",
            [t for t in config.targets if t.server_type == int(ServerType.WORLD)],
            register_msg=MsgID.GTW_GAME_REGISTERED,
            refresh_msg=MsgID.STS_SERVER_REPORT,
        )
        # world relay: public Player state forwarded up; remote games' sync
        # delivered to local clients (cross-game visibility without the
        # reference's world-side object mirror — the batched messages relay
        # verbatim; NFCWorldNet_ServerModule.cpp:600-830)
        self.cross_server_sync = cross_server_sync
        if cross_server_sync:  # gate BOTH directions (isolated realms)
            from .world import CROSS_SYNC_MSGS

            for msg in CROSS_SYNC_MSGS:
                self.world_link.on(msg, self._on_world_sync)
        # PVP rooms minted by matchmaking, pending their ectype step
        self._pvp_rooms: Dict = {}
        # cross-game-server switch (NFCGSSwichServerModule): staged blobs
        # by player ident, world-link handlers for the re-home protocol
        self._switch_blobs: Dict = {}
        self.world_link.on(MsgID.SWITCH_SERVER_DATA, self._on_switch_data)
        self.world_link.on(MsgID.REQ_SWITCH_SERVER, self._on_switch_in)
        self.world_link.on(MsgID.ACK_SWITCH_SERVER, self._on_switch_ack)
        # a playable default stat table when the deployment didn't load one
        # (reference ships Property*.xlsx configs; LevelModule refreshes the
        # JOBLEVEL stat row from it on level-up)
        pc = self.game_world.property_config
        if not np.any(pc._base):
            pc.fill_linear(
                0,
                base={"MAXHP": 100, "MAXMP": 50, "MAXSP": 50, "HPREGEN": 1,
                      "ATK_VALUE": 10, "DEF_VALUE": 5, "MOVE_SPEED": 30000},
                per_level={"MAXHP": 20, "ATK_VALUE": 2, "DEF_VALUE": 1},
            )
            pc.freeze()
        if self.data_agent is not None:
            # bind BEFORE our own class-event hooks so load-on-create runs
            # inside the COE chain ahead of the enter-scene snapshot
            self.data_agent.bind(self.kernel)
        self.kernel.register_class_event(self._on_class_event, "Player")
        self.kernel.register_class_event(self._on_npc_event, "NPC")
        # subscribe every public OR private property of the synced classes;
        # the kernel fires these for host writes synchronously AND from the
        # device diff masks after each tick — one mechanism for the whole
        # spine.  Public changes broadcast to the (scene, group); private-
        # only changes go to the owner's client (GetBroadCastObject
        # semantics, NFCSceneAOIModule.cpp:531-593).
        self._changed: Dict[Tuple[str, str], np.ndarray] = {}
        for cname in self.sync_classes:
            spec = self.kernel.store.spec(cname)
            for slot in spec.slots.values():
                if slot.prop.public or slot.prop.private:
                    self.kernel.register_property_event(
                        cname, slot.prop.name, self._queue_change
                    )
        # record sync: host per-op hooks + device record diffs feed one
        # accumulator, flushed per frame (the round-1 gap: bag/equip/buff
        # changes mid-session never reached clients;
        # reference NFCGameServerNet_ServerModule.cpp:75-81)
        # (cname, rname) -> {"add": set, "del": set, "upd": dict, "swap": list}
        self._rec_changed: Dict[Tuple[str, str], Dict[str, object]] = {}
        self.kernel.subscribe_record_host(self._on_record_host)
        self._synced_records: Dict[Tuple[str, str], bool] = {}  # -> public?
        for cname in self.sync_classes:
            spec = self.kernel.store.spec(cname)
            for rname, rs in spec.records.items():
                d = rs.rec
                if d.public or d.private or d.upload:
                    self._synced_records[(cname, rname)] = bool(d.public)
                    self.kernel.register_record_diff(
                        cname, rname, self._on_record_diff
                    )
        if self.interest_radius is not None:
            # creates/destroys change visible sets without a Position
            # diff — mark the class dirty so the gated interest flush runs
            def _mark_dirty(_g: Guid, cn: str, _ev) -> None:
                self._interest_dirty.add(cn)

            for cname in self.sync_classes:
                if self._interest_ok(cname):
                    self.kernel.register_class_event(_mark_dirty, cname)
        if self._journal_dir is not None:
            from ...ops.verlet import skin_from_env
            from ...replay.journal import (
                JournalWriter,
                SRC_SERVER,
                SRC_WORLD,
            )

            cfg = self.game_world.config
            # guid allocation is wall-clock seeded (epoch micros); wire
            # messages CARRY guids back into mutating handlers (e.g. the
            # switch ack destroys by guid), so an unpinned clock is a
            # hidden replay input.  Pin the allocator to a pure counter
            # from here on and journal the seed — replay pins the
            # offline role to the same point and every post-pin guid
            # comes out bit-identical (ISSUE 10)
            guid_seed = self.kernel.store.guids.pin()
            self.journal = JournalWriter(
                self._journal_dir,
                segment_bytes=self._journal_segment_bytes,
                meta={
                    "server_id": config.server_id,
                    "name": config.name,
                    "world_seed": cfg.seed,
                    "dt": cfg.dt,
                    "start_tick": self.kernel.tick_count,
                    "resumed": bool(resume),
                    "verlet_skin": float(skin_from_env()),
                    "guid_seed": int(guid_seed),
                    # serving-edge staleness contract: with overlap on,
                    # the interest Position lane serves the PRE-tick
                    # snapshot (clients run <= 1 tick behind); replay
                    # must honor the same engine to stay digest-clean
                    "serve_batch": bool(self.serve_batch),
                    "serve_overlap": bool(self.serve_overlap),
                    # trains deliver diffs/events in a burst after each
                    # K-tick megadispatch: staleness <= K-1 ticks.  The
                    # per-tick marks are stamped from in-lane tick
                    # numbers, so replay (one real tick per mark) is
                    # bit-identical with the knob flipped either way.
                    "tick_train": int(self.tick_train),
                    "serve_staleness_ticks": (
                        self.tick_train - 1 if self.tick_train
                        else (1 if self.serve_overlap else 0)
                    ),
                },
            )
            # tap BOTH dispatch choke points: client/proxy traffic on the
            # listening server, world commands/switches on the world link
            # — together with the tick marks this is the complete
            # host→device input stream
            self.server.dispatch.tap = self._journal_tap(SRC_SERVER)
            self.world_link.dispatch.tap = self._journal_tap(SRC_WORLD)
            reg = self.telemetry.registry
            self._jrn_bytes = reg.counter(
                "nf_journal_bytes_total", "flight-recorder bytes appended"
            )
            self._jrn_segments = reg.counter(
                "nf_journal_segments_total", "flight-recorder segments opened"
            )
            self._jrn_ticks = reg.counter(
                "nf_journal_ticks_total", "ticks journaled with a digest"
            )
            self._jrn_sampled = [0, 0, 0]  # bytes, segments, ticks
            self._journal_pump_counters()
        # write-behind durability (persist/writebehind.py): per-tick
        # Save-flagged diffs stream to the store off-thread, staged in a
        # crash-safe WAL.  Built from kwargs (not passed in ready-made)
        # so LocalCluster.revive_role's kwargs replay reconstructs the
        # pipeline over the SAME wal dir and recovers queued batches.
        self.persist = None
        self._persist_drain_timeout = float(persist_drain_timeout)
        self._persist_dirty: set = set()
        self._persist_class = None
        self._save_props: set = set()
        self._save_records: set = set()
        if persist_store is not None and persist_wal_dir is not None:
            from ...persist.writebehind import WriteBehindPipeline

            self.persist = WriteBehindPipeline(
                persist_store, persist_wal_dir,
                registry=self.telemetry.registry,
                name=f"game{config.server_id}",
            )
            if self.data_agent is not None:
                self.data_agent.pipeline = self.persist
                self._persist_class = self.data_agent.class_name
                spec = self.kernel.store.spec(self._persist_class)
                for slot in spec.slots.values():
                    p = slot.prop
                    if not p.flag("save"):
                        continue
                    self._save_props.add(p.name)
                    # own subscriber: harvest is independent of which
                    # props the sync spine happens to watch
                    self.kernel.register_property_event(
                        self._persist_class, p.name, self._persist_prop_change
                    )
                    if not (p.public or p.upload):
                        # save-only columns aren't in diff_flags: opt
                        # them into device diff extraction or tick-path
                        # writes would never mark them dirty
                        self.kernel.force_diff_property(
                            self._persist_class, p.name
                        )
                for rname, rs in spec.records.items():
                    if rs.rec.flag("save"):
                        self._save_records.add(rname)
                        self.kernel.register_record_diff(
                            self._persist_class, rname,
                            self._persist_rec_diff,
                        )
                self.kernel.subscribe_record_host(self._persist_rec_host)
        # frame observatory (ISSUE 7): per-frame exclusive stage clock
        # over the served path (tick → harvest → interest → encode →
        # send) + sampled wire trace state.  NF_STAGE_TIMING=1 flips the
        # kernel into honest per-stage device timing; NF_TRACE_SAMPLE=N
        # traces 1-in-N sessions (0 disables).
        self.stage_clock = StageClock(self.telemetry.registry)
        # serving-edge metrics (docs/OBSERVABILITY.md): dispatch count,
        # sessions covered per dispatch, emitted packets, deferred-lane
        # frames (overlap) — the assemble stage histogram itself comes
        # from StageClock ("nf_stage_assemble_seconds")
        sreg = self.telemetry.registry
        self._serve_dispatches = sreg.counter(
            "nf_serve_dispatches_total",
            "batched serve-kernel dispatches (per class, per chunk)",
        )
        self._serve_packets = sreg.counter(
            "nf_serve_packets_total",
            "per-session packets emitted by the batched serve edge",
        )
        self._serve_deferred = sreg.counter(
            "nf_serve_deferred_frames_total",
            "frames whose interest lane was served one tick late (overlap)",
        )
        self._serve_sessions_hist = sreg.histogram(
            "nf_serve_sessions",
            "sessions covered by one batched serve dispatch",
        )
        # K-tick train accounting (mirrors kernel.train_* — counted
        # here so bare-kernel benches still track their own ints)
        self._train_dispatches = sreg.counter(
            "nf_train_dispatches_total",
            "K-tick train megadispatches (one scan program per count)",
        )
        self._train_ticks = sreg.counter(
            "nf_train_ticks_total",
            "logical ticks advanced inside train dispatches",
        )
        self._train_fetch_bytes = sreg.counter(
            "nf_train_fetch_bytes_total",
            "stacked [K, ...] summary-lane bytes fetched per train",
        )
        self._stage_timing = stage_timing_enabled()
        self.kernel.stage_timing = self._stage_timing
        self._trace_sample = trace_sample_n()
        self._trace_seq = 0
        self._trace_pending: Dict[int, Tuple[int, int]] = {}
        self.trace_sent = 0
        self.trace_acked = 0
        self.last_trace: Optional[dict] = None
        treg = self.telemetry.registry
        self._trace_rtt_hist = treg.histogram(
            "nf_trace_rtt_seconds",
            "frame-trace round trip: encode → client ack received",
        )
        self._trace_relay_hist = treg.histogram(
            "nf_trace_proxy_relay_seconds",
            "proxy in→out relay of sampled frame traces (proxy clock)",
        )

    def _persist_prop_change(self, cname: str, pname: str, rows) -> None:
        self._persist_dirty.update(int(r) for r in rows)

    def _persist_rec_diff(self, cname: str, rname: str, codes) -> None:
        self._persist_dirty.update(int(e) for e in np.nonzero(
            np.any(codes != 0, axis=1))[0])

    def _persist_rec_host(self, cname, rname, op, erows, rec_row, tags) -> None:
        if cname == self._persist_class and rname in self._save_records:
            self._persist_dirty.update(int(e) for e in erows)

    def _persist_harvest(self) -> None:
        """Stage this tick's dirty Save-flagged entities into the
        write-behind queue as one coalesced batch.  Pump-thread only;
        never touches the store (the flusher owns every store call)."""
        tick = self.kernel.tick_count
        rows, self._persist_dirty = self._persist_dirty, set()
        if rows:
            agent = self.data_agent
            host = self.kernel.store._hosts[self._persist_class]
            k = self.kernel
            items = {}
            for r in sorted(rows):
                g = host.row_guid[r] if r < len(host.row_guid) else None
                if g is None:
                    continue  # died this tick; the destroy hook saved it
                key = agent._key_of(g)
                if key is None:
                    continue
                items[key] = snapshot_object(k.store, k.state, g, agent.flags)
            if items:
                self.persist.enqueue(tick, items)
        self.persist.note_tick(tick)
        self.persist.pump()

    def _journal_tap(self, source: int):
        def tap(ev) -> None:
            j = self.journal
            # frame-trace sidecars (TRACE_MSG_IDS) are pure observability
            # and never touch device state: journaling them would make
            # the recorded input stream — and thus replay byte-identity —
            # depend on whether tracing was sampled that run
            if j is not None and ev.msg_id not in TRACE_MSG_IDS:
                j.event(source, ev.kind, ev.conn_id, ev.msg_id, ev.body)
        return tap

    def _journal_pump_counters(self) -> None:
        """Fold the writer's monotonic totals into the registry as
        deltas (counters only go up; the writer is the source of
        truth)."""
        j = self.journal
        vals = (j.bytes_total, j.segments_total, j.ticks_total)
        for counter, new, i in zip(
            (self._jrn_bytes, self._jrn_segments, self._jrn_ticks),
            vals, range(3),
        ):
            d = new - self._jrn_sampled[i]
            if d:
                counter.inc(d)
                self._jrn_sampled[i] = new

    def journal_note(self, **info) -> None:
        """Drop an epoch marker into the journal (chaos seed + link
        budgets, config flips) — no-op when not recording."""
        if self.journal is not None:
            self.journal.note(info)

    def report(self):
        """Heartbeat report, extended with write-behind health: lag +
        degraded ride the ext map to the master's /json and status page
        (the SUSPECT-surfacing leg of the durability story), and a
        degraded store flips the advertised state to BUSY so balancers
        steer new logins elsewhere while the world stays up."""
        if self.persist is not None and self.state in (
                int(ServerState.NORMAL), int(ServerState.BUSY)):
            self.state = (int(ServerState.BUSY) if self.persist.degraded()
                          else int(ServerState.NORMAL))
        r = super().report()
        ext = r.server_info_list_ext
        if ext is None:
            ext = ServerInfoExt()
            r.server_info_list_ext = ext
        if self.persist is not None:
            for k, v in (
                ("persist_lag_ticks", self.persist.lag_ticks()),
                ("persist_queue_depth", self.persist.queue_depth()),
                ("persist_degraded", int(self.persist.degraded())),
                # durable-media locations for the world's failover
                # driver (ISSUE 10): when THIS role dies, the world
                # reconstructs its players' blobs read-only from here
                ("wal_dir", str(self.persist.wal.path)),
            ):
                ext.key.append(k.encode())
                ext.value.append(str(v).encode())
        if self.checkpoint_dir is not None:
            ext.key.append(b"ckpt_dir")
            ext.value.append(str(self.checkpoint_dir).encode())
        # frame-pipeline attribution blob: the master's /pipeline route
        # parses this into the cluster-wide stage waterfall
        ext.key.append(b"pipeline")
        ext.value.append(_json.dumps(self.pipeline_stats()).encode())
        # compiled-cost heartbeat: compact CostBook summary (per-entry
        # compiles/recompiles/flops/bytes + HBM live/peak) — the master's
        # /costbook route aggregates these into the cluster view
        ext.key.append(b"costbook")
        ext.value.append(
            _json.dumps(self.kernel.costbook.summary()).encode())
        # many-worlds occupancy blob: slot totals + per-room placement,
        # surfaced on the master's /json like pipeline/costbook
        if self.rooms is not None:
            ext.key.append(b"rooms")
            ext.value.append(_json.dumps(self.rooms.status()).encode())
        return r

    def pipeline_stats(self) -> dict:
        """Stage waterfall + wire-trace summary for /pipeline and bench."""
        sc = self.stage_clock
        out = {
            "frames": sc.frames,
            "last_tick": sc.last_tick,
            "last_wall_ms": round(sc.last_wall_ns / 1e6, 4),
            "last_ms": {k: round(v / 1e6, 4) for k, v in sc.last.items()},
            "stages": sc.stats(),
            "trace": {
                "sample": self._trace_sample,
                "sent": self.trace_sent,
                "acked": self.trace_acked,
                "pending": len(self._trace_pending),
            },
        }
        if self._trace_rtt_hist.count:
            out["trace"]["rtt_p50_ms"] = round(
                self._trace_rtt_hist.percentile(50.0) * 1e3, 4)
            out["trace"]["rtt_p95_ms"] = round(
                self._trace_rtt_hist.percentile(95.0) * 1e3, 4)
        if self._trace_relay_hist.count:
            out["trace"]["relay_p50_ms"] = round(
                self._trace_relay_hist.percentile(50.0) * 1e3, 4)
        return out

    def _install(self) -> None:
        s = self.server
        s.on(MsgID.REQ_ROLE_LIST, self._on_role_list)
        s.on(MsgID.REQ_CREATE_ROLE, self._on_create_role)
        s.on(MsgID.REQ_DELETE_ROLE, self._on_delete_role)
        s.on(MsgID.REQ_ENTER_GAME, self._on_enter_game)
        s.on(MsgID.REQ_LEAVE_GAME, self._on_leave_game)
        s.on(MsgID.REQ_SWAP_SCENE, self._on_swap_scene)
        s.on(MsgID.REQ_MOVE, self._on_move)
        s.on(MsgID.REQ_CHAT, self._on_chat)
        s.on(MsgID.REQ_SKILL_OBJECTX, self._on_skill)
        s.on(MsgID.REQ_SET_FIGHT_HERO, self._on_set_fight_hero)
        s.on(MsgID.REQ_SWITCH_SERVER, self._on_client_switch)
        s.on(MsgID.REQ_ITEM_OBJECT, self._on_use_item)
        s.on(MsgID.WEAR_EQUIP, self._on_wear_equip)
        s.on(MsgID.TAKEOFF_EQUIP, self._on_takeoff_equip)
        s.on(MsgID.REQ_ACCEPT_TASK, self._on_accept_task)
        s.on(MsgID.REQ_COMPLETE_TASK, self._on_complete_task)
        s.on(MsgID.REQ_CREATE_TEAM, self._on_create_team)
        s.on(MsgID.REQ_JOIN_TEAM, self._on_join_team)
        s.on(MsgID.REQ_LEAVE_TEAM, self._on_leave_team)
        s.on(MsgID.REQ_OPRMEMBER_TEAM, self._on_opr_team_member)
        s.on(MsgID.REQ_CREATE_GUILD, self._on_create_guild)
        s.on(MsgID.REQ_JOIN_GUILD, self._on_join_guild)
        s.on(MsgID.REQ_LEAVE_GUILD, self._on_leave_guild)
        s.on(MsgID.REQ_SEARCH_GUILD, self._on_search_guild)
        s.on(MsgID.REQ_CMD_NORMAL, self._on_gm_command)
        s.on(MsgID.REQ_PVP_APPLY_MATCH, self._on_pvp_apply)
        s.on(MsgID.REQ_CREATE_PVP_ECTYPE, self._on_pvp_create_ectype)
        s.on(MsgID.REQ_BUY_FORM_SHOP, self._on_slg_buy)
        s.on(MsgID.REQ_MOVE_BUILD_OBJECT, self._on_slg_move)
        s.on(MsgID.REQ_UP_BUILD_LVL, self._on_slg_upgrade)
        s.on(MsgID.REQ_CREATE_ITEM, self._on_slg_create_item)
        s.on(MsgID.REQ_BUILD_OPERATE, self._on_slg_operate)
        s.on(MsgID.FRAME_TRACE_ACK, self._on_frame_trace_ack)
        s.on_socket_event(self._on_socket)

    def cur_count(self) -> int:
        return len(self.sessions)

    # ------------------------------------------------------------ sending
    def _send_to(self, idents: Sequence[Ident], conn_id: int, msg_id: int,
                 msg: Message) -> None:
        # "send" stage = envelope encode + transport write; add_ns keeps
        # it exclusive of whichever stage (interest/encode) called us
        t0 = _time.perf_counter_ns()
        self.server.send_raw(
            conn_id, int(msg_id), wrap(msg, clients=list(idents))
        )
        self.stage_clock.add_ns("send", _time.perf_counter_ns() - t0)

    # ------------------------------------------------------ wire tracing
    def _emit_frame_traces(self) -> None:
        """End of a flushed frame: send the sampled sessions a FRAME_TRACE
        sidecar.  TCP ordering puts it *behind* the frame's sync traffic
        on the same connection, so the acked round trip upper-bounds the
        frame's true delivery latency."""
        n = self._trace_sample
        for sess in self.sessions.values():
            if sess.ident.index % n:
                continue
            self._trace_seq = (self._trace_seq + 1) & 0xFFFFFFFF
            seq = self._trace_seq
            t_enc = _time.perf_counter_ns()
            ctx = TraceContext(tick=self.kernel.tick_count,
                               game_id=self.config.server_id,
                               seq=seq, t_encode_ns=t_enc)
            self._trace_pending[seq] = (self.kernel.tick_count, t_enc)
            while len(self._trace_pending) > 4096:  # lost acks: drop oldest
                self._trace_pending.pop(next(iter(self._trace_pending)))
            base = MsgBase(player_id=sess.ident,
                           msg_data=encode_trace(ctx),
                           player_client_list=[sess.ident])
            self.server.send_raw(
                sess.conn_id, int(MsgID.FRAME_TRACE), base.encode()
            )
            self.trace_sent += 1

    def _on_frame_trace_ack(self, _conn_id: int, _msg_id: int,
                            body: bytes) -> None:
        """Client echoed the stamped header back: close the loop with
        same-clock deltas only — RTT on the game clock, relay on the
        proxy clock.  Never touches device state (replay identity)."""
        now_ns = _time.perf_counter_ns()
        base = MsgBase.decode(body)
        try:
            ctx = decode_trace(base.msg_data)
        except TraceError:
            return
        if ctx.game_id != self.config.server_id:
            return
        pend = self._trace_pending.pop(ctx.seq, None)
        if pend is None:
            return  # duplicate or aged out
        tick, t_enc = pend
        rtt_s = (now_ns - t_enc) / 1e9
        self._trace_rtt_hist.observe(rtt_s)
        relay_ms = None
        if ctx.proxy_out_ns and ctx.proxy_in_ns:
            relay_s = (ctx.proxy_out_ns - ctx.proxy_in_ns) / 1e9
            self._trace_relay_hist.observe(relay_s)
            relay_ms = round(relay_s * 1e3, 4)
        self.trace_acked += 1
        self.last_trace = {
            "tick": tick,
            "seq": ctx.seq,
            "rtt_ms": round(rtt_s * 1e3, 4),
            "proxy_relay_ms": relay_ms,
        }

    def _send_to_session(self, sess: Session, msg_id: int, msg: Message) -> None:
        self._send_to([sess.ident], sess.conn_id, msg_id, msg)

    def _broadcast(self, target_guids: Sequence[Guid], msg_id: int,
                   msg: Message, exclude: Optional[Guid] = None) -> None:
        """Fan a message out to the sessions of `target_guids`, grouping
        client idents per proxy connection (one envelope per proxy link —
        the multicast list the reference's Transpond expands)."""
        per_conn: Dict[int, List[Ident]] = {}
        for g in target_guids:
            if exclude is not None and g == exclude:
                continue
            key = self._guid_session.get(g)
            if key is None:
                continue
            sess = self.sessions.get(key)
            if sess is not None:
                per_conn.setdefault(sess.conn_id, []).append(sess.ident)
        for conn_id, idents in per_conn.items():
            self._send_to(idents, conn_id, msg_id, msg)

    def _scene_players(self, guid: Guid) -> List[Guid]:
        return self.scene.broadcast_targets(guid, public=True)

    # ------------------------------------------------------------ role CRUD
    def _session_for(self, conn_id: int, base: MsgBase) -> Session:
        key = _ident_key(base.player_id)
        sess = self.sessions.get(key)
        if sess is None:
            sess = Session(ident=base.player_id or Ident(), conn_id=conn_id)
            self.sessions[key] = sess
        sess.conn_id = conn_id
        return sess

    def _get_roles(self, account: str) -> List[RoleLiteInfo]:
        roles = self.roles.get(account)
        if roles is None:
            roles = (self.role_store.load(account)
                     if self.role_store is not None else [])
            self.roles[account] = roles
        return roles

    def _put_roles(self, account: str, roles: List[RoleLiteInfo]) -> None:
        self.roles[account] = roles
        if self.role_store is not None:
            self.role_store.save(account, roles)

    def _on_role_list(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqRoleList)
        sess = self._session_for(conn_id, base)
        sess.account = req.account.decode("utf-8", "replace") or sess.account
        ack = AckRoleLiteInfoList(char_data=self._get_roles(sess.account))
        self._send_to_session(sess, MsgID.ACK_ROLE_LIST, ack)

    def _on_create_role(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqCreateRole)
        sess = self._session_for(conn_id, base)
        account = req.account.decode("utf-8", "replace") or sess.account
        sess.account = account
        roles = self._get_roles(account)
        name = req.noob_name
        if any(r.noob_name == name for r in roles):
            code = int(EventCode.CHARACTER_EXIST)
        else:
            roles.append(
                RoleLiteInfo(
                    id=guid_ident(self.kernel.store.guids.next()),
                    career=req.career,
                    sex=req.sex,
                    race=req.race,
                    noob_name=name,
                    game_id=req.game_id,
                    role_level=1,
                )
            )
            self._put_roles(account, roles)
            code = int(EventCode.SUCCESS)
        self._send_to_session(
            sess, MsgID.EVENT_RESULT, AckEventResult(event_code=code)
        )
        # the reference replies with the refreshed role list either way
        ack = AckRoleLiteInfoList(char_data=roles)
        self._send_to_session(sess, MsgID.ACK_ROLE_LIST, ack)

    def _on_delete_role(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqDeleteRole)
        sess = self._session_for(conn_id, base)
        account = req.account.decode("utf-8", "replace") or sess.account
        remaining = [r for r in self._get_roles(account)
                     if r.noob_name != req.name]
        self._put_roles(account, remaining)
        if self.data_agent is not None:
            name = req.name.decode("utf-8", "replace")
            self.data_agent.delete(f"{account}:{name}")
        self._send_to_session(
            sess, MsgID.ACK_ROLE_LIST,
            AckRoleLiteInfoList(char_data=remaining),
        )

    # ------------------------------------------------------------ enter/leave
    def _on_enter_game(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqEnterGameServer)
        sess = self._session_for(conn_id, base)
        sess.account = req.account.decode("utf-8", "replace") or sess.account
        if sess.guid is not None:
            self._despawn(sess)  # re-entry replaces the old avatar
        name = req.name.decode("utf-8", "replace")
        store = self.kernel.store
        if store.live_count("Player") >= store.capacity("Player"):
            # world full: refuse gracefully BEFORE allocating, so no row
            # leaks and the pump keeps serving — the reference answers
            # with an event-result code on every enter-game failure path.
            # Other create failures propagate to the dispatch isolation
            # layer (logged + message dropped).
            self._send_to_session(
                sess,
                MsgID.ACK_ENTER_GAME,
                AckEventResult(event_code=int(EventCode.CHARACTER_NUMOUT)),
            )
            return
        guid = self.kernel.create_object(
            "Player",
            {"Name": name, "Account": sess.account, "GameID": self.config.server_id},
            scene=0,
            group=0,
        )
        sess.guid = guid
        self._guid_session[guid] = _ident_key(sess.ident)
        # stat init: fresh players get level 1 + full refill; returning
        # players keep their loaded Level/HP (the data agent attached the
        # saved blob during CREATE_LOADDATA) and only the derived stats
        # are rebuilt (reference OnObjectLevelEvent → RefreshBaseProperty)
        gw = self.game_world
        loaded = (self.data_agent is not None and sess.account
                  and self.data_agent.exists(f"{sess.account}:{name}"))
        if not loaded:
            self.kernel.set_property(guid, "Level", 1)
        gw.properties.refresh_base_property(guid, gw.property_config)
        gw.properties.recompute_now(guid)
        if not loaded:
            gw.properties.full_hp_mp(guid)
            gw.properties.full_sp(guid)
        # enter-scene pipeline (RequestEnterScene semantics; clone scenes
        # mint a private instance via SceneProcessModule)
        self._enter_scene(guid, self.scene_id)
        ack = AckEventResult(
            event_code=int(EventCode.ENTER_GAME_SUCCESS),
            event_object=guid_ident(guid),
        )
        self._send_to_session(sess, MsgID.ACK_ENTER_GAME, ack)
        self._send_snapshots(sess)
        if self.cross_server_sync:
            self._notify_online(sess, guid, self.scene_id, 0)

    def _notify_online(self, sess: Session, guid: Guid,
                       scene_id: int, group_id: int) -> None:
        """Cross-server online notify + session-bind sidecar (ISSUE 10):
        the world's roster learns the player came online, and its
        failover driver learns everything needed to re-home this session
        — durable save key included — should this role die unasked."""
        from ..wire import RoleOnlineNotify, SessionBindNotify

        ident = guid_ident(guid)
        self.world_link.send_to_all(
            int(MsgID.ACK_ONLINE_NOTIFY),
            wrap(RoleOnlineNotify(), player_id=ident),
        )
        save_key = ""
        if self.data_agent is not None:
            save_key = self.data_agent._key_of(guid) or ""
        bind = SessionBindNotify(
            selfid=ident,
            account=(sess.account or "").encode(),
            name=str(self.kernel.get_property(guid, "Name") or "").encode(),
            client_id=sess.ident,
            scene_id=int(scene_id),
            group_id=int(group_id),
            save_key=save_key.encode(),
            game_id=int(self.config.server_id),
        )
        self.world_link.send_to_all(
            int(MsgID.SESSION_BIND_NOTIFY), wrap(bind)
        )

    def _on_leave_game(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, _ = unwrap(body)
        key = _ident_key(base.player_id)
        sess = self.sessions.pop(key, None)
        if sess is not None:
            self._despawn(sess)

    def reset_view(self, sess: Session) -> dict:
        """Forget everything this session's client mirrors: fresh legacy
        seen-dict AND a wiped device seen-state row (batched engine).
        The single chokepoint for every view reset — despawn, switch-out,
        out-of-band destroy, and the lazy first-serve init all route
        here so the two engines can never drift on reset semantics."""
        seen = sess._interest_seen = {}
        self._session_table.reset_view(_ident_key(sess.ident))
        return seen

    def _despawn(self, sess: Session) -> None:
        if sess.guid is None:
            return
        guid = sess.guid
        targets = self._scene_players(guid)
        sess.guid = None
        # the interest seen-state belongs to the AVATAR's view: a fresh
        # client (crash + reconnect) starts with an empty mirror, so a
        # stale seen-state would suppress every stationary entity forever
        self.reset_view(sess)
        self._guid_session.pop(guid, None)
        # PVP hygiene: a queued ticket would ghost-match a gone player,
        # and an unconsumed room entry would leak forever
        pvp = getattr(self.game_world, "pvp", None)
        if pvp is not None:
            pvp.leave_queue(guid)
        for rid, pair in list(self._pvp_rooms.items()):
            if guid in pair:
                del self._pvp_rooms[rid]
                # the surviving fighter must hear the match died, or
                # they wait on a room that can never mint its ectype
                for other in pair:
                    if other == guid:
                        continue
                    key = self._guid_session.get(other)
                    s2 = self.sessions.get(key) if key is not None else None
                    if s2 is not None:
                        from ..wire import AckPVPApplyMatch

                        self._send_to_session(
                            s2, MsgID.ACK_PVP_APPLY_MATCH,
                            AckPVPApplyMatch(nResult=0),  # cancelled
                        )
        if guid in self.kernel.store.guid_map:
            self.kernel.destroy_object(guid)
        leave = AckPlayerLeaveList(object_list=[guid_ident(guid)])
        self._broadcast(targets, MsgID.ACK_OBJECT_LEAVE, leave, exclude=guid)
        if self.cross_server_sync:
            from ..wire import RoleOfflineNotify

            self.world_link.send_to_all(
                int(MsgID.ACK_OFFLINE_NOTIFY),
                wrap(RoleOfflineNotify(), player_id=guid_ident(guid)),
            )

    def _on_socket(self, conn_id: int, kind: int) -> None:
        if kind != EV_DISCONNECTED:
            return
        # a proxy link died: all its clients are gone
        for key, sess in list(self.sessions.items()):
            if sess.conn_id == conn_id:
                self._despawn(sess)
                self.sessions.pop(key, None)

    # ------------------------------------------------------------ snapshots
    def _entry_info(self, guid: Guid) -> PlayerEntryInfo:
        k = self.kernel
        cname, _ = k.store.row_of(guid)
        pos = k.get_property(guid, "Position")
        cfg = ""
        if k.store.spec(cname).has_property("ConfigID"):
            cfg = str(k.get_property(guid, "ConfigID"))
        return PlayerEntryInfo(
            object_guid=guid_ident(guid),
            x=pos[0], y=pos[1], z=pos[2] if len(pos) > 2 else 0.0,
            scene_id=int(k.get_property(guid, "SceneID")),
            class_id=cname.encode(),
            config_id=cfg.encode(),
        )

    def _property_list(self, guid: Guid, include_private: bool) -> ObjectPropertyList:
        """Full property snapshot (OnPropertyEnter: Public to others,
        Public+Private to self) via the shared serializer."""
        pred = (lambda d: d.flag("public") or d.flag("private")) \
            if include_private else (lambda d: d.flag("public"))
        out = serialize_properties(self.kernel.store, self.kernel.state,
                                   guid, pred)
        out.player_id = guid_ident(guid)
        return out

    def _record_list(self, guid: Guid, include_private: bool) -> ObjectRecordList:
        """Record snapshot for the flag-visible records (OnRecordEnter)
        via the shared serializer."""
        pred = (lambda d: d.flag("public") or d.flag("private")) \
            if include_private else (lambda d: d.flag("public"))
        out = serialize_records(self.kernel.store, self.kernel.state,
                                guid, pred)
        out.player_id = guid_ident(guid)
        return out

    def _send_snapshots(self, sess: Session) -> None:
        """Object-entry choreography toward the new client + the rest of
        the group (OnObjectListEnter / OnPropertyEnter / OnRecordEnter)."""
        guid = sess.guid
        visible: List[Guid] = []
        for cname in self.sync_classes:
            visible.extend(
                self.scene.objects_in_group(self.scene_id, 1, cname)
            )
        entry_all = AckPlayerEntryList(
            object_list=[self._entry_info(g) for g in visible]
        )
        self._send_to_session(sess, MsgID.ACK_OBJECT_ENTRY, entry_all)
        for g in visible:
            self._send_to_session(
                sess, MsgID.ACK_OBJECT_PROPERTY_ENTRY,
                self._property_list(g, include_private=(g == guid)),
            )
        self._send_to_session(
            sess, MsgID.ACK_OBJECT_RECORD_ENTRY,
            self._record_list(guid, include_private=True),
        )
        # announce the newcomer to everyone already there
        entry_self = AckPlayerEntryList(object_list=[self._entry_info(guid)])
        others = self._scene_players(guid)
        self._broadcast(others, MsgID.ACK_OBJECT_ENTRY, entry_self, exclude=guid)
        self._broadcast(
            others, MsgID.ACK_OBJECT_PROPERTY_ENTRY,
            self._property_list(guid, include_private=False), exclude=guid,
        )

    # ------------------------------------------------------------ gameplay
    def _enter_scene(self, guid, scene_id: int, group: int = 1) -> int:
        """Enter routed by scene type (NFCSceneProcessModule semantics):
        clone scenes mint a private instance for the enterer, normal
        scenes share `group` (created on first use)."""
        if scene_id not in self.scene.scenes:
            self.scene.create_scene(scene_id)
        sp = getattr(self.game_world, "scene_process", None)
        if sp is not None:
            return sp.enter(guid, scene_id, group)
        if group not in self.scene.scenes[scene_id].groups:
            self.scene.request_group(scene_id, group_id=group)
        self.scene.enter_scene(guid, scene_id, group)
        return group

    def _on_swap_scene(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqAckSwapScene)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return
        self._enter_scene(sess.guid, req.scene_id)
        self._send_to_session(sess, MsgID.ACK_SWAP_SCENE, req)

    def _on_move(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqAckPlayerMove)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None or not req.target_pos:
            return
        p = req.target_pos[0]
        self.kernel.set_property(sess.guid, "Position", (p.x, p.y, p.z))
        req.mover = guid_ident(sess.guid)
        self._broadcast(self._scene_players(sess.guid), MsgID.ACK_MOVE, req)

    def _on_chat(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqAckPlayerChat)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return
        req.chat_id = guid_ident(sess.guid)
        self._broadcast(self._scene_players(sess.guid), MsgID.ACK_CHAT, req)

    def _on_skill(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """Host-path skill resolution (`NFCSkillModule::OnUseSkill`
        HP-damage semantics, `NFCSkillModule.cpp:74-160`); batch AoE lives
        in game/combat.py on device."""
        base, req = unwrap(body, ReqAckUseSkill)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return
        req.user = guid_ident(sess.guid)
        for eff in req.effect_data:
            target = self._guid_of_ident(eff.effect_ident)
            if target is None or target not in self.kernel.store.guid_map:
                continue
            hp = int(self.kernel.get_property(target, "HP"))
            dmg = self.skill_damage
            self.kernel.set_property(target, "HP", max(0, hp - dmg))
            eff.effect_value = dmg
        self._broadcast(self._scene_players(sess.guid), MsgID.ACK_SKILL_OBJECTX, req)

    def _guid_of_ident(self, ident: Optional[Ident]) -> Optional[Guid]:
        if ident is None:
            return None
        return Guid(ident.svrid, ident.index)

    def _on_set_fight_hero(self, conn_id: int, _msg_id: int,
                           body: bytes) -> None:
        """NFCHeroModule::OnSetFightHeroMsg — the hero's record row rides
        heroid.index (heroes are row-identified)."""
        base, req = unwrap(body, ReqSetFightHero)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None or req.heroid is None:
            return
        heroes = self.game_world.heroes
        if heroes is not None:
            heroes.set_fight_hero(sess.guid, int(req.heroid.index),
                                  int(req.fight_pos))

    # ---------------------------------------------- middleware handlers
    # reference: NFCItemModule::OnClientUseItem, NFCEquipModule wear /
    # takeoff callbacks, NFCTaskModule::OnClientAcceptTask /
    # OnClientCompeleteTask, NFCTeamModule and the guild handlers.  All
    # degrade to no-ops when the world was assembled without the
    # middleware stack (bench worlds).
    def _mid_session(self, base) -> Optional[Session]:
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return None
        return sess

    def _on_use_item(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqAckUseItem)
        sess = self._mid_session(base)
        items = self.game_world.items
        if sess is None or items is None or req.item is None:
            return
        config_id = req.item.item_id.decode("utf-8", "replace")
        # row targets are tagged with svrid == 1 (ROW_TARGET_SVRID): row 0
        # is a VALID first record row, and protoc clients always send the
        # required targetid field (zeroed when untargeted), so the index
        # alone cannot discriminate "no target" from "row 0"
        target = (int(req.targetid.index)
                  if (req.targetid is not None
                      and int(req.targetid.svrid) == ROW_TARGET_SVRID)
                  else None)
        if items.use_item(sess.guid, config_id, target=target):
            req.user = guid_ident(sess.guid)
            self._send_to_session(sess, MsgID.ACK_ITEM_OBJECT, req)

    def _on_wear_equip(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqWearEquip)
        sess = self._mid_session(base)
        equip = self.game_world.equip
        if sess is None or equip is None or req.equipid is None:
            return
        equip.wear(sess.guid, int(req.equipid.index))

    def _on_takeoff_equip(self, conn_id: int, _msg_id: int,
                          body: bytes) -> None:
        base, req = unwrap(body, TakeOffEquip)
        sess = self._mid_session(base)
        equip = self.game_world.equip
        if sess is None or equip is None or req.equipid is None:
            return
        equip.take_off(sess.guid, int(req.equipid.index))

    def _on_accept_task(self, conn_id: int, _msg_id: int,
                        body: bytes) -> None:
        base, req = unwrap(body, ReqAcceptTask)
        sess = self._mid_session(base)
        tasks = self.game_world.tasks
        if sess is None or tasks is None:
            return
        tasks.accept(sess.guid, req.task_id.decode("utf-8", "replace"))

    def _on_complete_task(self, conn_id: int, _msg_id: int,
                          body: bytes) -> None:
        base, req = unwrap(body, ReqCompeleteTask)
        sess = self._mid_session(base)
        tasks = self.game_world.tasks
        if sess is None or tasks is None:
            return
        tasks.draw_award(sess.guid, req.task_id.decode("utf-8", "replace"))

    # ------------------------------------------------------------- teams
    def _team_info(self, info) -> "TeamInfo":
        k = self.kernel
        members = []
        for m in info.members:
            if m not in k.store.guid_map:
                continue
            members.append(TeammemberInfo(
                player_id=guid_ident(m),
                name=str(k.get_property(m, "Name")).encode(),
                nLevel=int(k.get_property(m, "Level")),
                job=int(k.get_property(m, "Job")),
            ))
        return TeamInfo(
            team_id=guid_ident(info.group_id),
            captain_id=guid_ident(info.leader),
            teammemberInfo=members,
        )

    def _on_create_team(self, conn_id: int, _msg_id: int,
                        body: bytes) -> None:
        base, _req = unwrap(body, ReqAckCreateTeam)
        sess = self._mid_session(base)
        team = self.game_world.team
        if sess is None or team is None:
            return
        tid = team.create_team(sess.guid)
        if tid is None:
            return
        info = team.team_of(sess.guid)
        self._send_to_session(
            sess, MsgID.ACK_CREATE_TEAM,
            ReqAckCreateTeam(team_id=guid_ident(tid),
                             xTeamInfo=self._team_info(info)),
        )

    def _on_join_team(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        base, req = unwrap(body, ReqAckJoinTeam)
        sess = self._mid_session(base)
        team = self.game_world.team
        if sess is None or team is None or req.team_id is None:
            return
        tid = self._guid_of_ident(req.team_id)
        if not team.join(tid, sess.guid):
            return
        info = team.team_of(sess.guid)
        ack = ReqAckJoinTeam(team_id=req.team_id,
                             xTeamInfo=self._team_info(info))
        # the whole roster hears about the new member
        self._broadcast(list(info.members), MsgID.ACK_JOIN_TEAM, ack)

    def _on_leave_team(self, conn_id: int, _msg_id: int,
                       body: bytes) -> None:
        base, req = unwrap(body, ReqAckLeaveTeam)
        sess = self._mid_session(base)
        team = self.game_world.team
        if sess is None or team is None:
            return
        info = team.team_of(sess.guid)
        if info is None or not team.leave(sess.guid):
            return
        ack = ReqAckLeaveTeam(team_id=guid_ident(info.group_id))
        self._broadcast(list(info.members) + [sess.guid],
                        MsgID.ACK_LEAVE_TEAM, ack)

    def _on_opr_team_member(self, conn_id: int, _msg_id: int,
                            body: bytes) -> None:
        """Captain member ops — KICK/KICKOUT implemented (the other
        EGTeamMemberOprType values are fight-position bookkeeping the
        line-up record owns here)."""
        base, req = unwrap(body, ReqAckOprTeamMember)
        sess = self._mid_session(base)
        team = self.game_world.team
        if sess is None or team is None or req.member_id is None:
            return
        if int(req.type) not in (2, 8):  # EGAT_KICK / EGAT_KICKOUT
            return
        info = team.team_of(sess.guid)
        if info is None or info.leader != sess.guid:
            return  # only the captain operates members
        member = self._guid_of_ident(req.member_id)
        if member == sess.guid or member not in info.members:
            return
        team.leave(member)
        ack = ReqAckOprTeamMember(team_id=guid_ident(info.group_id),
                                  member_id=req.member_id, type=req.type,
                                  xTeamInfo=self._team_info(info))
        self._broadcast(list(info.members) + [member],
                        MsgID.ACK_OPRMEMBER_TEAM, ack)

    # ------------------------------------------------------------ guilds
    def _on_create_guild(self, conn_id: int, _msg_id: int,
                         body: bytes) -> None:
        base, req = unwrap(body, ReqAckCreateGuild)
        sess = self._mid_session(base)
        guilds = self.game_world.guilds
        if sess is None or guilds is None:
            return
        name = req.guild_name.decode("utf-8", "replace")
        gid = guilds.create_guild(sess.guid, name)
        if gid is None:
            return
        self._send_to_session(
            sess, MsgID.ACK_CREATE_GUILD,
            ReqAckCreateGuild(guild_id=guid_ident(gid),
                              guild_name=req.guild_name),
        )

    def _on_join_guild(self, conn_id: int, _msg_id: int,
                       body: bytes) -> None:
        base, req = unwrap(body, ReqAckJoinGuild)
        sess = self._mid_session(base)
        guilds = self.game_world.guilds
        if sess is None or guilds is None:
            return
        name = req.guild_name.decode("utf-8", "replace")
        info = guilds.find_by_name(name)
        if info is None or not guilds.join(info.group_id, sess.guid):
            return
        self._send_to_session(
            sess, MsgID.ACK_JOIN_GUILD,
            ReqAckJoinGuild(guild_id=guid_ident(info.group_id),
                            guild_name=req.guild_name),
        )

    def _on_leave_guild(self, conn_id: int, _msg_id: int,
                        body: bytes) -> None:
        base, req = unwrap(body, ReqAckLeaveGuild)
        sess = self._mid_session(base)
        guilds = self.game_world.guilds
        if sess is None or guilds is None:
            return
        info = guilds.guild_of(sess.guid)
        if info is None or not guilds.leave(sess.guid):
            return
        self._send_to_session(
            sess, MsgID.ACK_LEAVE_GUILD,
            ReqAckLeaveGuild(guild_id=guid_ident(info.group_id),
                             guild_name=info.name.encode()),
        )

    def _on_search_guild(self, conn_id: int, _msg_id: int,
                         body: bytes) -> None:
        base, req = unwrap(body, ReqSearchGuild)
        sess = self._mid_session(base)
        guilds = self.game_world.guilds
        if sess is None or guilds is None:
            return
        needle = req.guild_name.decode("utf-8", "replace").lower()
        out = []
        for info in guilds.guilds.values():
            if needle and needle not in info.name.lower():
                continue
            out.append(SearchGuildObject(
                guild_ID=guid_ident(info.group_id),
                guild_name=info.name.encode(),
                guild_member_count=len(info.members),
                guild_member_max_count=info.capacity,
            ))
        self._send_to_session(sess, MsgID.ACK_SEARCH_GUILD,
                              AckSearchGuild(guild_list=out))

    # --------------------------------------------------------- GM + PVP
    def _on_gm_command(self, conn_id: int, _msg_id: int,
                       body: bytes) -> None:
        """EGMI_REQ_CMD_NORMAL (NFCGmModule::OnGMNormalProcess):
        ReqCommand's typed EGameCommandType mapped onto GmModule's
        chat-command grammar, so the GMLevel gate applies identically."""
        from ..wire import ReqCommand

        base, req = unwrap(body, ReqCommand)
        sess = self._mid_session(base)
        gm = self.game_world.gm
        if sess is None or gm is None:
            return
        k = self.kernel
        sval = (req.command_str_value or b"").decode("utf-8", "replace")
        ival = int(req.command_value_int or 0)
        cmd = int(req.command_id)
        if cmd == 0:  # EGCT_MODIY_PROPERTY: SET the named int property
            if int(k.get_property(sess.guid, "GMLevel")) < gm.min_gm_level:
                return
            spec = k.store.spec("Player")
            if sval and spec.has_property(sval) \
                    and spec.slot(sval).prop.type == DataType.INT:
                k.set_property(sess.guid, sval, ival)
            return
        text = {
            1: f"/item {sval} {ival or 1}",  # EGCT_MODIY_ITEM
            3: f"/exp {ival}",  # EGCT_ADD_ROLE_EXP
        }.get(cmd)
        if text is not None:
            gm.handle_command(sess.guid, text)

    def _on_pvp_apply(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        """EGMI_REQ_PVPAPPLYMACTCH (NFCGSPVPMatchModule shape): queue the
        player; when the score-window pairing matches two, BOTH get an
        ACK with the room (red/blue) and the role tracks it for the
        ectype step."""
        from ..wire import AckPVPApplyMatch, PVPRoomInfo, ReqPVPApplyMatch

        base, req = unwrap(body, ReqPVPApplyMatch)
        sess = self._mid_session(base)
        pvp = self.game_world.pvp
        if sess is None or pvp is None:
            return
        score = int(self.kernel.get_property(sess.guid, "Level")
                    if req.score is None else req.score)  # 0 is a real rating
        if not pvp.join_queue(sess.guid, score, mode=int(req.nPVPMode)):
            # already queued: re-apply means switch (new mode/score wins)
            pvp.leave_queue(sess.guid)
            pvp.join_queue(sess.guid, score, mode=int(req.nPVPMode))
        for ta, tb in pvp.match_once_tickets():
            red, blue = ta.player, tb.player
            room_id = self.kernel.store.guids.next()
            room = PVPRoomInfo(
                nCellStatus=0,
                RoomID=guid_ident(room_id),
                # the PAIR's queue mode — window-widening can match a
                # pair during someone else's request
                nPVPMode=ta.mode,
                MaxPalyer=2,
                xRedPlayer=[guid_ident(red)],
                xBluePlayer=[guid_ident(blue)],
                serverid=self.config.server_id,
            )
            self._pvp_rooms[(room_id.head, room_id.data)] = (red, blue)
            ack = AckPVPApplyMatch(xRoomInfo=room,
                                   ApplyType=int(req.ApplyType), nResult=1)
            for g in (red, blue):
                key = self._guid_session.get(g)
                s2 = self.sessions.get(key) if key is not None else None
                if s2 is not None:
                    ack.self_id = guid_ident(g)
                    self._send_to_session(s2, MsgID.ACK_PVP_APPLY_MATCH, ack)

    def _on_pvp_create_ectype(self, conn_id: int, _msg_id: int,
                              body: bytes) -> None:
        """EGMI_REQ_CREATEPVPECTYPE: mint the PVP instance — a CLONE
        scene group both fighters enter (the reference pulls both sides
        into the room's ectype scene)."""
        from ..wire import AckCreatePVPEctype, ReqCreatePVPEctype

        base, req = unwrap(body, ReqCreatePVPEctype)
        sess = self._mid_session(base)
        if sess is None or req.xRoomInfo is None or req.xRoomInfo.RoomID is None:
            return
        rid = (req.xRoomInfo.RoomID.svrid, req.xRoomInfo.RoomID.index)
        pair = self._pvp_rooms.get(rid)
        if pair is None or sess.guid not in pair:
            return  # unknown room, or a NON-participant: room stays live
        del self._pvp_rooms[rid]
        scene_id = int(req.xRoomInfo.SceneID or
                       self.kernel.get_property(sess.guid, "SceneID"))
        if scene_id not in self.scene.scenes:
            self.scene.create_scene(scene_id)
        # ONE shared instance for both fighters (scene_process.enter
        # would mint a private clone group per enterer)
        group = self.scene.request_group(scene_id)
        for g in pair:
            if g in self.kernel.store.guid_map:
                self.scene.enter_scene(g, scene_id, group)
        req.xRoomInfo.SceneID = scene_id
        req.xRoomInfo.groupID = group
        ack = AckCreatePVPEctype(xRoomInfo=req.xRoomInfo)
        for g in pair:
            key = self._guid_session.get(g)
            s2 = self.sessions.get(key) if key is not None else None
            if s2 is not None:
                ack.self_id = guid_ident(g)  # per-recipient, like apply
                self._send_to_session(s2, MsgID.ACK_CREATE_PVP_ECTYPE, ack)

    # ---------------------------------------------- cross-server switch
    # Reference NFCGSSwichServerModule.cpp: game A serializes nothing and
    # relies on a shared DB; here the player's save-flag snapshot rides a
    # SWITCH_SERVER_DATA companion message, so the re-home works without
    # one.  Flow: A.switch_server -> world -> B (_on_switch_in: create,
    # apply blob, enter scene, tell the proxy to re-route, ack) ->
    # world -> A (_on_switch_ack: drop session, destroy local copy).
    def switch_server(self, guid: Guid, target_server_id: int,
                      scene_id: int = 1, group: int = 0) -> bool:
        """ChangeServer (NFCGSSwichServerModule.cpp:49-77)."""
        from ...persist.codec import snapshot_object
        from ...persist.rowblob import frame_blob

        key = self._guid_session.get(guid)
        sess = self.sessions.get(key) if key is not None else None
        if sess is None or target_server_id == self.config.server_id:
            return False
        k = self.kernel
        # CRC-framed (persist/rowblob.py) so the target detects a blob
        # torn in transit before the codec ever parses it — the same
        # row-serialization story the on-mesh migration shares
        blob = frame_blob(snapshot_object(k.store, k.state, guid))
        ident = guid_ident(guid)
        data = SwitchServerData(
            selfid=ident,
            account=(sess.account or "").encode(),
            name=str(k.get_property(guid, "Name")).encode(),
            blob=blob,
            target_serverid=target_server_id,
        )
        req = ReqSwitchServer(
            selfid=ident,
            self_serverid=self.config.server_id,
            target_serverid=target_server_id,
            gate_serverid=0,  # proxy routing is by client ident here
            scene_id=scene_id,
            client_id=sess.ident,
            group_id=group,
        )
        # both messages MUST ride the same world link (suit-hash by the
        # player) — DATA arriving after REQ on a different link would
        # fail the switch silently
        suit = str(guid)
        self.world_link.send_by_suit(suit, int(MsgID.SWITCH_SERVER_DATA),
                                     wrap(data))
        self.world_link.send_by_suit(suit, int(MsgID.REQ_SWITCH_SERVER),
                                     wrap(req))
        return True

    def _on_client_switch(self, conn_id: int, _msg_id: int,
                          body: bytes) -> None:
        """Client-initiated switch (OnClientReqSwichServer)."""
        base, req = unwrap(body, ReqSwitchServer)
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return
        self.switch_server(sess.guid, int(req.target_serverid),
                           int(req.scene_id), int(req.group_id))

    SWITCH_BLOB_TTL_S = 30.0

    def _on_switch_data(self, _sid: int, _msg_id: int, body: bytes) -> None:
        _, data = unwrap(body, SwitchServerData)
        if int(data.target_serverid) != self.config.server_id:
            return
        # sweep expired staged blobs (a world crash between DATA and REQ
        # must not leak entries forever)
        now = _time.monotonic()
        self._switch_blobs = {
            k: (d, t) for k, (d, t) in self._switch_blobs.items()
            if now - t < self.SWITCH_BLOB_TTL_S
        }
        self._switch_blobs[_ident_key(data.selfid)] = (data, now)

    def _on_switch_in(self, _sid: int, _msg_id: int, body: bytes) -> None:
        """Target side (OnReqSwichServer,
        NFCGSSwichServerModule.cpp:96-148): recreate the player from the
        blob, enter the scene, bind the client, re-route the proxy, ack.

        Hardened for supervised failover (ISSUE 10): a duplicate REQ
        re-acks idempotently, a full Player store answers
        ACK_SWITCH_REFUSED (BUSY) instead of half-admitting, and a blob
        torn in transit destroys the half-built object and refuses —
        the driver retries another survivor in every refusal case."""
        from ...persist.codec import apply_snapshot
        from ...persist.rowblob import unframe_blob
        from ..failover import REFUSE_BAD_BLOB, REFUSE_BUSY
        _, req = unwrap(body, ReqSwitchServer)
        if int(req.target_serverid) != self.config.server_id:
            return
        if req.client_id is None or req.selfid is None:
            return
        ckey = _ident_key(req.client_id)
        staged = self._switch_blobs.pop(_ident_key(req.selfid), None)
        if staged is None:
            # duplicate REQ (dup'd link, or a failover re-stage racing
            # the first ack): if this client already owns a live avatar
            # here, repeat the re-route + ack instead of going silent —
            # the world-side driver needs the (possibly lost) ack
            sess = self.sessions.get(ckey)
            if sess is not None and sess.guid is not None:
                self._switch_accept(req, sess)
            return
        data = staged[0]
        k = self.kernel
        store = k.store
        if store.live_count("Player") >= store.capacity("Player"):
            # graceful degradation: no capacity for the refugee — refuse
            # BEFORE allocating so the driver can try another survivor
            self._switch_refuse(req, REFUSE_BUSY)
            return
        guid = k.create_object(
            "Player",
            {
                "Account": data.account.decode("utf-8", "replace"),
                "Name": data.name.decode("utf-8", "replace"),
                "GameID": self.config.server_id,
            },
            scene=int(req.scene_id), group=int(req.group_id),
        )
        if data.blob:
            try:
                # unframe validates CRC/length fail-closed; a legacy
                # (unframed) blob passes through to the codec unchanged
                k.state = apply_snapshot(k.store, k.state, guid,
                                         unframe_blob(data.blob))
            except Exception:
                # torn blob: k.state only mutates on success, so a clean
                # destroy admits nothing half-applied
                if guid in k.store.guid_map:
                    k.destroy_object(guid)
                self._switch_refuse(req, REFUSE_BAD_BLOB)
                return
        k.state = k.store.set_property(k.state, guid, "GameID",
                                       self.config.server_id)
        # bind the client session; the transport conn resolves to the
        # proxy link (single-proxy fast path) and self-corrects on the
        # client's first routed message (_session_for)
        sess = self.sessions.get(ckey)
        if sess is None:
            sess = Session(ident=req.client_id, conn_id=-1)
            self.sessions[ckey] = sess
        sess.account = data.account.decode("utf-8", "replace")
        sess.guid = guid
        self._guid_session[guid] = ckey
        self._enter_scene(guid, int(req.scene_id),
                          group=int(req.group_id) or 1)
        self._switch_accept(req, sess)
        if self.cross_server_sync:
            # adopted players rejoin the roster under THIS game id, so a
            # second failure can re-home them again (roster continuity)
            self._notify_online(sess, guid, int(req.scene_id),
                                int(req.group_id))

    def _switch_accept(self, req, sess: Session) -> None:
        """Re-route the proxy binding and ack the switch — shared by the
        first admit and the duplicate-REQ idempotent repeat."""
        proxy_conns = list(self.server.conn_tags)
        if len(proxy_conns) == 1:
            sess.conn_id = proxy_conns[0]
        # proxy re-route: every proxy link gets the req; the one owning
        # the client ident re-points it at this server
        for conn in proxy_conns:
            self.server.send_raw(conn, int(MsgID.REQ_SWITCH_SERVER),
                                 wrap(req, clients=[req.client_id]))
        ack = AckSwitchServer(
            selfid=req.selfid,
            self_serverid=req.self_serverid,
            target_serverid=req.target_serverid,
            gate_serverid=req.gate_serverid,
        )
        self.world_link.send_to_all(int(MsgID.ACK_SWITCH_SERVER), wrap(ack))

    def _switch_refuse(self, req, result: int) -> None:
        from ..wire import SwitchRefused

        self.world_link.send_to_all(
            int(MsgID.ACK_SWITCH_REFUSED),
            wrap(SwitchRefused(
                selfid=req.selfid,
                self_serverid=int(req.self_serverid),
                target_serverid=int(req.target_serverid),
                result=int(result),
            )),
        )

    def _on_switch_ack(self, _sid: int, _msg_id: int, body: bytes) -> None:
        """Origin side (OnAckSwichServer): the target owns the player
        now — drop the session binding and the local object."""
        _, ack = unwrap(body, AckSwitchServer)
        if int(ack.self_serverid) != self.config.server_id:
            return
        if ack.selfid is None:
            return
        guid = Guid(ack.selfid.svrid, ack.selfid.index)
        key = self._guid_session.pop(guid, None)
        if key is not None:
            sess = self.sessions.pop(key, None)
            if sess is not None:
                sess.guid = None
                self.reset_view(sess)
        if guid in self.kernel.store.guid_map:
            self.kernel.destroy_object(guid)

    # ------------------------------------------------------------ SLG city
    # reference handlers: NFCSLGShopModule::OnSLGClienBuyItem and
    # NFCSLGBuildingModule::OnSLGClienMoveObject/UpgradeBuilding/CreateItem
    def _slg_session(self, base) -> Optional[Session]:
        sess = self.sessions.get(_ident_key(base.player_id))
        if sess is None or sess.guid is None:
            return None
        if self.game_world.slg_building is None:
            return None  # world assembled without the middleware stack
        return sess

    def _on_slg_buy(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        from ..wire_families import ReqAckBuyObjectFormShop

        base, req = unwrap(body, ReqAckBuyObjectFormShop)
        sess = self._slg_session(base)
        if sess is None:
            return
        shop_id = req.config_id.decode("utf-8", "replace")
        if self.game_world.slg_shop.buy(sess.guid, shop_id,
                                        req.x, req.y, req.z):
            self._send_to_session(sess, MsgID.ACK_BUY_FORM_SHOP, req)

    def _on_slg_move(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        from ..wire_families import ReqAckMoveBuildObject

        base, req = unwrap(body, ReqAckMoveBuildObject)
        sess = self._slg_session(base)
        if sess is None or req.row is None:
            return
        if self.game_world.slg_building.move(sess.guid, int(req.row),
                                             req.x, req.y, req.z):
            self._send_to_session(sess, MsgID.ACK_MOVE_BUILD_OBJECT, req)

    def _on_slg_upgrade(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        from ..wire_families import ReqUpBuildLv

        base, req = unwrap(body, ReqUpBuildLv)
        sess = self._slg_session(base)
        if sess is None or req.row is None:
            return
        self.game_world.slg_building.upgrade(sess.guid, int(req.row))

    def _on_slg_create_item(self, conn_id: int, _msg_id: int,
                            body: bytes) -> None:
        from ..wire_families import ReqCreateItem

        base, req = unwrap(body, ReqCreateItem)
        sess = self._slg_session(base)
        if sess is None or req.row is None:
            return
        self.game_world.slg_building.produce(
            sess.guid, int(req.row),
            req.config_id.decode("utf-8", "replace"), int(req.count) or 1,
        )

    def _on_slg_operate(self, conn_id: int, _msg_id: int, body: bytes) -> None:
        from ..wire_families import ReqBuildOperate, SLGFuncType

        base, req = unwrap(body, ReqBuildOperate)
        sess = self._slg_session(base)
        if sess is None or req.row is None:
            return
        b = self.game_world.slg_building
        ft = int(req.functype)
        collect = {
            int(SLGFuncType.COLLECT_GOLD): "Gold",
            int(SLGFuncType.COLLECT_STONE): "Stone",
            int(SLGFuncType.COLLECT_STEEL): "Steel",
            int(SLGFuncType.COLLECT_DIAMOND): "Diamond",
        }.get(ft)
        if collect is not None:
            b.collect(sess.guid, int(req.row), collect)
            return
        fn = {
            int(SLGFuncType.BOOST): b.boost,
            int(SLGFuncType.LVLUP): b.upgrade,
            int(SLGFuncType.CANCEL): b.cancel,
        }.get(ft)
        if fn is not None:
            fn(sess.guid, int(req.row))

    # ------------------------------------------------------------ tick + sync
    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        super().execute(now)
        pm = self.game_world.pm
        sc = self.stage_clock
        tick_due = now - self._last_tick >= self.game_world.config.dt
        # one stage-clock frame spans tick + flush of this pump pass; a
        # flush can also fire alone (host writes between ticks)
        framed = tick_due or bool(self._changed or self._rec_changed
                                  or self._interest_dirty
                                  or self._serve_pending)
        if framed:
            sc.frame_begin(self.kernel.tick_count)
        flushed = False
        # overlap mode: interest lanes deferred by the last flush, to be
        # served against THIS frame's pre-tick snapshot (sync_classes
        # order, same as a flush)
        pend_classes: List[str] = []
        if self._serve_pending:
            pend_classes = [
                cn for cn in self.sync_classes if cn in self._serve_pending
            ]
            self._serve_pending.clear()
        train_outs = None
        if tick_due:
            self._last_tick = now
            ticks_this_frame = self.tick_train or 1
            with self.telemetry.tracer.span("game.tick"), sc.stage("tick"):
                t0 = _time.perf_counter()
                for m in pm.modules.values():
                    if m is not self.kernel:
                        m.execute()
                self.kernel.execute()
                if pend_classes:
                    # double-buffered serve: fetch the deferred lanes'
                    # deltas from the pre-tick state (the donated buffers
                    # die at dispatch), start the device tick, and do all
                    # host assembly/encode/send while the device runs
                    with sc.stage("interest"):
                        pend = [
                            d for d in (
                                self._serve_pos_collect(cn)
                                for cn in pend_classes
                            ) if d is not None
                        ]
                    raw = self.kernel.tick_begin()
                    self._serve_deferred.inc()
                    with sc.stage("assemble"):
                        for d in pend:
                            self._serve_pos_emit(d)
                    self.kernel.tick_finish(raw)
                elif self.tick_train:
                    # one K-frame megadispatch; per-frame host effects
                    # (events, diffs, tick-exact deaths, counters) fan
                    # out in order from the stacked lanes
                    d0, t0k, b0 = (self.kernel.train_dispatches,
                                   self.kernel.train_ticks,
                                   self.kernel.train_fetch_bytes)
                    train_outs = self.kernel.train(self.tick_train)
                    self._train_dispatches.inc(
                        self.kernel.train_dispatches - d0)
                    self._train_ticks.inc(self.kernel.train_ticks - t0k)
                    self._train_fetch_bytes.inc(
                        self.kernel.train_fetch_bytes - b0)
                else:
                    self.kernel.tick()
                pm.frame += ticks_this_frame
                # per-tick latency even under trains: one train frame is
                # K ticks of device work behind one dispatch
                self._tick_hist.observe(
                    (_time.perf_counter() - t0) / ticks_this_frame)
            if ticks_this_frame > 1:
                # nf_stage_tick_seconds stays a per-tick distribution
                # across NF_TICK_TRAIN settings (waterfall stays exact)
                sc.set_scale("tick", ticks_this_frame)
            if self.elastic is not None:
                # advance any in-flight grow/drain; when one completes,
                # force-reset exactly the sessions whose seen-state
                # intersects the rows the reshard actually moved
                with sc.stage("reshard"):
                    moved = self.elastic.poll()
                if moved:
                    self._reset_views_for_moved(moved)
            if self.journal is not None:
                # closes this tick's input window; the digest rode the
                # summary fetch the tick already paid for.  A train
                # writes one mark PER stacked frame from the in-lane
                # tick/digest stamps — replay runs one real tick per
                # mark and must compare like for like.
                if train_outs is not None:
                    for o in train_outs:
                        self.journal.tick_mark(
                            o.counters.get("tick", self.kernel.tick_count),
                            o.counters.get("state_digest", 0),
                        )
                else:
                    self.journal.tick_mark(
                        self.kernel.tick_count,
                        self.kernel.last_counters.get("state_digest", 0),
                    )
                self._journal_pump_counters()
            if self.persist is not None:
                # stage this tick's dirty set; all store I/O stays on
                # the flusher thread (the smoke asserts the tick never
                # blocks even with injected store latency)
                self._persist_harvest()
        elif pend_classes:
            # ticks stopped (idle pump): drain the deferred lanes
            # synchronously so staleness stays bounded by pump latency
            with self.telemetry.tracer.span("game.flush"):
                with sc.stage("interest"):
                    for cn in pend_classes:
                        self._send_interest_pos_batched(cn)
                flushed = True
        # _interest_dirty alone must also trigger a flush: a destroy with
        # no property diff still changes visible sets (gone lists)
        if self._changed or self._rec_changed or self._interest_dirty:
            with self.telemetry.tracer.span("game.flush"):
                if self.sessions:
                    self._flush_changes()
                    flushed = True
                else:
                    self._changed.clear()
                    self._rec_changed.clear()
                    self._interest_dirty.clear()
        if framed:
            sc.frame_end()
            if flushed and self._trace_sample > 0:
                self._emit_frame_traces()
        if tick_due:
            # periodic HBM census: live/peak device bytes sampled in-band
            # (scrape-time sampling alone misses peaks between scrapes)
            from ...telemetry.costbook import HBM_SAMPLE_FRAMES

            if self.kernel.tick_count % HBM_SAMPLE_FRAMES == 0:
                self.kernel.costbook.hbm_sample()
        # periodic autosave: device-side deaths free the row before any
        # BEFORE_DESTROY hook can run, so the blob must already be fresh
        if (self.data_agent is not None
                and now - self._last_autosave >= self.autosave_seconds):
            self._last_autosave = now
            for sess in self.sessions.values():
                if sess.guid is not None and sess.guid in self.kernel.store.guid_map:
                    self.data_agent.save(sess.guid)
        # periodic whole-world checkpoint (atomic rename; see
        # persist/checkpoint.py) — the resume path in __init__ restores
        # the latest one after a crash
        if (self.checkpoint_dir is not None
                and now - self._last_checkpoint >= self.checkpoint_seconds):
            self._last_checkpoint = now
            self.checkpoint_now()

    # ------------------------------------------------------- elastic mesh
    @property
    def elastic(self):
        """The world's grow/drain driver (parallel/elastic.py), or None
        for a single-device world.  Read through the world each time so
        a revive that swaps the world swaps the driver with it."""
        return getattr(self.game_world, "elastic", None)

    def grow_mesh(self, n_devices: int) -> None:
        """Expand the serving mesh; the reshard and spatial rebalance
        run inside subsequent ticks' ``reshard`` stage."""
        el = self.elastic
        if el is None:
            raise RuntimeError(f"{self.config.name}: world is not sharded")
        el.begin_grow(int(n_devices))

    def drain_device(self, device_index: int) -> None:
        """Evict one mesh device via the budgeted row exodus, then
        shrink — driven tick-by-tick from the ``reshard`` stage."""
        el = self.elastic
        if el is None:
            raise RuntimeError(f"{self.config.name}: world is not sharded")
        el.begin_drain(int(device_index))

    # ------------------------------------------------------- many worlds
    def attach_rooms(self, directory) -> None:
        """Host a many-worlds RoomDirectory (parallel/rooms.py) beside
        the single world: room status rides the heartbeat ext and the
        room churn verbs below become drill-addressable."""
        self.rooms = directory

    def _rooms_or_raise(self):
        if self.rooms is None:
            raise RuntimeError(
                f"{self.config.name}: no RoomDirectory attached")
        return self.rooms

    def create_room(self, seed: Optional[int] = None,
                    room_id: Optional[int] = None,
                    control: bool = False) -> int:
        return self._rooms_or_raise().create_room(
            seed=seed, room_id=room_id, control=control)

    def destroy_room(self, room_id: int) -> int:
        """Free the room's slot and release every session routed to it
        (same reset discipline as a completed reshard: the seen-state
        wipe is lazy, the routing column clears now)."""
        d = self._rooms_or_raise()
        slot = d.destroy_room(room_id)
        table = self._session_table
        if table is not None:
            for key in table.sessions_in_room(room_id):
                table.release(key)
        return slot

    def rehome_room(self, room_id: int):
        """Move a room to another slot/device; sessions keep their
        routing (the room id is stable — only its slot changed), but
        their views reset so the next serve pass resends from the
        re-homed state."""
        d = self._rooms_or_raise()
        src_dst = d.rehome_room(room_id)
        table = self._session_table
        if table is not None:
            for key in table.sessions_in_room(room_id):
                table.reset_view(key)
        return src_dst

    def _reset_views_for_moved(self, moved: Dict[str, np.ndarray]) -> None:
        """Force reset_view for sessions whose seen-state references rows
        a completed reshard moved — and ONLY those.  The batched engine
        intersects per-slot SeenTable rows exactly; the legacy engine's
        per-session seen dicts carry no row index, so it conservatively
        resets every session with a non-empty mirror."""
        from ..serving import sessions_seeing_rows

        affected = set()
        for cname, rows in moved.items():
            if len(rows) == 0:
                continue
            if self.serve_batch:
                affected.update(
                    sessions_seeing_rows(self._session_table, cname, rows))
            else:
                affected.update(
                    k for k, s in self.sessions.items()
                    if getattr(s, "_interest_seen", None))
        count = 0
        for key in affected:
            sess = self.sessions.get(key)
            if sess is not None:
                self.reset_view(sess)
                count += 1
        if count:
            self._reshard_resets.inc(count)

    def checkpoint_now(self):
        """Write one atomic whole-world checkpoint; returns its path."""
        self.game_world.save(self.checkpoint_dir)
        self._ckpt_counter.inc()
        if self.journal is not None:
            # durability point: fsync the journal at the checkpoint mark
            # so the (checkpoint, journal-suffix) pair on disk is always
            # a recoverable replay basis
            self.journal.checkpoint_mark(self.kernel.tick_count)
            self._journal_pump_counters()
        if self.persist is not None:
            # same durability point for the write-behind WAL: after this
            # fsync the newest (checkpoint, WAL suffix) pair on disk is
            # mutually recoverable
            self.persist.barrier(self.kernel.tick_count)
        return self.checkpoint_dir

    def kill(self) -> None:
        """Crash semantics (ISSUE 10 failover drills): tear the sockets
        down WITHOUT the graceful drain — no session saves, no persist
        flush, the WAL keeps whatever reached it.  This is the in-process
        stand-in for kill -9; :meth:`shut` is the orderly exit."""
        ServerRole.shut(self)
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        if self.persist is not None:
            self.persist.kill()
            if self.data_agent is not None:
                self.data_agent.pipeline = None
            self.persist = None

    def shut(self) -> None:
        # pending-save drain: stage every live session player BEFORE the
        # sockets come down, then give the flusher a bounded window to
        # empty the queue — anything still unflushed (store down) stays
        # durable in the WAL for the next pipeline over this directory
        if self.persist is not None and self.data_agent is not None:
            for sess in self.sessions.values():
                if (sess.guid is not None
                        and sess.guid in self.kernel.store.guid_map):
                    self.data_agent.save(sess.guid)
        super().shut()
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        if self.persist is not None:
            self.persist.drain(self._persist_drain_timeout)
            self.persist.close()
            if self.data_agent is not None:
                self.data_agent.pipeline = None
            self.persist = None

    def _queue_change(self, cname: str, pname: str, rows: np.ndarray) -> None:
        """Property-event sink: accumulate changed rows per (class, prop);
        flushed once per frame (per-write callbacks → per-tick batch)."""
        key = (cname, pname)
        prev = self._changed.get(key)
        self._changed[key] = (
            rows.copy() if prev is None else np.union1d(prev, rows)
        )

    # ---------------------------------------------------- record accumulation
    def _rec_bucket(self, cname: str, rname: str) -> Dict[str, object]:
        b = self._rec_changed.get((cname, rname))
        if b is None:
            # resync: rows whose FINAL state should be re-sent wholesale
            # (used -> add-row, unused -> remove).  Swaps land here: a
            # fixed replay order can't preserve intra-frame interleaving
            # of swap with other ops, but final-state resync always can.
            b = {"add": set(), "del": set(), "upd": {}, "resync": set()}
            self._rec_changed[(cname, rname)] = b
        return b

    def _on_record_host(self, cname, rname, op, erows, rec_row, tags) -> None:
        """Host-path per-op record hook (store mutators)."""
        if (cname, rname) not in self._synced_records:
            return
        b = self._rec_bucket(cname, rname)
        if op == RecordOp.ADD:
            for e in erows:
                key = (int(e), int(rec_row))
                if key not in b["resync"]:
                    b["add"].add(key)
        elif op == RecordOp.DEL:
            for e in erows:
                key = (int(e), int(rec_row))
                b["del"].add(key)
                b["add"].discard(key)
                b["upd"].pop(key, None)
                b["resync"].discard(key)
        elif op == RecordOp.UPDATE:
            for e in erows:
                key = (int(e), int(rec_row))
                if key in b["add"] or key in b["resync"]:
                    continue  # full-row send already pending
                cur = b["upd"].get(key, set())
                if cur is None or tags is None:
                    b["upd"][key] = None  # None = resend every column
                else:
                    b["upd"][key] = cur | set(tags)
        elif op == RecordOp.SWAP:
            origin, target = rec_row
            for e in erows:
                for r in (int(origin), int(target)):
                    key = (int(e), r)
                    b["resync"].add(key)
                    b["add"].discard(key)
                    b["upd"].pop(key, None)
                    b["del"].discard(key)

    def _on_record_diff(self, cname: str, rname: str, codes: np.ndarray) -> None:
        """Device-path record diff sink (buff expiry, stat groups, any
        jitted phase that rewrites record arrays)."""
        b = self._rec_bucket(cname, rname)
        ent, rr = np.nonzero(codes)
        for e, r, c in zip(ent.tolist(), rr.tolist(), codes[ent, rr].tolist()):
            key = (e, r)
            if c == REC_ADDED:
                if key not in b["resync"]:
                    b["add"].add(key)
            elif c == REC_REMOVED:
                b["del"].add(key)
                b["add"].discard(key)
                b["upd"].pop(key, None)
                b["resync"].discard(key)
            elif c == REC_UPDATED and key not in b["add"] and key not in b["resync"]:
                b["upd"][key] = None

    # ---------------------------------------------------- record serialization
    def _obj_ident(self, raw: int) -> Ident:
        g = self.kernel.store.guid_of_handle(int(raw))
        return guid_ident(g) if g is not None else Ident()

    def _record_cells(self, rs, r_i32, r_f32, r_vec, ent: int, r: int, tags):
        """Per-kind cell messages for one record row, via the ONE shared
        record→wire mapping (persist.codec.record_row_cells) so snapshots
        and per-change sync can never diverge."""
        from ...persist.codec import record_row_cells

        return record_row_cells(
            self.kernel.store, rs,
            r_i32[ent] if r_i32 is not None else None,
            r_f32[ent] if r_f32 is not None else None,
            r_vec[ent] if r_vec is not None else None,
            r, tags,
        )

    def _flush_records(self, player_idx=None) -> None:
        """Mid-session record sync: accumulated per-op + device-diff record
        changes → ACK_ADD_ROW / ACK_REMOVE_ROW / ACK_RECORD_* messages
        (reference OnRecordEvent, NFCGameServerNet_ServerModule.cpp:75-81)."""
        rec_changed, self._rec_changed = self._rec_changed, {}
        k = self.kernel
        if player_idx is None and rec_changed:
            player_idx = self._build_player_index()
        for (cname, rname), b in rec_changed.items():
            public = self._synced_records.get((cname, rname), False)
            spec = k.store.spec(cname)
            rs = spec.records[rname]
            rstate = k.state.classes[cname].records[rname]
            used = np.asarray(rstate.used)
            r_i32 = np.asarray(rstate.i32) if rs.n_i32 else None
            r_f32 = np.asarray(rstate.f32) if rs.n_f32 else None
            r_vec = np.asarray(rstate.vec) if rs.n_vec else None
            host = k.store._hosts[cname]
            rname_b = rname.encode()
            per_entity: Dict[int, Dict[str, object]] = {}

            def ops_of(e: int) -> Dict[str, object]:
                o = per_entity.get(e)
                if o is None:
                    o = {"del": [], "add": [], "upd": {}}
                    per_entity[e] = o
                return o

            for e, r in b["del"]:
                ops_of(e)["del"].append(r)
            for e, r in b["add"]:
                ops_of(e)["add"].append(r)
            # resync rows (swaps): final state decides add-row vs remove
            for e, r in b["resync"]:
                if used[e, r]:
                    ops_of(e)["add"].append(r)
                else:
                    ops_of(e)["del"].append(r)
            for (e, r), tags in b["upd"].items():
                ops_of(e)["upd"][r] = tags

            ent_rows = np.asarray(sorted(per_entity), np.int64)
            ent_cells = (
                self._rows_cells(cname, ent_rows)
                if ent_rows.size else np.zeros((0, 2), np.int64)
            )
            cell_of = {
                int(r): ent_cells[i].tolist() for i, r in enumerate(ent_rows)
            }
            vis_map = None
            if (public and self.interest_radius is not None
                    and self._interest_ok(cname)):
                # public record diffs reach only observers in range (and
                # the owner), same scope as the property lanes
                vis_map = self._interest_targets(cname, ent_rows)
            for e, ops in per_entity.items():
                guid = host.row_guid[e] if e < len(host.row_guid) else None
                if guid is None:
                    continue  # died since the change was queued
                sc, gr = cell_of[e]
                if vis_map is not None:
                    targets = list(vis_map.get(e, []))
                    if cname == "Player" and guid not in targets:
                        targets.append(guid)
                else:
                    targets = self._targets_from_index(
                        player_idx, guid, sc, gr, public, cname
                    )
                if not targets:
                    continue
                pid = guid_ident(guid)
                forward = public and cname == "Player"

                def emit(msg_id, msg):
                    self._broadcast(targets, msg_id, msg)
                    if forward:
                        self._forward_world(msg_id, msg, pid)

                if ops["del"]:
                    emit(MsgID.ACK_REMOVE_ROW,
                         ObjectRecordRemove(
                             player_id=pid, record_name=rname_b,
                             remove_row=sorted(set(ops["del"]))))
                add_rows = []
                for r in sorted(set(ops["add"])):
                    if not used[e, r]:
                        continue  # added then removed within the frame
                    add_rows.append(record_row_struct(
                        k.store, rs,
                        r_i32[e] if r_i32 is not None else None,
                        r_f32[e] if r_f32 is not None else None,
                        r_vec[e] if r_vec is not None else None,
                        r))
                if add_rows:
                    emit(MsgID.ACK_ADD_ROW,
                         ObjectRecordAddRow(
                             player_id=pid, record_name=rname_b,
                             row_data=add_rows))
                u_ints: List[RecordInt] = []
                u_floats: List[RecordFloat] = []
                u_strings: List[RecordString] = []
                u_objects: List[RecordObject] = []
                u_vecs: List[RecordVector3] = []
                for r, tags in sorted(ops["upd"].items()):
                    if not used[e, r]:
                        continue
                    ints, floats, strings, objects, vecs = self._record_cells(
                        rs, r_i32, r_f32, r_vec, e, r, tags)
                    u_ints += ints
                    u_floats += floats
                    u_strings += strings
                    u_objects += objects
                    u_vecs += vecs
                if u_ints:
                    emit(MsgID.ACK_RECORD_INT,
                         ObjectRecordInt(player_id=pid, record_name=rname_b,
                                         property_list=u_ints))
                if u_floats:
                    emit(MsgID.ACK_RECORD_FLOAT,
                         ObjectRecordFloat(player_id=pid, record_name=rname_b,
                                           property_list=u_floats))
                if u_strings:
                    emit(MsgID.ACK_RECORD_STRING,
                         ObjectRecordString(player_id=pid, record_name=rname_b,
                                            property_list=u_strings))
                if u_objects:
                    emit(MsgID.ACK_RECORD_OBJECT,
                         ObjectRecordObject(player_id=pid, record_name=rname_b,
                                            property_list=u_objects))
                if u_vecs:
                    emit(MsgID.ACK_RECORD_VECTOR3,
                         ObjectRecordVector3(player_id=pid, record_name=rname_b,
                                             property_list=u_vecs))

    # ------------------------------------------- frame-batched target index
    def _build_player_index(self, player_class: str = "Player"):
        """One-frame broadcast index: players by (scene, group) and by
        scene — built with ONE device fetch per frame, replacing the
        per-entity broadcast_targets calls (each of which fetched whole
        columns; round-1: O(N) host cost at scale)."""
        k = self.kernel
        by_cell: Dict[Tuple[int, int], List[Guid]] = {}
        by_scene: Dict[int, List[Guid]] = {}
        spec = k.store.spec(player_class)
        cs = k.state.classes[player_class]
        host = k.store._hosts[player_class]
        rows = np.flatnonzero(host.alloc_mask)
        if rows.size:
            cols = gather_rows(
                cs.i32, rows,
                cols=[spec.slots["SceneID"].col, spec.slots["GroupID"].col],
            )
            for r, (sc, gr) in zip(rows.tolist(), cols.tolist()):
                g = host.row_guid[r]
                if g is None:
                    continue
                by_cell.setdefault((sc, gr), []).append(g)
                by_scene.setdefault(sc, []).append(g)
        return by_cell, by_scene

    def _targets_from_index(self, idx, guid: Guid, sc: int, gr: int,
                            public: bool, cname: str) -> List[Guid]:
        """GetBroadCastObject over the frame index: Public → players in the
        same (scene, group), GroupID 0 → scene-wide; Private → self if a
        player (NFCSceneAOIModule.cpp:531-593)."""
        if not public:
            return [guid] if cname == "Player" else []
        by_cell, by_scene = idx
        if gr == 0:
            return by_scene.get(sc, [])
        return by_cell.get((sc, gr), [])

    def _interest_targets(self, cname: str,
                          rows: np.ndarray) -> Dict[int, List[Guid]]:
        """Per-row visible OBSERVERS for the per-entity sync lanes: one
        device interest query over the changed rows, inverted into
        row -> [observer avatar guid].  With a radius set, "Public"
        means public to whoever can SEE you — not to the whole group
        (round-4 verdict item 4; reference broadcast scope is the
        coarse (scene, group), NFCSceneAOIModule.cpp:531-593)."""
        import jax.numpy as jnp

        out: Dict[int, List[Guid]] = {}
        if rows.size == 0:
            return out
        obs, obs_rows, obs_valid = self._observer_arrays()
        if not obs:
            return out
        k = self.kernel
        changed = np.zeros(k.store.capacity(cname), bool)
        changed[rows] = True
        cs = k.state.classes[cname]
        fn = self._interest_query(cname, len(obs_rows))
        if self._interest_skin > 0.0:
            ckey, cache = self._interest_cache_for(cname)
            vrows, vok, cache = fn(
                cs.vec, cs.i32, jnp.asarray(changed), cs.alive,
                k.state.classes["Player"].vec, k.state.classes["Player"].i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid), cache,
            )
            self._interest_cache_store(ckey, cache)
        else:
            vrows, vok = fn(
                cs.vec, cs.i32, jnp.asarray(changed),
                k.state.classes["Player"].vec, k.state.classes["Player"].i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid),
            )
        vrows, vok = np.asarray(vrows), np.asarray(vok)
        # nf-lint: disable=serve-loop -- per-entity property lane shared
        # by both engines; diffs here are < batch_sync_min rows, so the
        # loop is small-N (batching it is ROADMAP debt, not serve-path)
        for i, sess in enumerate(obs):
            g = sess.guid
            if g is None:
                continue
            for r in vrows[i][vok[i]].tolist():
                out.setdefault(int(r), []).append(g)
        return out

    def _rows_cells(self, cname: str, rows: np.ndarray) -> np.ndarray:
        """[n, 2] (SceneID, GroupID) for the given rows — one device
        gather instead of two get_property round trips per entity."""
        k = self.kernel
        spec = k.store.spec(cname)
        cs = k.state.classes[cname]
        return gather_rows(
            cs.i32, rows,
            cols=[spec.slots["SceneID"].col, spec.slots["GroupID"].col],
        )

    def _flush_changes(self) -> None:
        """The batched §3.3 spine: changed cells → grouped property-sync
        messages → proxy (client lists in the envelope).  All device reads
        are row-subset gathers done once per class per frame."""
        k = self.kernel
        sc = self.stage_clock
        with sc.stage("harvest"):
            changed, self._changed = self._changed, {}
            player_idx = self._build_player_index()
            obs_moved = False
            if self.interest_radius is not None:
                # observer-set gate: any session join/leave/respawn must
                # wake the interest lane even with zero Position diffs.
                # Lives in HARVEST (it walks the session dict — shared
                # bookkeeping, not serve work; the batched engine's
                # interest stage is loop-free, nf-lint serve-loop rule)
                obs_sig = tuple(sorted(
                    (key, s.guid)
                    for key, s in self.sessions.items()
                    if s.guid is not None
                    and s.guid in self.kernel.store.guid_map
                ))
                obs_moved = obs_sig != self._last_obs_sig
                self._last_obs_sig = obs_sig
                if self.serve_batch:
                    self._serve_refresh_table()
        # interest lane: Position diffs of synced classes leave as
        # per-session interest-filtered streams when a radius is set.
        # The pipeline only runs when something that can change a visible
        # set happened — a Position diff in the class, observer movement
        # (Player Position), an observer set change, or a create/destroy
        # in the class (the dirty marks) — so an idle world pays nothing.
        self._obs_cache = None  # one _observer_arrays() per flush
        if self.interest_radius is not None:
            with sc.stage("interest"):

                def zone_changed(cn: str) -> bool:
                    # visible sets mask on scene+group too — a swap with
                    # no Position diff still changes who sees whom.
                    # These keys are NOT popped: zone props also ride
                    # the normal broadcast sync.
                    return ((cn, "SceneID") in changed
                            or (cn, "GroupID") in changed)

                player_moved = ("Player", "Position") in changed \
                    or zone_changed("Player")
                for cname in self.sync_classes:
                    # only claim the diff when the class can ride the
                    # interest lane — non-spatial classes (no SceneID/
                    # GroupID) fall through to the broadcast lanes below
                    if not self._interest_ok(cname):
                        continue
                    pos_changed = changed.pop(
                        (cname, "Position"), None) is not None
                    if (pos_changed or player_moved or obs_moved
                            or zone_changed(cname)
                            or cname in self._interest_dirty):
                        self._interest_dirty.discard(cname)
                        if self.serve_overlap:
                            # double-buffered: serve this class's lane
                            # against the PRE-tick snapshot of the next
                            # frame, overlapping assembly with its tick
                            self._serve_pending[cname] = True
                        elif self.serve_batch:
                            self._send_interest_pos_batched(cname)
                        else:
                            self._send_interest_pos(cname)
        with sc.stage("encode"):
            # columnar fast lane: large public scalar/vector diffs leave
            # as packed-array batches (100k movers = a handful of
            # messages, not 100k python serializations)
            if self.batch_sync_min > 0:
                for key in [
                    kk for kk, rows in changed.items()
                    if rows.size >= self.batch_sync_min
                ]:
                    cname, pname = key
                    p = k.store.spec(cname).slot(pname).prop
                    if p.public and p.type in (
                        DataType.INT, DataType.FLOAT,
                        DataType.VECTOR2, DataType.VECTOR3,
                    ):
                        self._send_batch_property(
                            cname, pname, changed.pop(key), player_idx
                        )
            # regroup per (class, row): one message per entity per kind
            per_entity: Dict[Tuple[str, int], List[str]] = {}
            for (cname, pname), rows in changed.items():
                for row in rows:
                    per_entity.setdefault((cname, int(row)), []).append(pname)
            rows_by_class: Dict[str, np.ndarray] = {}
            for cname, row in per_entity:
                rows_by_class.setdefault(cname, []).append(row)
            pos_by_class: Dict[str, Dict[int, int]] = {}
            cells_by_class: Dict[str, np.ndarray] = {}
            vis_by_class: Dict[str, Dict[int, List[Guid]]] = {}
            for cname, rws in list(rows_by_class.items()):
                arr = np.asarray(sorted(set(rws)), np.int64)
                rows_by_class[cname] = arr
                pos_by_class[cname] = {int(r): i for i, r in enumerate(arr)}
                cells_by_class[cname] = self._rows_cells(cname, arr)
                if (self.interest_radius is not None
                        and self._interest_ok(cname)):
                    # device visibility query: interest work even though
                    # it feeds the encode loop below
                    with sc.stage("interest"):
                        vis_by_class[cname] = self._interest_targets(
                            cname, arr)
            sub_cache: Dict[Tuple[str, str], np.ndarray] = {}

            def bank_vals(cname: str, bank: Bank) -> np.ndarray:
                """Row-subset bank fetch, indexed by LOCAL position."""
                key = (cname, bank.value)
                if key not in sub_cache:
                    cs = k.state.classes[cname]
                    sub_cache[key] = gather_rows(
                        getattr(cs, bank.value), rows_by_class[cname]
                    )
                return sub_cache[key]

            for (cname, row), pnames in per_entity.items():
                host = k.store._hosts[cname]
                guid = host.row_guid[row] if row < len(host.row_guid) else None
                if guid is None:
                    continue  # died since the change was queued
                spec = k.store.spec(cname)
                pos = pos_by_class[cname][row]
                scn, gr = cells_by_class[cname][pos].tolist()
                # public props broadcast to the (scene, group); private-
                # only props go to the owner's client alone
                for public in (True, False):
                    sel = [
                        p for p in pnames
                        if bool(spec.slot(p).prop.public) is public
                        and (public or spec.slot(p).prop.private)
                    ]
                    if not sel:
                        continue
                    if public and cname in vis_by_class:
                        # interest lane: public to whoever can see you,
                        # plus always the owner's own client
                        targets = list(vis_by_class[cname].get(row, []))
                        if cname == "Player" and guid not in targets:
                            targets.append(guid)
                    else:
                        targets = self._targets_from_index(
                            player_idx, guid, scn, gr, public, cname
                        )
                    if not targets:
                        continue
                    self._send_property_msgs(
                        cname, pos, guid, sel, targets, bank_vals,
                        forward=(public and cname == "Player"),
                    )
            self._flush_records(player_idx)

    def _interest_step(self, cname: str, s_pad: int):
        """Cached per-(class, padded-session-count) jit of the interest
        pipeline: quantize positions, bin ALL alive in-extent entities into
        the cell table, read each observer's 3x3 neighborhood, distance+zone
        mask (ops/interest; the same stencil engine combat runs on).

        Visibility runs over the full alive set — not just movers — so the
        host can diff each session's visible set against what that session
        last saw: entities that moved while unobserved and then stopped are
        re-sent the moment an observer walks into range (the reference's
        enter-view resend, NFCSceneAOIModule OnObjectListEnter)."""
        key = (cname, s_pad)
        fn = self._interest_jit.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ...ops.interest import (
            quantize,
            visible_candidates,
            visible_candidates_cached,
        )
        from ...ops.stencil import auto_bucket

        k = self.kernel
        spec = k.store.spec(cname)
        pspec = k.store.spec("Player")
        pos_col = spec.slots["Position"].col
        sc_col, gr_col = spec.slots["SceneID"].col, spec.slots["GroupID"].col
        p_pos = pspec.slots["Position"].col
        p_sc, p_gr = pspec.slots["SceneID"].col, pspec.slots["GroupID"].col
        extent = float(self.game_world.config.extent)
        radius = float(self.interest_radius)
        skin = float(self._interest_skin)
        # skin > 0 inflates the cell so the 3x3 read still covers the true
        # radius from anchors up to skin/2 stale (ops/verlet.py)
        cell = radius + skin if skin > 0.0 else radius
        width = max(1, int(np.ceil(extent / cell)))
        cap = k.store.capacity(cname)
        bucket = auto_bucket(cap, width)

        if skin > 0.0:
            def step(evec, ei32, alive, pvec, pi32, obs_rows, obs_valid,
                     cache):
                pos3 = evec[:, pos_col]
                q, in_extent = quantize(pos3, alive, extent)
                res, cache, _rebuilt = visible_candidates_cached(
                    cache, pos3, in_extent, alive,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket, skin=skin,
                )
                return q, res.rows, res.ok & obs_valid[:, None], cache
        else:
            def step(evec, ei32, alive, pvec, pi32, obs_rows, obs_valid):
                pos3 = evec[:, pos_col]
                q, in_extent = quantize(pos3, alive, extent)
                res = visible_candidates(
                    pos3, in_extent,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket,
                )
                return q, res.rows, res.ok & obs_valid[:, None]

        fn = self.kernel.costbook.wrap(
            f"interest.step/{cname}", step, stage="interest"
        )
        self._interest_jit[key] = fn
        return fn

    def _interest_query(self, cname: str, s_pad: int):
        """Cached jit of the query-only interest pipeline: caller supplies
        the changed-row mask (any property's diff), gets per-observer
        visible candidates.  The Position stream has its own variant with
        the quantize/delta gate fused in (_interest_step)."""
        key = ("q", cname, s_pad)
        fn = self._interest_jit.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ...ops.interest import visible_candidates, visible_candidates_cached
        from ...ops.stencil import auto_bucket

        k = self.kernel
        spec = k.store.spec(cname)
        pspec = k.store.spec("Player")
        pos_col = spec.slots["Position"].col
        sc_col, gr_col = spec.slots["SceneID"].col, spec.slots["GroupID"].col
        p_pos = pspec.slots["Position"].col
        p_sc, p_gr = pspec.slots["SceneID"].col, pspec.slots["GroupID"].col
        extent = float(self.game_world.config.extent)
        radius = float(self.interest_radius)
        skin = float(self._interest_skin)
        cell = radius + skin if skin > 0.0 else radius
        width = max(1, int(np.ceil(extent / cell)))
        bucket = auto_bucket(k.store.capacity(cname), width)

        if skin > 0.0:
            def query(evec, ei32, changed, alive, pvec, pi32, obs_rows,
                      obs_valid, cache):
                res, cache, _rebuilt = visible_candidates_cached(
                    cache, evec[:, pos_col], changed, alive,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket, skin=skin,
                )
                return res.rows, res.ok & obs_valid[:, None], cache
        else:
            def query(evec, ei32, changed, pvec, pi32, obs_rows, obs_valid):
                res = visible_candidates(
                    evec[:, pos_col], changed,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket,
                )
                return res.rows, res.ok & obs_valid[:, None]

        fn = self.kernel.costbook.wrap(
            f"interest.query/{cname}", query, stage="interest"
        )
        self._interest_jit[key] = fn
        return fn

    def _interest_cache_for(self, cname: str):
        """The class's interest Verlet cache, carried in WorldState.aux
        (key "verlet/interest/<class>") so telemetry, invalidate() and
        sharded placement treat it like any other grid cache.  Registers
        the aux init lazily on first use."""
        from ...ops.verlet import init_cache

        k = self.kernel
        key = f"verlet/interest/{cname}"
        if key not in k._aux_init:
            cap = k.store.capacity(cname)
            k.register_aux(key, lambda c=cap: init_cache(c))
        k._ensure_aux()
        return key, k.state.aux[key]

    def _interest_cache_store(self, key: str, cache) -> None:
        k = self.kernel
        k.state = k.state.replace(aux={**k.state.aux, key: cache})

    def _interest_ok(self, cname: str) -> bool:
        """The interest lane needs spatial columns; classes without them
        stay on the broadcast lane."""
        slots = self.kernel.store.spec(cname).slots
        return all(n in slots for n in ("Position", "SceneID", "GroupID"))

    def _observer_arrays(self):
        """(sessions with live avatars, padded row array, validity mask);
        computed once per flush (_obs_cache cleared in _flush_changes)."""
        cached = getattr(self, "_obs_cache", None)
        if cached is not None:
            return cached
        from ...core.datatypes import next_pow2

        k = self.kernel
        # nf-lint: disable=serve-loop -- legacy engine's observer
        # collector (the parity oracle for NF_SERVE_BATCH); the batched
        # path reads the SessionTable columns instead
        obs = [
            s for s in self.sessions.values()
            if s.guid is not None and s.guid in k.store.guid_map
        ]
        if not obs:
            self._obs_cache = ([], None, None)
            return self._obs_cache
        rows = np.zeros(next_pow2(len(obs), lo=8), np.int32)
        for i, s in enumerate(obs):
            rows[i] = k.store.row_of(s.guid)[1]
        valid = np.zeros(rows.shape, bool)
        valid[: len(obs)] = True
        self._obs_cache = (obs, rows, valid)
        return self._obs_cache

    def _send_interest_pos(self, cname: str) -> None:
        """Per-session Position stream: ONE compact message per client
        carrying only the entities inside its interest radius, positions
        u16-quantized over the scene extent (scale rides the message).
        Replaces the group-broadcast lane for Position when
        `interest_radius` is set.

        Each session carries its OWN seen-state (sorted row array + guid +
        last-sent quantized position): an entity hits a session's wire
        when it enters that session's view (first sight or re-entry) or
        when its quantized position differs from what that session last
        received.  Leaving view drops the entity from the seen-state, so
        re-entry resends — the per-observer correctness the reference gets
        from OnObjectListEnter, without any global last-synced table (and
        hence no stale-row hazard when rows are recycled: the guid is part
        of the match)."""
        import jax.numpy as jnp

        from ...ops.interest import QMAX
        from ..wire import InterestPosSync

        k = self.kernel
        spec = k.store.spec(cname)
        if "Position" not in spec.slots:
            return
        obs, obs_rows, obs_valid = self._observer_arrays()
        if not obs:
            return

        cs = k.state.classes[cname]
        pcs = k.state.classes["Player"]
        fn = self._interest_step(cname, len(obs_rows))
        if self._interest_skin > 0.0:
            ckey, cache = self._interest_cache_for(cname)
            q, rows, ok, cache = fn(
                cs.vec, cs.i32, cs.alive,
                pcs.vec, pcs.i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid), cache,
            )
            self._interest_cache_store(ckey, cache)
        else:
            q, rows, ok = fn(
                cs.vec, cs.i32, cs.alive,
                pcs.vec, pcs.i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid),
            )
        q_np = np.asarray(q).astype(np.uint16)
        rows_np, ok_np = np.asarray(rows), np.asarray(ok)
        host = k.store._hosts[cname]
        scale = float(self.game_world.config.extent) / QMAX
        # nf-lint: disable=serve-loop -- the legacy per-session engine
        # itself (NF_SERVE_BATCH=0): kept as the bit-identity oracle for
        # tests/test_serve_batch.py, never the production hot path
        for i, sess in enumerate(obs):
            vis = rows_np[i][ok_np[i]]
            vis = vis[host.alloc_mask[vis]]  # drop just-died rows
            seen = getattr(sess, "_interest_seen", None)
            if seen is None:
                seen = self.reset_view(sess)
            vis = np.sort(vis)
            heads = host.guid_head[vis]
            datas = host.guid_data[vis]
            qv = q_np[vis]  # [n, 3]
            prev = seen.get(cname)
            if prev is None:
                send = np.ones(vis.size, bool)
                gone_h = gone_d = np.empty(0, np.int64)
            else:
                p_rows, p_heads, p_datas, p_q = prev
                idx = np.searchsorted(p_rows, vis)
                idx_c = np.minimum(idx, max(len(p_rows) - 1, 0))
                same = (
                    (len(p_rows) > 0)
                    & (p_rows[idx_c] == vis)
                    & (p_heads[idx_c] == heads)
                    & (p_datas[idx_c] == datas)
                    & np.all(p_q[idx_c] == qv, axis=-1)
                )
                send = ~same
                # leave-view: previously-seen guids whose row is gone from
                # the visible set (or recycled to another guid) — the
                # delta stream needs an explicit despawn signal
                if vis.size:
                    j = np.searchsorted(vis, p_rows)
                    j_c = np.minimum(j, vis.size - 1)
                    still = (
                        (vis[j_c] == p_rows)
                        & (heads[j_c] == p_heads)
                        & (datas[j_c] == p_datas)
                    )
                else:
                    still = np.zeros(len(p_rows), bool)
                gone_h, gone_d = p_heads[~still], p_datas[~still]
            if vis.size == 0:
                seen.pop(cname, None)
            else:
                seen[cname] = (vis, heads, datas, qv)
            if not send.any() and gone_h.size == 0:
                continue
            msg = InterestPosSync(
                scale=scale,
                count=int(send.sum()),
                svrid=heads[send].tobytes(),
                index=datas[send].tobytes(),
                qpos=np.ascontiguousarray(qv[send]).tobytes(),
                gone_svrid=gone_h.tobytes(),
                gone_index=gone_d.tobytes(),
            )
            self._send_to_session(sess, MsgID.ACK_INTEREST_POS, msg)

    # ------------------------------------------------ batched serve edge
    # ISSUE 13: the NF_SERVE_BATCH engine.  Same wire bytes as the legacy
    # loops above (tests/test_serve_batch.py proves bit-identity), but
    # the per-session set algebra runs as ONE vmap-over-sessions device
    # dispatch (ops/serving.py) against the SessionTable's seen-state,
    # and the host's only per-session work is slicing precomputed byte
    # buffers into packets (net/serving.py).

    def _serve_geometry(self, cname: str):
        """(cell, width, bucket, m): grid geometry shared with the legacy
        jits — identical candidate sets are the parity precondition.  `m`
        is the seen-table width: 9*bucket covers every candidate slot
        exactly; NF_SERVE_SLOTS can cap it (memory at huge session
        counts) at the cost of dropping the farthest-slot candidates of
        overfull views for a frame."""
        geom = getattr(self, "_serve_geom", None)
        if geom is None:
            geom = self._serve_geom = {}
        g = geom.get(cname)
        if g is not None:
            return g
        from ...ops.stencil import auto_bucket

        extent = float(self.game_world.config.extent)
        radius = float(self.interest_radius)
        skin = float(self._interest_skin)
        cell = radius + skin if skin > 0.0 else radius
        width = max(1, int(np.ceil(extent / cell)))
        bucket = auto_bucket(self.kernel.store.capacity(cname), width)
        m = 9 * bucket
        cap_m = _env_int("NF_SERVE_SLOTS", 0)
        if cap_m > 0:
            m = min(m, cap_m)
        g = (cell, width, bucket, m)
        geom[cname] = g
        return g

    def _serve_refresh_table(self) -> None:
        """Harvest-stage SessionTable sync: one slot per session with a
        live avatar.  Slots of departed sessions free here (robust to
        every removal path), stale seen-state wipes on realloc."""
        st = self._session_table
        for key in list(st.slot_of):
            if key not in self.sessions:
                st.release(key)
        k = self.kernel
        for key, s in self.sessions.items():
            if s.guid is not None and s.guid in k.store.guid_map:
                st.ensure(key, s.conn_id, k.store.row_of(s.guid)[1])
            else:
                st.invalidate(key)

    def _serve_prepare(self, cname: str):
        """Per-class 'prepare' jit: quantize + position-version bump +
        cell-table build, ONCE per frame regardless of session chunking
        (per-chunk bumping would multi-count a single move)."""
        key = ("sprep", cname)
        fn = self._serve_jit.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ...ops.interest import _interest_feats, quantize
        from ...ops.serving import bump_qver
        from ...ops.stencil import build_cell_table
        from ...ops.verlet import refresh, sub_table

        spec = self.kernel.store.spec(cname)
        pos_col = spec.slots["Position"].col
        sc_col, gr_col = spec.slots["SceneID"].col, spec.slots["GroupID"].col
        extent = float(self.game_world.config.extent)
        skin = float(self._interest_skin)
        cell, width, bucket, _m = self._serve_geometry(cname)

        if skin > 0.0:
            def prep(evec, ei32, alive, qver, prev_q, cache):
                pos3 = evec[:, pos_col]
                q, in_extent = quantize(pos3, alive, extent)
                qver2, prev2 = bump_qver(q, prev_q, qver)
                cache, _rebuilt = refresh(
                    cache, pos3, alive, cell, width, bucket, skin
                )
                feats = _interest_feats(
                    pos3,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                )
                table = sub_table(
                    cache, in_extent & alive, feats, width * width,
                    cell, width, bucket,
                )
                return q, qver2, prev2, table.payload, cache
        else:
            def prep(evec, ei32, alive, qver, prev_q):
                pos3 = evec[:, pos_col]
                q, in_extent = quantize(pos3, alive, extent)
                qver2, prev2 = bump_qver(q, prev_q, qver)
                feats = _interest_feats(
                    pos3,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                )
                table = build_cell_table(
                    pos3, in_extent, feats, cell, width, bucket
                )
                return q, qver2, prev2, table.payload

        fn = self.kernel.costbook.wrap(
            f"serve.prepare/{cname}", prep, stage="interest"
        )
        self._serve_jit[key] = fn
        return fn

    def _serve_scan(self, cname: str, s_chunk: int):
        """Per-(class, chunk) 'scan' jit: 3x3 candidate read + the full
        delta set algebra for a contiguous block of session slots.  Only
        the payload array crosses the prepare/scan seam — the grid
        geometry is static in both closures."""
        key = ("sscan", cname, s_chunk)
        fn = self._serve_jit.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ...ops.interest import _scan_observers
        from ...ops.serving import SeenTable, interest_delta, slot_compact
        from ...ops.stencil import CellTable

        pspec = self.kernel.store.spec("Player")
        p_pos = pspec.slots["Position"].col
        p_sc, p_gr = pspec.slots["SceneID"].col, pspec.slots["GroupID"].col
        radius = float(self.interest_radius)
        cell, width, bucket, m = self._serve_geometry(cname)
        k9 = 9 * bucket

        def scan(payload, pvec, pi32, obs_rows, valid, alloc_ok, gen,
                 qver, seen_rows, seen_gen, seen_qver):
            table = CellTable(
                payload, jnp.zeros((1,), jnp.int32),
                jnp.zeros((), jnp.int32), width, cell, bucket,
            )
            res = _scan_observers(
                table,
                pvec[obs_rows, p_pos][:, :2],
                pi32[obs_rows, p_sc].astype(jnp.float32),
                pi32[obs_rows, p_gr].astype(jnp.float32),
                radius, cell,
            )
            # device-side alloc filter: the legacy loop's just-died-row
            # drop (host.alloc_mask), applied before the delta algebra
            ok = res.ok & valid[:, None] & alloc_ok[res.rows]
            rows = res.rows
            if m < k9:  # NF_SERVE_SLOTS cap: keep slot-order prefix
                rows, counts = slot_compact(rows, ok)
                rows = rows[:, :m]
                ok = jnp.arange(m, dtype=jnp.int32)[None, :] < counts[:, None]
            return interest_delta(
                rows, ok, gen, qver,
                SeenTable(seen_rows, seen_gen, seen_qver),
            )

        fn = self.kernel.costbook.wrap(
            f"serve.scan/{cname}", scan, stage="interest"
        )
        self._serve_jit[key] = fn
        return fn

    def _serve_pos_collect(self, cname: str):
        """Device half of the batched Position lane: dispatch prepare +
        chunked scans and FETCH the dense delta buffers.  Returns the
        host-assembly payload, or None when there are no observers.
        Must run before tick dispatch (donation invalidates the serve
        kernel's input buffers); the returned dict needs no device."""
        import jax
        import jax.numpy as jnp

        k = self.kernel
        st = self._session_table
        if st.capacity == 0 or not st.valid.any():
            return None
        host = k.store._hosts[cname]
        cs = k.state.classes[cname]
        pcs = k.state.classes["Player"]
        _cell, _width, _bucket, m = self._serve_geometry(cname)

        cap = k.store.capacity(cname)
        qp = self._serve_qver.get(cname)
        if qp is None or qp[0].shape[0] != cap:
            # prev_q = -1: every row's first observed quantum counts as
            # a change, so a fresh engine never suppresses a first send
            qp = (jnp.zeros(cap, jnp.int32),
                  jnp.full((cap, 3), -1, jnp.int32))
        qver, prev_q = qp

        prep = self._serve_prepare(cname)
        if self._interest_skin > 0.0:
            ckey, cache = self._interest_cache_for(cname)
            q, qver, prev_q, payload, cache = prep(
                cs.vec, cs.i32, cs.alive, qver, prev_q, cache
            )
            self._interest_cache_store(ckey, cache)
        else:
            q, qver, prev_q, payload = prep(
                cs.vec, cs.i32, cs.alive, qver, prev_q
            )
        self._serve_qver[cname] = (qver, prev_q)

        gen = jnp.asarray(host.row_gen)
        alloc_ok = jnp.asarray(host.alloc_mask)
        obs_rows = jnp.asarray(st.avatar_row)
        valid = jnp.asarray(st.valid)
        seen = st.seen_for(cname, m)

        s_total = st.capacity
        chunk = _env_int("NF_SERVE_CHUNK", 0)
        if chunk <= 0 or chunk >= s_total:
            chunk = s_total
        parts = []
        for c0 in range(0, s_total, chunk):
            c1 = c0 + chunk
            fn = self._serve_scan(cname, chunk)
            delta = fn(
                payload, pcs.vec, pcs.i32,
                obs_rows[c0:c1], valid[c0:c1], alloc_ok, gen, qver,
                seen.rows[c0:c1], seen.gen[c0:c1], seen.qver[c0:c1],
            )
            self._serve_dispatches.inc()
            parts.append(jax.device_get(
                (delta.vis, delta.send, delta.gone, delta.gone_rows)
            ))
            seen = type(seen)(
                rows=seen.rows.at[c0:c1].set(delta.seen.rows),
                gen=seen.gen.at[c0:c1].set(delta.seen.gen),
                qver=seen.qver.at[c0:c1].set(delta.seen.qver),
            )
        st.store_seen(cname, seen)
        self._serve_sessions_hist.observe(int(st.valid.sum()))

        # gone lists carry guids AS LAST SERVED — freed rows have their
        # live guid zeroed, so gather from the previous run's mirrors
        prev_h, prev_d = self._serve_prev_guids.get(
            cname, (host.guid_head, host.guid_data)
        )
        self._serve_prev_guids[cname] = (
            host.guid_head.copy(), host.guid_data.copy()
        )
        cat = (lambda i: np.concatenate([p[i] for p in parts])
               if len(parts) > 1 else parts[0][i])
        return {
            "cname": cname,
            "q": np.asarray(q).astype(np.uint16),
            "vis": cat(0), "send": cat(1),
            "gone": cat(2), "gone_rows": cat(3),
            "prev_h": prev_h, "prev_d": prev_d,
        }

    def _serve_pos_emit(self, data) -> None:
        """Host half: batched frame assembly.  Flatten the [S, M] masks
        (row-major = session-major, per-session ascending because vis is
        sorted), gather ONCE from the host guid mirrors, materialize ONE
        payload per wire field, and slice per-session packets at cumsum
        byte offsets — zero per-session device syncs or numpy passes."""
        from ...ops.interest import QMAX
        from ..serving import segments
        from ..wire import InterestPosSync

        k = self.kernel
        st = self._session_table
        host = k.store._hosts[data["cname"]]
        q_np, vis, send = data["q"], data["vis"], data["send"]
        gone, gone_rows = data["gone"], data["gone_rows"]

        send_counts = send.sum(axis=1)
        gone_counts = gone.sum(axis=1)
        flat_rows = vis[send]
        heads_b = host.guid_head[flat_rows].tobytes()
        datas_b = host.guid_data[flat_rows].tobytes()
        qpos_b = np.ascontiguousarray(q_np[flat_rows]).tobytes()
        o8, _ = segments(send_counts, 8, heads_b)
        o6, _ = segments(send_counts, 6, qpos_b)
        flat_gone = gone_rows[gone]
        gh_b = data["prev_h"][flat_gone].tobytes()
        gd_b = data["prev_d"][flat_gone].tobytes()
        g8, _ = segments(gone_counts, 8, gh_b)

        scale = float(self.game_world.config.extent) / QMAX
        sent = 0
        for key, sess in self.sessions.items():
            slot = st.slot_of.get(key)
            if slot is None or not st.valid[slot]:
                continue
            ns, ng = int(send_counts[slot]), int(gone_counts[slot])
            if ns == 0 and ng == 0:
                continue
            msg = InterestPosSync(
                scale=scale,
                count=ns,
                svrid=heads_b[o8[slot]:o8[slot + 1]],
                index=datas_b[o8[slot]:o8[slot + 1]],
                qpos=qpos_b[o6[slot]:o6[slot + 1]],
                gone_svrid=gh_b[g8[slot]:g8[slot + 1]],
                gone_index=gd_b[g8[slot]:g8[slot + 1]],
            )
            self._send_to_session(sess, MsgID.ACK_INTEREST_POS, msg)
            sent += 1
        self._serve_packets.inc(sent)

    def _send_interest_pos_batched(self, cname: str) -> None:
        """Synchronous batched Position lane (NF_SERVE_BATCH without
        overlap): collect in the interest stage, assemble+send nested
        under 'assemble' so the waterfall attributes the host slicing."""
        data = self._serve_pos_collect(cname)
        if data is None:
            return
        with self.stage_clock.stage("assemble"):
            self._serve_pos_emit(data)

    def _serve_query(self, cname: str, s_pad: int):
        """Batched interest-scoped BatchPropertySync query jit: legacy
        `_interest_query` + device alloc filter + stable slot-order
        compaction (the lane's wire order is candidate slot order)."""
        key = ("bscan", cname, s_pad)
        fn = self._serve_jit.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from ...ops.interest import (
            visible_candidates,
            visible_candidates_cached,
        )
        from ...ops.serving import slot_compact

        k = self.kernel
        spec = k.store.spec(cname)
        pspec = k.store.spec("Player")
        pos_col = spec.slots["Position"].col
        sc_col, gr_col = spec.slots["SceneID"].col, spec.slots["GroupID"].col
        p_pos = pspec.slots["Position"].col
        p_sc, p_gr = pspec.slots["SceneID"].col, pspec.slots["GroupID"].col
        radius = float(self.interest_radius)
        skin = float(self._interest_skin)
        cell, width, bucket, _m = self._serve_geometry(cname)

        if skin > 0.0:
            def query(evec, ei32, changed, alive, pvec, pi32, obs_rows,
                      valid, alloc_ok, cache):
                res, cache, _rebuilt = visible_candidates_cached(
                    cache, evec[:, pos_col], changed, alive,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket, skin=skin,
                )
                ok = res.ok & valid[:, None] & alloc_ok[res.rows]
                rows, counts = slot_compact(res.rows, ok)
                return rows, counts, cache
        else:
            def query(evec, ei32, changed, pvec, pi32, obs_rows, valid,
                      alloc_ok):
                res = visible_candidates(
                    evec[:, pos_col], changed,
                    ei32[:, sc_col].astype(jnp.float32),
                    ei32[:, gr_col].astype(jnp.float32),
                    pvec[obs_rows, p_pos][:, :2],
                    pi32[obs_rows, p_sc].astype(jnp.float32),
                    pi32[obs_rows, p_gr].astype(jnp.float32),
                    radius=radius, cell_size=cell, width=width,
                    bucket=bucket,
                )
                ok = res.ok & valid[:, None] & alloc_ok[res.rows]
                rows, counts = slot_compact(res.rows, ok)
                return rows, counts

        fn = self.kernel.costbook.wrap(
            f"serve.query/{cname}", query, stage="interest"
        )
        self._serve_jit[key] = fn
        return fn

    def _send_batch_property_interest_batched(
        self, cname: str, pname: str, rows: np.ndarray
    ) -> None:
        """Batched interest-scoped columnar sync: one device query for
        all sessions, one value gather, per-session byte slices."""
        import jax
        import jax.numpy as jnp

        from ..serving import segments
        from ..wire import BatchPropertySync

        k = self.kernel
        st = self._session_table
        host = k.store._hosts[cname]
        spec = k.store.spec(cname)
        slot = spec.slot(pname)
        rows = rows[host.alloc_mask[rows]]
        if rows.size == 0 or st.capacity == 0 or not st.valid.any():
            return
        cap = k.store.capacity(cname)
        changed = np.zeros(cap, bool)
        changed[rows] = True
        cs = k.state.classes[cname]
        pcs = k.state.classes["Player"]
        fn = self._serve_query(cname, st.capacity)
        obs_rows = jnp.asarray(st.avatar_row)
        valid = jnp.asarray(st.valid)
        alloc_ok = jnp.asarray(host.alloc_mask)
        if self._interest_skin > 0.0:
            ckey, cache = self._interest_cache_for(cname)
            vrows, counts, cache = fn(
                cs.vec, cs.i32, jnp.asarray(changed), cs.alive,
                pcs.vec, pcs.i32, obs_rows, valid, alloc_ok, cache,
            )
            self._interest_cache_store(ckey, cache)
        else:
            vrows, counts = fn(
                cs.vec, cs.i32, jnp.asarray(changed),
                pcs.vec, pcs.i32, obs_rows, valid, alloc_ok,
            )
        self._serve_dispatches.inc()
        vrows, counts = jax.device_get((vrows, counts))

        if slot.bank == Bank.VEC:
            vals = gather_rows(cs.vec, rows, cols=slot.col)[:, 0]
        elif slot.bank == Bank.F32:
            vals = gather_rows(cs.f32, rows, cols=slot.col)[:, 0]
        else:
            vals = gather_rows(cs.i32, rows, cols=slot.col)[:, 0]
        vals = np.asarray(vals)
        pos_of = np.full(cap, -1, np.int64)
        pos_of[rows] = np.arange(rows.size)

        with self.stage_clock.stage("assemble"):
            mask = np.arange(vrows.shape[1])[None, :] < counts[:, None]
            flat = vrows[mask]  # session-major, slot order per session
            heads_b = host.guid_head[flat].tobytes()
            datas_b = host.guid_data[flat].tobytes()
            vals_flat = np.ascontiguousarray(vals[pos_of[flat]])
            item = vals_flat.itemsize * (
                int(np.prod(vals_flat.shape[1:])) if vals_flat.ndim > 1
                else 1
            )
            data_b = vals_flat.tobytes()
            o8, _ = segments(counts, 8, heads_b)
            ov, _ = segments(counts, item, data_b)
            name_b, cls_b = pname.encode(), cname.encode()
            ptype = int(slot.prop.type)
            sent = 0
            for key, sess in self.sessions.items():
                si = st.slot_of.get(key)
                if si is None or not st.valid[si]:
                    continue
                n = int(counts[si])
                if n == 0:
                    continue
                msg = BatchPropertySync(
                    class_name=cls_b,
                    property_name=name_b,
                    ptype=ptype,
                    count=n,
                    svrid=heads_b[o8[si]:o8[si + 1]],
                    index=datas_b[o8[si]:o8[si + 1]],
                    data=data_b[ov[si]:ov[si + 1]],
                )
                self._send_to_session(sess, MsgID.ACK_BATCH_PROPERTY, msg)
                sent += 1
            self._serve_packets.inc(sent)

    def _send_batch_property_interest(self, cname: str, pname: str,
                                      rows: np.ndarray) -> None:
        """Interest-scoped columnar sync: each session gets ONE
        BatchPropertySync with only the changed entities inside its
        interest radius (same message type as the broadcast lane, so
        clients are agnostic to the fan-out mode)."""
        import jax.numpy as jnp

        from ..wire import BatchPropertySync

        k = self.kernel
        host = k.store._hosts[cname]
        spec = k.store.spec(cname)
        slot = spec.slot(pname)
        rows = rows[host.alloc_mask[rows]]
        if rows.size == 0:
            return
        obs, obs_rows, obs_valid = self._observer_arrays()
        if not obs:
            return
        cap = k.store.capacity(cname)
        changed = np.zeros(cap, bool)
        changed[rows] = True
        cs = k.state.classes[cname]
        fn = self._interest_query(cname, len(obs_rows))
        if self._interest_skin > 0.0:
            ckey, cache = self._interest_cache_for(cname)
            vrows, vok, cache = fn(
                cs.vec, cs.i32, jnp.asarray(changed), cs.alive,
                k.state.classes["Player"].vec, k.state.classes["Player"].i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid), cache,
            )
            self._interest_cache_store(ckey, cache)
        else:
            vrows, vok = fn(
                cs.vec, cs.i32, jnp.asarray(changed),
                k.state.classes["Player"].vec, k.state.classes["Player"].i32,
                jnp.asarray(obs_rows), jnp.asarray(obs_valid),
            )
        vrows, vok = np.asarray(vrows), np.asarray(vok)
        # one value gather for the changed set; per-session subsets map
        # through pos_of (changed row -> position in `rows`)
        if slot.bank == Bank.VEC:
            vals = gather_rows(cs.vec, rows, cols=slot.col)[:, 0]
        elif slot.bank == Bank.F32:
            vals = gather_rows(cs.f32, rows, cols=slot.col)[:, 0]
        else:
            vals = gather_rows(cs.i32, rows, cols=slot.col)[:, 0]
        vals = np.asarray(vals)
        pos_of = np.full(cap, -1, np.int64)
        pos_of[rows] = np.arange(rows.size)
        name_b, cls_b = pname.encode(), cname.encode()
        ptype = int(slot.prop.type)
        # nf-lint: disable=serve-loop -- legacy columnar lane
        # (NF_SERVE_BATCH=0), the parity oracle for the batched
        # _send_batch_property_interest_batched above
        for i, sess in enumerate(obs):
            vis = vrows[i][vok[i]]
            vis = vis[host.alloc_mask[vis]]
            if vis.size == 0:
                continue
            idx = pos_of[vis]
            msg = BatchPropertySync(
                class_name=cls_b,
                property_name=name_b,
                ptype=ptype,
                count=int(vis.size),
                svrid=host.guid_head[vis].tobytes(),
                index=host.guid_data[vis].tobytes(),
                data=np.ascontiguousarray(vals[idx]).tobytes(),
            )
            self._send_to_session(sess, MsgID.ACK_BATCH_PROPERTY, msg)

    def _send_batch_property(self, cname: str, pname: str, rows: np.ndarray,
                             player_idx) -> None:
        """Columnar sync: ONE gather off the device + packed-array message
        per (scene, group) cell with observers.  This is the wire mirror
        of the SoA store — the per-entity proto path stays for strings,
        objects, private props and small diffs."""
        if self.interest_radius is not None and self._interest_ok(cname):
            if self.serve_batch:
                self._send_batch_property_interest_batched(cname, pname, rows)
            else:
                self._send_batch_property_interest(cname, pname, rows)
            return
        from ...kernel.scene import MAX_GROUPS_PER_SCENE
        from ..wire import BatchPropertySync

        k = self.kernel
        host = k.store._hosts[cname]
        spec = k.store.spec(cname)
        slot = spec.slot(pname)
        rows = rows[host.alloc_mask[rows]]  # drop rows that died
        if rows.size == 0:
            return
        cells = self._rows_cells(cname, rows)  # [n, 2]
        cs = k.state.classes[cname]
        if slot.bank == Bank.VEC:
            vals = gather_rows(cs.vec, rows, cols=slot.col)[:, 0]  # [n, 3]
        elif slot.bank == Bank.F32:
            vals = gather_rows(cs.f32, rows, cols=slot.col)[:, 0]
        else:
            vals = gather_rows(cs.i32, rows, cols=slot.col)[:, 0]
        heads = host.guid_head[rows]
        datas = host.guid_data[rows]
        cell_ids = cells[:, 0].astype(np.int64) * MAX_GROUPS_PER_SCENE + cells[:, 1]
        order = np.argsort(cell_ids, kind="stable")
        sorted_ids = cell_ids[order]
        uniq, starts = np.unique(sorted_ids, return_index=True)
        bounds = list(starts.tolist()) + [len(order)]
        name_b = pname.encode()
        cls_b = cname.encode()
        ptype = int(slot.prop.type)
        for i, cid in enumerate(uniq.tolist()):
            sc, gr = divmod(int(cid), MAX_GROUPS_PER_SCENE)
            targets = self._targets_from_index(
                player_idx, None, sc, gr, True, cname
            )
            if not targets:
                continue
            seg = order[bounds[i]:bounds[i + 1]]
            msg = BatchPropertySync(
                class_name=cls_b,
                property_name=name_b,
                ptype=ptype,
                count=int(seg.size),
                svrid=heads[seg].tobytes(),
                index=datas[seg].tobytes(),
                data=np.ascontiguousarray(vals[seg]).tobytes(),
            )
            self._broadcast(targets, MsgID.ACK_BATCH_PROPERTY, msg)

    def _forward_world(self, msg_id: int, msg: Message, pid: Ident) -> None:
        """Push a sync message up the world link for cross-game relay."""
        if self.cross_server_sync:
            self.world_link.send_to_all(int(msg_id), wrap(msg, player_id=pid))

    def _send_property_msgs(self, cname, row, guid, pnames, targets,
                            bank_vals, forward: bool = False) -> None:
        k = self.kernel
        spec = k.store.spec(cname)
        ints: List[PropertyInt] = []
        floats: List[PropertyFloat] = []
        strings: List[PropertyString] = []
        objects: List[PropertyObject] = []
        vec2s: List[PropertyVector2] = []
        vec3s: List[PropertyVector3] = []
        for pname in pnames:
            slot = spec.slot(pname)
            raw = bank_vals(cname, slot.bank)[row, slot.col]
            p = slot.prop
            if p.type == DataType.INT:
                ints.append(PropertyInt(
                    property_name=p.name.encode(), data=int(raw)))
            elif p.type == DataType.FLOAT:
                floats.append(PropertyFloat(
                    property_name=p.name.encode(), data=float(raw)))
            elif p.type == DataType.STRING:
                strings.append(PropertyString(
                    property_name=p.name.encode(),
                    data=k.store.strings.lookup(int(raw)).encode()))
            elif p.type == DataType.OBJECT:
                objects.append(PropertyObject(
                    property_name=p.name.encode(),
                    data=self._obj_ident(int(raw))))
            elif p.type == DataType.VECTOR2:
                vec2s.append(PropertyVector2(
                    property_name=p.name.encode(),
                    data=Vector2(x=float(raw[0]), y=float(raw[1]))))
            else:
                vec3s.append(PropertyVector3(
                    property_name=p.name.encode(),
                    data=Vector3(x=float(raw[0]), y=float(raw[1]),
                                 z=float(raw[2]))))
        pid = guid_ident(guid)
        # dedicated per-type messages matching the reference proto
        # (ObjectProperty{Int,Float,String,Object,Vector2,Vector3} all carry
        # player_id=1, property_list=2 — a protoc-generated client decodes
        # these directly)
        for msg_id, cls, items in (
            (MsgID.ACK_PROPERTY_INT, ObjectPropertyInt, ints),
            (MsgID.ACK_PROPERTY_FLOAT, ObjectPropertyFloat, floats),
            (MsgID.ACK_PROPERTY_STRING, ObjectPropertyString, strings),
            (MsgID.ACK_PROPERTY_OBJECT, ObjectPropertyObject, objects),
            (MsgID.ACK_PROPERTY_VECTOR2, ObjectPropertyVector2, vec2s),
            (MsgID.ACK_PROPERTY_VECTOR3, ObjectPropertyVector3, vec3s),
        ):
            if items:
                msg = cls(player_id=pid, property_list=items)
                self._broadcast(targets, msg_id, msg)
                if forward:
                    self._forward_world(msg_id, msg, pid)

    # --------------------------------------------------- cross-game delivery
    def _on_world_sync(self, _sid: int, msg_id: int, body: bytes) -> None:
        """World-relayed sync from another game server: deliver to every
        local client (world-scope visibility; the client mirror creates
        remote objects lazily on first property message)."""
        if not self.sessions:
            return
        base = MsgBase.decode(body)
        src = self._guid_of_ident(base.player_id)
        if src is not None and src in self.kernel.store.guid_map:
            return  # the entity lives here — local broadcast already covered it
        if msg_id == int(MsgID.ACK_ONLINE_NOTIFY):
            return  # mirror objects appear lazily with the first sync message
        per_conn: Dict[int, List[Ident]] = {}
        for sess in self.sessions.values():
            per_conn.setdefault(sess.conn_id, []).append(sess.ident)
        if msg_id == int(MsgID.ACK_OFFLINE_NOTIFY):
            leave = AckPlayerLeaveList(object_list=[base.player_id])
            for conn_id, idents in per_conn.items():
                self._send_to(idents, conn_id, MsgID.ACK_OBJECT_LEAVE, leave)
            return
        for conn_id, idents in per_conn.items():
            self.server.send_raw(
                conn_id, msg_id,
                MsgBase(player_id=base.player_id, msg_data=base.msg_data,
                        player_client_list=idents).encode(),
            )

    # ------------------------------------------------------------ leave events
    def _on_class_event(self, guid: Guid, _cname: str, ev: ObjectEvent) -> None:
        if ev == ObjectEvent.DESTROY and guid in self._guid_session:
            # destroyed outside _despawn (e.g. device death): clear binding
            key = self._guid_session.pop(guid)
            sess = self.sessions.get(key)
            if sess is not None:
                sess.guid = None
                self.reset_view(sess)

    def _on_npc_event(self, guid: Guid, _cname: str, ev: ObjectEvent) -> None:
        if ev == ObjectEvent.DESTROY and self.sessions:
            leave = AckPlayerLeaveList(object_list=[guid_ident(guid)])
            for sess in self.sessions.values():
                self._send_to_session(sess, MsgID.ACK_OBJECT_LEAVE, leave)
