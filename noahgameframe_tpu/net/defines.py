"""Protocol constants: message-ID space, server types/states, event codes.

Byte/value-compatible with the reference's `NFDefine.proto` EGameMsgID
(`NFComm/NFMessageDefine/NFDefine.proto:61-200`), the server-type enum
(`NFComm/NFPluginModule/NFINetModule.h:22-32`) and EServerState
(`NFMsgPreGame.proto:9-16`).
"""

from __future__ import annotations

import enum


class ServerType(enum.IntEnum):
    NONE = 0
    REDIS = 1
    MYSQL = 2
    MASTER = 3
    LOGIN = 4
    PROXY = 5
    GAME = 6
    WORLD = 7


class ServerState(enum.IntEnum):
    CRASH = 0
    NORMAL = 1  # "EST_NARMAL" in the reference
    BUSY = 2
    FIRE = 3
    MAINTEN = 4


class SwitchNoticeCode(enum.IntEnum):
    """ACK_SWITCH_NOTICE codes (TPU-native; no reference equivalent —
    the reference lets orphaned clients time out on a dead game)."""

    REHOMING = 1  # bound game died; failover in progress, frames parked
    BUSY = 2      # no survivor has capacity right now; retry after delay
    DROPPED = 3   # parked frames were dropped (deadline or overflow)


class EventCode(enum.IntEnum):
    SUCCESS = 0
    UNKNOWN_ERROR = 1
    ACCOUNT_EXIST = 2
    ACCOUNTPWD_INVALID = 3
    ACCOUNT_USING = 4
    ACCOUNT_LOCKED = 5
    ACCOUNT_SUCCESS = 6
    VERIFY_KEY_SUCCESS = 7
    VERIFY_KEY_FAIL = 8
    SELECTSERVER_SUCCESS = 9
    SELECTSERVER_FAIL = 10
    CHARACTER_EXIST = 110
    CHARACTER_NUMOUT = 112
    CHARACTER_INVALID = 113
    CHARACTER_NOTEXIST = 114
    NOT_ONLINE = 118
    ENTER_GAME_SUCCESS = 144


class MsgID(enum.IntEnum):
    """EGameMsgID — the shared message-ID space for every link."""

    UNKNOWN = 0
    EVENT_RESULT = 1
    EVENT_TRANSPOND = 2
    CLOSE_SOCKET = 3

    # master <-> world / login registration
    MTL_WORLD_REGISTERED = 10
    MTL_WORLD_UNREGISTERED = 11
    MTL_WORLD_REFRESH = 12
    LTM_LOGIN_REGISTERED = 20
    LTM_LOGIN_UNREGISTERED = 21
    LTM_LOGIN_REFRESH = 22
    PTWG_PROXY_REGISTERED = 30
    PTWG_PROXY_UNREGISTERED = 31
    PTWG_PROXY_REFRESH = 32
    GTW_GAME_REGISTERED = 40
    GTW_GAME_UNREGISTERED = 41
    GTW_GAME_REFRESH = 42

    STS_NET_INFO = 50
    STS_SERVER_REPORT = 90
    STS_HEART_BEAT = 100

    # login flow
    REQ_LOGIN = 101
    ACK_LOGIN = 102
    REQ_LOGOUT = 103
    REQ_WORLD_LIST = 110
    ACK_WORLD_LIST = 111
    REQ_CONNECT_WORLD = 112
    ACK_CONNECT_WORLD = 113
    REQ_KICK_CLIENT_INWORLD = 114
    REQ_CONNECT_KEY = 120
    ACK_CONNECT_KEY = 122

    # role flow
    REQ_SELECT_SERVER = 130
    ACK_SELECT_SERVER = 131
    REQ_ROLE_LIST = 132
    ACK_ROLE_LIST = 133
    REQ_CREATE_ROLE = 134
    REQ_DELETE_ROLE = 135
    REQ_RECOVER_ROLE = 136

    # game entry
    REQ_ENTER_GAME = 150
    ACK_ENTER_GAME = 151
    REQ_LEAVE_GAME = 152
    ACK_LEAVE_GAME = 153
    REQ_SWAP_GAME = 154
    REQ_SWAP_SCENE = 155
    ACK_SWAP_SCENE = 156

    # object sync
    ACK_OBJECT_ENTRY = 200
    ACK_OBJECT_LEAVE = 201
    ACK_OBJECT_PROPERTY_ENTRY = 202
    ACK_OBJECT_RECORD_ENTRY = 203
    ACK_PROPERTY_INT = 210
    ACK_PROPERTY_FLOAT = 211
    ACK_PROPERTY_STRING = 212
    ACK_PROPERTY_OBJECT = 214
    ACK_PROPERTY_VECTOR2 = 215
    ACK_PROPERTY_VECTOR3 = 216
    ACK_ADD_ROW = 220
    ACK_REMOVE_ROW = 221
    ACK_SWAP_ROW = 222
    ACK_RECORD_INT = 223
    ACK_RECORD_FLOAT = 224
    ACK_RECORD_STRING = 226
    ACK_RECORD_OBJECT = 227
    ACK_RECORD_VECTOR2 = 228
    ACK_RECORD_VECTOR3 = 229
    ACK_RECORD_CLEAR = 250
    ACK_RECORD_SORT = 251
    # TPU-native extension (outside the reference EGameMsgID space):
    # columnar batch property sync — one message carries every changed
    # entity's value for one (class, property) as packed arrays, replacing
    # tens of thousands of per-entity messages per frame at 100k+ scale
    ACK_BATCH_PROPERTY = 8001
    # per-session interest-filtered position stream (u16-quantized):
    # each client receives only entities within its interest radius
    ACK_INTEREST_POS = 8002
    # serialized-player companion to REQ_SWITCH_SERVER (re-home without
    # a shared database; game -> world -> target game)
    SWITCH_SERVER_DATA = 8003
    # frame observatory (ISSUE 7): sampled trace context riding the
    # served path game -> proxy -> client, acked back client -> proxy ->
    # game.  Pure observability — both ids are excluded from the flight
    # recorder journal so replays stay bit-identical with tracing on.
    FRAME_TRACE = 8004
    FRAME_TRACE_ACK = 8005
    # session failover (ISSUE 10): proxy -> client notice that the bound
    # game died and the session is being re-homed (or was given up on) —
    # clients see an explicit BUSY/retry-after instead of a silent stall
    ACK_SWITCH_NOTICE = 8006
    # game -> world sidecar to ACK_ONLINE_NOTIFY carrying the session
    # metadata (account/name/client ident/scene/group/save key) the
    # world's failover driver needs to re-home the player after the
    # owning game dies without being asked
    SESSION_BIND_NOTIFY = 8007
    # target game -> world: staged switch-in refused (capacity / torn
    # blob) — the reference AckSwitchServer has no failure leg
    ACK_SWITCH_REFUSED = 8008

    # in-game actions
    REQ_MOVE = 1230
    ACK_MOVE = 1231
    REQ_MOVE_IMMUNE = 1232
    ACK_MOVE_IMMUNE = 1233
    REQ_SKILL_OBJECTX = 1240
    ACK_SKILL_OBJECTX = 1241
    REQ_SKILL_POS = 1242
    ACK_SKILL_POS = 1243
    REQ_ITEM_OBJECT = 1244
    ACK_ITEM_OBJECT = 1245
    REQ_CHAT = 1250
    ACK_CHAT = 1251
    REQ_SALE_ITEM = 1252
    REQ_SPLIT_ITEM = 1253
    REQ_PRODUCE_ITEM = 1254
    REQ_PICK_ITEM = 1255
    REQ_ACCEPT_TASK = 1256
    REQ_COMPLETE_TASK = 1257
    # guild ops (NFDefine.proto:184-193)
    REQ_CREATE_GUILD = 1300
    ACK_CREATE_GUILD = 1301
    REQ_JOIN_GUILD = 1302
    ACK_JOIN_GUILD = 1303
    REQ_LEAVE_GUILD = 1304
    ACK_LEAVE_GUILD = 1305
    REQ_SEARCH_GUILD = 1308
    ACK_SEARCH_GUILD = 1309
    REQ_SET_FIGHT_HERO = 1508  # EGEC_REQ_SET_FIGHT_HERO
    WEAR_EQUIP = 1509  # EGEC_WEAR_EQUIP
    TAKEOFF_EQUIP = 1510  # EGEC_TAKEOFF_EQUIP
    # cross-game-server switch (NFDefine.proto:268-269)
    REQ_SWITCH_SERVER = 1840  # EGMI_REQSWICHSERVER
    ACK_SWITCH_SERVER = 1841  # EGMI_ACKSWICHSERVER
    # teams (NFDefine.proto:271-278)
    REQ_CREATE_TEAM = 1860
    ACK_CREATE_TEAM = 1861
    REQ_JOIN_TEAM = 1862
    ACK_JOIN_TEAM = 1863
    REQ_LEAVE_TEAM = 1864
    ACK_LEAVE_TEAM = 1865
    REQ_OPRMEMBER_TEAM = 1867
    ACK_OPRMEMBER_TEAM = 1868
    ACK_ONLINE_NOTIFY = 1290
    ACK_OFFLINE_NOTIFY = 1291

    # GM commands (NFDefine.proto:304-312); only the NORMAL entry point
    # is registered by the reference's NFCGmModule
    REQ_CMD_NORMAL = 10008
    # PVP matchmaking (NFDefine.proto:299-302)
    REQ_PVP_APPLY_MATCH = 10100
    ACK_PVP_APPLY_MATCH = 10101
    REQ_CREATE_PVP_ECTYPE = 10102
    ACK_CREATE_PVP_ECTYPE = 10103

    # SLG city building (NFDefine.proto:292-299 EGMI_REQ_BUY_FORM_SHOP..)
    REQ_BUY_FORM_SHOP = 20000
    ACK_BUY_FORM_SHOP = 20001
    REQ_MOVE_BUILD_OBJECT = 20002
    ACK_MOVE_BUILD_OBJECT = 20003
    REQ_UP_BUILD_LVL = 20101
    REQ_CREATE_ITEM = 20102
    REQ_BUILD_OPERATE = 20103


#: Frame-observatory sidecar opcodes: excluded from the flight-recorder
#: journal (net/roles/game.py ``_journal_tap``) so a journaled run
#: replays bit-identically whether tracing was on or off.
TRACE_MSG_IDS = frozenset({int(MsgID.FRAME_TRACE), int(MsgID.FRAME_TRACE_ACK)})

#: Reference cadence constants (NFINetClientModule.hpp:349,397)
KEEPALIVE_SECONDS = 10.0
RECONNECT_SECONDS = 10.0

#: Backoff ceiling for the reconnect RetryPolicy (net/retry.py);
#: RECONNECT_SECONDS stays the policy's *base* delay.
RECONNECT_CAP_SECONDS = 30.0

#: Heartbeat-lease thresholds (net/roles/master.py, world.py): an entry
#: not refreshed for SUSPECT ages is flagged, past DOWN it is treated as
#: dead (CRASH state, evicted from routed lists).  Tied to the 10 s
#: keepalive: 1.5 missed beats suspect, 3 missed beats down.
LEASE_SUSPECT_SECONDS = 1.5 * KEEPALIVE_SECONDS
LEASE_DOWN_SECONDS = 3.0 * KEEPALIVE_SECONDS
