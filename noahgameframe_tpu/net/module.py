"""Server/client network modules: dispatch, envelope, pool, reconnect.

- :class:`NetServerModule` ≙ the reference's `NFINetModule`
  (msgID→handler registry, socket-event callbacks, MsgBase envelope
  send/receive — `NFComm/NFPluginModule/NFINetModule.h:135-520`).
- :class:`NetClientModule` ≙ `NFINetClientModule.hpp`: outbound pool
  keyed by server id, per-link NORMAL/CONNECTING/RECONNECT state
  machine with 10 s backoff (`:312-370`), keepalive hook (`:395-405`),
  `send_by_server_id` / `send_by_suit` (consistent hash) /
  `send_to_all` routing (`:151-239`).

Both are pumped from the main loop via ``execute()`` — no threads.
Time is injected (``now``) so tests can drive the FSM deterministically.
"""

from __future__ import annotations

import dataclasses
import logging
import time as _time
from typing import Callable, Dict, List, Optional

from ..core.chash import ConsistentHash
from .defines import KEEPALIVE_SECONDS, RECONNECT_SECONDS, ServerType
from .retry import RetryPolicy
from .transport import EV_CONNECTED, EV_DISCONNECTED, EV_MSG, NetEvent, create_client, create_server
from .wire import Ident, Message, MsgBase

ReceiveHandler = Callable[[int, int, bytes], None]  # (conn_id, msg_id, body)
EventHandler = Callable[[int, int], None]  # (conn_id, event_kind)


class NetCounters:
    """Per-opcode message/byte counters for one endpoint (both
    directions).  Plain dicts keyed by msg_id — sampled lazily by
    telemetry's ``nf_net_msgs_total`` / ``nf_net_bytes_total`` callbacks,
    so the hot send/receive path pays two dict bumps and nothing else."""

    def __init__(self) -> None:
        self.in_msgs: Dict[int, int] = {}
        self.in_bytes: Dict[int, int] = {}
        self.out_msgs: Dict[int, int] = {}
        self.out_bytes: Dict[int, int] = {}
        # forward-relay latency per opcode (proxy _transpond): total ns
        # from dispatch arrival to fan-out complete, sampled lazily by
        # telemetry's nf_relay_msgs_total / nf_relay_seconds_total
        self.relay_msgs: Dict[int, int] = {}
        self.relay_ns: Dict[int, int] = {}

    def count_in(self, msg_id: int, nbytes: int) -> None:
        self.in_msgs[msg_id] = self.in_msgs.get(msg_id, 0) + 1
        self.in_bytes[msg_id] = self.in_bytes.get(msg_id, 0) + nbytes

    def count_out(self, msg_id: int, nbytes: int) -> None:
        self.out_msgs[msg_id] = self.out_msgs.get(msg_id, 0) + 1
        self.out_bytes[msg_id] = self.out_bytes.get(msg_id, 0) + nbytes

    def count_relay(self, msg_id: int, dur_ns: int) -> None:
        self.relay_msgs[msg_id] = self.relay_msgs.get(msg_id, 0) + 1
        self.relay_ns[msg_id] = self.relay_ns.get(msg_id, 0) + dur_ns


class _Dispatch:
    """msgID -> handler fan-out with per-message fault isolation.

    A handler that raises (malformed body failing proto decode, capacity
    errors mid-handler, plain bugs) must never kill the server pump: the
    reference logs the packet and keeps serving
    (NFINetModule::OnReceiveNetPack, NFINetModule.h:473-520).  Each
    handler call is isolated; failures are logged and counted."""

    def __init__(self, counters: Optional[NetCounters] = None) -> None:
        self._handlers: Dict[int, List[ReceiveHandler]] = {}
        self._default: List[ReceiveHandler] = []
        self._events: List[EventHandler] = []
        self._log = logging.getLogger("nf.net.dispatch")
        self.dropped_msgs = 0  # observability: handler faults survived
        self.counters = counters
        # flight-recorder seam: when set, sees every event in dispatch
        # order BEFORE any handler runs (replay/journal.py taps here —
        # this is the single choke point both endpoints deliver through)
        self.tap: Optional[Callable[[NetEvent], None]] = None

    def on(self, msg_id: int, fn: ReceiveHandler) -> None:
        self._handlers.setdefault(int(msg_id), []).append(fn)

    def on_any(self, fn: ReceiveHandler) -> None:
        """Catch-all for unregistered ids (the proxy's transpond path)."""
        self._default.append(fn)

    def on_socket_event(self, fn: EventHandler) -> None:
        self._events.append(fn)

    def _safe(self, fn, conn_id: int, msg_id: int, body: bytes) -> None:
        try:
            fn(conn_id, msg_id, body)
        except Exception:  # noqa: BLE001 — isolate the serving edge
            self.dropped_msgs += 1
            self._log.exception(
                "handler failed: conn=%d msg_id=%d len=%d (dropped)",
                conn_id, msg_id, len(body),
            )

    def feed(self, events: List[NetEvent]) -> None:
        for ev in events:
            if self.tap is not None:
                self.tap(ev)
            if ev.kind == EV_MSG:
                if self.counters is not None:
                    self.counters.count_in(ev.msg_id, len(ev.body))
                fns = self._handlers.get(ev.msg_id)
                if fns:
                    for fn in fns:
                        self._safe(fn, ev.conn_id, ev.msg_id, ev.body)
                else:
                    for fn in self._default:
                        self._safe(fn, ev.conn_id, ev.msg_id, ev.body)
            else:
                for fn in self._events:
                    try:
                        fn(ev.conn_id, ev.kind)
                    except Exception:  # noqa: BLE001
                        self.dropped_msgs += 1
                        self._log.exception(
                            "socket-event handler failed: conn=%d kind=%d",
                            ev.conn_id, ev.kind,
                        )


class NetServerModule:
    """Listening endpoint + dispatch + envelope helpers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "auto") -> None:
        self.transport = create_server(host, port, backend=backend)
        self.host = host
        self.port = self.transport.port
        self.counters = NetCounters()
        self.dispatch = _Dispatch(counters=self.counters)
        # connection tags, mirroring NetObject's account/id binding
        # (`NFINet.h:246-405`): conn_id -> dict of app tags
        self.conn_tags: Dict[int, Dict[str, object]] = {}
        self.dispatch.on_socket_event(self._track)

    def _track(self, conn_id: int, kind: int) -> None:
        if kind == EV_CONNECTED:
            self.conn_tags[conn_id] = {}
        elif kind == EV_DISCONNECTED:
            self.conn_tags.pop(conn_id, None)

    # -------------------------------------------------------- registry
    def on(self, msg_id: int, fn: ReceiveHandler) -> None:
        self.dispatch.on(msg_id, fn)

    def on_any(self, fn: ReceiveHandler) -> None:
        self.dispatch.on_any(fn)

    def on_socket_event(self, fn: EventHandler) -> None:
        self.dispatch.on_socket_event(fn)

    # ------------------------------------------------------------ send
    def send_raw(self, conn_id: int, msg_id: int, body: bytes) -> bool:
        ok = self.transport.send(conn_id, msg_id, body)
        if ok:
            self.counters.count_out(msg_id, len(body))
        return ok

    def send_pb(self, conn_id: int, msg_id: int, msg: Message,
                player_id: Optional[Ident] = None,
                clients: Optional[List[Ident]] = None) -> bool:
        env = MsgBase(
            player_id=player_id or Ident(),
            msg_data=msg.encode(),
            player_client_list=clients or [],
        )
        return self.send_raw(conn_id, msg_id, env.encode())

    def broadcast_pb(self, msg_id: int, msg: Message,
                     player_id: Optional[Ident] = None) -> None:
        for conn_id in list(self.conn_tags):
            self.send_pb(conn_id, msg_id, msg, player_id=player_id)

    def close_conn(self, conn_id: int) -> None:
        self.transport.close_conn(conn_id)
        self.conn_tags.pop(conn_id, None)

    # ------------------------------------------------------------ pump
    def execute(self) -> None:
        self.dispatch.feed(self.transport.poll())

    def shut(self) -> None:
        self.transport.close()

    @property
    def num_connections(self) -> int:
        return len(self.conn_tags)


# connection-pool FSM states (NFINetClientModule.hpp ConnectDataState)
DISCONNECT, CONNECTING, NORMAL, RECONNECT = 0, 1, 2, 3


@dataclasses.dataclass
class ServerData:
    server_id: int
    server_type: int
    ip: str
    port: int
    name: str = ""
    state: int = DISCONNECT
    last_attempt: float = 0.0
    client: object = None  # transport client
    attempts: int = 0  # consecutive failed dials (resets on connect)


class NetClientModule:
    """Outbound connection pool with consistent-hash routing."""

    def __init__(self, backend: str = "auto",
                 reconnect_seconds: float = RECONNECT_SECONDS,
                 keepalive_seconds: float = KEEPALIVE_SECONDS,
                 retry: Optional[RetryPolicy] = None) -> None:
        self._backend = backend
        self.servers: Dict[int, ServerData] = {}
        self.ring: ConsistentHash[int] = ConsistentHash()
        self.counters = NetCounters()
        self.dispatch = _Dispatch(counters=self.counters)
        # reconnect_seconds doubles as the CONNECTING timeout and, when
        # no explicit policy is given, the RetryPolicy base delay
        self.reconnect_seconds = reconnect_seconds
        self.retry = retry if retry is not None else RetryPolicy(base=reconnect_seconds)
        self.keepalive_seconds = keepalive_seconds
        # re-dial attempts after a failure, per server id (telemetry:
        # nf_reconnects_total samples this lazily)
        self.retries_total: Dict[int, int] = {}
        # chaos seam: wraps each freshly-created transport client
        # (fn(client, server_data) -> client); see net/chaos.py
        self.transport_wrapper: Optional[Callable] = None
        self._last_keepalive = 0.0
        self._keepalive_fns: List[Callable[[], None]] = []
        self._connected_fns: List[Callable[[int], None]] = []

    # -------------------------------------------------------- topology
    def add_server(self, server_id: int, server_type: int, ip: str,
                   port: int, name: str = "") -> None:
        """Register a target endpoint (AddServer,
        `NFINetClientModule.hpp:90-110`); connection happens in execute()."""
        if server_id in self.servers:
            return
        self.servers[server_id] = ServerData(server_id, server_type, ip, port, name)
        self.ring.add(str(server_id), server_id)

    def remove_server(self, server_id: int) -> None:
        sd = self.servers.pop(server_id, None)
        if sd is not None:
            if sd.client is not None:
                sd.client.close()
            self.ring.remove(str(server_id))

    # -------------------------------------------------------- registry
    def on(self, msg_id: int, fn: ReceiveHandler) -> None:
        """Handler receives (server_id, msg_id, body)."""
        self.dispatch.on(msg_id, fn)

    def on_any(self, fn: ReceiveHandler) -> None:
        self.dispatch.on_any(fn)

    def on_connected(self, fn: Callable[[int], None]) -> None:
        self._connected_fns.append(fn)

    def on_keepalive(self, fn: Callable[[], None]) -> None:
        """Called every keepalive period (the ServerInfoReport hook)."""
        self._keepalive_fns.append(fn)

    # ------------------------------------------------------------ send
    def send_by_server_id(self, server_id: int, msg_id: int, body: bytes) -> bool:
        sd = self.servers.get(server_id)
        if sd is None or sd.state != NORMAL:
            return False
        ok = sd.client.send_msg(msg_id, body)
        if ok:
            self.counters.count_out(msg_id, len(body))
        return ok

    def send_pb_by_server_id(self, server_id: int, msg_id: int, msg: Message,
                             player_id: Optional[Ident] = None,
                             clients: Optional[List[Ident]] = None) -> bool:
        env = MsgBase(player_id=player_id or Ident(), msg_data=msg.encode(),
                      player_client_list=clients or [])
        return self.send_by_server_id(server_id, msg_id, env.encode())

    def send_by_suit(self, key: str, msg_id: int, body: bytes) -> bool:
        """Consistent-hash routing (`SendBySuit`,
        `NFINetClientModule.hpp:214-239`)."""
        sid = self.ring.get(key)
        return sid is not None and self.send_by_server_id(sid, msg_id, body)

    def send_to_all(self, msg_id: int, body: bytes,
                    server_type: Optional[int] = None) -> int:
        n = 0
        for sd in self.servers.values():
            if server_type is not None and sd.server_type != server_type:
                continue
            if self.send_by_server_id(sd.server_id, msg_id, body):
                n += 1
        return n

    def connected_servers(self, server_type: Optional[int] = None) -> List[int]:
        return [
            sd.server_id
            for sd in self.servers.values()
            if sd.state == NORMAL
            and (server_type is None or sd.server_type == server_type)
        ]

    # ------------------------------------------------------------ pump
    def execute(self, now: Optional[float] = None) -> None:
        now = _time.monotonic() if now is None else now
        for sd in self.servers.values():
            self._pump_link(sd, now)
        if now - self._last_keepalive >= self.keepalive_seconds:
            self._last_keepalive = now
            for fn in self._keepalive_fns:
                fn()

    def _pump_link(self, sd: ServerData, now: float) -> None:
        if sd.state in (DISCONNECT, RECONNECT):
            if sd.state == RECONNECT:
                # capped exponential backoff with deterministic jitter
                # replaces the reference's fixed 10 s timer
                wait = self.retry.delay(sd.attempts, key=sd.server_id)
                if now - sd.last_attempt < wait:
                    return
                self.retries_total[sd.server_id] = (
                    self.retries_total.get(sd.server_id, 0) + 1
                )
            if sd.client is not None:
                sd.client.close()
            client = create_client(sd.ip, sd.port, backend=self._backend)
            if self.transport_wrapper is not None:
                client = self.transport_wrapper(client, sd)
            sd.client = client
            sd.client.connect()
            sd.state = CONNECTING
            sd.last_attempt = now
            sd.attempts += 1
            return
        events = sd.client.poll()
        for ev in events:
            if ev.kind == EV_CONNECTED:
                sd.state = NORMAL
                sd.attempts = 0  # reset-on-success: next failure backs off from base
                for fn in self._connected_fns:
                    fn(sd.server_id)
            elif ev.kind == EV_DISCONNECTED:
                sd.state = RECONNECT
                sd.last_attempt = now
            elif ev.kind == EV_MSG:
                # present the *server id* as the connection identity
                self.dispatch.feed(
                    [NetEvent(EV_MSG, sd.server_id, ev.msg_id, ev.body)]
                )
        if sd.state == CONNECTING and now - sd.last_attempt > self.reconnect_seconds:
            sd.client.disconnect()
            sd.state = RECONNECT
            sd.last_attempt = now

    def shut(self) -> None:
        for sd in self.servers.values():
            if sd.client is not None:
                sd.client.close()
