"""Frame codec: the 6-byte NF wire header, byte-compatible with the
reference protocol (`NFComm/NFNet/NFINet.h:168-233` — header =
big-endian u16 msgID + u32 total packet size *including* the header).

The decoder is incremental: feed arbitrary byte chunks, get complete
(msg_id, body) frames out.  This is the single framing implementation
used by both the pure-Python transport and the role processes; the
native C++ transport implements the identical layout in
``native/nfnet.cc``.
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

HEAD_LENGTH = 6
_HEAD = struct.Struct(">HI")  # msg_id, total_size (body + header)

#: Hard upper bound on a single frame, mirroring sane server limits; a
#: peer announcing more than this is treated as a protocol violation.
MAX_FRAME_SIZE = 64 * 1024 * 1024


class ProtocolError(Exception):
    """Peer sent bytes that cannot be a valid NF frame."""


def pack_frame(msg_id: int, body: bytes) -> bytes:
    """Encode one frame: header(msgID, len(body)+6) + body."""
    return _HEAD.pack(msg_id, len(body) + HEAD_LENGTH) + body


def unpack_head(data: bytes) -> Tuple[int, int]:
    """Decode a 6-byte header -> (msg_id, body_length)."""
    msg_id, total = _HEAD.unpack_from(data)
    return msg_id, total - HEAD_LENGTH


class FrameDecoder:
    """Incremental frame reassembly over a byte stream.

    Mirrors the reference's `Dismantle` loop (`NFCNet.cpp:110-160`):
    buffer until a full header + body is available, emit, repeat.
    """

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[int, bytes]]:
        self._buf.extend(data)
        out: List[Tuple[int, bytes]] = []
        buf = self._buf
        off = 0
        while len(buf) - off >= HEAD_LENGTH:
            msg_id, total = _HEAD.unpack_from(buf, off)
            if total < HEAD_LENGTH or total > MAX_FRAME_SIZE:
                raise ProtocolError(f"bad frame size {total} (msg_id={msg_id})")
            if len(buf) - off < total:
                break
            body = bytes(buf[off + HEAD_LENGTH : off + total])
            out.append((msg_id, body))
            off += total
        if off:
            del buf[:off]
        return out

    def pending(self) -> int:
        return len(self._buf)


def iter_frames(blob: bytes) -> Iterator[Tuple[int, bytes]]:
    """Decode a complete byte blob containing whole frames."""
    dec = FrameDecoder()
    yield from dec.feed(blob)
    if dec.pending():
        raise ProtocolError(f"{dec.pending()} trailing bytes after last frame")
