"""noahgameframe_tpu — a TPU-native distributed entity framework.

A ground-up rebuild of the capabilities of NoahGameFrame (plugin/module
kernel, schema-driven entities, events/heartbeats, scene/group AOI
broadcast, five-role server topology, persistence) designed TPU-first: the
world is a Structure-of-Arrays pytree on device and the frame tick is one
jit-compiled JAX function, sharded over a device mesh with shard_map.
"""

__version__ = "0.1.0"
