"""Room-batch coverage contract (ISSUE 19).

The many-worlds engine stacks FULL ``WorldState`` pytrees along a
leading room axis: every leaf ``parallel/rooms.py``'s walk yields is
broadcast into the batch, scattered on admit and gathered on extract.
Like the migration walk, the runtime recursion is generic — a bank
added to the store is picked up automatically at trace time — so the
reviewed INTENT lives in two literals: ``ROOM_PACK_SPEC`` enumerates
what a room IS, and ``ROOM_EXCLUDED`` waivers the leaves deliberately
left out of re-home blobs (the ``aux.*`` caches, rebuilt from blanks on
admit).  This rule is the static complement of the trace-time assertion
in ``world_room_leaf_items``: every ``WorldState`` leaf must be
enumerated or waivered, and every spec entry must still name a real
leaf — a store bank the room walk silently skips would be wiped on
re-home, and a stale entry hides the next real gap.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List

from .engine import Finding, PackageContext, Rule
from .rules_store import (
    _NESTED,
    _dataclass_fields,
    _find_module,
    _literal_str_tuple,
)

STORE_SUFFIX = "core/store.py"
ROOMS_SUFFIX = "parallel/rooms.py"


class RoomAxisCoveredRule(Rule):
    """Every WorldState leaf is enumerated by the room pack spec (or
    carries a waivered exclusion), and the spec names no leaf that no
    longer exists — a bank the room walk skips is silently zeroed the
    first time its room is re-homed across engines."""

    name = "room-axis-covered"
    description = ("parallel/rooms.py ROOM_PACK_SPEC (+ ROOM_EXCLUDED) "
                   "must enumerate every WorldState leaf in "
                   "core/store.py, and name only leaves that exist.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        store = _find_module(ctx, STORE_SUFFIX)
        rooms_mod = _find_module(ctx, ROOMS_SUFFIX)
        if store is None or rooms_mod is None:
            return self.findings  # contract pair absent: out of scope
        if store.tree is None or rooms_mod.tree is None:
            return self.findings  # parse-error finding already emitted

        classes = _dataclass_fields(store.tree)
        if "WorldState" not in classes:
            self.flag(1, "WorldState vanished from core/store.py — the "
                      "room-axis coverage contract has nothing to hold "
                      "onto", path=store.rel)
            return self.findings

        # expand WorldState fields into the dotted paths the room walk
        # yields: classes.* recurses ClassState (sharing the migration
        # rule's nested-dataclass table), other Dict fields are keyed
        # collections (aux.*), the rest are plain scalar/array leaves
        expected: Dict[str, ast.AnnAssign] = {}
        for field, node in classes["WorldState"]:
            # strip quotes so stringified annotations compare the same
            ann = ast.unparse(node.annotation).strip("'\"")
            if "ClassState" in ann:
                for leaf, sub in classes.get("ClassState", []):
                    leaf_ann = ast.unparse(sub.annotation)
                    nested = next((c for c in _NESTED if c in leaf_ann),
                                  None)
                    if nested is None:
                        expected[f"{field}.*.{leaf}"] = node
                        continue
                    for inner, _n in classes.get(nested, []):
                        path = _NESTED[nested].format(field=leaf,
                                                      leaf=inner)
                        expected[f"{field}.*.{path}"] = node
                if not classes.get("ClassState"):
                    self.flag(node, "ClassState has no resolvable fields "
                              f"to expand `{field}` with", path=store.rel)
            elif ann.startswith(("Dict", "dict")):
                expected[f"{field}.*"] = node
            else:
                expected[field] = node

        spec, spec_node = _literal_str_tuple(rooms_mod.tree,
                                             "ROOM_PACK_SPEC")
        excl, excl_node = _literal_str_tuple(rooms_mod.tree,
                                             "ROOM_EXCLUDED")
        if spec_node is None:
            self.flag(1, "ROOM_PACK_SPEC vanished from parallel/rooms.py",
                      path=rooms_mod.rel)
            return self.findings
        if spec is None:
            self.flag(spec_node, "ROOM_PACK_SPEC must be a literal tuple "
                      "of strings — a computed spec cannot be reviewed "
                      "statically", path=rooms_mod.rel)
            return self.findings
        if excl_node is not None and excl is None:
            self.flag(excl_node, "ROOM_EXCLUDED must be a literal tuple "
                      "of strings", path=rooms_mod.rel)
            excl = []
        excl = excl or []

        patterns = list(spec) + list(excl)
        for path, node in sorted(expected.items()):
            if not any(fnmatch.fnmatch(path, pat) for pat in patterns):
                self.flag(node, f"store leaf `{path}` is not covered by "
                          "ROOM_PACK_SPEC or ROOM_EXCLUDED — re-homing a "
                          "room would silently wipe this bank",
                          path=store.rel)
        for pat in patterns:
            if not any(fnmatch.fnmatch(path, pat) for path in expected):
                where = spec_node if pat in spec else (excl_node
                                                      or spec_node)
                self.flag(where, f"spec entry `{pat}` matches no "
                          "WorldState leaf — stale after a store "
                          "refactor", path=rooms_mod.rel)
        return self.findings
