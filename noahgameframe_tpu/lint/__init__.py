"""nf-lint: first-class static analysis for trace-safety, device-sync
and protocol contracts.

Run it::

    python -m noahgameframe_tpu.lint            # human-readable
    python -m noahgameframe_tpu.lint --json     # machine-readable
    scripts/nf_lint.py --rule trace-safety      # one rule only

The engine (``engine.py``) is stdlib-only — no jax import, no device —
so it runs in CI, hooks and editors.  Rules live in ``rules_*.py`` and
register here; ``docs/LINT.md`` is the catalog, suppression syntax and
how-to-add-a-rule guide.
"""

from __future__ import annotations

from .engine import (  # noqa: F401
    BAD_SUPPRESSION,
    Finding,
    PARSE_ERROR,
    PackageContext,
    Report,
    Rule,
    UNUSED_SUPPRESSION,
    load_baseline,
    run_lint,
    write_baseline,
)
from .rules_contracts import (
    DrillClocklessRule,
    FsyncBarrierRule,
    JournalTapGuardRule,
    PumpSurfaceRule,
)
from .rules_determinism import UnseededRngRule, WallClockRule
from .rules_mesh import MeshNotCapturedRule
from .rules_pallas import PallasParityPinnedRule
from .rules_rooms import RoomAxisCoveredRule
from .rules_serving import ServeLoopRule
from .rules_store import MigrateCoversStoreRule
from .rules_trace import RecompileHazardRule, TraceSafetyRule
from .rules_train import TrainLanesCoveredRule
from .rules_wire import DispatchHandlerRule, StructCodecRule

#: every shipped rule, in catalog order (docs/LINT.md mirrors this)
ALL_RULES = (
    WallClockRule,
    UnseededRngRule,
    PumpSurfaceRule,
    FsyncBarrierRule,
    DrillClocklessRule,
    JournalTapGuardRule,
    TraceSafetyRule,
    RecompileHazardRule,
    StructCodecRule,
    DispatchHandlerRule,
    ServeLoopRule,
    MigrateCoversStoreRule,
    MeshNotCapturedRule,
    PallasParityPinnedRule,
    RoomAxisCoveredRule,
    TrainLanesCoveredRule,
)

RULES_BY_NAME = {cls.name: cls for cls in ALL_RULES}
