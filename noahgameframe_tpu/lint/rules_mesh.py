"""Elastic-mesh rule: traced code must not capture a mesh via object state.

A ``jax.sharding.Mesh`` names physical devices.  Code that runs inside
the compiled tick bakes whatever mesh it read at trace time into the
executable — so a mesh reached through mutable object state
(``self.mesh``) silently pins the OLD device set after an elastic
reshard (``ShardedKernel.reshard`` / ``ElasticMesh``) unless the holder
is re-aimed and the kernel invalidated in the same breath.  Passing the
mesh as a function parameter keeps the dependency visible at every
call site and re-binds naturally on the post-reshard retrace.

The rule walks the jit-reachable call graph (same roots as
trace-safety: jit sites + ``add_phase`` registrations) and flags
``self.<attr>`` reads where the attribute is mesh-named (``mesh`` or
``*_mesh``).  A read that genuinely participates in the reshard
contract — retarget() + invalidate() before every retrace — carries a
same-line ``nf-lint: disable=mesh-not-captured -- <why>`` waiver.
"""

from __future__ import annotations

import ast
from typing import List

from .callgraph import traced_reachable
from .engine import Finding, PackageContext, Rule
from .rules_trace import _TracedScan

_MESH_ATTRS = ("mesh",)


def _mesh_named(attr: str) -> bool:
    return attr in _MESH_ATTRS or attr.endswith("_mesh")


class _MeshScan(_TracedScan):
    def visit_Attribute(self, node):
        if isinstance(node.ctx, ast.Load) and _mesh_named(node.attr) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            self.rule.flag(
                node,
                f"`self.{node.attr}` read {self.where()} — a mesh "
                "captured through object state pins the trace to a stale "
                "device set after an elastic reshard; pass the mesh as a "
                "parameter (or retarget()+invalidate() and waive with a "
                "reason)",
                path=self.tf.info.rel)
        self.generic_visit(node)


class MeshNotCapturedRule(Rule):
    """Stale-device-set hazard: mesh reads through `self` in traced code."""

    name = "mesh-not-captured"
    description = (
        "jit-reachable code must not read a mesh via object state "
        "(`self.mesh`); pass it as a parameter so an elastic reshard "
        "re-binds it on the retrace.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        for tf in traced_reachable(ctx).values():
            if tf.info.rel not in ctx.modules:
                continue
            self.module = ctx.modules[tf.info.rel]
            _MeshScan(self, tf).scan()
        return self.findings
