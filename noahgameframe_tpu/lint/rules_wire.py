"""Wire/codec consistency rules.

Two hazard classes the test-embedded lints never covered:

- **struct-codec**: every ``struct`` format string must parse, its
  ``calcsize`` must match any paired length constant (``_HEAD`` ↔
  ``HEAD_LENGTH``, ``X = _STRUCT.size  # 52 bytes``), and tuple
  destructures of ``unpack`` (and argument lists of ``pack``) must
  match the format's field count.  This is how the ``>HII`` journal/WAL
  framing and the 52-byte ``<BBHIIQQQQQ`` trace codec stay honest when
  someone adds a field to one side of the wire.
- **dispatch-handler**: every opcode registered on a dispatch table
  (``server.on(MsgID.X, self._handler)``) must reference a handler
  that actually exists — a renamed method otherwise fails at role
  startup (or worse, only when the first frame of that opcode lands).
"""

from __future__ import annotations

import ast
import re
import struct as _struct
from typing import Dict, List, Optional

from .engine import Rule, dotted_name

_BYTES_COMMENT = re.compile(r"#[^#]*?\b(\d+)\s*bytes?\b")
_CONST_SUFFIXES = ("_LENGTH", "_SIZE", "_LEN", "_BYTES")


def _field_count(fmt: str) -> int:
    """Number of Python values a format packs/unpacks."""
    n = 0
    count: Optional[int] = None
    for ch in fmt:
        if ch in "<>=!@ ":
            continue
        if ch.isdigit():
            count = (count or 0) * 10 + int(ch)
            continue
        if ch == "x":
            pass  # pad bytes produce no value
        elif ch in "sp":
            n += 1  # a counted string is ONE value
        else:
            n += count if count is not None else 1
        count = None
    return n


def _struct_base(var: str) -> str:
    base = var.lstrip("_")
    for suf in ("_STRUCT", "_FMT", "_HEAD"):
        if base.endswith(suf) and base != suf:
            base = base[: -len(suf)]
    return base


class StructCodecRule(Rule):
    """Format-string / length-constant / arity consistency."""

    name = "struct-codec"
    description = ("struct formats must parse; calcsize must equal paired "
                   "*_LENGTH/_SIZE constants and '# N bytes' comments; "
                   "unpack destructures and pack argument lists must "
                   "match the field count.")

    def check_module(self, module, ctx):
        tree = module.tree
        struct_vars: Dict[str, str] = {}  # var -> fmt (module level)
        int_consts: Dict[str, int] = {}
        # pass 1: module-level bindings
        for node in tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            fmt = self._struct_ctor_fmt(node.value)
            if fmt is not None:
                struct_vars[name] = fmt
            elif isinstance(node.value, ast.Constant) \
                    and type(node.value.value) is int:
                int_consts[name] = node.value.value
        # pass 2: every struct call in the file
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._check_call(node, struct_vars)
            elif isinstance(node, ast.Assign):
                self._check_unpack_assign(node, struct_vars)
        # pass 3: paired length constants
        for var, fmt in struct_vars.items():
            size = self._calcsize(None, fmt)
            if size is None:
                continue
            base = _struct_base(var)
            for cname in [base + s for s in _CONST_SUFFIXES] \
                    + [_struct_base(base) + s for s in _CONST_SUFFIXES]:
                if cname in int_consts and int_consts[cname] != size:
                    self.flag(self._line_of(tree, var),
                              f"`{var}` packs {size} bytes ({fmt!r}) but "
                              f"paired constant {cname} = "
                              f"{int_consts[cname]}")
        # pass 4: '# N bytes' trailing comments on struct/size lines
        self._check_size_comments(module, struct_vars)

    # -- helpers ----------------------------------------------------------

    def _struct_ctor_fmt(self, value) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = dotted_name(value.func)
        if d is None or d.split(".")[-1] != "Struct":
            return None
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            return value.args[0].value
        return None

    def _calcsize(self, node, fmt: str) -> Optional[int]:
        try:
            return _struct.calcsize(fmt)
        except _struct.error as e:
            if node is not None:
                self.flag(node, f"invalid struct format {fmt!r}: {e}")
            return None

    def _line_of(self, tree, var: str) -> int:
        for node in tree.body:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == var:
                return node.lineno
        return 1

    def _call_fmt(self, node: ast.Call,
                  struct_vars: Dict[str, str]):
        """(fmt, n_fixed_args) for struct.pack/unpack/Struct-method calls."""
        d = dotted_name(node.func)
        if d is None:
            return None
        parts = d.split(".")
        leaf = parts[-1]
        if leaf not in ("pack", "unpack", "unpack_from", "pack_into",
                        "calcsize", "iter_unpack", "Struct"):
            return None
        if len(parts) == 2 and parts[0] in struct_vars:
            return struct_vars[parts[0]], 0  # V.pack(...) — fmt bound
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value, 1  # struct.pack(fmt, ...)
        return None

    def _check_call(self, node: ast.Call, struct_vars) -> None:
        got = self._call_fmt(node, struct_vars)
        if got is None:
            return
        fmt, n_fmt_args = got
        size = self._calcsize(node, fmt)
        if size is None:
            return
        d = dotted_name(node.func)
        leaf = d.split(".")[-1]
        if leaf == "pack" and not any(
                isinstance(a, ast.Starred) for a in node.args):
            supplied = len(node.args) - n_fmt_args
            want = _field_count(fmt)
            if supplied != want:
                self.flag(node, f"pack({fmt!r}) takes {want} values, "
                          f"{supplied} supplied")

    def _check_unpack_assign(self, node: ast.Assign, struct_vars) -> None:
        if not isinstance(node.value, ast.Call):
            return
        got = self._call_fmt(node.value, struct_vars)
        if got is None:
            return
        d = dotted_name(node.value.func)
        if d.split(".")[-1] not in ("unpack", "unpack_from"):
            return
        fmt, _ = got
        if self._calcsize(None, fmt) is None:
            return
        want = _field_count(fmt)
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple) and not any(
                    isinstance(e, ast.Starred) for e in tgt.elts):
                if len(tgt.elts) != want:
                    self.flag(node, f"unpack({fmt!r}) yields {want} "
                              f"values, {len(tgt.elts)} targets")

    def _check_size_comments(self, module, struct_vars) -> None:
        for node in module.tree.body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            line = module.lines[node.lineno - 1] \
                if node.lineno <= len(module.lines) else ""
            m = _BYTES_COMMENT.search(line)
            if not m:
                continue
            claimed = int(m.group(1))
            fmt = self._struct_ctor_fmt(node.value)
            if fmt is None:
                # X = V.size  # N bytes
                d = dotted_name(node.value)
                if d is None:
                    continue
                parts = d.split(".")
                if len(parts) == 2 and parts[1] == "size" \
                        and parts[0] in struct_vars:
                    fmt = struct_vars[parts[0]]
            if fmt is None:
                continue
            size = self._calcsize(None, fmt)
            if size is not None and size != claimed:
                self.flag(node, f"comment claims {claimed} bytes but "
                          f"{fmt!r} packs {size}")


_REG_LEAVES = {"on", "on_any", "on_socket_event"}


class DispatchHandlerRule(Rule):
    """Registered opcodes must reference handlers that exist."""

    name = "dispatch-handler"
    description = ("Every `X.on(msg_id, handler)` registration must point "
                   "at a resolvable handler (method/function/lambda/"
                   "partial) — a renamed handler otherwise dies at role "
                   "startup or on first frame.")

    def check_module(self, module, ctx):
        self._cls_stack: List[str] = []
        self._local_defs: List[set] = []
        self.visit(module.tree)

    def visit_ClassDef(self, node):
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_fn(self, node):
        nested = {n.name for n in ast.walk(node)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # parameters count: wrapper methods forward `fn` straight through
        # (net/module.py `def on(self, msg_id, fn): self.dispatch.on(...)`)
        a = node.args
        nested |= {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
        if a.vararg:
            nested.add(a.vararg.arg)
        if a.kwarg:
            nested.add(a.kwarg.arg)
        self._local_defs.append(nested)
        self.generic_visit(node)
        self._local_defs.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node):
        d = dotted_name(node.func)
        if d is not None and d.split(".")[-1] in _REG_LEAVES:
            leaf = d.split(".")[-1]
            handler = None
            if leaf == "on" and len(node.args) == 2:
                handler = node.args[1]
            elif leaf in ("on_any", "on_socket_event") \
                    and len(node.args) == 1:
                handler = node.args[0]
            if handler is not None:
                self._check_handler(node, handler)
        self.generic_visit(node)

    def _check_handler(self, node, handler) -> None:
        if isinstance(handler, ast.Lambda):
            return
        if isinstance(handler, ast.Call):
            # handler factory: self._on_register(ServerType.WORLD) — the
            # factory itself must resolve
            self._check_handler(node, handler.func)
            return
        if isinstance(handler, ast.Attribute):
            d = dotted_name(handler)
            if d is None:
                return
            parts = d.split(".")
            if parts[0] == "self" and len(parts) == 2 and self._cls_stack:
                if not self._self_method_exists(parts[1]):
                    self.flag(node, f"opcode registered with handler "
                              f"`{d}` but no such method exists on "
                              f"{self._cls_stack[-1]} (or its "
                              "package-resolvable bases)")
            return
        if isinstance(handler, ast.Name):
            if any(handler.id in s for s in self._local_defs):
                return
            syms = self.ctx.index.by_rel.get(self.module.rel)
            if syms is not None and (handler.id in syms.funcs
                                     or handler.id in syms.classes
                                     or handler.id in syms.imports):
                return
            self.flag(node, f"opcode registered with handler "
                      f"`{handler.id}` which is not defined in this "
                      "module")

    def _self_method_exists(self, name: str) -> bool:
        index = self.ctx.index
        syms = index.by_rel.get(self.module.rel)
        if syms is None:
            return True  # unindexed (parse issue) — don't guess
        ci = syms.classes.get(self._cls_stack[-1])
        if ci is None:
            return True
        if index.method_on(ci, name) is not None:
            return True
        # assigned callables (self.handler = ... in __init__) count
        for m in ci.methods.values():
            for n in ast.walk(m.node):
                if isinstance(n, ast.Attribute) and n.attr == name \
                        and isinstance(n.ctx, ast.Store):
                    return True
        return False
