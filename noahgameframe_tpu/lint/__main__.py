"""CLI for nf-lint: ``python -m noahgameframe_tpu.lint``.

Exit status: 0 when every finding is suppressed or baselined, 1 when
any open finding remains, 2 on usage errors.  ``--update-baseline``
rewrites the baseline from the current open findings and exits 0.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import ALL_RULES, RULES_BY_NAME
from .engine import run_lint, write_baseline

_PKG_ROOT = Path(__file__).resolve().parent.parent
_DEFAULT_BASELINE = _PKG_ROOT.parent / "nf_lint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nf-lint",
        description="static analysis for trace-safety, device-sync and "
                    "protocol contracts (see docs/LINT.md)")
    p.add_argument("--root", type=Path, default=_PKG_ROOT,
                   help="directory to scan (default: the installed "
                        "noahgameframe_tpu package)")
    p.add_argument("--rule", action="append", dest="rules", default=None,
                   metavar="NAME",
                   help="run only this rule (repeatable); see --list-rules")
    p.add_argument("--json", action="store_true",
                   help="emit the JSON findings report on stdout")
    p.add_argument("--baseline", type=Path, default=None,
                   help=f"baseline file (default: {_DEFAULT_BASELINE.name} "
                        "next to the package, when present)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current open findings")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:20s} {cls.description}")
        return 0
    if args.rules:
        unknown = [r for r in args.rules if r not in RULES_BY_NAME]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
    baseline = args.baseline
    if baseline is None and _DEFAULT_BASELINE.exists():
        baseline = _DEFAULT_BASELINE
    report = run_lint(args.root, rules=ALL_RULES, rule_filter=args.rules,
                      baseline_path=None if args.update_baseline
                      else baseline)

    if args.update_baseline:
        target = args.baseline or _DEFAULT_BASELINE
        write_baseline(target, report.open_findings)
        print(f"baseline updated: {target} "
              f"({len(report.open_findings)} finding(s))")
        return 0

    if args.json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in sorted(report.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            tag = "" if f.status == "open" else f" [{f.status}]"
            print(f"{f.path}:{f.line}: [{f.rule}]{tag} {f.message}")
        for key in report.stale_baseline:
            print(f"stale baseline entry (fixed? run --update-baseline): "
                  f"{key}")
        open_n = len(report.open_findings)
        sup = sum(1 for f in report.findings if f.status == "suppressed")
        base = sum(1 for f in report.findings if f.status == "baselined")
        print(f"nf-lint: {open_n} open, {sup} suppressed, {base} "
              f"baselined ({len(report.rules)} rules)")
    return 1 if report.open_findings else 0


if __name__ == "__main__":
    sys.exit(main())
