"""Package-wide symbol index + jit-rooted call-graph reachability.

The trace-safety and recompile-hazard rules need to know which
functions can run *inside* a compiled tick.  Python gives no static
guarantee, so this module computes a name-based over-approximation:

- **Roots**: every function handed to ``jax.jit`` (call form,
  ``partial(jax.jit, ...)``, decorator form) anywhere in the package,
  plus every phase registered with ``Module.add_phase`` — the kernel
  composes those straight into the traced step.
- **Edges**: from a traced function, any call whose target resolves
  through local defs, module functions, package-internal imports,
  ``self.<method>`` on the enclosing class, or — for ``obj.method()``
  attribute calls — a method name defined exactly once in the whole
  package (ambiguous names are skipped, an under-approximation the
  contract tests pin).  Function references passed as call arguments
  (``lax.fori_loop(0, k, body, st)``, ``shard_map(fn, ...)``) are
  treated as called.  Instantiating a package class pulls in its
  methods (``TickCtx`` helpers run traced).

The result is deliberately name-based and conservative: a missed edge
means a missed check (the paired violation tests keep the important
edges alive); a spurious edge only means an extra file gets scanned.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from .engine import ModuleInfo, PackageContext, dotted_name

# attribute-call names too generic to resolve by bare-name lookup even
# when unique — they collide with dict/list/ndarray methods constantly
_GENERIC_ATTRS = {
    "get", "set", "add", "items", "keys", "values", "append", "extend",
    "pop", "update", "copy", "clear", "sort", "join", "split", "strip",
    "read", "write", "close", "open", "send", "put", "sum", "min", "max",
    "mean", "any", "all", "astype", "reshape", "replace", "encode",
    "decode", "format", "count",
}

# call heads that take functions as arguments and call them inside the
# trace (so their args are harvested for function references)
_COMBINATORS = {
    "jit", "vmap", "pmap", "fori_loop", "while_loop", "scan", "cond",
    "switch", "partial", "shard_map", "named_call", "checkpoint",
    "remat", "custom_vjp", "custom_jvp", "grad", "value_and_grad",
    "tree_map", "map",
}


@dataclasses.dataclass(frozen=True)
class FuncInfo:
    rel: str  # module path relative to scan root
    modname: str  # dotted ("kernel.kernel")
    qual: str  # "Kernel._trace_step"
    cls: Optional[str]
    node: Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

    @property
    def key(self) -> Tuple[str, str, int]:
        return (self.rel, self.qual, self.node.lineno)


@dataclasses.dataclass
class ClassInfo:
    rel: str
    modname: str
    name: str
    bases: Tuple[str, ...]  # dotted base expressions
    methods: Dict[str, FuncInfo]


@dataclasses.dataclass
class ModuleSyms:
    modname: str
    rel: str
    funcs: Dict[str, FuncInfo]
    classes: Dict[str, ClassInfo]
    # local name -> ("mod", dotted-modname) | ("sym", modname, orig_name)
    imports: Dict[str, Tuple]


def _modname(rel: str) -> str:
    parts = rel[:-3].split("/")  # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class PackageIndex:
    """Symbol tables for every module under the scan root."""

    def __init__(self, ctx: PackageContext):
        self.ctx = ctx
        self.pkg_name = ctx.root.name
        self.modules: Dict[str, ModuleSyms] = {}
        self.by_rel: Dict[str, ModuleSyms] = {}
        self.by_func_name: Dict[str, List[FuncInfo]] = {}
        for rel, mod in ctx.modules.items():
            if mod.tree is None:
                continue
            syms = self._index_module(rel, mod)
            self.modules[syms.modname] = syms
            self.by_rel[rel] = syms
        for syms in self.modules.values():
            for fi in syms.funcs.values():
                self.by_func_name.setdefault(fi.qual.rsplit(".", 1)[-1],
                                             []).append(fi)
            for ci in syms.classes.values():
                for name, fi in ci.methods.items():
                    self.by_func_name.setdefault(name, []).append(fi)

    # -- construction -----------------------------------------------------

    def _index_module(self, rel: str, mod: ModuleInfo) -> ModuleSyms:
        modname = _modname(rel)
        syms = ModuleSyms(modname=modname, rel=rel, funcs={}, classes={},
                          imports={})
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                syms.funcs[node.name] = FuncInfo(rel, modname, node.name,
                                                 None, node)
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for b in node.body:
                    if isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        methods[b.name] = FuncInfo(
                            rel, modname, f"{node.name}.{b.name}",
                            node.name, b)
                bases = tuple(d for d in (dotted_name(b) for b in node.bases)
                              if d is not None)
                syms.classes[node.name] = ClassInfo(rel, modname, node.name,
                                                    bases, methods)
        for node in ast.walk(mod.tree):
            self._index_imports(node, modname, syms.imports)
        return syms

    def _index_imports(self, node, modname: str, out: Dict) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                target = self._abs_module(a.name)
                if target is not None:
                    out[local] = ("mod", target if a.asname else
                                  target.split(".")[0])
                    if a.asname:
                        out[local] = ("mod", target)
        elif isinstance(node, ast.ImportFrom):
            base = self._from_base(modname, node)
            if base is None:
                return
            for a in node.names:
                local = a.asname or a.name
                child = f"{base}.{a.name}" if base else a.name
                if child in self._known_modnames():
                    out[local] = ("mod", child)
                else:
                    out[local] = ("sym", base, a.name)

    def _known_modnames(self) -> Set[str]:
        if not hasattr(self, "_known"):
            self._known = {_modname(rel) for rel in self.ctx.modules}
        return self._known

    def _abs_module(self, dotted: str) -> Optional[str]:
        """Map an absolute import to a root-relative module name."""
        parts = dotted.split(".")
        if parts[0] == self.pkg_name:
            inner = ".".join(parts[1:])
            return inner if inner in self._known_modnames() or not inner \
                else None
        return None  # external

    def _from_base(self, modname: str, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return self._abs_module(node.module or "")
        container = modname.split(".") if modname else []
        rel = self.ctx.root / (modname.replace(".", "/") + ".py")
        # a package __init__'s level-1 refers to itself; a module's to
        # its parent.  Our modname for pkg/__init__.py already drops the
        # __init__ segment, so both cases are "drop (level-1) from the
        # container", where a plain module's container excludes itself.
        if rel.exists() or f"{modname}".replace(".", "/") + ".py" in self.ctx.modules:
            container = container[:-1]
        drop = node.level - 1
        if drop > len(container):
            return None
        base = container[: len(container) - drop] if drop else container
        if node.module:
            base = base + node.module.split(".")
        name = ".".join(base)
        return name if name in self._known_modnames() or name == "" else None

    # -- resolution -------------------------------------------------------

    def module_syms(self, modname: str) -> Optional[ModuleSyms]:
        return self.modules.get(modname)

    def resolve_in_module(self, modname: str, name: str):
        syms = self.modules.get(modname)
        if syms is None:
            return None
        if name in syms.funcs:
            return syms.funcs[name]
        if name in syms.classes:
            return syms.classes[name]
        imp = syms.imports.get(name)
        if imp is not None:
            return self._resolve_import(imp)
        return None

    def _resolve_import(self, imp: Tuple):
        if imp[0] == "mod":
            return ("mod", imp[1])
        _, base, orig = imp
        return self.resolve_in_module(base, orig)

    def class_info(self, modname: str, cls_name: str) -> Optional[ClassInfo]:
        syms = self.modules.get(modname)
        if syms and cls_name in syms.classes:
            return syms.classes[cls_name]
        return None

    def method_on(self, ci: ClassInfo, name: str,
                  _depth: int = 0) -> Optional[FuncInfo]:
        """Method lookup through package-resolvable base classes."""
        if name in ci.methods:
            return ci.methods[name]
        if _depth > 4:
            return None
        for base in ci.bases:
            head = base.split(".")[-1]
            target = self.resolve_in_module(ci.modname, base.split(".")[0])
            if isinstance(target, ClassInfo):
                found = self.method_on(target, name, _depth + 1)
                if found:
                    return found
            elif isinstance(target, tuple) and target[0] == "mod":
                bsyms = self.modules.get(target[1])
                if bsyms and head in bsyms.classes:
                    found = self.method_on(bsyms.classes[head], name,
                                           _depth + 1)
                    if found:
                        return found
        return None

    def unique_by_name(self, name: str) -> Optional[FuncInfo]:
        if name in _GENERIC_ATTRS or name.startswith("__"):
            return None
        cands = self.by_func_name.get(name, ())
        return cands[0] if len(cands) == 1 else None


# -------------------------------------------------------------------------
# scopes + reference harvesting
# -------------------------------------------------------------------------

@dataclasses.dataclass
class Scope:
    index: PackageIndex
    modname: str
    cls: Optional[ClassInfo]
    locals: Dict[str, FuncInfo]
    assigns: Dict[str, ast.expr]
    imports: Dict[str, Tuple]

    def child_for(self, fn_node) -> "Scope":
        locals_: Dict[str, FuncInfo] = {}
        assigns: Dict[str, ast.expr] = {}
        imports: Dict[str, Tuple] = dict(self.imports)
        for node in ast.walk(fn_node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn_node:
                locals_[node.name] = FuncInfo(
                    self.index.modules[self.modname].rel
                    if self.modname in self.index.modules else "?",
                    self.modname, node.name, None, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                assigns[node.targets[0].id] = node.value
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self.index._index_imports(node, self.modname, imports)
        return dataclasses.replace(self, locals={**self.locals, **locals_},
                                   assigns={**self.assigns, **assigns},
                                   imports=imports)


Target = Union[FuncInfo, ClassInfo]


def resolve_name(scope: Scope, name: str, _depth: int = 0) -> List[Target]:
    if name in scope.locals:
        return [scope.locals[name]]
    if name in scope.assigns and _depth < 6:
        return harvest(scope.assigns[name], scope, _depth + 1)
    imp = scope.imports.get(name)
    if imp is not None:
        t = scope.index._resolve_import(imp)
        if isinstance(t, (FuncInfo, ClassInfo)):
            return [t]
        return []
    t = scope.index.resolve_in_module(scope.modname, name)
    if isinstance(t, (FuncInfo, ClassInfo)):
        return [t]
    return []


def resolve_attr(scope: Scope, node: ast.Attribute,
                 as_call: bool) -> List[Target]:
    dotted = dotted_name(node)
    if dotted is None:
        # dynamic root (call result, subscript …): bare-name fallback
        if as_call:
            fi = scope.index.unique_by_name(node.attr)
            return [fi] if fi else []
        return []
    parts = dotted.split(".")
    if parts[0] == "self" and scope.cls is not None and len(parts) == 2:
        m = scope.index.method_on(scope.cls, parts[1])
        if m:
            return [m]
        return []
    # module-alias chains: nf_mod.sub.fn
    imp = scope.imports.get(parts[0])
    if imp is not None and imp[0] == "mod" or (
            imp is not None and scope.index._resolve_import(imp) is not None):
        t = scope.index._resolve_import(imp) if imp else None
        i = 1
        while isinstance(t, tuple) and t[0] == "mod" and i < len(parts):
            syms = scope.index.modules.get(t[1])
            if syms is None:
                t = None
                break
            nxt = scope.index.resolve_in_module(t[1], parts[i])
            if nxt is None and f"{t[1]}.{parts[i]}" in scope.index.modules:
                nxt = ("mod", f"{t[1]}.{parts[i]}")
            t = nxt
            i += 1
        if isinstance(t, (FuncInfo, ClassInfo)) and i == len(parts):
            return [t]
        if isinstance(t, (FuncInfo, ClassInfo)):
            return []
    if as_call and len(parts) >= 2:
        fi = scope.index.unique_by_name(parts[-1])
        return [fi] if fi else []
    return []


def harvest(expr, scope: Scope, _depth: int = 0) -> List[Target]:
    """Every package function/class an expression could hand to jax."""
    if _depth > 8 or expr is None:
        return []
    out: List[Target] = []
    if isinstance(expr, ast.Name):
        out.extend(resolve_name(scope, expr.id, _depth))
    elif isinstance(expr, ast.Attribute):
        out.extend(resolve_attr(scope, expr, as_call=False))
        if not out:
            fi = scope.index.unique_by_name(expr.attr)
            if fi:
                out.append(fi)
    elif isinstance(expr, ast.Lambda):
        out.append(FuncInfo("<lambda>", scope.modname, "<lambda>", None,
                            expr))
    elif isinstance(expr, ast.Call):
        out.extend(harvest(expr.func, scope, _depth + 1))
        for a in list(expr.args) + [k.value for k in expr.keywords]:
            out.extend(harvest(a, scope, _depth + 1))
    elif isinstance(expr, (ast.Tuple, ast.List)):
        for e in expr.elts:
            out.extend(harvest(e, scope, _depth + 1))
    return out


# -------------------------------------------------------------------------
# roots + reachability
# -------------------------------------------------------------------------

def _is_jit_ref(node, scope: Scope) -> bool:
    """Is this expression a reference to jax.jit (alias-tolerant)?"""
    d = dotted_name(node)
    if d is None:
        return False
    return d in ("jax.jit", "jit") or d.endswith(".jit")


def _jit_call_kind(call: ast.Call, scope: Scope) -> Optional[str]:
    """'direct' for jax.jit(f, ...), 'partial' for partial(jax.jit, ...)."""
    if _is_jit_ref(call.func, scope):
        return "direct"
    d = dotted_name(call.func)
    if d is not None and d.split(".")[-1] == "partial" and call.args \
            and _is_jit_ref(call.args[0], scope):
        return "partial"
    return None


def _static_info(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


@dataclasses.dataclass
class JitSite:
    rel: str
    lineno: int
    call: Optional[ast.Call]  # None for bare-decorator form
    targets: List[Target]
    direct_targets: List[FuncInfo]  # eligible for static-arg analysis
    static_argnums: Set[int]
    static_argnames: Set[str]
    kind: str  # "jit" | "phase"


class _RootCollector(ast.NodeVisitor):
    def __init__(self, index: PackageIndex, syms: ModuleSyms):
        self.index = index
        self.syms = syms
        self.scope = Scope(index, syms.modname, None, {}, {}, syms.imports)
        self.sites: List[JitSite] = []
        self._cls_stack: List[ClassInfo] = []
        self._fn_stack: List[Scope] = []

    def visit_ClassDef(self, node):
        ci = self.syms.classes.get(node.name)
        self._cls_stack.append(ci)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _cur_scope(self) -> Scope:
        base = self._fn_stack[-1] if self._fn_stack else self.scope
        cls = self._cls_stack[-1] if self._cls_stack else None
        return dataclasses.replace(base, cls=cls)

    def _visit_fn(self, node):
        scope = self._cur_scope()
        # decorator roots: @jax.jit / @jit / @partial(jax.jit, ...)
        for dec in node.decorator_list:
            nums: Set[int] = set()
            names: Set[str] = set()
            is_root = False
            if _is_jit_ref(dec, scope):
                is_root = True
            elif isinstance(dec, ast.Call) and _jit_call_kind(dec, scope):
                is_root = True
                nums, names = _static_info(dec)
            if is_root:
                fi = self._owned_info(node)
                self.sites.append(JitSite(
                    self.syms.rel, node.lineno, None, [fi], [fi],
                    nums, names, "jit"))
        self._fn_stack.append(scope.child_for(node))
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _owned_info(self, node) -> FuncInfo:
        cls = self._cls_stack[-1] if self._cls_stack else None
        if cls is not None and node.name in cls.methods:
            return cls.methods[node.name]
        if node.name in self.syms.funcs:
            return self.syms.funcs[node.name]
        return FuncInfo(self.syms.rel, self.syms.modname, node.name,
                        cls.name if cls else None, node)

    def visit_Call(self, node):
        scope = self._cur_scope()
        kind = _jit_call_kind(node, scope)
        if kind == "direct" and node.args:
            targets = harvest(node.args[0], scope)
            direct = [t for t in targets if isinstance(t, FuncInfo)] \
                if isinstance(node.args[0],
                              (ast.Name, ast.Attribute, ast.Lambda)) else []
            nums, names = _static_info(node)
            self.sites.append(JitSite(self.syms.rel, node.lineno, node,
                                      targets, direct, nums, names, "jit"))
        elif kind == "partial" and len(node.args) > 1:
            targets = harvest(node.args[1], scope)
            direct = [t for t in targets if isinstance(t, FuncInfo)] \
                if isinstance(node.args[1],
                              (ast.Name, ast.Attribute, ast.Lambda)) else []
            nums, names = _static_info(node)
            self.sites.append(JitSite(self.syms.rel, node.lineno, node,
                                      targets, direct, nums, names, "jit"))
        else:
            d = dotted_name(node.func)
            if d is not None and d.split(".")[-1] == "add_phase":
                fn_expr = None
                if len(node.args) >= 2:
                    fn_expr = node.args[1]
                else:
                    for kw in node.keywords:
                        if kw.arg == "fn":
                            fn_expr = kw.value
                if fn_expr is not None:
                    targets = harvest(fn_expr, scope)
                    self.sites.append(JitSite(
                        self.syms.rel, node.lineno, node, targets,
                        [], set(), set(), "phase"))
            elif (d is not None and d.split(".")[-1] == "wrap"
                  and len(node.args) >= 2
                  and (isinstance(node.args[0], ast.JoinedStr)
                       or (isinstance(node.args[0], ast.Constant)
                           and isinstance(node.args[0].value, str)))):
                # CostBook.wrap("entry", fn, ...) jits fn behind the
                # cost-observatory dispatcher; treat it as a jit root so
                # trace-safety coverage survives the indirection.  The
                # string(-literal or f-string) first argument keeps this
                # from matching unrelated wrap() methods (e.g. chaos
                # client wrapping).
                targets = harvest(node.args[1], scope)
                direct = [t for t in targets if isinstance(t, FuncInfo)] \
                    if isinstance(node.args[1],
                                  (ast.Name, ast.Attribute, ast.Lambda)) \
                    else []
                nums, names = _static_info(node)
                self.sites.append(JitSite(
                    self.syms.rel, node.lineno, node, targets, direct,
                    nums, names, "jit"))
        self.generic_visit(node)


def jit_sites(ctx: PackageContext) -> List[JitSite]:
    index = ctx.index
    sites: List[JitSite] = []
    for rel, syms in index.by_rel.items():
        if rel.startswith("lint/"):
            continue  # the analyzer does not analyze itself
        mod = ctx.modules[rel]
        col = _RootCollector(index, syms)
        col.visit(mod.tree)
        sites.extend(col.sites)
    return sites


@dataclasses.dataclass
class TracedFunc:
    info: FuncInfo
    scope: Scope
    via: str  # human-readable root provenance


def traced_reachable(ctx: PackageContext) -> Dict[Tuple, TracedFunc]:
    """BFS the call graph from every jit/phase root."""
    index = ctx.index
    reached: Dict[Tuple, TracedFunc] = {}
    queue: List[TracedFunc] = []

    def scope_for(fi: FuncInfo) -> Scope:
        syms = index.modules.get(fi.modname)
        imports = syms.imports if syms else {}
        cls = index.class_info(fi.modname, fi.cls) if fi.cls else None
        base = Scope(index, fi.modname, cls, {}, {}, imports)
        if isinstance(fi.node, ast.Lambda):
            return base
        return base.child_for(fi.node)

    def push(t: Target, via: str):
        if isinstance(t, ClassInfo):
            for m in t.methods.values():
                push(m, via + f" -> {t.name}()")
            return
        if not isinstance(t, FuncInfo):
            return
        if t.rel.startswith("lint/"):
            return
        if t.key in reached:
            return
        tf = TracedFunc(t, scope_for(t), via)
        reached[t.key] = tf
        queue.append(tf)

    for site in jit_sites(ctx):
        via = f"{site.rel}:{site.lineno} ({site.kind})"
        for t in site.targets:
            push(t, via)

    while queue:
        tf = queue.pop()
        scope = dataclasses.replace(tf.scope)
        for node in ast.walk(tf.info.node):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name):
                for t in resolve_name(scope, node.func.id):
                    push(t, tf.via)
            elif isinstance(node.func, ast.Attribute):
                for t in resolve_attr(scope, node.func, as_call=True):
                    push(t, tf.via)
            # combinator args: functions passed by reference are called
            d = dotted_name(node.func)
            leaf = d.split(".")[-1] if d else ""
            if leaf in _COMBINATORS:
                for a in list(node.args) + [k.value for k in node.keywords]:
                    for t in harvest(a, scope):
                        push(t, tf.via)
            else:
                for a in node.args:
                    if isinstance(a, (ast.Name, ast.Attribute)):
                        for t in (resolve_name(scope, a.id)
                                  if isinstance(a, ast.Name)
                                  else resolve_attr(scope, a, as_call=False)):
                            if isinstance(t, FuncInfo):
                                push(t, tf.via)
    return reached
