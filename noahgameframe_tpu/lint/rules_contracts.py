"""Structural thread/clock contracts, migrated from the test-embedded
AST checks (ISSUEs 6, 7, 10, 11).

These rules are scoped to the specific files whose *shape* is the
contract: the write-behind pump surface, the failover parking path, the
drill clock discipline, and the journal tap's trace-sidecar guard.  A
vanished class or method is itself a finding — the contract silently
evaporating is exactly what the original tests defended against.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

from .engine import Rule, dotted_name

# -- write-behind pump surface (ISSUE 6) ----------------------------------

PUMP_METHODS = {"enqueue", "enqueue_one", "note_tick", "barrier", "pump",
                "pending", "discard", "lag_ticks", "queue_depth",
                "degraded"}
SYNC_ALLOWED = {"barrier", "drain", "close", "kill"}

# -- failover parking path (ISSUE 10) -------------------------------------

PARKING_METHODS = {"park", "expire", "replay", "discard", "depth", "keys"}
PROXY_PARKING_SURFACE = {"_parking_pump", "_on_client_message",
                         "_on_switch_route", "_notify_switch"}
_BLOCKING = ("sleep", "fsync", "open", "connect", "recv", "accept")

# -- drill clock discipline (ISSUE 11) ------------------------------------

DRILL_CLOCKLESS = ("drill/schedule.py", "drill/invariants.py")
RUNNER_CLOCK_ALLOWED = {"monotonic", "sleep"}


def _class_methods(tree, class_name: str) -> Optional[Dict]:
    for n in tree.body:
        if isinstance(n, ast.ClassDef) and n.name == class_name:
            return {m.name: m for m in n.body
                    if isinstance(m, ast.FunctionDef)}
    return None


def _calls(fn) -> Iterator[Tuple[int, str]]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func)
            if dotted is not None:
                yield node.lineno, dotted


def _blocking_calls(fn) -> Iterator[Tuple[int, str]]:
    for line, dotted in _calls(fn):
        if dotted.rsplit(".", 1)[-1] in _BLOCKING:
            yield line, dotted


class PumpSurfaceRule(Rule):
    """The per-tick pump surfaces never block: WriteBehindPipeline's
    pump-thread methods touch no store and never sleep (the flusher
    thread's ``_flush_batch`` is the single store caller), and the
    proxy-side ParkingBuffer/parking pump — which every OTHER client's
    traffic waits behind — makes no blocking call."""

    name = "pump-surface"
    description = ("Write-behind pump methods: no store/sleep; "
                   "_flush_batch owns all store I/O.  ParkingBuffer and "
                   "the proxy parking pump: no blocking calls.")
    scope = ("persist/writebehind.py", "net/failover.py",
             "net/roles/proxy.py")

    def check_module(self, module, ctx):
        if module.rel.endswith("persist/writebehind.py") \
                or module.rel == "persist/writebehind.py":
            self._check_writebehind(module)
        elif module.rel.endswith("failover.py"):
            self._check_parking(module)
        elif module.rel.endswith("proxy.py"):
            self._check_proxy(module)

    def _check_writebehind(self, module):
        methods = _class_methods(module.tree, "WriteBehindPipeline")
        if methods is None:
            self.flag(1, "WriteBehindPipeline class vanished — the "
                      "pump-surface contract has nothing to hold onto")
            return
        missing = PUMP_METHODS - set(methods)
        if missing:
            self.flag(1, "pump-thread methods vanished: "
                      f"{sorted(missing)}")
        for name in sorted(PUMP_METHODS & set(methods)):
            for line, dotted in _calls(methods[name]):
                if dotted.startswith("self.backend.") \
                        or dotted == "self._flush_batch" \
                        or dotted.endswith(".sleep") or dotted == "sleep":
                    self.flag(line, f"store/sleep call `{dotted}` on the "
                              f"pump-thread surface ({name})")
        store_callers = {
            name for name, fn in methods.items()
            if any(d.startswith("self.backend.") for _, d in _calls(fn))
        }
        if store_callers - {"_flush_batch"}:
            for name in sorted(store_callers - {"_flush_batch"}):
                self.flag(methods[name].lineno,
                          f"`{name}` calls the store directly — "
                          "_flush_batch (flusher thread) must own every "
                          "store call")

    def _check_parking(self, module):
        methods = _class_methods(module.tree, "ParkingBuffer")
        if methods is None:
            self.flag(1, "ParkingBuffer class vanished — the parking "
                      "no-blocking contract has nothing to hold onto")
            return
        missing = PARKING_METHODS - set(methods)
        if missing:
            self.flag(1, f"parking methods vanished: {sorted(missing)}")
        for name in sorted(PARKING_METHODS & set(methods)):
            for line, dotted in _blocking_calls(methods[name]):
                self.flag(line, f"blocking call `{dotted}` inside "
                          f"ParkingBuffer.{name}")

    def _check_proxy(self, module):
        methods = _class_methods(module.tree, "ProxyRole")
        if methods is None:
            return  # fixture proxies without the class are out of scope
        for name in sorted(PROXY_PARKING_SURFACE):
            if name not in methods:
                self.flag(1, f"proxy parking surface lost `{name}`")
                continue
            for line, dotted in _blocking_calls(methods[name]):
                self.flag(line, f"blocking call `{dotted}` on the proxy "
                          f"parking path ({name})")


class FsyncBarrierRule(Rule):
    """WAL fsync only at barrier/drain/close/kill — per-tick fsync puts
    disk latency on the tick path."""

    name = "fsync-barrier"
    description = ("Only WriteBehindPipeline.barrier/drain/close/kill may "
                   "fsync the WAL.")
    scope = ("persist/writebehind.py",)

    def check_module(self, module, ctx):
        methods = _class_methods(module.tree, "WriteBehindPipeline")
        if methods is None:
            return  # PumpSurfaceRule already reports the vanished class
        for name, fn in methods.items():
            if name in SYNC_ALLOWED:
                continue
            for line, dotted in _calls(fn):
                if dotted in ("self.wal.sync", "os.fsync"):
                    self.flag(line, f"per-tick WAL fsync in `{name}` "
                              "(disk latency on the tick path)")


class DrillClocklessRule(Rule):
    """Campaign schedules/invariants reference no clock AT ALL; the
    runner touches monotonic()/sleep() pacing only."""

    name = "drill-clockless"
    description = ("drill/schedule.py + drill/invariants.py must not "
                   "reference the time module; drill/runner.py only "
                   "monotonic/sleep.")
    scope = ("drill/schedule.py", "drill/invariants.py", "drill/runner.py")

    def check_module(self, module, ctx):
        clockless = any(module.rel.endswith(f) or module.rel == f
                        for f in DRILL_CLOCKLESS)
        aliases = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        aliases.add(a.asname or a.name)
                        if clockless:
                            self.flag(node, "import time — campaign "
                                      "schedules/invariants are "
                                      "tick-indexed by contract")
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if clockless or a.name not in RUNNER_CLOCK_ALLOWED:
                        self.flag(node, f"from time import {a.name} — "
                                  "beyond the drill clock contract")
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute):
                dotted = dotted_name(node)
                if dotted is None or dotted.split(".")[0] not in aliases:
                    continue
                leaf = dotted.split(".")[-1]
                if clockless:
                    self.flag(node, f"clock reference `{dotted}` — "
                              "schedules/invariants must be clockless")
                elif leaf not in RUNNER_CLOCK_ALLOWED:
                    self.flag(node, f"clock use `{dotted}` beyond "
                              "monotonic/sleep pacing")


class JournalTapGuardRule(Rule):
    """FRAME_TRACE sidecars must never enter the journal: the tap's
    event writes stay guarded by a TRACE_MSG_IDS membership test, so
    replay is bit-identical with tracing on or off."""

    name = "journal-tap-guard"
    description = ("GameRole._journal_tap's journal writes must be "
                   "guarded by TRACE_MSG_IDS.")
    scope = ("net/roles/game.py",)

    def check_module(self, module, ctx):
        methods = _class_methods(module.tree, "GameRole")
        if methods is None or "_journal_tap" not in (methods or {}):
            self.flag(1, "GameRole._journal_tap vanished — the trace "
                      "journal-exclusion contract has nothing to hold onto")
            return
        outer = methods["_journal_tap"]
        tap = next((n for n in ast.walk(outer)
                    if isinstance(n, ast.FunctionDef) and n.name == "tap"),
                   None)
        if tap is None:
            self.flag(outer.lineno, "_journal_tap no longer defines the "
                      "`tap` closure")
            return
        writes = [n for n in ast.walk(tap)
                  if isinstance(n, ast.Call)
                  and dotted_name(n.func) is not None
                  and dotted_name(n.func).endswith(".event")]
        if not writes:
            self.flag(tap.lineno, "journal tap no longer writes events")
            return
        guarded = [
            n for n in ast.walk(tap)
            if isinstance(n, ast.If)
            and any(isinstance(x, ast.Name) and x.id == "TRACE_MSG_IDS"
                    for x in ast.walk(n.test))
            and any(w in ast.walk(n) for w in writes)
        ]
        if not guarded:
            self.flag(tap.lineno, "journal writes are not guarded by a "
                      "TRACE_MSG_IDS test — trace sidecars would enter "
                      "the journal and break replay identity between "
                      "traced and untraced runs")
