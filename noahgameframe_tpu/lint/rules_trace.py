"""Compiled-side rules: trace-safety and recompile hazards.

Both walk the jit-reachable call graph from :mod:`.callgraph` — the set
of functions that can run inside (or at trace time of) the compiled
tick — because that is where a stray host sync or data-dependent shape
silently destroys the perf and replay contracts the repo is built on.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .callgraph import TracedFunc, jit_sites, traced_reachable
from .engine import Finding, PackageContext, Rule, dotted_name

#: names whose appearance in an enclosing ``if`` test sanctions a host
#: sync: the stage clock's honest-device-timing span (NF_STAGE_TIMING)
_SANCTION_MARKERS = ("stage_timing", "NF_STAGE_TIMING")

_SYNC_LEAVES = {"block_until_ready", "device_get"}
_SHAPE_FNS = {"arange", "zeros", "ones", "full", "empty", "linspace"}
_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "list", "tuple",
                       "dict", "List", "Tuple", "Dict", "Sequence"}


def _scalar_declared(arg: ast.arg) -> bool:
    """A param annotated as a Python scalar is a DECLARED host value —
    converting it at trace time is sizing math, not a device sync (the
    jit-boundary static check is RecompileHazardRule's job)."""
    ann = arg.annotation
    return isinstance(ann, ast.Name) and ann.id in ("int", "float",
                                                    "bool", "str")


def _tainted_names(fn_node) -> Set[str]:
    """Parameter-rooted names: a cheap tracer proxy.  Params (minus
    ``self`` and scalar-annotated/scalar-defaulted ones) start tainted;
    simple assignments propagate to fixpoint."""
    args = fn_node.args
    pos = args.posonlyargs + args.args
    scalar = {a.arg for a in pos + args.kwonlyargs if _scalar_declared(a)}
    for a, dflt in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(dflt, ast.Constant) \
                and isinstance(dflt.value, (int, float, bool, str)):
            scalar.add(a.arg)
    for a, dflt in zip(args.kwonlyargs, args.kw_defaults):
        if isinstance(dflt, ast.Constant) \
                and isinstance(dflt.value, (int, float, bool, str)):
            scalar.add(a.arg)
    names = {a.arg for a in (pos + args.kwonlyargs)} - scalar
    names |= {a.arg for a in (args.vararg, args.kwarg) if a is not None}
    names.discard("self")
    if isinstance(fn_node, ast.Lambda):
        return names
    for _ in range(8):
        grew = False
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign):
                continue
            rhs_tainted = any(
                isinstance(x, ast.Name) and x.id in names
                for x in ast.walk(node.value))
            if not rhs_tainted:
                continue
            for tgt in node.targets:
                for x in ast.walk(tgt):
                    if isinstance(x, ast.Name) and x.id not in names:
                        names.add(x.id)
                        grew = True
        if not grew:
            break
    return names


def _param_rooted(expr, tainted: Set[str]) -> bool:
    for x in ast.walk(expr):
        if isinstance(x, ast.Name) and x.id in tainted:
            return True
    return False


class _TracedScan(ast.NodeVisitor):
    """Shared traced-function walker with NF_STAGE_TIMING sanctioning."""

    def __init__(self, rule: Rule, tf: TracedFunc):
        self.rule = rule
        self.tf = tf
        self.tainted = _tainted_names(tf.info.node)
        self._sanction_depth = 0

    def scan(self) -> None:
        node = self.tf.info.node
        body = node.body if isinstance(node.body, list) else [node.body]
        for stmt in body:
            self.visit(stmt)

    def _sanctioned_test(self, test) -> bool:
        for x in ast.walk(test):
            if isinstance(x, ast.Name) and any(
                    m in x.id for m in _SANCTION_MARKERS):
                return True
            if isinstance(x, ast.Attribute) and any(
                    m in x.attr for m in _SANCTION_MARKERS):
                return True
            if isinstance(x, ast.Constant) and isinstance(x.value, str) \
                    and "NF_STAGE_TIMING" in x.value:
                return True
        return False

    def visit_If(self, node):
        if self._sanctioned_test(node.test):
            self._sanction_depth += 1
            for stmt in node.body:
                self.visit(stmt)
            self._sanction_depth -= 1
            for stmt in node.orelse:
                self.visit(stmt)
        else:
            self.generic_visit(node)

    def visit_With(self, node):
        if any(self._sanctioned_test(item.context_expr)
               for item in node.items):
            self._sanction_depth += 1
            self.generic_visit(node)
            self._sanction_depth -= 1
        else:
            self.generic_visit(node)

    # nested defs are separate reachability nodes; do not double-scan
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    @property
    def sanctioned(self) -> bool:
        return self._sanction_depth > 0

    def where(self) -> str:
        return f"in jit-reachable `{self.tf.info.qual}` (root: {self.tf.via})"


class _TraceSafetyScan(_TracedScan):
    def visit_Call(self, node):
        d = dotted_name(node.func)
        leaf = d.split(".")[-1] if d else (
            node.func.attr if isinstance(node.func, ast.Attribute) else "")
        if leaf in _SYNC_LEAVES:
            if not self.sanctioned:
                self.rule.flag(node, f"host sync `{leaf}` {self.where()} — "
                               "outside the sanctioned NF_STAGE_TIMING span",
                               path=self.tf.info.rel)
        elif leaf == "item" and not node.args:
            if not self.sanctioned:
                self.rule.flag(node, f"`.item()` forces a device->host "
                               f"transfer {self.where()}",
                               path=self.tf.info.rel)
        elif d == "print" and not self.sanctioned:
            self.rule.flag(node, f"`print` {self.where()} — host I/O "
                           "inside the compiled tick path",
                           path=self.tf.info.rel)
        elif leaf in ("asarray", "array") and d is not None \
                and d.split(".")[0] in ("np", "numpy", "onp"):
            if node.args and _param_rooted(node.args[0], self.tainted) \
                    and not self.sanctioned:
                self.rule.flag(node, "`np." + leaf + "` on a traced value "
                               f"{self.where()} — forces a host readback",
                               path=self.tf.info.rel)
        elif d in ("float", "int") and node.args \
                and isinstance(node.args[0], (ast.Name, ast.Attribute,
                                              ast.Subscript)) \
                and _param_rooted(node.args[0], self.tainted) \
                and not self.sanctioned:
            # direct conversion of a param-rooted value only: wrapped
            # host math (int(math.ceil(...)), int(round(...))) yields a
            # Python scalar already and is trace-time sizing, not a sync
            self.rule.flag(node, f"`{d}()` on a traced value "
                           f"{self.where()} — concretizes (host sync)",
                           path=self.tf.info.rel)
        elif leaf == "getenv" or (d is not None and ".environ" in f".{d}."):
            self.rule.flag(node, f"os.environ read {self.where()} — config "
                           "is a setup-time input, not a trace-time one",
                           path=self.tf.info.rel)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        d = dotted_name(node.value)
        if d is not None and d.split(".")[-1] == "environ":
            # bare os.environ[...] access (no .get call)
            self.rule.flag(node, f"os.environ read {self.where()} — config "
                           "is a setup-time input, not a trace-time one",
                           path=self.tf.info.rel)
        self.generic_visit(node)


class TraceSafetyRule(Rule):
    """Host-sync escapes inside the jit-reachable call graph."""

    name = "trace-safety"
    description = (
        "No block_until_ready / device_get / .item() / np.asarray(traced) "
        "/ print / os.environ reads in jit-reachable code outside the "
        "sanctioned NF_STAGE_TIMING span.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        for tf in traced_reachable(ctx).values():
            if tf.info.rel not in ctx.modules:
                continue
            self.module = ctx.modules[tf.info.rel]
            _TraceSafetyScan(self, tf).scan()
        return self.findings


class _RecompileScan(_TracedScan):
    def visit_Call(self, node):
        d = dotted_name(node.func)
        leaf = d.split(".")[-1] if d else (
            node.func.attr if isinstance(node.func, ast.Attribute) else "")
        if leaf == "tolist" and not node.args:
            self.rule.flag(node, f"`.tolist()` {self.where()} — "
                           "concretizes and feeds Python containers back "
                           "into the trace (retrace per distinct value)",
                           path=self.tf.info.rel)
        elif leaf in _SHAPE_FNS:
            for a in list(node.args) + [k.value for k in node.keywords]:
                for x in ast.walk(a):
                    if isinstance(x, ast.Call) \
                            and isinstance(x.func, ast.Name) \
                            and x.func.id == "len" and x.args \
                            and _param_rooted(x.args[0], self.tainted):
                        self.rule.flag(
                            node, f"data-dependent shape: `{leaf}(len(...))`"
                            f" {self.where()} — every distinct length is a "
                            "fresh trace+compile",
                            path=self.tf.info.rel)
        self.generic_visit(node)


class RecompileHazardRule(Rule):
    """Retrace traps: undeclared-static Python scalars at jit boundaries
    and data-dependent shapes inside the trace."""

    name = "recompile-hazard"
    description = (
        "jitted functions must declare Python-scalar/container params "
        "static; no .tolist()/len()-derived shapes in traced code.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        # (a) jit boundary: scalar-typed params not declared static
        for site in jit_sites(ctx):
            if site.kind != "jit":
                continue
            for fi in site.direct_targets:
                if isinstance(fi.node, ast.Lambda):
                    continue
                self.module = ctx.modules.get(fi.rel)
                if self.module is None:
                    continue
                self._check_params(site, fi)
        # (b) traced interior: data-dependent shapes
        for tf in traced_reachable(ctx).values():
            if tf.info.rel not in ctx.modules:
                continue
            self.module = ctx.modules[tf.info.rel]
            _RecompileScan(self, tf).scan()
        return self.findings

    def _check_params(self, site, fi) -> None:
        args = fi.node.args
        params = args.posonlyargs + args.args
        offset = 0
        if params and params[0].arg == "self":
            params = params[1:]  # bound method: self never reaches jit
        defaults = list(args.defaults)
        # align defaults to the tail of params
        dmap = {}
        for p, dflt in zip(params[len(params) - len(defaults):], defaults):
            dmap[p.arg] = dflt
        for pos, p in enumerate(params):
            if pos + offset in site.static_argnums \
                    or p.arg in site.static_argnames:
                continue
            ann = p.annotation
            ann_name = None
            if isinstance(ann, ast.Name):
                ann_name = ann.id
            elif isinstance(ann, ast.Subscript) \
                    and isinstance(ann.value, ast.Name):
                ann_name = ann.value.id
            hazard = None
            if ann_name in _SCALAR_ANNOTATIONS:
                hazard = f"param `{p.arg}: {ann_name}`"
            elif p.arg in dmap and isinstance(dmap[p.arg], ast.Constant) \
                    and isinstance(dmap[p.arg].value, (int, float, bool,
                                                       str)) \
                    and not isinstance(dmap[p.arg].value, type(None)):
                hazard = (f"param `{p.arg}` defaulting to Python scalar "
                          f"{dmap[p.arg].value!r}")
            if hazard:
                self.flag(fi.node,
                          f"jitted `{fi.qual}` (site {site.rel}:"
                          f"{site.lineno}): {hazard} is not declared "
                          "static — every distinct value retraces",
                          path=fi.rel)
