"""Pallas parity discipline: every jit-reachable ``pl.pallas_call``
site must be pinned by an interpret-mode parity test.

The repo's Pallas kernels only run natively on the accelerator; CI is
CPU-only and exercises them through ``interpret=True``.  The ONLY thing
standing between a fused kernel and a silent bitwise divergence from
the reference fold is the interpret-mode parity test that compares the
two — so that pin is a contract, not a courtesy.  Each module that owns
a pallas_call declares a literal registry::

    PALLAS_PARITY_TESTS = {
        "combat_fold_pallas": "tests/test_stencil_pallas.py",
        "fused_neighborhood": "tests/test_stencil_pallas.py",
    }

mapping the enclosing function name to the test file that pins it.  The
rule walks the jit-reachable call graph (same roots as trace-safety),
finds every reachable pallas_call, and checks the registry names its
enclosing function, the named file exists, and the file's text actually
mentions both the function and ``interpret`` (a registry pointing at an
unrelated file is as good as no registry).  Stale registry keys — a
kernel renamed or deleted without updating its pin — are findings too,
so the registry tracks reality in both directions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import traced_reachable
from .engine import Finding, PackageContext, Rule, dotted_name

REGISTRY_NAME = "PALLAS_PARITY_TESTS"

#: the registry file must contain this word: a parity test that never
#: runs the kernel in interpret mode proves nothing on a CPU CI image
INTERPRET_MARKER = "interpret"


def _literal_registry(tree) -> Optional[Tuple[int, Dict[str, str]]]:
    """The module's top-level ``PALLAS_PARITY_TESTS`` literal, if any.

    Only str->str constant dicts count: a computed registry can't be
    audited statically, which defeats the point of the pin.
    """
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name) and tgt.id == REGISTRY_NAME:
                if not isinstance(value, ast.Dict):
                    return node.lineno, {}
                out: Dict[str, str] = {}
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str) \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, str):
                        out[k.value] = v.value
                return node.lineno, out
    return None


class PallasParityPinnedRule(Rule):
    """Every jit-reachable pallas_call is named by an interpret-mode
    parity test via its module's ``PALLAS_PARITY_TESTS`` registry."""

    name = "pallas-parity-pinned"
    description = (
        "Each jit-reachable pl.pallas_call's enclosing function must "
        "appear in its module's literal PALLAS_PARITY_TESTS registry, "
        "pointing at an existing test file whose text names the "
        "function and runs it in interpret mode; stale registry keys "
        "are findings too.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        # rel -> {func name -> first pallas_call line}
        callers: Dict[str, Dict[str, int]] = {}
        for tf in traced_reachable(ctx).values():
            if tf.info.rel not in ctx.modules:
                continue
            for node in ast.walk(tf.info.node):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d is None or d.split(".")[-1] != "pallas_call":
                    continue
                per = callers.setdefault(tf.info.rel, {})
                name = tf.info.qual.rsplit(".", 1)[-1]
                per.setdefault(name, node.lineno)

        for rel in sorted(callers):
            self.module = ctx.modules[rel]
            reg = _literal_registry(self.module.tree)
            for fname, line in sorted(callers[rel].items()):
                if reg is None:
                    self.flag(line, "jit-reachable pallas_call in "
                              f"`{fname}` but the module declares no "
                              f"literal {REGISTRY_NAME} registry — the "
                              "kernel has no interpret-mode parity pin",
                              path=rel)
                    continue
                _, entries = reg
                if fname not in entries:
                    self.flag(line, f"jit-reachable pallas_call in "
                              f"`{fname}` is not named in "
                              f"{REGISTRY_NAME} — no interpret-mode "
                              "parity test pins this kernel",
                              path=rel)
                    continue
                self._check_pin(ctx, rel, line, fname, entries[fname])

        # stale keys: a registry entry whose kernel vanished (renamed,
        # deleted, or no longer jit-reachable) is a pin guarding nothing
        for rel, mod in ctx.modules.items():
            if mod.tree is None:
                continue
            reg = _literal_registry(mod.tree)
            if reg is None:
                continue
            reg_line, entries = reg
            live: Set[str] = set(callers.get(rel, ()))
            self.module = mod
            for fname in sorted(set(entries) - live):
                self.flag(reg_line, f"{REGISTRY_NAME} entry `{fname}` "
                          "matches no jit-reachable pallas_call in this "
                          "module — stale pin (kernel renamed, deleted, "
                          "or unrooted)", path=rel)
        return self.findings

    def _check_pin(self, ctx: PackageContext, rel: str, line: int,
                   fname: str, pin: str) -> None:
        # pins resolve against the scan root first (fixture layouts),
        # then its parent (the real tree: root is the package dir and
        # tests/ is its sibling)
        for base in (ctx.root, ctx.root.parent):
            path = base / pin
            if path.is_file():
                break
        else:
            self.flag(line, f"{REGISTRY_NAME} pins `{fname}` to "
                      f"`{pin}`, which does not exist — the parity "
                      "test has vanished", path=rel)
            return
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            self.flag(line, f"{REGISTRY_NAME} pin `{pin}` for "
                      f"`{fname}` is unreadable", path=rel)
            return
        if fname not in text:
            self.flag(line, f"parity pin `{pin}` never mentions "
                      f"`{fname}` — the registry points at a file that "
                      "does not test this kernel", path=rel)
        elif INTERPRET_MARKER not in text:
            self.flag(line, f"parity pin `{pin}` for `{fname}` never "
                      f"uses `{INTERPRET_MARKER}` mode — on the CPU CI "
                      "image the kernel is only exercised through "
                      "interpret=True, so this pins nothing", path=rel)
