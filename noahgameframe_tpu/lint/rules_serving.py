"""Serving-edge rule: the batched serve path stays loop-free.

ISSUE 13 moved per-session interest/encode work onto the device — one
vmap-over-sessions dispatch (``ops/serving.py``) against the
SessionTable's seen-state — leaving the host exactly one per-session
job: slicing precomputed byte buffers into packets, attributed to the
StageClock ``assemble`` stage.  A Python ``for`` over the session set
inside an ``interest`` or ``encode`` stage reintroduces the O(sessions)
host wall the tentpole removed, and it does so silently: the frame still
ships, only the waterfall regresses.

The rule walks every ``with ...stage("interest"|"encode")`` block in the
serve roles, follows ``self._method(...)`` calls transitively (without
descending into nested ``stage("assemble")`` blocks — that stage is the
sanctioned per-session emission), and flags loops or comprehensions that
iterate the session set: any ``self.sessions`` chain, or names bound
from ``self._observer_arrays()`` (the legacy path's per-session
collector).  The legacy engine keeps its loops by design — it is the
parity oracle for NF_SERVE_BATCH — so its sites carry reviewed
``# nf-lint: disable=serve-loop -- ...`` waivers.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .engine import Finding, ModuleInfo, PackageContext, Rule, dotted_name

#: stages where per-session Python iteration is the bug
_HOT_STAGES = {"interest", "encode"}
#: the stage whose whole point is per-session packet slicing
_EXEMPT_STAGE = "assemble"
#: self-methods whose results ARE the session set (legacy collector)
_SESSION_SOURCES = {"_observer_arrays"}

_COMPS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _stage_names(node: ast.With) -> Set[str]:
    """Stage labels opened by a ``with`` statement (any item that is a
    ``*.stage("<literal>")`` call)."""
    out: Set[str] = set()
    for item in node.items:
        call = item.context_expr
        if not isinstance(call, ast.Call):
            continue
        d = dotted_name(call.func)
        leaf = d.split(".")[-1] if d else (
            call.func.attr if isinstance(call.func, ast.Attribute) else "")
        if leaf == "stage" and call.args \
                and isinstance(call.args[0], ast.Constant) \
                and isinstance(call.args[0].value, str):
            out.add(call.args[0].value)
    return out


def _session_aliases(fn) -> Set[str]:
    """Local names bound from a ``self._observer_arrays()``-style call —
    iterating them is iterating the session set."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        d = dotted_name(node.value.func)
        if d is None or d.split(".")[-1] not in _SESSION_SOURCES:
            continue
        for tgt in node.targets:
            for x in ast.walk(tgt):
                if isinstance(x, ast.Name):
                    out.add(x.id)
    return out


def _iters_sessions(expr, aliases: Set[str]) -> bool:
    for x in ast.walk(expr):
        if isinstance(x, ast.Attribute) and x.attr == "sessions":
            return True
        if isinstance(x, ast.Name) and x.id in aliases:
            return True
    return False


class ServeLoopRule(Rule):
    """Per-session Python loops inside hot serve stages."""

    name = "serve-loop"
    description = (
        "No `for ... in self.sessions` (or _observer_arrays aliases) "
        "inside StageClock 'interest'/'encode' stages or methods they "
        "call — per-session host work belongs to the 'assemble' stage.")
    scope = ("net/roles/*.py",)

    def check_module(self, module: ModuleInfo, ctx: PackageContext) -> None:
        tree = module.tree
        methods: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods.setdefault(node.name, node)

        # seeds: hot-stage with-blocks, attributed to their (outermost)
        # enclosing function.  ast.walk is breadth-first, so the parent
        # function sees each with-block before any nested def does.
        seen_withs: Set[int] = set()
        queue: List[Tuple[str, str]] = []  # (method name, stage)
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            aliases = _session_aliases(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.With) or id(node) in seen_withs:
                    continue
                seen_withs.add(id(node))
                for stage in _stage_names(node) & _HOT_STAGES:
                    self._scan(node.body, stage, fn.name, aliases, queue)

        # transitive closure over self-method calls made in hot stages
        reached: Dict[str, Set[str]] = {}
        while queue:
            name, stage = queue.pop()
            if stage in reached.setdefault(name, set()):
                continue
            reached[name].add(stage)
            fn = methods.get(name)
            if fn is None:
                continue
            self._scan(fn.body, stage, name, _session_aliases(fn), queue)

    def _scan(self, nodes, stage: str, where: str, aliases: Set[str],
              queue: List[Tuple[str, str]]) -> None:
        """Flag session loops and collect self-calls under one stage.

        Labelled nested ``with`` blocks are NOT descended into:
        'assemble' is the sanctioned per-session emission stage, and any
        other stage label is its own seed (harvested by check_module).
        Nested defs ARE descended into — they execute when called inside
        this stage.  Statements and expressions recurse uniformly via
        ``iter_child_nodes``.
        """
        for node in nodes:
            if isinstance(node, ast.With) and _stage_names(node):
                continue
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and _iters_sessions(node.iter, aliases):
                self._flag_loop(node, stage, where)
            if isinstance(node, _COMPS):
                for gen in node.generators:
                    if _iters_sessions(gen.iter, aliases):
                        self._flag_loop(node, stage, where)
                        break
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                queue.append((node.func.attr, stage))
            self._scan(list(ast.iter_child_nodes(node)), stage, where,
                       aliases, queue)

    def _flag_loop(self, node, stage: str, where: str) -> None:
        self.flag(node,
                  f"per-session Python loop in the '{stage}'-stage serve "
                  f"path (`{where}`) — the batched edge does per-session "
                  "work only in the 'assemble' stage; waivers are for the "
                  "legacy engine only")
