"""Tick-train lane coverage contract (ISSUE 20).

``NF_TICK_TRAIN=K`` compiles a ``lax.scan`` over K kernel ticks into
ONE dispatch; every per-tick output lane of ``_trace_step`` that host
code consumes (journal digests, death masks, diff counts, event
params) is stacked ``[K, ...]`` so the train loses no per-tick
history.  Like the room-pack and migration walks, the stacking is
generic — ``lax.scan`` stacks whatever the step returns — so the
reviewed INTENT lives in one literal: ``TRAIN_LANE_SPEC`` in
``kernel/kernel.py`` enumerates the lanes a train must carry, and
``TRAIN_EXCLUDED`` waivers lanes deliberately dropped (each with a
reason).  This rule is the static complement of the trace-time
``_assert_train_lanes`` check: every key of ``_trace_step``'s out-dict
literal must be enumerated or waivered, and every spec pattern must
still match a real lane — an out lane the spec skips would silently
lose its per-tick history the first time a train replaces the single
ticks, and a stale pattern hides the next real gap.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import List, Optional

from .engine import Finding, PackageContext, Rule
from .rules_store import _find_module, _literal_str_tuple

KERNEL_SUFFIX = "kernel/kernel.py"


def _trace_step_out_keys(tree: ast.AST):
    """The literal string keys of the ``out = {...}`` dict that
    ``_trace_step`` returns, plus the dict node (or ``(None, None)``
    when the shape is not statically reviewable)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == "_trace_step"):
            continue
        for stmt in ast.walk(node):
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "out"
                    and isinstance(stmt.value, ast.Dict)):
                continue
            keys: List[str] = []
            for k in stmt.value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    return None, stmt.value  # computed key: unreviewable
                keys.append(k.value)
            return keys, stmt.value
    return None, None


class TrainLanesCoveredRule(Rule):
    """Every per-tick out lane of ``_trace_step`` is enumerated by
    TRAIN_LANE_SPEC (or waivered in TRAIN_EXCLUDED), and the spec names
    no lane that no longer exists — a lane the train's stacked fetch
    skips silently loses its per-tick history inside a K-tick train."""

    name = "train-lanes-covered"
    description = ("kernel/kernel.py TRAIN_LANE_SPEC (+ TRAIN_EXCLUDED) "
                   "must enumerate every key of _trace_step's out dict, "
                   "and match only keys that exist.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        kern = _find_module(ctx, KERNEL_SUFFIX)
        if kern is None:
            return self.findings  # contract module absent: out of scope
        if kern.tree is None:
            return self.findings  # parse-error finding already emitted

        keys, out_node = _trace_step_out_keys(kern.tree)
        if out_node is None:
            self.flag(1, "_trace_step's `out = {...}` dict literal "
                      "vanished from kernel/kernel.py — the train-lane "
                      "coverage contract has nothing to hold onto",
                      path=kern.rel)
            return self.findings
        if keys is None:
            self.flag(out_node, "_trace_step's out dict has a computed "
                      "key — train lanes must be literal strings to be "
                      "reviewed statically", path=kern.rel)
            return self.findings

        spec, spec_node = _literal_str_tuple(kern.tree, "TRAIN_LANE_SPEC")
        excl, excl_node = _literal_str_tuple(kern.tree, "TRAIN_EXCLUDED")
        if spec_node is None:
            self.flag(1, "TRAIN_LANE_SPEC vanished from kernel/kernel.py",
                      path=kern.rel)
            return self.findings
        if spec is None:
            self.flag(spec_node, "TRAIN_LANE_SPEC must be a literal "
                      "tuple of strings — a computed spec cannot be "
                      "reviewed statically", path=kern.rel)
            return self.findings
        if excl_node is not None and excl is None:
            self.flag(excl_node, "TRAIN_EXCLUDED must be a literal "
                      "tuple of strings", path=kern.rel)
            excl = []
        excl = excl or []

        patterns = list(spec) + list(excl)
        for key in keys:
            if not any(fnmatch.fnmatch(key, pat) for pat in patterns):
                self.flag(out_node, f"out lane `{key}` is not covered "
                          "by TRAIN_LANE_SPEC or TRAIN_EXCLUDED — a "
                          "K-tick train would silently lose its "
                          "per-tick history", path=kern.rel)
        for pat in patterns:
            if not any(fnmatch.fnmatch(key, pat) for key in keys):
                where = spec_node if pat in spec else (excl_node
                                                      or spec_node)
                self.flag(where, f"spec entry `{pat}` matches no "
                          "_trace_step out lane — stale after a kernel "
                          "refactor", path=kern.rel)
        return self.findings
