"""Store/migration coverage contract (ISSUE 15).

The unified mesh engine moves an entity between shards as a FULL
``ClassState`` row: every leaf ``persist/rowblob.py``'s walk yields is
packed, ppermuted and scatter-inserted.  The walk is generic — it
recurses ``dataclasses.fields`` — so a bank added to ``ClassState`` (or
``TimerState``/``RecordState``) is picked up automatically at trace
time.  What the runtime cannot see is INTENT: ``ROW_LEAF_SPEC`` is the
reviewed enumeration of what a row IS, and ``MIGRATION_EXCLUDED`` the
waivered exclusions (must stay empty while caches live in
``WorldState.aux``).  This rule cross-checks the two statically: every
store field must be enumerated (or explicitly waivered), and every spec
entry must still name a real field — the static complement of the
trace-time assertion in ``class_row_leaf_items``, and the migration
twin of PR 10's off-device session-blob re-home sharing the same walk.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Tuple

from .engine import Finding, ModuleInfo, PackageContext, Rule

STORE_SUFFIX = "core/store.py"
SPEC_SUFFIX = "persist/rowblob.py"

#: ClassState fields holding nested row-axis dataclasses, and how their
#: leaves appear as dotted spec paths
_NESTED = {"TimerState": "{field}.{leaf}", "RecordState": "{field}.*.{leaf}"}


def _find_module(ctx: PackageContext, suffix: str) -> Optional[ModuleInfo]:
    for rel, mod in ctx.modules.items():
        if rel == suffix or rel.endswith("/" + suffix):
            return mod
    return None


def _dataclass_fields(tree) -> Dict[str, List[Tuple[str, ast.AnnAssign]]]:
    """name -> [(field, AnnAssign node)] for every class in the module."""
    out: Dict[str, List[Tuple[str, ast.AnnAssign]]] = {}
    for n in tree.body:
        if isinstance(n, ast.ClassDef):
            out[n.name] = [
                (s.target.id, s) for s in n.body
                if isinstance(s, ast.AnnAssign)
                and isinstance(s.target, ast.Name)
            ]
    return out


def _literal_str_tuple(tree, name: str):
    """(values, node) for a module-level ``NAME = ("a", "b", ...)``
    literal; (None, node) when the assignment exists but is not a plain
    literal tuple/list of strings; (None, None) when absent."""
    for n in tree.body:
        targets = []
        if isinstance(n, ast.Assign):
            targets, value = n.targets, n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                if isinstance(value, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in value.elts
                ):
                    return [e.value for e in value.elts], n
                return None, n
    return None, None


class MigrateCoversStoreRule(Rule):
    """Every ClassState leaf is enumerated by the migration pack spec
    (or carries a waivered exclusion), and the spec names no field that
    no longer exists — a bank silently left behind by cross-shard
    migration corrupts the entity on arrival."""

    name = "migrate-covers-store"
    description = ("persist/rowblob.py ROW_LEAF_SPEC (+ MIGRATION_"
                   "EXCLUDED) must enumerate every ClassState leaf in "
                   "core/store.py, and name only leaves that exist.")
    per_module = False

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        store = _find_module(ctx, STORE_SUFFIX)
        spec_mod = _find_module(ctx, SPEC_SUFFIX)
        if store is None or spec_mod is None:
            return self.findings  # contract pair absent: out of scope
        if store.tree is None or spec_mod.tree is None:
            return self.findings  # parse-error finding already emitted

        classes = _dataclass_fields(store.tree)
        if "ClassState" not in classes:
            self.flag(1, "ClassState vanished from core/store.py — the "
                      "migration coverage contract has nothing to hold "
                      "onto", path=store.rel)
            return self.findings
        expected: Dict[str, ast.AnnAssign] = {}
        for field, node in classes["ClassState"]:
            ann = ast.unparse(node.annotation)
            nested = next((c for c in _NESTED if c in ann), None)
            if nested is None:
                expected[field] = node
                continue
            for leaf, _sub in classes.get(nested, []):
                expected[_NESTED[nested].format(field=field,
                                                leaf=leaf)] = node
            if not classes.get(nested):
                self.flag(node, f"nested row dataclass `{nested}` for "
                          f"field `{field}` has no resolvable fields",
                          path=store.rel)

        spec, spec_node = _literal_str_tuple(spec_mod.tree,
                                             "ROW_LEAF_SPEC")
        excl, excl_node = _literal_str_tuple(spec_mod.tree,
                                             "MIGRATION_EXCLUDED")
        if spec_node is None:
            self.flag(1, "ROW_LEAF_SPEC vanished from persist/rowblob.py",
                      path=spec_mod.rel)
            return self.findings
        if spec is None:
            self.flag(spec_node, "ROW_LEAF_SPEC must be a literal tuple "
                      "of strings — a computed spec cannot be reviewed "
                      "statically", path=spec_mod.rel)
            return self.findings
        if excl_node is not None and excl is None:
            self.flag(excl_node, "MIGRATION_EXCLUDED must be a literal "
                      "tuple of strings", path=spec_mod.rel)
            excl = []
        excl = excl or []

        patterns = list(spec) + list(excl)
        for path, node in sorted(expected.items()):
            if not any(fnmatch.fnmatch(path, pat) for pat in patterns):
                self.flag(node, f"store leaf `{path}` is not covered by "
                          "ROW_LEAF_SPEC or MIGRATION_EXCLUDED — "
                          "cross-shard migration would silently leave "
                          "this bank behind", path=store.rel)
        for pat in patterns:
            if not any(fnmatch.fnmatch(path, pat) for path in expected):
                where = spec_node if pat in spec else (excl_node
                                                       or spec_node)
                self.flag(where, f"spec entry `{pat}` matches no "
                          "ClassState leaf — stale after a store "
                          "refactor", path=spec_mod.rel)
        return self.findings
