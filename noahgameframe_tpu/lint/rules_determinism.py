"""Determinism rules: no wall clocks, no unseeded RNGs.

Migrated from the original ``tests/test_determinism_lint.py`` AST
walker (ISSUE 4) and widened from five hand-picked directories to the
whole package: record/replay's contract is that device state is a pure
function of (checkpoint, journaled inputs), and one stray
``time.time()`` or process-global ``random.random()`` on a tick-adjacent
path silently breaks every replay.  Intentional wall-clock reads (live
telemetry stamps, the GuidAllocator's wall mode) carry inline
suppressions with reasons instead of being invisible to the scan.
"""

from __future__ import annotations

import ast

from .engine import Rule, dotted_name


class _AliasTracker(ast.NodeVisitor):
    """Per-file import-alias bookkeeping shared by both rules."""

    def __init__(self) -> None:
        super().__init__()
        self.time_aliases = set()  # modules: import time [as _t]
        self.time_fn_aliases = set()  # names: from time import time [as t]
        self.random_aliases = set()  # modules: import random [as _r]
        self.numpy_aliases = set()  # modules: import numpy [as np]

    def visit_Import(self, node):
        for a in node.names:
            name = a.asname or a.name
            if a.name == "time":
                self.time_aliases.add(name)
            elif a.name == "random":
                self.random_aliases.add(name)
            elif a.name == "numpy":
                self.numpy_aliases.add(name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == "time":
            for a in node.names:
                if a.name == "time":
                    self.time_fn_aliases.add(a.asname or a.name)
        self.generic_visit(node)


class WallClockRule(_AliasTracker, Rule):
    """``time.time()`` under any import alias."""

    name = "wall-clock"
    description = ("No time.time() reads: monotonic()/perf_counter() are "
                   "the injectable-now patterns; wall time in a journaled "
                   "input or compiled path breaks bit-identical replay.")

    def check_module(self, module, ctx):
        self.time_aliases = set()
        self.time_fn_aliases = set()
        self.random_aliases = set()
        self.numpy_aliases = set()
        self.visit(module.tree)

    def visit_Call(self, node):
        dotted = dotted_name(node.func)
        if dotted is not None:
            parts = dotted.split(".")
            if dotted in self.time_fn_aliases:
                self.flag(node, f"wall clock read: {dotted}()")
            elif parts[0] in self.time_aliases and parts[1:] == ["time"]:
                self.flag(node, f"wall clock read: {dotted}()")
        self.generic_visit(node)


class UnseededRngRule(_AliasTracker, Rule):
    """Module-global ``random.*`` / unseeded numpy generators."""

    name = "unseeded-rng"
    description = ("No process-global random.* calls and no unseeded "
                   "np.random generators: all randomness flows from an "
                   "explicit seed so replays reproduce it.")

    def check_module(self, module, ctx):
        self.time_aliases = set()
        self.time_fn_aliases = set()
        self.random_aliases = set()
        self.numpy_aliases = set()
        self.visit(module.tree)

    def visit_Call(self, node):
        dotted = dotted_name(node.func)
        if dotted is not None:
            parts = dotted.split(".")
            head, rest = parts[0], parts[1:]
            if head in self.random_aliases and len(rest) == 1:
                if not (rest[0] == "Random" and node.args):
                    self.flag(node, f"process-global RNG: {dotted}()")
            elif (head in self.numpy_aliases and len(rest) == 2
                  and rest[0] == "random"):
                if not (rest[1] == "default_rng" and node.args):
                    self.flag(node, f"unseeded numpy RNG: {dotted}()")
        self.generic_visit(node)
