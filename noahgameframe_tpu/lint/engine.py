"""nf-lint engine: parsed-module cache, suppressions, baseline, report.

The engine is deliberately dependency-free (``ast`` + ``struct`` + the
standard library only): it must run in CI images, pre-commit hooks and
editors without importing jax or touching a device.  One parse per file
feeds every rule; rules are :class:`Rule` subclasses — an
``ast.NodeVisitor`` with file-set scoping — registered in
``noahgameframe_tpu.lint.ALL_RULES``.

Suppression syntax (same line as the finding, or a standalone comment
above it — a wrapped reason may continue over further comment lines)::

    x = time.time()  # nf-lint: disable=wall-clock -- live-mode stamp

The reason after ``--`` is mandatory: a suppression is a reviewed
decision, not an escape hatch.  A suppression that matches no finding
is itself a finding (``unused-suppression``), so stale waivers cannot
linger after the offense is fixed.

The baseline file records real-but-deferred findings keyed by
``(rule, path, message)`` — line numbers drift, messages don't — so an
old debt doesn't fail CI while any NEW finding still does.  Stale
baseline entries are reported (non-fatally) so ``--update-baseline``
gets run when debt is paid down.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import json
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_TAG = "nf-lint:"

# findings the engine itself emits (never rule names)
UNUSED_SUPPRESSION = "unused-suppression"
BAD_SUPPRESSION = "bad-suppression"
PARSE_ERROR = "parse-error"


@dataclasses.dataclass
class Finding:
    """One diagnostic: a rule, a location, and what went wrong."""

    rule: str
    path: str  # posix path relative to the scan root
    line: int
    message: str
    status: str = "open"  # open | suppressed | baselined
    reason: Optional[str] = None  # suppression reason when suppressed

    def key(self) -> str:
        """Line-free identity used for baseline matching."""
        return f"{self.rule}::{self.path}::{self.message}"

    def to_json(self) -> dict:
        d = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "status": self.status,
        }
        if self.reason is not None:
            d["reason"] = self.reason
        return d


@dataclasses.dataclass
class Suppression:
    line: int  # line the suppression APPLIES to
    rules: Tuple[str, ...]
    reason: str
    comment_line: int
    used: bool = False


class ModuleInfo:
    """One parsed source file: AST + raw lines + suppressions."""

    def __init__(self, rel: str, source: str):
        self.rel = rel  # posix, relative to the scan root
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        self.suppressions: List[Suppression] = []
        self.bad_suppressions: List[Tuple[int, str]] = []
        try:
            self.tree = ast.parse(source, filename=rel)
        except SyntaxError as e:  # surfaced as a PARSE_ERROR finding
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
            return
        self._collect_suppressions()

    # -- suppressions -----------------------------------------------------

    def _collect_suppressions(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except tokenize.TokenError:
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT or SUPPRESS_TAG not in tok.string:
                continue
            lineno, col = tok.start
            text = tok.string
            body = text.split(SUPPRESS_TAG, 1)[1].strip()
            parsed = self._parse_suppression(body)
            if parsed is None:
                self.bad_suppressions.append(
                    (lineno,
                     "malformed suppression: expected "
                     "'# nf-lint: disable=<rule>[,<rule>] -- <reason>'"))
                continue
            rules, reason = parsed
            # a comment alone on its line applies to the next CODE line
            # (continuation comment lines — a wrapped reason — and
            # blanks are skipped); trailing a statement it applies to
            # that statement's line
            prefix = self.lines[lineno - 1][:col] if lineno <= len(self.lines) else ""
            if not prefix.strip():
                target = lineno + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
            else:
                target = lineno
            self.suppressions.append(
                Suppression(line=target, rules=rules, reason=reason,
                            comment_line=lineno))

    @staticmethod
    def _parse_suppression(body: str) -> Optional[Tuple[Tuple[str, ...], str]]:
        if not body.startswith("disable="):
            return None
        body = body[len("disable="):]
        if "--" not in body:
            return None  # reason is mandatory
        rules_part, reason = body.split("--", 1)
        reason = reason.strip()
        rules = tuple(r.strip() for r in rules_part.split(",") if r.strip())
        if not rules or not reason:
            return None
        return rules, reason

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.line == line and rule in s.rules:
                return s
        return None


class PackageContext:
    """Every parsed module under the scan root, plus lazy cross-file
    indexes (built by callgraph.py on first use)."""

    def __init__(self, root: Path,
                 overrides: Optional[Dict[str, str]] = None):
        self.root = Path(root)
        self.modules: Dict[str, ModuleInfo] = {}
        self._index = None  # callgraph.PackageIndex, built lazily
        overrides = dict(overrides or {})
        for path in sorted(self.root.rglob("*.py")):
            rel = path.relative_to(self.root).as_posix()
            src = overrides.pop(rel, None)
            if src is None:
                src = path.read_text(encoding="utf-8")
            self.modules[rel] = ModuleInfo(rel, src)
        for rel, src in sorted(overrides.items()):  # purely-virtual files
            self.modules[rel] = ModuleInfo(rel, src)

    @property
    def index(self):
        if self._index is None:
            from .callgraph import PackageIndex

            self._index = PackageIndex(self)
        return self._index


class Rule(ast.NodeVisitor):
    """Base class for lint rules.

    Subclasses set ``name``/``description`` and either implement
    ``visit_*`` methods (per-module mode: the engine calls :meth:`run`
    once per in-scope file) or set ``per_module = False`` and override
    :meth:`run_package` for whole-package analyses (call graphs,
    dispatch tables).

    ``scope`` is a tuple of fnmatch globs over root-relative posix
    paths; empty means every ``*.py`` under the root.
    """

    name: str = ""
    description: str = ""
    scope: Tuple[str, ...] = ()
    per_module: bool = True

    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.module: Optional[ModuleInfo] = None
        self.ctx: Optional[PackageContext] = None

    # -- scoping ----------------------------------------------------------

    def applies(self, rel: str) -> bool:
        if not self.scope:
            return True
        return any(fnmatch.fnmatch(rel, pat) for pat in self.scope)

    # -- drivers ----------------------------------------------------------

    def run(self, module: ModuleInfo, ctx: PackageContext) -> List[Finding]:
        self.findings = []
        self.module = module
        self.ctx = ctx
        if module.tree is not None:
            self.check_module(module, ctx)
        return self.findings

    def check_module(self, module: ModuleInfo, ctx: PackageContext) -> None:
        """Default per-module driver: visit the AST."""
        self.visit(module.tree)

    def run_package(self, ctx: PackageContext) -> List[Finding]:
        """Whole-package driver for ``per_module = False`` rules."""
        raise NotImplementedError

    # -- reporting --------------------------------------------------------

    def flag(self, node, message: str, path: Optional[str] = None) -> None:
        line = getattr(node, "lineno", node if isinstance(node, int) else 0)
        self.findings.append(
            Finding(rule=self.name, path=path or self.module.rel,
                    line=line, message=message))


# -- helpers shared by several rules --------------------------------------

def dotted_name(node) -> Optional[str]:
    """Attribute/Name chain as 'a.b.c', or None for dynamic expressions."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- baseline -------------------------------------------------------------

def load_baseline(path: Optional[Path]) -> Dict[str, dict]:
    if path is None or not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    return {e["rule"] + "::" + e["path"] + "::" + e["message"]: e
            for e in data.get("findings", ())}


def write_baseline(path: Path, findings: Iterable[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "line": f.line,
         "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    ]
    payload = {
        "version": 1,
        "comment": "real-but-deferred nf-lint findings; regenerate with "
                   "`python -m noahgameframe_tpu.lint --update-baseline`",
        "findings": entries,
    }
    Path(path).write_text(json.dumps(payload, indent=2) + "\n",
                          encoding="utf-8")


# -- report ---------------------------------------------------------------

@dataclasses.dataclass
class Report:
    root: str
    rules: List[str]
    findings: List[Finding]
    stale_baseline: List[str]

    @property
    def open_findings(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "open"]

    def to_json(self) -> dict:
        counts = {"total": len(self.findings)}
        for st in ("open", "suppressed", "baselined"):
            counts[st] = sum(1 for f in self.findings if f.status == st)
        return {
            "version": 1,
            "root": self.root,
            "rules": list(self.rules),
            "counts": counts,
            "findings": [f.to_json() for f in sorted(
                self.findings, key=lambda f: (f.path, f.line, f.rule))],
            "stale_baseline": list(self.stale_baseline),
        }


def run_lint(root: Path,
             rules: Sequence[type] = None,
             rule_filter: Optional[Sequence[str]] = None,
             baseline_path: Optional[Path] = None,
             overrides: Optional[Dict[str, str]] = None) -> Report:
    """Run ``rules`` (classes) over every ``*.py`` under ``root``.

    ``rule_filter`` keeps only the named rules (engine-level findings —
    suppression hygiene, parse errors — always run).  ``overrides`` maps
    root-relative paths to replacement source text, letting tests inject
    a violation into a real module without touching disk.
    """
    if rules is None:
        from . import ALL_RULES

        rules = ALL_RULES
    selected = [cls for cls in rules
                if not rule_filter or cls.name in rule_filter]
    if rule_filter:
        known = {cls.name for cls in rules}
        unknown = [r for r in rule_filter if r not in known]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    ctx = PackageContext(root, overrides=overrides)
    findings: List[Finding] = []

    for rel, mod in ctx.modules.items():
        if mod.parse_error is not None:
            findings.append(Finding(PARSE_ERROR, rel, 0, mod.parse_error))
        for lineno, msg in mod.bad_suppressions:
            findings.append(Finding(BAD_SUPPRESSION, rel, lineno, msg))

    for cls in selected:
        rule = cls()
        if rule.per_module:
            for rel, mod in ctx.modules.items():
                if rule.applies(rel):
                    findings.extend(rule.run(mod, ctx))
        else:
            rule.ctx = ctx
            findings.extend(rule.run_package(ctx))

    # dedupe: one line can trip a rule twice (float(r) * float(r)) and
    # call-graph rules can reach a function through several roots
    seen: set = set()
    unique: List[Finding] = []
    for f in findings:
        ident = (f.rule, f.path, f.line, f.message)
        if ident not in seen:
            seen.add(ident)
            unique.append(f)
    findings = unique

    # suppression matching (engine findings are never suppressible:
    # a suppression of "unused-suppression" would be self-defeating)
    engine_rules = {UNUSED_SUPPRESSION, BAD_SUPPRESSION, PARSE_ERROR}
    for f in findings:
        if f.rule in engine_rules:
            continue
        mod = ctx.modules.get(f.path)
        if mod is None:
            continue
        sup = mod.suppression_for(f.rule, f.line)
        if sup is not None:
            sup.used = True
            f.status = "suppressed"
            f.reason = sup.reason

    # unused suppressions — only for rules that actually ran, so a
    # --rule-filtered run doesn't misreport every other waiver as stale
    ran = {cls.name for cls in selected}
    for rel, mod in ctx.modules.items():
        for sup in mod.suppressions:
            if sup.used or not (set(sup.rules) & ran):
                continue
            findings.append(Finding(
                UNUSED_SUPPRESSION, rel, sup.comment_line,
                f"suppression of {','.join(sup.rules)} matches no finding"))

    # baseline
    baseline = load_baseline(baseline_path)
    matched = set()
    for f in findings:
        if f.status == "open" and f.key() in baseline:
            f.status = "baselined"
            matched.add(f.key())
    stale = sorted(set(baseline) - matched)

    return Report(root=str(root), rules=[cls.name for cls in selected],
                  findings=findings, stale_baseline=stale)
